"""Device (TPU) compaction data plane: host orchestration.

Replaces the CPU heap-merge + CompactionIterator with:
  1. raw sequential reads of every input file (no host merge),
  2. one device sort realizing internal-key order (ops.compaction_kernels),
  3. device GC masking (stripes, visibility, tombstone shadowing),
  4. host resolution of "complex" groups (merge operands / single-delete),
  5. the SAME build_outputs() as the CPU path → byte-identical SSTs.

This is the kernel surface called out in SURVEY.md §3.4/§7 step 5; the
serializable executor boundary (compaction/executor.py) selects it with
device="tpu"|"cpu" (the jax backend).
"""

from __future__ import annotations

import bisect
import time

import numpy as np

from toplingdb_tpu.compaction.compaction_iterator import CompactionIterator
from toplingdb_tpu.compaction.compaction_job import (
    CompactionStats,
    build_outputs,
    surviving_tombstone_fragments,
)
from toplingdb_tpu.db import dbformat
from toplingdb_tpu.db.range_del import RangeDelAggregator, RangeTombstone, fragment_tombstones
from toplingdb_tpu.ops import compaction_kernels as ck
from toplingdb_tpu.ops.columnar import ColumnarEntries


def collect_raw_entries(compaction, table_cache, icmp):
    """Sequentially read every input file's entries (NO host merge — the
    device sort is the merge). Returns (entries list, RangeDelAggregator)."""
    entries: list[tuple[bytes, bytes]] = []
    rd = RangeDelAggregator(icmp.user_comparator)
    for _, f in compaction.all_inputs():
        r = table_cache.get_reader(f.number)
        it = r.new_iterator()
        it.seek_to_first()
        for k, v in it.entries():
            entries.append((k, v))
        for b, e in r.range_del_entries():
            rd.add(RangeTombstone.from_table_entry(b, e))
    return entries, rd


def _tombstone_cover(sorted_user_keys: list[bytes], rd: RangeDelAggregator,
                     ucmp) -> np.ndarray | None:
    """Per-sorted-entry max covering tombstone seqno (uint64), via interval
    mapping on host (tombstone fragments are few; entries are many)."""
    if rd.empty():
        return None
    n = len(sorted_user_keys)
    cover = np.zeros(n, dtype=np.uint64)
    for frag in fragment_tombstones(rd.tombstones(), ucmp):
        lo = bisect.bisect_left(sorted_user_keys, frag.begin)
        hi = bisect.bisect_left(sorted_user_keys, frag.end)
        if lo < hi:
            np.maximum(cover[lo:hi], np.uint64(frag.seq), out=cover[lo:hi])
    return cover


def device_gc_entries(entries, icmp, snapshots, bottommost,
                      merge_operator=None, compaction_filter=None,
                      compaction_filter_level=0, rd=None,
                      max_key_bytes=None):
    """Runs the device data plane over raw (unsorted) entries; yields the
    surviving (internal_key, value) stream — semantically identical to
    CompactionIterator.entries() over the merged sorted input."""
    if not entries:
        return
    if icmp.user_comparator.name() != dbformat.BYTEWISE.name():
        # The device sort realizes bytewise-ascending user-key order; other
        # comparators must use the host path (scheduler falls back).
        from toplingdb_tpu.utils.status import NotSupported

        raise NotSupported(
            f"device compaction requires the bytewise comparator, "
            f"got {icmp.user_comparator.name()!r}"
        )
    col = ColumnarEntries.from_entries(entries, max_key_bytes)
    padded = ck.pad_columns(col)
    sorted_cols, perm = ck.device_sort(padded)
    cover = None
    sorted_uks = None
    if rd is not None:
        sorted_uks = [col.user_key(i) for i in perm]
        cover = _tombstone_cover(sorted_uks, rd, icmp.user_comparator)
    keep, zero_seq, host_resolve, group_id = ck.gc_mask(
        sorted_cols, snapshots, cover, bottommost
    )

    # Host-side finishing: complex groups through the reference state
    # machine; simple survivors filtered/zeroed to match it exactly.
    helper = CompactionIterator(
        _EmptyIter(), icmp, snapshots, bottommost_level=bottommost,
        merge_operator=merge_operator, compaction_filter=compaction_filter,
        compaction_filter_level=compaction_filter_level, range_del_agg=rd,
    )
    earliest = min(snapshots) if snapshots else dbformat.MAX_SEQUENCE_NUMBER
    from toplingdb_tpu.utils.compaction_filter import Decision

    n = col.n
    values = col.values
    ikeys = col.ikeys
    fast = compaction_filter is None  # fast path: emit original ikey bytes
    i = 0
    while i < n:
        if host_resolve[i]:
            g = group_id[i]
            j = i
            group = []
            while j < n and group_id[j] == g:
                oi = perm[j]
                group.append((int(col.seq[oi]), int(col.vtype[oi]), values[oi]))
                j += 1
            yield from helper._process_group(col.user_key(perm[i]), group)
            i = j
            continue
        if keep[i]:
            oi = perm[i]
            if fast:
                if zero_seq[i]:
                    yield dbformat.make_internal_key(
                        ikeys[oi][:-8], 0, int(col.vtype[oi])
                    ), values[oi]
                else:
                    yield ikeys[oi], values[oi]
                i += 1
                continue
            seq, t = int(col.seq[oi]), int(col.vtype[oi])
            val = values[oi]
            uk = col.user_key(oi)
            if t == dbformat.ValueType.VALUE and seq <= earliest:
                d, newv = compaction_filter.filter(
                    compaction_filter_level, uk, val
                )
                if d == Decision.REMOVE:
                    i += 1
                    continue
                if d == Decision.CHANGE_VALUE:
                    val = newv if newv is not None else b""
            if zero_seq[i]:
                seq = 0
            yield dbformat.make_internal_key(uk, seq, t), val
        i += 1


class _EmptyIter:
    def valid(self):
        return False


def run_device_compaction(env, dbname, icmp, compaction, table_cache,
                          table_options, snapshots, merge_operator=None,
                          compaction_filter=None, new_file_number=None,
                          creation_time=None, device_name="tpu"):
    """Device counterpart of run_compaction_to_tables — same signature shape,
    byte-identical outputs."""
    t0 = time.time()
    stats = CompactionStats(device=device_name)
    stats.input_bytes = compaction.total_input_bytes()
    entries, rd = collect_raw_entries(compaction, table_cache, icmp)
    stats.input_records = len(entries)
    rd_or_none = None if rd.empty() else rd
    stream = device_gc_entries(
        entries, icmp, snapshots, compaction.bottommost,
        merge_operator=merge_operator, compaction_filter=compaction_filter,
        compaction_filter_level=compaction.output_level, rd=rd_or_none,
    )
    tombs = surviving_tombstone_fragments(
        rd, snapshots, compaction.bottommost, icmp.user_comparator
    )
    outputs = build_outputs(
        env, dbname, icmp, compaction, stream, tombs, new_file_number,
        table_options, stats,
        creation_time if creation_time is not None else int(time.time()),
    )
    stats.work_time_usec = int((time.time() - t0) * 1e6)
    return outputs, stats
