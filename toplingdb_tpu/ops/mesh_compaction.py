"""Mesh-sharded device compaction: one job's uniform key-range shards
fanned out over every chip of a (jobs=1, range=R) `jax.sharding.Mesh`.

The single-chip plane (ops/device_compaction.py) already splits a big job
into presorted uniform shards and runs one fused merge+GC program per
shard; those programs carry no device pin — the committed inputs decide
where they run. Mesh mode is therefore placement, not a new kernel: each
shard's `upload_uniform_shard` buffers are committed to a chip picked
round-robin from the mesh's range axis, so S shards execute on D chips
concurrently while the host streams finishes in shard order into the
same block/zip writers. Outputs are byte-identical to the single-chip
path BY CONSTRUCTION (same per-shard kernel, same per-shard inputs, same
stitch order).

Dispatch is double-buffered per chip (mesh_plan.UPLOAD_DEPTH uploads in
flight per device): shard s+D's H2D transfer streams while shard s
computes on the same chip, and every program's D2H copies are enqueued at
dispatch, so the writer's encode overlaps the remaining chips' compute.

Gating: `TPULSM_MESH_COMPACT=1` enables the mode; ineligible jobs
(complex merge groups, non-uniform shards, below the row floor, a single
shard/device) fall back to the serial single-device plane automatically —
mesh_plan.check_eligibility is the one fallback matrix. A chip that
fails mid-job is WEDGED: its queued shards re-dispatch onto the surviving
chips (or the default device when none remain) and the job completes with
the same bytes; the demotion is counted on CompactionStats.mesh_fallbacks
and visible as a `compaction.mesh.fallback` span event beside the
per-chip `compaction.mesh.shard` spans in the stitched waterfall.
"""

from __future__ import annotations

import os
import time

from toplingdb_tpu.parallel import mesh_plan
from toplingdb_tpu.utils import errors as _errors
from toplingdb_tpu.utils import telemetry
from toplingdb_tpu.utils.status import NotSupported

# Test seam: callable(shard_idx, device) invoked before each dispatch;
# raising simulates a chip failure at that point (chaos/demotion tests).
_FAULT_HOOK = None


def mesh_enabled() -> bool:
    return os.environ.get("TPULSM_MESH_COMPACT") == "1"


def maybe_plan(shards, any_complex: bool = False, stats=None,
               trace=None):
    """A MeshPlan when the knob is on and the job is eligible, else None.
    Eligibility misses while the knob is ON are fallbacks: counted on
    `stats.mesh_fallbacks` and emitted as a `compaction.mesh.fallback`
    event so waterfalls show WHY a job stayed single-chip."""
    if not mesh_enabled():
        return None
    try:
        devices = mesh_plan.mesh_devices()
    except Exception as e:  # no jax backend at all → serial plane
        _errors.swallow(reason="mesh-no-backend", exc=e)
        devices = []
    plan, reason = mesh_plan.plan_shards(shards, any_complex, devices)
    if plan is None:
        if stats is not None:
            stats.mesh_fallbacks = getattr(stats, "mesh_fallbacks", 0) + 1
        telemetry.span_event_under(trace, "compaction.mesh.fallback", 0,
                                   reason=reason)
        return None
    if stats is not None:
        stats.mesh_chips = plan.n_devices
        stats.mesh_shards = len(shards)
    return plan


class MeshShardRun:
    """Windowed round-robin dispatch of one job's shards over a plan's
    chips. `finish(s)` must be called for s = 0..n_shards-1 in order (the
    writers consume survivor orders in shard order); each finish tops the
    dispatch window back up, keeping every chip double-buffered.

    plan=None is the serial twin: every shard uploads up front to the
    default device — exactly the single-chip plane's dispatch, so the
    bench's 1-chip runs and mesh runs share this driver."""

    def __init__(self, plan, shards, cover, snapshots, bottommost,
                 stats=None, trace=None):
        from toplingdb_tpu.ops import compaction_kernels as ck

        self._ck = ck
        self._plan = plan
        self._shards = shards
        self._cover = cover
        self._snapshots = snapshots
        self._bottommost = bottommost
        self._stats = stats
        self._trace = trace
        self._mesh = (mesh_plan.build_range_mesh(plan.devices)
                      if plan is not None else None)
        self._wedged: set[int] = set()
        self._pend: dict[int, tuple] = {}
        self._next = 0
        self._window = plan.window if plan is not None else len(shards)
        self._fill()

    # -- placement ---------------------------------------------------------

    def _device_for(self, s: int):
        """Shard s's chip: the plan's round-robin assignment, re-mapped
        onto the surviving chips once any are wedged; None (= default
        device) when no planned chip survives."""
        if self._plan is None:
            return None
        if not self._wedged:
            return self._plan.devices[self._plan.assignments[s]]
        healthy = [d for i, d in enumerate(self._plan.devices)
                   if i not in self._wedged]
        if not healthy:
            return None
        return healthy[s % len(healthy)]

    def _wedge(self, device, exc) -> None:
        if self._plan is None or device is None:
            return
        for i, d in enumerate(self._plan.devices):
            if d is device and i not in self._wedged:
                self._wedged.add(i)
                if self._stats is not None:
                    self._stats.mesh_fallbacks = getattr(
                        self._stats, "mesh_fallbacks", 0) + 1
                    self._stats.mesh_chips = max(
                        1, len(self._plan.devices) - len(self._wedged))
                telemetry.span_event_under(
                    self._trace, "compaction.mesh.fallback", 0,
                    reason="chip-wedged", chip=str(device),
                    error=type(exc).__name__)
                break

    # -- dispatch ----------------------------------------------------------

    def _covers_for(self, ranges):
        if self._cover is None:
            return None
        return [self._cover[lo:hi] for lo, hi in ranges]

    def _start_on(self, s: int, device):
        chunks, ranges = self._shards[s]
        if _FAULT_HOOK is not None:
            _FAULT_HOOK(s, device)
        h = self._ck.upload_uniform_shard(chunks, self._covers_for(ranges),
                                          device=device)
        return self._ck.fused_uniform_shard_start(
            h, self._snapshots, self._bottommost)

    def _dispatch(self, s: int) -> None:
        while True:
            device = self._device_for(s)
            try:
                pending = self._start_on(s, device)
            except NotSupported:
                raise  # job-shape refusal: the caller's fallback ladder
            except Exception as e:
                if device is None:
                    raise  # even the default device failed: real error
                self._wedge(device, e)
                continue  # demote: next surviving chip / default device
            self._pend[s] = (pending, device, time.time())
            return

    def _fill(self) -> None:
        n = len(self._shards)
        while self._next < n and len(self._pend) < self._window:
            self._dispatch(self._next)
            self._next += 1

    # -- consume -----------------------------------------------------------

    def finish(self, s: int):
        """Block on shard s's result (order, zero_flags, cx_flags,
        has_complex); re-dispatches the shard on a surviving chip if its
        chip dies under the wait, then refills the window."""
        pending, device, t_disp = self._pend.pop(s)
        while True:
            try:
                out = self._ck.fused_uniform_shard_finish(pending)
                break
            except Exception as e:
                if device is None:
                    raise
                self._wedge(device, e)
                self._dispatch(s)  # re-runs on a healthy chip, same bytes
                pending, device, t_disp = self._pend.pop(s)
        # Callers time the blocking wait into stats.device_wait_usec
        # around finish() itself; only the per-chip span is emitted here.
        if self._plan is not None:
            chunks, _ranges = self._shards[s]
            telemetry.span_event_under(
                self._trace, "compaction.mesh.shard",
                (time.time() - t_disp) * 1e6, shard=s,
                chip=str(device) if device is not None else "default",
                rows=sum(int(c[3]) for c in chunks))
        self._fill()
        return out


def dispatch_shards(shards, cover, snapshots, bottommost, stats=None,
                    any_complex: bool = False, trace=None):
    """The single seam device_compaction.py calls: plan (knob + the
    eligibility matrix), then return (finish(s) callable, mesh_active).
    Ineligible/disabled jobs get the classic serial dispatch — every
    shard uploaded up front to the default device — so callers never
    branch on the mode."""
    plan = maybe_plan(shards, any_complex=any_complex, stats=stats,
                      trace=trace)
    run = MeshShardRun(plan, shards, cover, snapshots, bottommost,
                       stats=stats, trace=trace)
    return run.finish, plan is not None


def pipeline_devices(n_shards: int, stats=None, trace=None):
    """Chips for the pipelined plane's compute stage: the same gate as
    dispatch_shards minus the shard-shape checks (the pipeline validates
    uniformity itself, shard by shard, as scans land). Returns a device
    list (len >= 2) or None for the classic single-buffer path."""
    if not mesh_enabled() or n_shards < 2:
        return None
    try:
        devices = mesh_plan.mesh_devices()
    except Exception as e:
        _errors.swallow(reason="mesh-no-backend", exc=e)
        return None
    if len(devices) < 2:
        return None
    if stats is not None:
        stats.mesh_chips = len(devices)
        stats.mesh_shards = n_shards
    return devices
