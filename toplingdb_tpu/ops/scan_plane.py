"""Chunked forward-scan data plane: native block decode + k-way merge
for DBIter.

The per-entry read path (DBIter over MergingIterator) pays a Python heap
pop/push, a comparator call, and an internal-key split for EVERY version
of every key — while compaction (ops/pipeline.py) and MultiGet already
run native and batched. This module gives forward scans the same shape:

  source runs   each SST source decodes a run of entries per native
                call (`tpulsm_scan_blocks` through a pre-armed
                FilePrefetchBuffer window, reusing the pipeline's
                machinery; zip tables instead decode a window of entries
                via `ZipTableReader.scan_columnar` — bulk key
                front-decode plus `tpulsm_zip_group_decode` over the
                compressed value groups, no whole-file inflate); the
                memtable contributes its run via the native rep export
                (`tpulsm_skiplist_export`)
  merge         ONE `tpulsm_merge_runs` call (native full-sort fallback
                for >8B user keys) orders the concatenated runs and
                hands back per-row (seq, type) trailers + new-key marks
  resolve       snapshot visibility, newest-visible-per-key selection,
                point/range-tombstone masking — all vectorized numpy
                over the merged chunk; only emitted survivors touch
                Python

DBIter serves key()/value()/next() from the resulting chunk cursor and
the plane refills from the per-source resume positions when the cursor
runs out. Chunk boundaries are cut at the minimum last-buffered user key
over the non-exhausted sources, so every emitted key's visible-version
group is complete (versions of one user key may be spread over every
source). `iterate_upper_bound` prunes block/file fetch so chunking never
over-reads more than one index block past the bound.

Fallbacks — the plane refuses (construction) or bails mid-stream
(PlaneIneligible, DBIter degrades to the per-entry path at the current
position) for: TPULSM_ITER_CHUNK=0, missing native lib, non-bytewise
comparators (user timestamps ride on u64ts and are excluded with them),
merge operators, prefix-mode iteration, WritePrepared excluded ranges,
backward iteration (seek_to_last/seek_for_prev/prev), block files that
are dict-compressed or use codecs the native scanner can't inflate, zip
files when TPULSM_ZIP_PLANE=0 or the zip decode kernels are absent
(ticked as ZIP_PLANE_FALLBACKS), and any other table format.

`TPULSM_ITER_CHUNK`: 0 disables, unset/1 = default chunk rows, N>1 =
chunk rows.
"""

from __future__ import annotations

import ctypes
import os

import numpy as np

from toplingdb_tpu import native
from toplingdb_tpu.db import dbformat
from toplingdb_tpu.db.dbformat import ValueType
from toplingdb_tpu.table.prefetch import FilePrefetchBuffer
from toplingdb_tpu.utils import statistics as _stats_mod
from toplingdb_tpu.utils import errors as _errors


class PlaneIneligible(Exception):
    """Shapes the chunked plane does not cover; DBIter re-runs the
    current operation on the per-entry path (which also produces the
    canonical error for corrupt inputs)."""


DEFAULT_CHUNK = 4096
# Blocks decoded per source fetch: starts at 1 (a seek costs one block,
# like the per-entry path) and doubles on sequential refills.
_MAX_FETCH_BLOCKS = 64
_PF_INIT = 64 << 10
_PF_MAX = 4 << 20

_U64P = ctypes.POINTER(ctypes.c_uint64)
_PACKED_SENTINEL = np.uint64(0xFFFFFFFFFFFFFFFF)

# Value types the resolver can surface (everything else bails to the
# per-entry path, which raises the canonical error).
_EMIT_TYPES = (int(ValueType.VALUE), int(ValueType.BLOB_INDEX),
               int(ValueType.WIDE_COLUMN_ENTITY))


def chunk_rows() -> int:
    """Parsed TPULSM_ITER_CHUNK knob: 0 = disabled."""
    env = os.environ.get("TPULSM_ITER_CHUNK", "")
    if not env:
        return DEFAULT_CHUNK
    try:
        v = int(env)
    except ValueError:
        return DEFAULT_CHUNK
    if v <= 0:
        return 0
    return DEFAULT_CHUNK if v == 1 else v


def _native_order(lib, kb, ko, kl, run_starts):
    """(order, new_key, packed) over the concatenated presorted runs:
    `tpulsm_merge_runs` (k-way, multi-threaded, 8B-key fast path) with
    the `tpulsm_sort_entries` stable sort as the general fallback.
    Output contract matches compaction_kernels.host_sort_order."""
    n = len(ko)
    offs = np.ascontiguousarray(ko, dtype=np.int64)
    lens = np.ascontiguousarray(kl, dtype=np.int64)
    kbc = np.ascontiguousarray(kb)
    order = np.empty(n, dtype=np.int32)
    new_key = np.empty(n, dtype=np.uint8)
    packed = np.full(n, _PACKED_SENTINEL, dtype=np.uint64)
    rc = -1
    rs = np.ascontiguousarray(run_starts, dtype=np.int64)
    if n and len(rs) > 1 and hasattr(lib, "tpulsm_merge_runs"):
        rc = lib.tpulsm_merge_runs(
            native.np_u8p(kbc), native.np_i64p(offs), native.np_i64p(lens),
            n, native.np_i64p(rs), len(rs) - 1,
            native.np_i32p(order), native.np_u8p(new_key),
            packed.ctypes.data_as(_U64P),
        )
    if rc != 0:
        rc = lib.tpulsm_sort_entries(
            native.np_u8p(kbc), native.np_i64p(offs), native.np_i64p(lens),
            n, native.np_i32p(order), native.np_u8p(new_key),
            packed.ctypes.data_as(_U64P),
        )
    if rc != 0:
        raise PlaneIneligible("native merge unavailable")
    if n and packed[0] == _PACKED_SENTINEL:
        raise PlaneIneligible("stale native binary (no packed_out)")
    return order, new_key, packed


class _Pending:
    """One source's decoded-but-unconsumed rows, columnar. Offsets are
    absolute into kb/vb and contiguous ascending (decode order), so the
    live byte span can be sliced without per-row work."""

    __slots__ = ("kb", "ko", "kl", "vb", "vo", "vl", "start", "n", "_vbb")

    def __init__(self):
        self.clear()

    def clear(self):
        self.kb = self.vb = None
        self.ko = self.kl = self.vo = self.vl = None
        self.start = self.n = 0
        self._vbb = None

    def vb_bytes(self) -> bytes:
        """The value buffer as one Python bytes object (bulk memcpy once
        per refill; Python-level slicing beats per-row ndarray views)."""
        b = self._vbb
        if b is None:
            b = self._vbb = self.vb.tobytes()
        return b

    def rows(self) -> int:
        return self.n - self.start

    def uk_at(self, i: int) -> bytes:
        o = int(self.ko[i])
        return self.kb[o: o + int(self.kl[i]) - 8].tobytes()

    def ik_at(self, i: int) -> bytes:
        o = int(self.ko[i])
        return self.kb[o: o + int(self.kl[i])].tobytes()

    def last_uk(self) -> bytes:
        return self.uk_at(self.n - 1)

    def drop_below(self, uk: bytes) -> None:
        """Consume every row whose user key sorts below `uk` (rows are
        internal-key sorted, so user keys are nondecreasing)."""
        lo, hi = self.start, self.n
        while lo < hi:
            mid = (lo + hi) // 2
            if self.uk_at(mid) < uk:
                lo = mid + 1
            else:
                hi = mid
        self.start = lo

    def drop_upto(self, uk: bytes) -> None:
        """Consume every row whose user key sorts at or below `uk`."""
        lo, hi = self.start, self.n
        while lo < hi:
            mid = (lo + hi) // 2
            if self.uk_at(mid) <= uk:
                lo = mid + 1
            else:
                hi = mid
        self.start = lo

    def drop_all(self) -> None:
        self.start = self.n

    def first_ge(self, ikey: bytes, icmp) -> int:
        """Index of the first row with internal key >= ikey."""
        lo, hi = self.start, self.n
        while lo < hi:
            mid = (lo + hi) // 2
            if icmp.compare(self.ik_at(mid), ikey) < 0:
                lo = mid + 1
            else:
                hi = mid
        return lo

    def append(self, kb, ko, kl, vb, vo, vl) -> None:
        self._vbb = None
        if self.rows() == 0:
            self.kb, self.ko, self.kl = kb, ko, kl
            self.vb, self.vo, self.vl = vb, vo, vl
            self.start, self.n = 0, len(ko)
            return
        st, n = self.start, self.n
        k0 = int(self.ko[st])
        k1 = int(self.ko[n - 1]) + int(self.kl[n - 1])
        v0 = int(self.vo[st])
        v1 = int(self.vo[n - 1]) + int(self.vl[n - 1])
        self.kb = np.concatenate([self.kb[k0:k1], kb])
        self.ko = np.concatenate([self.ko[st:n] - k0, ko + (k1 - k0)])
        self.kl = np.concatenate([self.kl[st:n], kl])
        self.vb = np.concatenate([self.vb[v0:v1], vb])
        self.vo = np.concatenate([self.vo[st:n] - v0, vo + (v1 - v0)])
        self.vl = np.concatenate([self.vl[st:n], vl])
        self.start, self.n = 0, len(self.ko)


def _bank_rows(bank: set, pb: int, kb, ko, kl, vb, vo, vl,
               start: int, n: int) -> None:
    """Record each decoded row's (user_key, value) checksum at the moment
    it enters a pending buffer — the scan plane's source-side half of the
    protection handoff. Emission re-hashes and requires membership
    (ScanPlane._verify_emission), so bytes garbled anywhere between the
    native block decode and chunk emission are caught before serving."""
    from toplingdb_tpu.utils import protection as _p

    for i in range(start, n):
        o = int(ko[i])
        uk = kb[o: o + int(kl[i]) - 8].tobytes()
        vo_i = int(vo[i])
        v = vb[vo_i: vo_i + int(vl[i])].tobytes()
        bank.add(_p.truncate(_p.kv_checksum(uk, v), pb))


class _MemSource:
    """Memtable run: materialized ONCE (lazily, at first use) via the
    rep's native columnar export when available, else a Python walk of
    iter_entries(). The copy pins the iterator's view — later inserts
    carry seqnos above the snapshot anyway, so missing them is exactly
    the per-entry path's visibility behavior."""

    def __init__(self, mem, prot_bank=None, protection_bytes: int = 0):
        self._mem = mem
        self.pending = _Pending()
        self.exhausted = True
        self._mat = False
        self._kb = None  # materialized arrays (seek re-slices them)
        self._n = 0
        self._vbb_cache = None  # bytes view of _vb, shared across seeks
        self._prot_bank = prot_bank
        self._pb = protection_bytes

    def _materialize(self) -> None:
        self._mat = True
        mem = self._mem
        res = None
        try:
            res = mem.export_columnar()
        except Exception as e:  # noqa: BLE001 — concurrent mutation: slow path
            _errors.swallow(reason="memtable-export-race", exc=e)
            res = None
        if res is not None:
            kv, _seqs, _vtypes = res
            self._kb = kv.key_buf
            self._ko = kv.key_offs.astype(np.int64)
            self._kl = kv.key_lens.astype(np.int64)
            self._vb = kv.val_buf
            self._vo = kv.val_offs.astype(np.int64)
            self._vl = kv.val_lens.astype(np.int64)
            self._n = kv.n
            return
        ks, vs = [], []
        for ik, v in mem.iter_entries():
            ks.append(ik)
            vs.append(v)
        self._n = len(ks)
        self._kb = np.frombuffer(b"".join(ks), dtype=np.uint8)
        self._kl = np.fromiter((len(k) for k in ks), np.int64, self._n)
        self._ko = np.zeros(self._n, dtype=np.int64)
        np.cumsum(self._kl[:-1], out=self._ko[1:])
        self._vb = np.frombuffer(b"".join(vs), dtype=np.uint8)
        self._vl = np.fromiter((len(v) for v in vs), np.int64, self._n)
        self._vo = np.zeros(self._n, dtype=np.int64)
        np.cumsum(self._vl[:-1], out=self._vo[1:])

    def seek(self, target: bytes | None, icmp) -> None:
        if not self._mat:
            self._materialize()
            if self._prot_bank is not None and self._n:
                _bank_rows(self._prot_bank, self._pb, self._kb, self._ko,
                           self._kl, self._vb, self._vo, self._vl,
                           0, self._n)
        self.pending.clear()
        if self._n == 0:
            return
        self.pending.kb, self.pending.ko, self.pending.kl = \
            self._kb, self._ko, self._kl
        self.pending.vb, self.pending.vo, self.pending.vl = \
            self._vb, self._vo, self._vl
        self.pending.start, self.pending.n = 0, self._n
        if self._vbb_cache is None:
            self._vbb_cache = self._vb.tobytes()
        self.pending._vbb = self._vbb_cache
        if target is not None:
            self.pending.start = self.pending.first_ge(target, icmp)

    def top_up(self, min_rows: int) -> None:
        pass  # fully materialized

    def prefetch_counts(self) -> tuple[int, int]:
        return 0, 0


class _NoPf:
    """Prefetch-buffer stand-in for zip files: the reader is fully
    resident (sections mmap'd/loaded at open), so there is nothing to
    prefetch and the counters stay zero."""

    hits = 0
    misses = 0

    def reset(self) -> None:
        pass


class _SSTSource:
    """A sorted run of SST files (one L0 file, or one level's disjoint
    file chain). Files open lazily through the table cache (the pinned
    Version keeps them on disk); per fetch, one `tpulsm_scan_blocks`
    call decodes a doubling window of data blocks read through a
    pre-armed FilePrefetchBuffer. Zip files window in entries instead:
    `scan_columnar` bulk-decodes value groups natively, so the plane
    keeps chunk-merge eligibility on searchable-compression levels."""

    def __init__(self, files, table_cache, icmp, upper_target,
                 readahead_size: int = 0, prot_bank=None,
                 protection_bytes: int = 0, stats=None, aio_ring=None):
        self._files = files
        self._tc = table_cache
        self._icmp = icmp
        self._upper_t = upper_target
        self._ra = readahead_size
        # Async read plane: readahead windows become reader-ring tasks.
        self._aio = aio_ring
        self._prot_bank = prot_bank
        self._pb = protection_bytes
        self._stats = stats
        self.pending = _Pending()
        self.exhausted = not files
        self._next_fi = 0
        self._reader = None
        self._pf = None
        self._zip = False
        self._win = 1
        self._seek_t: bytes | None = None
        # file number -> (reader, offs, lens, seps, pf): repeated seeks
        # into the same file must not re-walk its index block.
        self._fmemo: dict = {}

    # -- positioning ---------------------------------------------------

    def seek(self, target: bytes | None, icmp) -> None:
        self.pending.clear()
        self._close_file()
        self._win = 1
        self._seek_t = target
        self.exhausted = False
        if target is None:
            self._next_fi = 0
        else:
            lo, hi = 0, len(self._files) - 1
            pick = len(self._files)
            while lo <= hi:
                mid = (lo + hi) // 2
                if self._icmp.compare(self._files[mid].largest, target) >= 0:
                    pick = mid
                    hi = mid - 1
                else:
                    lo = mid + 1
            self._next_fi = pick
        if self._next_fi >= len(self._files):
            self.exhausted = True

    def _close_file(self) -> None:
        self._reader = None
        self._pf = None
        self._zip = False

    def _open_next_file(self) -> None:
        self._close_file()
        if self._next_fi >= len(self._files):
            self.exhausted = True
            return
        meta = self._files[self._next_fi]
        if self._upper_t is not None and self._icmp.compare(
                meta.smallest, self._upper_t) >= 0:
            # Every key of this (and, for level runs, any later) file is
            # at or beyond the upper bound: stop fetching entirely.
            self.exhausted = True
            return
        self._next_fi += 1
        memo = self._fmemo.get(meta.number)
        if memo is None:
            reader = self._tc.get_reader(meta.number)
            if hasattr(reader, "scan_columnar"):
                # Zip table: served natively through scan_columnar, no
                # index/prefetch machinery (sections are resident).
                if not reader.scan_native_ready():
                    if self._stats is not None:
                        self._stats.record_tick(
                            _stats_mod.ZIP_PLANE_FALLBACKS)
                    raise PlaneIneligible("zip plane disabled/unavailable")
                memo = (reader, None, None, None, _NoPf())
                self._fmemo[meta.number] = memo
                self._open_memo(memo)
                return
            elif not hasattr(reader, "new_index_iterator") or \
                    getattr(reader, "_compression_dict", b""):
                raise PlaneIneligible("non-block or dict-compressed input")
            idx = reader.new_index_iterator()
            idx.seek_to_first()
            handles, seps = [], []
            from toplingdb_tpu.table import format as fmt

            for k, enc in idx.entries():
                handles.append(fmt.BlockHandle.decode_exact(enc))
                seps.append(k)
            if self._ra > 0:
                pf = FilePrefetchBuffer(
                    reader._f, max_readahead=self._ra,
                    initial_readahead=self._ra, arm_immediately=True,
                    aio_ring=self._aio)
            else:
                # Auto-scaling: the window arms after two sequential
                # span reads and doubles per refill; a point seek pays
                # one block-sized pread, like the per-entry path.
                pf = FilePrefetchBuffer(
                    reader._f, max_readahead=_PF_MAX,
                    initial_readahead=_PF_INIT, aio_ring=self._aio)
            memo = (reader,
                    np.array([h.offset for h in handles], dtype=np.int64),
                    np.array([h.size for h in handles], dtype=np.int64),
                    seps, pf)
            self._fmemo[meta.number] = memo
        self._open_memo(memo)

    def _open_memo(self, memo) -> None:
        reader, self._offs, self._lens, seps, pf = memo
        self._reader = reader
        if self._seek_t is not None:
            pf.reset()  # seek: restart the auto-scaling readahead ramp
        self._pf = pf
        if self._offs is None:
            # Zip file: windows advance in entries (value-group
            # multiples); positioning is exact via entry_lower_bound, so
            # there is no straddling block to include at either end.
            self._zip = True
            self._nwin = reader.n
            bi = (reader.entry_lower_bound(self._seek_t)
                  if self._seek_t is not None else 0)
            bstop = (reader.entry_lower_bound(self._upper_t)
                     if self._upper_t is not None else reader.n)
            self._bi, self._bstop = bi, max(bi, bstop)
            return
        self._verify = bool(reader.opts.verify_checksums)
        self._nwin = len(self._offs)
        bi = 0
        if self._seek_t is not None:
            lo, hi = 0, len(seps)
            while lo < hi:
                mid = (lo + hi) // 2
                if self._icmp.compare(seps[mid], self._seek_t) < 0:
                    lo = mid + 1
                else:
                    hi = mid
            bi = lo
        bstop = len(self._offs)
        if self._upper_t is not None:
            lo, hi = bi, len(seps)
            while lo < hi:
                mid = (lo + hi) // 2
                if self._icmp.compare(seps[mid], self._upper_t) < 0:
                    lo = mid + 1
                else:
                    hi = mid
            # Include the straddling block; later blocks hold only keys
            # at or beyond the bound.
            bstop = min(lo + 1, len(self._offs))
        self._bi, self._bstop = bi, bstop

    # -- fetching ------------------------------------------------------

    def top_up(self, min_rows: int) -> None:
        lib = native.lib()
        while not self.exhausted and self.pending.rows() < min_rows:
            if self._reader is None or self._bi >= self._bstop:
                if self._reader is not None and self._bi >= self._bstop \
                        and self._bstop < self._nwin:
                    # Upper-bound prune hit inside the file: the rest of
                    # this run is entirely at/beyond the bound.
                    self.exhausted = True
                    return
                self._open_next_file()
                continue
            if self._zip:
                self._fetch_zip_window()
            else:
                self._fetch_window(lib)

    def _fetch_window(self, lib) -> None:
        b0 = self._bi
        b1 = min(b0 + self._win, self._bstop)
        self._win = min(self._win * 2, _MAX_FETCH_BLOCKS)
        w0 = int(self._offs[b0])
        w1 = int(self._offs[b1 - 1] + self._lens[b1 - 1]) + 5
        raw = self._pf.read(w0, w1 - w0)
        rawb = np.frombuffer(raw, dtype=np.uint8)
        boffs = np.ascontiguousarray(self._offs[b0:b1] - w0)
        blens = np.ascontiguousarray(self._lens[b0:b1])
        span = int(blens.sum())
        n_cap = 192 * (b1 - b0) + 64
        k_cap = span * 3 + 4096
        v_cap = span * 3 + 4096
        for _ in range(4):
            kb = np.empty(k_cap, dtype=np.uint8)
            vb = np.empty(v_cap, dtype=np.uint8)
            ko = np.empty(n_cap, dtype=np.int32)
            kl = np.empty(n_cap, dtype=np.int32)
            vo = np.empty(n_cap, dtype=np.int32)
            vl = np.empty(n_cap, dtype=np.int32)
            rc = lib.tpulsm_scan_blocks(
                native.np_u8p(rawb), len(rawb),
                native.np_i64p(boffs), native.np_i64p(blens), b1 - b0,
                1 if self._verify else 0,
                native.np_u8p(kb), k_cap, native.np_u8p(vb), v_cap,
                native.np_i32p(ko), native.np_i32p(kl),
                native.np_i32p(vo), native.np_i32p(vl), n_cap, 0, 0,
            )
            if rc == -2:
                k_cap *= 4
            elif rc == -3:
                v_cap *= 4
            elif rc == -4:
                n_cap *= 4
            else:
                break
        if rc < 0:
            # Codec/corruption/capacity shapes the plane doesn't cover:
            # the per-entry path re-reads and raises the canonical error.
            raise PlaneIneligible(f"native scan rc={rc}")
        if _stats_mod.perf_level:
            # PerfContext parity with the per-entry path: every data block
            # this window decoded counts once, bytes at on-disk block size
            # (== decoded size for the uncompressed blocks the plane
            # serves natively).
            _pctx = _stats_mod.perf_context()
            _pctx.block_read_count += b1 - b0
            _pctx.block_read_byte += span
        self._bi = b1
        if rc == 0:
            return
        ko = ko[:rc].astype(np.int64)
        kl = kl[:rc].astype(np.int64)
        vo = vo[:rc].astype(np.int64)
        vl = vl[:rc].astype(np.int64)
        lo = 0
        if self._seek_t is not None:
            tmp = _Pending()
            tmp.kb, tmp.ko, tmp.kl = kb, ko, kl
            tmp.start, tmp.n = 0, rc
            lo = tmp.first_ge(self._seek_t, self._icmp)
            if lo >= rc:
                return
            self._seek_t = None
        if self._prot_bank is not None:
            _bank_rows(self._prot_bank, self._pb, kb, ko, kl, vb, vo, vl,
                       lo, rc)
        self.pending.append(kb, ko[lo:], kl[lo:], vb, vo[lo:], vl[lo:])

    def _fetch_zip_window(self) -> None:
        """Zip analogue of _fetch_window: one scan_columnar call decodes
        a doubling window of entries (sized in value groups so each
        group's zstd inflate amortizes over a full window). No seek trim
        is needed — _open_memo positioned _bi with entry_lower_bound."""
        r = self._reader
        vg = max(1, int(r.VG))
        e0 = self._bi
        e1 = min(e0 + self._win * vg, self._bstop)
        self._win = min(self._win * 2, _MAX_FETCH_BLOCKS)
        kb, ko, kl, vb, vo, vl = r.scan_columnar(e0, e1)
        self._bi = e1
        n = e1 - e0
        if n <= 0:
            return
        self._seek_t = None
        if self._stats is not None:
            self._stats.record_tick(
                _stats_mod.ZIP_GROUP_DECODES, -(-e1 // vg) - e0 // vg)
            self._stats.record_tick(
                _stats_mod.ZIP_GROUP_DECODE_BYTES, int(len(vb)))
        if self._prot_bank is not None:
            _bank_rows(self._prot_bank, self._pb, kb, ko, kl, vb, vo, vl,
                       0, n)
        self.pending.append(kb, ko, kl, vb, vo, vl)

    def prefetch_counts(self) -> tuple[int, int]:
        h = m = 0
        for _r, _o, _l, _s, pf in self._fmemo.values():
            h += pf.hits
            m += pf.misses
        return h, m


class ScanPlane:
    """Forward-scan chunk server for DBIter. Cursor surface:
    seek_first()/seek(user_key)/advance() position it; is_valid,
    cur_key, cur_value, cur_type expose the current entry."""

    def __init__(self, sources, icmp, snap_seq: int, rd, upper, lower,
                 blob_resolver, stats, chunk: int, prot_bank=None,
                 protection_bytes: int = 0):
        self._srcs = sources
        self._icmp = icmp
        self._seq = snap_seq
        self._rd = rd
        self._upper = upper
        self._lower = lower
        self._blob = blob_resolver
        self._stats = stats
        # Protection (Options.protection_bytes_per_key): sources banked
        # every decoded row's checksum into prot_bank; emission must find
        # each served (user_key, value) there (_verify_emission).
        self._prot_bank = prot_bank
        self._pb = protection_bytes
        self._chunk = max(2, chunk)
        self.is_valid = False
        self.cur_key = self.cur_value = None
        self.cur_type = int(ValueType.VALUE)
        self._keys: list = []
        self._vals: list = []
        self._types: list = []
        self._i = 0
        self._done = False
        self._pf_banked = (0, 0)
        # Per-source refill quota: small right after a seek (a point
        # lookup decodes ~one block per source), doubling on sequential
        # refills up to the chunk budget.
        self._quota_max = max(64, self._chunk // max(1, len(sources)))
        self._quota = 64

    # -- positioning ---------------------------------------------------

    def seek_first(self) -> None:
        self.seek(self._lower if self._lower is not None else None)

    def seek(self, user_key: bytes | None) -> None:
        self._done = False
        self._keys, self._vals, self._types = [], [], []
        self._i = 0
        self.is_valid = False
        target = None
        if user_key is not None:
            if self._upper is not None and user_key >= self._upper:
                self._done = True
                return
            target = dbformat.make_internal_key(
                user_key, self._seq, dbformat.VALUE_TYPE_FOR_SEEK)
        self._quota = 64
        for s in self._srcs:
            s.seek(target, self._icmp)
        self._refill()

    def advance(self) -> None:
        i = self._i + 1
        if i < len(self._keys):
            self._i = i
            self.cur_key = self._keys[i]
            self.cur_value = self._vals[i]
            self.cur_type = self._types[i]
            return
        self._keys, self._vals, self._types = [], [], []
        self._i = 0
        self.is_valid = False
        self._refill()

    # -- refill --------------------------------------------------------

    def _bank_prefetch(self) -> None:
        if self._stats is None:
            return
        h = m = 0
        for s in self._srcs:
            sh, sm = s.prefetch_counts()
            h += sh
            m += sm
        dh, dm = h - self._pf_banked[0], m - self._pf_banked[1]
        if dh or dm:
            from toplingdb_tpu.utils import statistics as st

            if dh:
                self._stats.record_tick(st.PREFETCH_HITS, dh)
            if dm:
                self._stats.record_tick(st.PREFETCH_MISSES, dm)
            self._pf_banked = (h, m)

    def _refill(self) -> None:
        if self._done:
            return
        lib = native.lib()
        if lib is None:
            raise PlaneIneligible("native lib unavailable")
        quota = self._quota
        self._quota = min(self._quota * 2, self._quota_max)
        keys, vals, types = self._keys, self._vals, self._types
        while not keys and not self._done:
            for s in self._srcs:
                if not s.exhausted:
                    s.top_up(quota)
            parts = [s for s in self._srcs if s.pending.rows() > 0]
            if not parts:
                self._done = True
                break
            bound = None
            for s in self._srcs:
                if not s.exhausted and s.pending.rows() > 0:
                    u = s.pending.last_uk()
                    if bound is None or u < bound:
                        bound = u
            cat_kb, cat_ko, cat_kl, rs, src_of, loc_of = self._concat(parts)
            order, new_key, packed = _native_order(
                lib, cat_kb, cat_ko, cat_kl, rs)
            n = len(order)
            cut = n
            if bound is not None:
                lo, hi = 0, n
                while lo < hi:
                    mid = (lo + hi) // 2
                    r = int(order[mid])
                    o = int(cat_ko[r])
                    if cat_kb[o: o + int(cat_kl[r]) - 8].tobytes() < bound:
                        lo = mid + 1
                    else:
                        hi = mid
                cut = lo
            if cut == 0:
                quota *= 2  # one user key spans every buffered row
                continue
            # Emission cap: bounds the Python materialization during the
            # post-seek ramp; at steady state (quota maxed) refills emit
            # the whole cut so nothing is ever re-merged.
            cap = quota * len(parts) if quota < self._quota_max else None
            consume_uk = self._resolve(
                cat_kb, cat_ko, cat_kl, order, new_key, packed,
                cut, parts, src_of, loc_of, keys, vals, types, cap=cap)
            if not self._done:
                for s in parts:
                    if consume_uk is not None:
                        # Emission was capped: keep the unprocessed tail
                        # of the cut buffered for the next refill.
                        s.pending.drop_upto(consume_uk)
                    elif bound is None:
                        s.pending.drop_all()
                    else:
                        s.pending.drop_below(bound)
            if self._stats is not None:
                from toplingdb_tpu.utils import statistics as st

                self._stats.record_tick(st.ITER_CHUNK_REFILLS)
            self._bank_prefetch()
        if keys:
            self.is_valid = True
            self.cur_key = keys[0]
            self.cur_value = vals[0]
            self.cur_type = types[0]

    def _concat(self, parts):
        kbs, kos, kls, counts, locs = [], [], [], [], []
        base = 0
        for s in parts:
            p = s.pending
            st_, n = p.start, p.n
            k0 = int(p.ko[st_])
            k1 = int(p.ko[n - 1]) + int(p.kl[n - 1])
            kbs.append(p.kb[k0:k1])
            kos.append(p.ko[st_:n] - k0 + base)
            kls.append(p.kl[st_:n])
            locs.append(np.arange(st_, n, dtype=np.int64))
            counts.append(n - st_)
            base += k1 - k0
        cat_kb = kbs[0] if len(kbs) == 1 else np.concatenate(kbs)
        cat_ko = kos[0] if len(kos) == 1 else np.concatenate(kos)
        cat_kl = kls[0] if len(kls) == 1 else np.concatenate(kls)
        rs = np.zeros(len(counts) + 1, dtype=np.int64)
        np.cumsum(counts, out=rs[1:])
        src_of = np.repeat(np.arange(len(parts), dtype=np.int32), counts)
        loc_of = locs[0] if len(locs) == 1 else np.concatenate(locs)
        return cat_kb, cat_ko, cat_kl, rs, src_of, loc_of

    def _resolve(self, cat_kb, cat_ko, cat_kl, order, new_key, packed,
                 cut, parts, src_of, loc_of, keys, vals, types, cap=None):
        """Newest-visible-per-user-key selection over merged positions
        [0, cut), tombstone masking, emission. Everything but blob
        resolution and range-tombstone probes is vectorized.

        Returns the consume boundary: None = the whole cut was
        processed; a user key = emission was capped at it (consume
        through that key, keep the rest buffered)."""
        import bisect

        ordc = order[:cut]
        pk = packed[ordc]
        seqs = pk >> np.uint64(8)
        vts = (pk & np.uint64(0xFF)).astype(np.int32)
        vis = seqs <= np.uint64(self._seq)
        pos = np.nonzero(vis)[0]
        if not len(pos):
            return None
        gid = np.cumsum(new_key[:cut], dtype=np.int64)
        _, first = np.unique(gid[pos], return_index=True)
        win = pos[first]
        consume_uk = None
        if cap is not None and len(win) > cap:
            win = win[:cap]
            last = int(ordc[int(win[-1])])
            o = int(cat_ko[last])
            consume_uk = cat_kb[o: o + int(cat_kl[last]) - 8].tobytes()
        vtw = vts[win]
        if np.any(vtw == int(ValueType.MERGE)):
            # Merge chains need operand folding (or the per-entry path's
            # MergeInProgress when no operator is configured).
            raise PlaneIneligible("merge operands in chunk")
        live = (vtw != int(ValueType.DELETION)) \
            & (vtw != int(ValueType.SINGLE_DELETION))
        if not live.any():
            return consume_uk
        win = win[live]
        vtw = vtw[live]
        if not np.all(np.isin(vtw, _EMIT_TYPES)):
            raise PlaneIneligible("unexpected value type in chunk")
        wrows = ordc[win]
        uo = cat_kl[wrows] - 8  # reuse as length first
        uks_o = cat_ko[wrows]
        kbytes = cat_kb.tobytes()
        uks = [kbytes[o:e] for o, e in
               zip(uks_o.tolist(), (uks_o + uo).tolist())]
        if self._upper is not None:
            c = bisect.bisect_left(uks, self._upper)  # winners are sorted
            if c < len(uks):
                self._done = True
                uks = uks[:c]
                win, vtw, wrows = win[:c], vtw[:c], wrows[:c]
            if not uks:
                return consume_uk
        if self._rd is not None:
            seq_l = seqs[win].tolist()
            keep = [j for j, uk in enumerate(uks)
                    if self._rd.max_covering_seq(uk, self._seq) <= seq_l[j]]
            if len(keep) != len(uks):
                if not keep:
                    return consume_uk
                ki = np.asarray(keep)
                uks = [uks[j] for j in keep]
                vtw, wrows = vtw[ki], wrows[ki]
        k = len(wrows)
        wsrc = src_of[wrows]
        wloc = loc_of[wrows]
        wvo = np.empty(k, dtype=np.int64)
        wve = np.empty(k, dtype=np.int64)
        for i, s in enumerate(parts):
            m = wsrc == i
            if m.any():
                lo = wloc[m]
                o = s.pending.vo[lo]
                wvo[m] = o
                wve[m] = o + s.pending.vl[lo]
        vbufs = [s.pending.vb_bytes() for s in parts]
        ws_l = wsrc.tolist()
        wvo_l = wvo.tolist()
        wve_l = wve.tolist()
        if np.all(vtw == int(ValueType.VALUE)):
            if self._prot_bank is None:
                keys.extend(uks)
                vals.extend(vbufs[s][o:e]
                            for s, o, e in zip(ws_l, wvo_l, wve_l))
                types.extend([int(ValueType.VALUE)] * k)
                return consume_uk
            emit_vals = [vbufs[s][o:e]
                         for s, o, e in zip(ws_l, wvo_l, wve_l)]
            for j in range(k):
                self._verify_emission(uks[j], emit_vals[j])
            keys.extend(uks)
            vals.extend(emit_vals)
            types.extend([int(ValueType.VALUE)] * k)
            return consume_uk
        vt_l = vtw.tolist()
        for j in range(k):
            v = vbufs[ws_l[j]][wvo_l[j]: wve_l[j]]
            t = vt_l[j]
            if self._prot_bank is not None:
                # Verify the raw bytes BEFORE blob resolution rewrites them.
                self._verify_emission(uks[j], v)
            if t == int(ValueType.BLOB_INDEX):
                v = self._blob(v)
                t = int(ValueType.VALUE)
            keys.append(uks[j])
            vals.append(v)
            types.append(t)
        return consume_uk

    def _verify_emission(self, uk: bytes, value: bytes) -> None:
        """Scan-plane chunk-emission protection check: the served bytes
        must re-hash to a checksum banked when the row was decoded."""
        from toplingdb_tpu.utils import protection as _p
        from toplingdb_tpu.utils.status import Corruption

        cs = _p.truncate(_p.kv_checksum(uk, value), self._pb)
        if cs not in self._prot_bank:
            if self._stats is not None:
                from toplingdb_tpu.utils import statistics as st

                self._stats.record_tick(st.INTEGRITY_PROTECTION_MISMATCHES)
            raise Corruption(
                f"scan-plane protection mismatch emitting key {uk!r}: "
                f"served bytes match no decoded source row"
            )


def make_scan_plane(mems, l0_files, level_runs, table_cache, icmp,
                    snap_seq, rd, lower, upper, blob_resolver,
                    merge_operator, prefix_mode, excluded, read_ts,
                    stats, readahead_size: int = 0,
                    protection_bytes: int = 0, aio_rings=None):
    """Build a ScanPlane for DB.new_iterator, or None when the iterator
    shape is ineligible at construction time (per-file eligibility is
    checked lazily and bails mid-stream instead)."""
    chunk = chunk_rows()
    if chunk == 0:
        return None
    if merge_operator is not None or prefix_mode or excluded \
            or read_ts is not None:
        return None
    if icmp.user_comparator.name() != "tpulsm.BytewiseComparator":
        return None
    lib = native.lib()
    if lib is None or not hasattr(lib, "tpulsm_scan_blocks") \
            or not hasattr(lib, "tpulsm_sort_entries"):
        return None
    # L0 readers are already open (new_iterator built children from
    # them): reject known-bad formats now instead of bailing later.
    for f in l0_files:
        r = table_cache.get_reader(f.number)
        if hasattr(r, "scan_columnar"):
            if not r.scan_native_ready():
                if stats is not None:
                    stats.record_tick(_stats_mod.ZIP_PLANE_FALLBACKS)
                return None
        elif not hasattr(r, "new_index_iterator") or \
                getattr(r, "_compression_dict", b""):
            return None
    upper_t = None
    if upper is not None:
        upper_t = dbformat.make_internal_key(
            upper, dbformat.MAX_SEQUENCE_NUMBER, dbformat.VALUE_TYPE_FOR_SEEK)
    bank = set() if protection_bytes else None
    sources: list = [_MemSource(m, prot_bank=bank,
                                protection_bytes=protection_bytes)
                     for m in mems]
    # Async read plane: each SST source pins one reader ring so its
    # doubling readahead windows stay ordered per source while distinct
    # sources overlap their I/O (aio_rings is an AsyncReadBatcher).
    def _ring(seq):
        return aio_rings.ring_for(seq) if aio_rings is not None else None

    for i, f in enumerate(l0_files):
        sources.append(_SSTSource([f], table_cache, icmp, upper_t,
                                  readahead_size, prot_bank=bank,
                                  protection_bytes=protection_bytes,
                                  stats=stats, aio_ring=_ring(i)))
    for i, files in enumerate(level_runs):
        sources.append(_SSTSource(list(files), table_cache, icmp, upper_t,
                                  readahead_size, prot_bank=bank,
                                  protection_bytes=protection_bytes,
                                  stats=stats,
                                  aio_ring=_ring(len(l0_files) + i)))
    if not sources:
        return None
    return ScanPlane(sources, icmp, snap_seq, rd, upper, lower,
                     blob_resolver, stats, chunk, prot_bank=bank,
                     protection_bytes=protection_bytes)
