"""Columnar fixed-shape representation of KV entries for device kernels.

The variable-length problem (SURVEY.md §7 risk 2): keys become [N, W] int32
big-endian words (zero-padded, with an explicit length word as tie-break);
values stay host-side as a Python list indexed by the `idx` column — the
device decides ordering/survival, the host moves bytes.

Word transform: big-endian packing makes lexicographic byte order equal
numeric word order; XOR 0x80000000 maps unsigned order onto int32 order so
`jax.lax.sort` (signed) sorts correctly.
"""

from __future__ import annotations

import numpy as np

from toplingdb_tpu.db import dbformat

_SIGN = np.uint32(0x80000000)


def keys_to_words(user_keys: list[bytes], max_key_bytes: int) -> np.ndarray:
    """[N, W] int32, W = ceil(max_key_bytes/4), big-endian packed, sign-mapped."""
    n = len(user_keys)
    w = (max_key_bytes + 3) // 4
    buf = np.zeros((n, w * 4), dtype=np.uint8)
    for i, k in enumerate(user_keys):
        buf[i, : len(k)] = np.frombuffer(k, dtype=np.uint8)
    words = buf.reshape(n, w, 4).astype(np.uint32)
    packed = (
        (words[:, :, 0] << 24) | (words[:, :, 1] << 16)
        | (words[:, :, 2] << 8) | words[:, :, 3]
    )
    return (packed ^ _SIGN).astype(np.int32)


class ColumnarEntries:
    """Host-side columnar view of N internal-key entries."""

    __slots__ = (
        "key_words", "key_len", "inv_hi", "inv_lo", "vtype", "values",
        "user_keys", "max_key_bytes", "n",
    )

    def __init__(self, key_words, key_len, inv_hi, inv_lo, vtype, values,
                 user_keys, max_key_bytes):
        self.key_words = key_words
        self.key_len = key_len
        self.inv_hi = inv_hi
        self.inv_lo = inv_lo
        self.vtype = vtype
        self.values = values
        self.user_keys = user_keys
        self.max_key_bytes = max_key_bytes
        self.n = len(values)

    @staticmethod
    def from_entries(entries: list[tuple[bytes, bytes]],
                     max_key_bytes: int | None = None) -> "ColumnarEntries":
        """entries: [(internal_key, value)] in any order."""
        user_keys: list[bytes] = []
        values: list[bytes] = []
        n = len(entries)
        key_len = np.zeros(n, dtype=np.int32)
        inv_hi = np.zeros(n, dtype=np.int32)
        inv_lo = np.zeros(n, dtype=np.int32)
        vtype = np.zeros(n, dtype=np.int32)
        maxlen = 0
        inv_max = (1 << 64) - 1
        for i, (ikey, val) in enumerate(entries):
            uk, seq, t = dbformat.split_internal_key(ikey)
            user_keys.append(uk)
            values.append(val)
            maxlen = max(maxlen, len(uk))
            key_len[i] = len(uk)
            inv = inv_max - dbformat.pack_seq_type(seq, t)
            # Two sign-mapped big-endian-ordered words: hi first.
            inv_hi[i] = np.int32(np.uint32(inv >> 32) ^ _SIGN)
            inv_lo[i] = np.int32(np.uint32(inv & 0xFFFFFFFF) ^ _SIGN)
            vtype[i] = t
        if max_key_bytes is None:
            max_key_bytes = max(4, maxlen)
        if maxlen > max_key_bytes:
            raise ValueError(
                f"key length {maxlen} exceeds device key budget {max_key_bytes}"
            )
        key_words = keys_to_words(user_keys, max_key_bytes)
        return ColumnarEntries(
            key_words, key_len, inv_hi, inv_lo, vtype, values, user_keys,
            max_key_bytes,
        )

    def seq_type_of(self, i: int) -> tuple[int, int]:
        inv_max = (1 << 64) - 1
        hi = np.uint32(np.int32(self.inv_hi[i])) ^ _SIGN
        lo = np.uint32(np.int32(self.inv_lo[i])) ^ _SIGN
        packed = inv_max - ((int(hi) << 32) | int(lo))
        return dbformat.unpack_seq_type(packed)


def seq_words(snapshot_seqs: list[int]) -> tuple[np.ndarray, np.ndarray]:
    """Snapshot seqnos as (hi, lo) uint32 pairs (plain, not sign-mapped) for
    device searchsorted over 64-bit values split into words."""
    hi = np.array([s >> 32 for s in snapshot_seqs], dtype=np.uint32)
    lo = np.array([s & 0xFFFFFFFF for s in snapshot_seqs], dtype=np.uint32)
    return hi, lo
