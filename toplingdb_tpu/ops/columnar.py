"""Columnar fixed-shape representation of KV entries for device kernels.

The variable-length problem (SURVEY.md §7 risk 2): keys become [N, W] int32
big-endian words (zero-padded, with an explicit length word as tie-break);
values stay host-side as a Python list indexed by the `idx` column — the
device decides ordering/survival, the host moves bytes.

Word transform: big-endian packing makes lexicographic byte order equal
numeric word order; XOR 0x80000000 maps unsigned order onto int32 order so
`jax.lax.sort` (signed) sorts correctly.

Encoding is fully vectorized (numpy): one `np.array(keys, 'S...')` pad, one
take_along_axis for the 8-byte trailers — no per-entry Python loop.
"""

from __future__ import annotations

import sys

import numpy as np

from toplingdb_tpu.db import dbformat

_SIGN = np.uint32(0x80000000)
_INV_MAX = np.uint64(0xFFFFFFFFFFFFFFFF)


class ColumnarEntries:
    """Host-side columnar view of N internal-key entries."""

    __slots__ = (
        "key_words", "key_len", "inv_hi", "inv_lo", "vtype", "values",
        "ikeys", "seq", "max_key_bytes", "n",
    )

    def __init__(self, key_words, key_len, inv_hi, inv_lo, vtype, values,
                 ikeys, seq, max_key_bytes):
        self.key_words = key_words
        self.key_len = key_len      # user-key lengths [N] int32
        self.inv_hi = inv_hi
        self.inv_lo = inv_lo
        self.vtype = vtype          # [N] int32
        self.values = values        # list[bytes]
        self.ikeys = ikeys          # list[bytes] original internal keys
        self.seq = seq              # [N] uint64 seqnos
        self.max_key_bytes = max_key_bytes
        self.n = len(values)

    @staticmethod
    def from_entries(entries: list[tuple[bytes, bytes]],
                     max_key_bytes: int | None = None) -> "ColumnarEntries":
        """entries: [(internal_key, value)] in any order."""
        n = len(entries)
        ikeys = [k for k, _ in entries]
        values = [v for _, v in entries]
        lens = np.fromiter((len(k) for k in ikeys), dtype=np.int64, count=n)
        if n and lens.min() < 8:
            from toplingdb_tpu.utils.status import Corruption

            raise Corruption("internal key shorter than 8 bytes")
        max_ik = int(lens.max()) if n else 8
        # Zero-padded byte matrix of all internal keys (C-level pad).
        arr = (
            np.array(ikeys, dtype=f"S{max_ik}")
            .view(np.uint8)
            .reshape(n, max_ik)
            if n else np.zeros((0, max_ik), dtype=np.uint8)
        )
        # Little-endian fixed64 trailer per row.
        tr_idx = (lens[:, None] - 8) + np.arange(8)[None, :]
        trailer = np.take_along_axis(arr, tr_idx, axis=1)
        packed = np.ascontiguousarray(trailer).view(np.uint64).reshape(n)
        if sys.byteorder == "big":  # the trailer bytes on disk are LE
            packed = packed.byteswap()
        seq = packed >> np.uint64(8)
        vtype = (packed & np.uint64(0xFF)).astype(np.int32)
        inv = _INV_MAX - packed
        inv_hi = ((inv >> np.uint64(32)).astype(np.uint32) ^ _SIGN).view(np.int32)
        inv_lo = ((inv & np.uint64(0xFFFFFFFF)).astype(np.uint32) ^ _SIGN).view(np.int32)

        uk_len = (lens - 8).astype(np.int32)
        maxlen = int(uk_len.max()) if n else 0
        if max_key_bytes is None:
            max_key_bytes = max(4, maxlen)
        if maxlen > max_key_bytes:
            raise ValueError(
                f"key length {maxlen} exceeds device key budget {max_key_bytes}"
            )
        w = (max_key_bytes + 3) // 4
        kb = np.zeros((n, w * 4), dtype=np.uint8)
        span = min(max_ik, w * 4)
        kb[:, :span] = arr[:, :span]
        # Zero out trailer bytes that bled into the key region.
        col = np.arange(w * 4, dtype=np.int64)[None, :]
        kb *= col < uk_len[:, None]
        words = np.ascontiguousarray(kb).reshape(n, w, 4).astype(np.uint32)
        packed_words = (
            (words[:, :, 0] << 24) | (words[:, :, 1] << 16)
            | (words[:, :, 2] << 8) | words[:, :, 3]
        )
        key_words = (packed_words ^ _SIGN).view(np.int32)
        return ColumnarEntries(
            key_words, uk_len, inv_hi, inv_lo, vtype, values, ikeys, seq,
            max_key_bytes,
        )

    def user_key(self, i: int) -> bytes:
        return self.ikeys[i][:-8]

    def seq_type_of(self, i: int) -> tuple[int, int]:
        return int(self.seq[i]), int(self.vtype[i])
