"""Disaggregated SST storage (ROADMAP item 5, PAPER.md item 2).

A content-addressed object store for SSTs keyed by the integrity plane's
whole-file checksums, an Env wrapper (`SharedSstEnv`) that lets DB
directories hold SSTs by reference, reference-mode checkpoints/bootstrap
(utilities/checkpoint.py), zero-SST-byte dcompact jobs
(compaction/executor.py + worker.py), and a leased mark-sweep GC.

Opt-in via `Options.shared_store` / `TPULSM_SHARED_STORE`: a filesystem
path selects the LocalObjectStore backend, an http:// URL the
StoreServer/StoreClient pair, and "0"/"" leaves the classic local-files
path (the byte-parity oracle) in charge.
"""

from toplingdb_tpu.storage.gc import (  # noqa: F401
    collect_live_addresses,
    mark_sweep,
)
from toplingdb_tpu.storage.object_store import (  # noqa: F401
    LocalObjectStore,
    address_of_meta,
    address_size,
    compute_address,
    object_address,
    parse_address,
    verify_payload,
)
from toplingdb_tpu.storage.shared_env import (  # noqa: F401
    REFS_NAME,
    SharedSstEnv,
    StoreCacheTier,
)
from toplingdb_tpu.storage.store_server import (  # noqa: F401
    StoreClient,
    StoreServer,
)


def store_spec_enabled(spec) -> bool:
    """Is a shared_store knob value actually ON? ("0"/""/None are off)."""
    return bool(spec) and spec != "0"


def open_store(spec, env=None):
    """Build a store backend from a knob value: an existing store object
    passes through, an http(s):// URL builds a StoreClient, anything else
    is a LocalObjectStore root path."""
    if not store_spec_enabled(spec):
        raise ValueError(f"shared store disabled by spec {spec!r}")
    if not isinstance(spec, str):
        return spec  # already a store-shaped object
    if spec.startswith(("http://", "https://")):
        return StoreClient(spec)
    return LocalObjectStore(spec, env=env)
