"""Mark-sweep GC for the shared SST object store.

An object is garbage only when NO live root can reach it:

  mark   every root directory (DB dirs, checkpoint dirs) contributes the
         addresses of its MANIFEST-recorded live SSTs (checksum + size
         straight from the VersionEdit stream — no DB open) plus every
         entry of its STORE_REFS.json table (references that may not be
         in a MANIFEST yet: a mid-restore bootstrap, an adopted dcompact
         output awaiting install);
  pins   the store's own pin table shields published-but-not-yet-installed
         objects (the publisher pins with a TTL before the manifest edit
         lands);
  grace  objects younger than `grace_sec` are kept regardless — a publish
         that happened after the mark phase scanned its root cannot be
         reaped by the same sweep;
  lease  when a LeaseCoordinator / LeaseClient is given, the sweep runs
         under the "store-gc" lease (PR 16 fencing) so two GC processes
         can't interleave their mark and sweep phases.

Sweeping is the ONLY deletion path for store objects; everything else
(publish, adopt, fetch) is monotone."""

from __future__ import annotations

import json

from toplingdb_tpu.storage.object_store import object_address
from toplingdb_tpu.storage.shared_env import REFS_NAME
from toplingdb_tpu.utils import statistics as stats_mod
from toplingdb_tpu.utils.status import Busy, NotFound

GC_LEASE_SHARD = "store-gc"


def manifest_live_addresses(dbdir: str, env) -> set[str]:
    """Addresses of every live, checksum-stamped SST recorded by the
    directory's CURRENT+MANIFEST (offline — mirrors
    file_checksum.manifest_file_checksums but keeps the file sizes the
    address needs)."""
    from toplingdb_tpu.db import filename
    from toplingdb_tpu.db.log import LogReader
    from toplingdb_tpu.db.version_edit import VersionEdit

    cur = env.read_file(filename.current_file_name(dbdir)).decode().strip()
    live: dict[int, str] = {}
    for rec in LogReader(
            env.new_sequential_file(f"{dbdir}/{cur}")).records():
        e = VersionEdit.decode(rec)
        for _lvl, num in e.deleted_files:
            live.pop(num, None)
        for _lvl, meta in e.new_files:
            if meta.file_checksum:
                live[meta.number] = object_address(
                    meta.file_checksum_func_name, meta.file_checksum,
                    meta.file_size)
    return set(live.values())


def refs_table_addresses(root: str, env) -> set[str]:
    """Addresses referenced by a directory's STORE_REFS.json (read through
    the BASE env — SharedSstEnv hides the table from get_children but not
    from read_file)."""
    base = getattr(env, "base", env)
    try:
        raw = base.read_file(f"{root}/{REFS_NAME}")
        return {str(v) for v in json.loads(raw.decode()).values()}
    except (OSError, NotFound, ValueError):
        return set()


def collect_live_addresses(roots, env=None) -> set[str]:
    """The mark phase: union of manifest-live and refs-table addresses
    over every root directory. Roots without a CURRENT (mid-bootstrap
    dirs) still contribute their refs table."""
    if env is None:
        from toplingdb_tpu.env import default_env

        env = default_env()
    live: set[str] = set()
    for root in roots:
        try:
            live |= manifest_live_addresses(root, env)
        except (OSError, NotFound):
            pass  # no CURRENT yet: refs below still count
        live |= refs_table_addresses(root, env)
    return live


def mark_sweep(store, roots, env=None, grace_sec: float = 0.0,
               lease=None, holder: str = "store-gc",
               lease_ttl: float = 60.0, statistics=None) -> dict:
    """One GC round. Returns a report dict; raises Busy when another
    process holds the store-gc lease (callers retry on their cadence).

    `store` is a LocalObjectStore or StoreClient; `roots` the directories
    whose manifests/refs define liveness; `lease` an optional
    LeaseCoordinator/LeaseClient serializing concurrent sweeps."""
    import time

    token = None
    if lease is not None:
        grant = lease.acquire(GC_LEASE_SHARD, holder, lease_ttl)
        token = grant.get("token") if isinstance(grant, dict) else None
    try:
        live = collect_live_addresses(roots, env)
        pinned = set(store.pinned())
        now = time.time()
        swept, kept_young = [], 0
        for addr in store.list_addresses():
            if addr in live or addr in pinned:
                continue
            if grace_sec > 0:
                mtime = store.object_mtime(addr)
                # No mtime = the backend can't prove age: keep (the next
                # sweep with the object in no manifest will see it again).
                if mtime is None or now - mtime < grace_sec:
                    kept_young += 1
                    continue
            if store.delete(addr):
                swept.append(addr)
        if statistics is not None and swept:
            statistics.record_tick(stats_mod.STORE_GC_SWEPT, len(swept))
        return {"live": len(live), "pinned": len(pinned),
                "swept": swept, "kept_young": kept_young}
    finally:
        if lease is not None and token is not None:
            try:
                lease.release(GC_LEASE_SHARD, holder, token)
            except Busy:
                pass  # the lease lapsed mid-sweep: nothing to release
