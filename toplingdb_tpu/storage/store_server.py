"""HTTP backend for the shared SST object store.

`StoreServer` fronts a LocalObjectStore (GET/POST /store/*, raw payload
bodies — the dcompact/fleet transport shape: JSON control, bytes data).
`StoreClient` speaks the same interface as LocalObjectStore so
SharedSstEnv, the dcompact worker, and the GC take either interchangeably.

The client reuses the dcompact resilience stack (compaction/resilience.py):
per-request timeouts, bounded retry with exponential backoff + jitter
(DcompactOptions), and a CircuitBreaker so a dead store fails fast instead
of stacking timeouts under every table open. Every store operation is
idempotent under content addressing — a replayed put stores the same bytes
under the same name — so unlike the lease client every verb is
retry-safe."""

from __future__ import annotations

import http.client
import json
import urllib.error
import urllib.parse
import urllib.request
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from toplingdb_tpu.compaction.resilience import CircuitBreaker, DcompactOptions
from toplingdb_tpu.storage.object_store import LocalObjectStore
from toplingdb_tpu.utils import concurrency as ccy
from toplingdb_tpu.utils.status import (
    Corruption,
    InvalidArgument,
    IOError_,
    NotFound,
)


class StoreServer:
    """One process's store front door. Raw object bodies ride the HTTP
    payload; control verbs answer JSON. 404 means "object not present"
    (an answer, never retried by the client); 422 means the payload
    failed address verification (the uploader's bytes are wrong)."""

    def __init__(self, store: LocalObjectStore):
        self.store = store
        self._server: ThreadingHTTPServer | None = None

    def start(self, port: int = 0, host: str = "127.0.0.1") -> int:
        store = self.store

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *a):
                pass

            def _reply_json(self, code: int, body: dict):
                data = json.dumps(body).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(data)))
                self.end_headers()
                self.wfile.write(data)

            def _reply_raw(self, payload: bytes):
                self.send_response(200)
                self.send_header("Content-Type",
                                 "application/octet-stream")
                self.send_header("Content-Length", str(len(payload)))
                self.end_headers()
                self.wfile.write(payload)

            def do_GET(self):
                path = urllib.parse.unquote(self.path)
                try:
                    if path.startswith("/store/obj/"):
                        addr = path[len("/store/obj/"):]
                        self._reply_raw(store.fetch(addr))
                    elif path.startswith("/store/has/"):
                        addr = path[len("/store/has/"):]
                        self._reply_json(200, {
                            "present": store.contains(addr),
                            "mtime": store.object_mtime(addr),
                        })
                    elif path == "/store/list":
                        self._reply_json(
                            200, {"addresses": store.list_addresses()})
                    elif path == "/store/pins":
                        self._reply_json(
                            200, {"pinned": sorted(store.pinned())})
                    elif path == "/store/status":
                        self._reply_json(200, store.status())
                    elif path == "/health":
                        self._reply_json(200, {"ok": True, "role": "store"})
                    else:
                        self._reply_json(404, {"error": "not found"})
                except NotFound as e:
                    self._reply_json(404, {"error": str(e)})
                except Exception as e:  # transport must answer, not die
                    self._reply_json(500, {"error": repr(e)[:300]})

            def do_POST(self):
                path = urllib.parse.unquote(self.path)
                n = int(self.headers.get("Content-Length", 0))
                body = self.rfile.read(n) if n else b""
                try:
                    if path.startswith("/store/obj/"):
                        addr = path[len("/store/obj/"):]
                        self._reply_json(
                            200, {"stored": store.put(addr, body)})
                        return
                    req = json.loads(body or b"{}")
                    if path == "/store/pin":
                        store.pin(req["addr"], req.get("holder", "?"),
                                  req.get("ttl"))
                        self._reply_json(200, {"ok": True})
                    elif path == "/store/unpin":
                        store.unpin(req["addr"], req.get("holder"))
                        self._reply_json(200, {"ok": True})
                    elif path == "/store/delete":
                        self._reply_json(
                            200, {"deleted": store.delete(req["addr"])})
                    else:
                        self._reply_json(404, {"error": "not found"})
                except (Corruption, InvalidArgument) as e:
                    self._reply_json(422, {"error": str(e)})
                except ValueError:
                    self._reply_json(400, {"error": "bad json"})
                except Exception as e:
                    self._reply_json(500, {"error": repr(e)[:300]})

        self._server = ThreadingHTTPServer((host, port), Handler)
        ccy.spawn("store-server", self._server.serve_forever,
                  owner=self, stop=self.stop)
        return self._server.server_address[1]

    @property
    def port(self) -> int:
        return self._server.server_address[1] if self._server else 0

    def stop(self) -> None:
        if self._server is not None:
            self._server.shutdown()
            self._server = None


class StoreClient:
    """LocalObjectStore-shaped client for a StoreServer URL. 404 maps to
    NotFound, 422 to Corruption (both answers, never retried); transport
    errors retry with DcompactOptions backoff behind a CircuitBreaker."""

    def __init__(self, url: str, timeout: float = 5.0,
                 options: DcompactOptions | None = None,
                 breaker: CircuitBreaker | None = None):
        self.url = url.rstrip("/")
        self.timeout = timeout
        self.options = options or DcompactOptions(
            max_attempts=3, backoff_base=0.05, attempt_timeout=timeout)
        self.breaker = breaker or CircuitBreaker(
            failure_threshold=self.options.breaker_failure_threshold,
            reset_timeout=self.options.breaker_reset_timeout)

    def _call(self, method: str, path: str, body: bytes | None = None,
              json_body: dict | None = None) -> tuple[int, bytes]:
        import time as _t

        if not self.breaker.allow():
            raise IOError_(f"store {self.url}: circuit breaker open")
        if json_body is not None:
            body = json.dumps(json_body).encode()
        last: Exception | None = None
        for attempt in range(1, self.options.max_attempts + 1):
            if attempt > 1:
                _t.sleep(self.options.backoff_delay(attempt - 1))
            try:
                req = urllib.request.Request(
                    self.url + urllib.parse.quote(path), data=body,
                    method=method)
                with urllib.request.urlopen(req,
                                            timeout=self.timeout) as r:
                    payload = r.read()
                self.breaker.on_success()
                return r.status, payload
            except urllib.error.HTTPError as e:
                # An HTTP status is an ANSWER from a live server: the
                # breaker records success and the caller maps the code.
                payload = e.read()
                self.breaker.on_success()
                if e.code == 404:
                    raise NotFound(self._err(payload)) from e
                if e.code == 422:
                    raise Corruption(self._err(payload)) from e
                raise IOError_(
                    f"store {path}: HTTP {e.code} "
                    f"{self._err(payload)}") from e
            except (OSError, http.client.HTTPException) as e:
                last = e
        self.breaker.on_failure()
        raise IOError_(
            f"store {self.url}{path} unreachable after "
            f"{self.options.max_attempts} attempts: {last}") from last

    @staticmethod
    def _err(payload: bytes) -> str:
        try:
            return json.loads(payload).get("error", "")
        except (ValueError, AttributeError):
            return payload[:200].decode(errors="replace")

    # -- the LocalObjectStore interface --------------------------------

    def contains(self, addr: str) -> bool:
        _, payload = self._call("GET", f"/store/has/{addr}")
        return bool(json.loads(payload)["present"])

    def object_mtime(self, addr: str) -> float | None:
        _, payload = self._call("GET", f"/store/has/{addr}")
        return json.loads(payload).get("mtime")

    def fetch(self, addr: str) -> bytes:
        _, payload = self._call("GET", f"/store/obj/{addr}")
        return payload

    def put(self, addr: str, payload: bytes) -> bool:
        _, resp = self._call("POST", f"/store/obj/{addr}", body=payload)
        return bool(json.loads(resp)["stored"])

    def publish_file(self, src_path: str, addr: str, src_env=None) -> bool:
        if src_env is None:
            from toplingdb_tpu.env import default_env

            src_env = default_env()
        if self.contains(addr):
            return False
        return self.put(addr, src_env.read_file(src_path))

    def delete(self, addr: str) -> bool:
        _, payload = self._call("POST", "/store/delete",
                                json_body={"addr": addr})
        return bool(json.loads(payload)["deleted"])

    def list_addresses(self) -> list[str]:
        _, payload = self._call("GET", "/store/list")
        return list(json.loads(payload)["addresses"])

    def pin(self, addr: str, holder: str, ttl: float | None = None) -> None:
        self._call("POST", "/store/pin",
                   json_body={"addr": addr, "holder": holder, "ttl": ttl})

    def unpin(self, addr: str, holder: str | None = None) -> None:
        self._call("POST", "/store/unpin",
                   json_body={"addr": addr, "holder": holder})

    def pinned(self) -> set[str]:
        _, payload = self._call("GET", "/store/pins")
        return set(json.loads(payload)["pinned"])

    def status(self) -> dict:
        _, payload = self._call("GET", "/store/status")
        doc = json.loads(payload)
        doc["backend"] = "http"
        doc["url"] = self.url
        return doc
