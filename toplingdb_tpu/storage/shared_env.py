"""SharedSstEnv: the disaggregated-storage Env seam.

Wraps a base Env (the same seam overlay.py / encrypted.py /
fault_injection.py interpose on) so a DB directory can hold SSTs *by
reference*: a hidden per-directory table `STORE_REFS.json` maps SST file
names to content addresses in a shared object store
(storage/object_store.py). Locally-written files behave exactly as before;
a referenced-but-absent file materializes on first open through the local
cache tier, after which every read is a plain local read.

The cache tier (`StoreCacheTier`) fronts the store with
utils/persistent_cache.py (a CRC-checked disk tier keyed by address, shared
across every directory this env serves) and an AsyncIORing for background
prefetch (`warm_refs` — the lazy cache warm after a reference-mode
checkpoint restore). Every cold fetch is verified against its own address
before it is installed anywhere — a corrupt or truncated store response is
retried from the store, never materialized.

The DB layer never sees the plumbing: `get_children` merges referenced
names (and hides the refs table), `delete_file` of a referenced name drops
the reference, `get_file_size` answers from the address (which encodes the
size) without a fetch. `DB._delete_obsolete_files`, checkpoint restore,
table-cache opens and the dcompact worker all work unchanged on top.
"""

from __future__ import annotations

import json
import time
import uuid

from toplingdb_tpu.env.env import Env
from toplingdb_tpu.storage.object_store import (
    address_of_meta,
    address_size,
    verify_payload,
)
from toplingdb_tpu.utils import concurrency as ccy
from toplingdb_tpu.utils import statistics as stats_mod
from toplingdb_tpu.utils import telemetry as _tm
from toplingdb_tpu.utils import errors as _errors
from toplingdb_tpu.utils.status import Corruption, IOError_, NotFound

REFS_NAME = "STORE_REFS.json"


class StoreCacheTier:
    """Verified fetch path: store -> (verify) -> persistent cache ->
    materialized file. `fetch` never returns unverified bytes; transport
    failures and corrupt payloads are retried with backoff (`attempts`),
    so a faulty store degrades to latency, not to corruption."""

    def __init__(self, store, cache_dir: str | None = None,
                 cache_bytes: int = 256 << 20, stats=None,
                 attempts: int = 6, backoff_base: float = 0.01):
        self.store = store
        self.stats = stats
        self.attempts = max(1, attempts)
        self.backoff_base = backoff_base
        self._pcache = None
        self._cache_dir = cache_dir
        self._cache_bytes = cache_bytes
        self._ring = None
        self._mu = ccy.Lock("shared_env.StoreCacheTier._mu")

    # -- lazily built internals ---------------------------------------

    def _cache(self):
        with self._mu:
            if self._pcache is None and self._cache_dir is not None:
                from toplingdb_tpu.utils.persistent_cache import (
                    PersistentCache,
                )

                self._pcache = PersistentCache(
                    self._cache_dir, capacity_bytes=self._cache_bytes)
            return self._pcache

    def _warm_ring(self):
        with self._mu:
            if self._ring is None:
                from toplingdb_tpu.env.env import AsyncIORing

                self._ring = AsyncIORing(name="store-warm")
            return self._ring

    def _tick(self, name: str, count: int = 1) -> None:
        if self.stats is not None:
            self.stats.record_tick(name, count)

    # -- the fetch path ------------------------------------------------

    def fetch(self, addr: str) -> bytes:
        """Verified payload for `addr`: persistent-cache hit, else a cold
        store fetch (verified, retried, recorded in the fetch-latency
        histogram). NotFound is an answer and is never retried."""
        pc = self._cache()
        key = addr.encode()
        if pc is not None:
            payload = pc.lookup(key)
            if payload is not None:
                self._tick(stats_mod.STORE_HITS)
                return payload
        t0 = time.monotonic()
        last: Exception | None = None
        with _tm.span("store.fetch", addr=addr):
            for attempt in range(1, self.attempts + 1):
                if attempt > 1:
                    self._tick(stats_mod.STORE_FETCH_RETRIES)
                    time.sleep(self.backoff_base * (2 ** (attempt - 2)))
                try:
                    payload = self.store.fetch(addr)
                    verify_payload(addr, payload)
                    break
                except NotFound:
                    raise
                except (Corruption, IOError_, OSError) as e:
                    last = e
            else:
                raise IOError_(
                    f"store object {addr} unfetchable after "
                    f"{self.attempts} attempts: {last}") from last
        self._tick(stats_mod.STORE_MISSES)
        self._tick(stats_mod.STORE_BYTES_FETCHED, len(payload))
        if self.stats is not None:
            self.stats.record_in_histogram(
                stats_mod.STORE_FETCH_MICROS,
                int((time.monotonic() - t0) * 1e6))
        if pc is not None:
            pc.insert(key, payload)
        return payload

    def warm(self, fetch_fns) -> int:
        """Fire-and-forget prefetch: each callable runs on the warm ring;
        failures are swallowed (warming is an optimization — the
        synchronous path re-fetches with its own retries)."""
        ring = self._warm_ring()
        n = 0
        for fn in fetch_fns:
            def task(fn=fn):
                try:
                    fn()
                except Exception as e:  # noqa: BLE001
                    _errors.swallow(reason="store-warm-prefetch", exc=e)
            try:
                ring.submit_task(task)
                n += 1
            except IOError_:
                break  # ring closed mid-shutdown: warming is best-effort
        return n

    def drain(self) -> None:
        with self._mu:
            ring = self._ring
        if ring is not None:
            ring.drain()

    def close(self) -> None:
        with self._mu:
            ring, self._ring = self._ring, None
            pc, self._pcache = self._pcache, None
        if ring is not None:
            ring.close()
        if pc is not None:
            pc.close()

    def cache_stats(self) -> dict:
        with self._mu:
            pc = self._pcache
        return pc.stats() if pc is not None else {}

    def prune(self) -> int:
        """Disk-pressure reclaim: drop the clean cached objects (they
        refetch from the store on demand). Returns bytes freed."""
        with self._mu:
            pc = self._pcache
        return pc.prune() if pc is not None else 0


class SharedSstEnv(Env):
    """Env wrapper that resolves referenced SSTs from a content-addressed
    store. Construction is cheap; the cache tier spins up lazily. The
    owner must close() it (DB.close does when DB.open built the wrapper
    from Options.shared_store / TPULSM_SHARED_STORE)."""

    def __init__(self, base: Env, store, cache_dir: str | None = None,
                 cache_bytes: int = 256 << 20, stats=None):
        self._base = base
        self.store = store
        self.tier = StoreCacheTier(store, cache_dir=cache_dir,
                                   cache_bytes=cache_bytes, stats=stats)
        self._mu = ccy.Lock("shared_env.SharedSstEnv._mu")
        self._refs: dict[str, dict[str, str]] = {}  # dir -> {name: addr}
        self._attached = 0  # DBs sharing this env (retain/release)

    @property
    def stats(self):
        return self.tier.stats

    @stats.setter
    def stats(self, value) -> None:
        self.tier.stats = value

    @property
    def base(self) -> Env:
        return self._base

    def get_free_space(self, path: str) -> int:
        return self._base.get_free_space(path)

    def close(self) -> None:
        self.tier.close()

    # -- shared ownership ------------------------------------------------
    # One SharedSstEnv serves many DBs over its lifetime (a migration's
    # destination reuses the source's env; checkpoint restores reopen on
    # it). Each DB.open on the env retains; each DB.close releases; the
    # last release closes the tier's cache/prefetch threads.

    def retain(self) -> "SharedSstEnv":
        with self._mu:
            self._attached += 1
        return self

    def release(self) -> None:
        with self._mu:
            self._attached -= 1
            last = self._attached <= 0
        if last:
            self.close()

    # -- reference table -----------------------------------------------

    @staticmethod
    def _split(path: str) -> tuple[str, str]:
        d, _, name = path.rpartition("/")
        return d, name

    def _load_refs(self, d: str) -> dict[str, str]:
        """In-memory refs for directory `d`, loaded from its refs table on
        first touch. Callers must hold no lock; the brief _mu section only
        guards the map."""
        with self._mu:
            cached = self._refs.get(d)
        if cached is not None:
            return cached
        table: dict[str, str] = {}
        try:
            raw = self._base.read_file(f"{d}/{REFS_NAME}")
            table = {str(k): str(v)
                     for k, v in json.loads(raw.decode()).items()}
        except (OSError, NotFound, ValueError):
            table = {}
        with self._mu:
            # First loader wins; a concurrent mutator already installed.
            return self._refs.setdefault(d, table)

    def _persist_refs(self, d: str) -> None:
        with self._mu:
            table = dict(self._refs.get(d) or {})
        final = f"{d}/{REFS_NAME}"
        if not table:
            try:
                self._base.delete_file(final)
            except (OSError, NotFound):
                pass
            return
        tmp = f"{final}.tmp-{uuid.uuid4().hex[:8]}"
        self._base.write_file(
            tmp, json.dumps(table, indent=1, sort_keys=True).encode(),
            sync=True)
        self._base.rename_file(tmp, final)

    def _ref_addr(self, path: str) -> str | None:
        d, name = self._split(path)
        return self._load_refs(d).get(name)

    def refs_of(self, d: str) -> dict[str, str]:
        """Copy of the directory's name -> address table."""
        return dict(self._load_refs(d))

    def adopt(self, path: str, addr: str) -> None:
        """Record that `path` is backed by store object `addr` (reference
        checkpoint restore, dcompact output adoption). Metadata-only: no
        bytes move until the file is first read."""
        d, name = self._split(path)
        self._load_refs(d)
        with self._mu:
            self._refs.setdefault(d, {})[name] = addr
        self._persist_refs(d)

    def drop_ref(self, path: str) -> bool:
        d, name = self._split(path)
        self._load_refs(d)
        with self._mu:
            dropped = (self._refs.get(d) or {}).pop(name, None) is not None
        if dropped:
            self._persist_refs(d)
        return dropped

    def invalidate_refs(self, d: str) -> None:
        """Forget the in-memory table (another process rewrote the refs
        file); the next touch reloads from disk."""
        with self._mu:
            self._refs.pop(d, None)

    # -- publish / adopt / warm ----------------------------------------

    def publish_sst(self, path: str, meta) -> str | None:
        """Publish an installed SST to the store under its checksum
        address (DB._stamp_file_checksums calls this at flush/compaction/
        import install). Returns the address, or None when the meta is
        unstamped or the file has no local bytes to publish."""
        addr = address_of_meta(meta)
        if addr is None or not self._base.file_exists(path):
            return None
        with _tm.span("store.publish", addr=addr):
            self.store.publish_file(path, addr, src_env=self._base)
        if self.stats is not None:
            self.stats.record_tick(stats_mod.STORE_PUBLISHES)
        return addr

    def warm_refs(self, d: str) -> int:
        """Background-prefetch every referenced object of directory `d`
        into local bytes (the lazy cache warm after a reference-mode
        bootstrap). Returns the number of prefetches queued."""
        pairs = [(f"{d}/{name}", addr)
                 for name, addr in self._load_refs(d).items()]
        return self.tier.warm(
            (lambda p=p, a=a: self._materialize(p, a)) for p, a in pairs)

    # -- materialization -----------------------------------------------

    def _materialize(self, path: str, addr: str) -> None:
        """Turn a reference into local bytes (idempotent; concurrent
        materializers race benignly through an atomic rename)."""
        if self._base.file_exists(path):
            return
        payload = self.tier.fetch(addr)
        tmp = f"{path}.materialize-{uuid.uuid4().hex[:8]}"
        self._base.write_file(tmp, payload, sync=True)
        self._base.rename_file(tmp, path)

    def _ensure_local(self, path: str) -> None:
        if self._base.file_exists(path):
            return
        addr = self._ref_addr(path)
        if addr is not None:
            self._materialize(path, addr)

    # -- Env surface ---------------------------------------------------

    def new_writable_file(self, path: str):
        self.drop_ref(path)  # an overwrite supersedes any old reference
        return self._base.new_writable_file(path)

    def new_random_access_file(self, path: str):
        self._ensure_local(path)
        return self._base.new_random_access_file(path)

    def new_sequential_file(self, path: str):
        self._ensure_local(path)
        return self._base.new_sequential_file(path)

    def file_exists(self, path: str) -> bool:
        return self._base.file_exists(path) \
            or self._ref_addr(path) is not None

    def get_file_size(self, path: str) -> int:
        if self._base.file_exists(path):
            return self._base.get_file_size(path)
        addr = self._ref_addr(path)
        if addr is not None:
            return address_size(addr)  # the address encodes the size
        return self._base.get_file_size(path)  # raise the base's error

    def delete_file(self, path: str) -> None:
        dropped = self.drop_ref(path)
        try:
            self._base.delete_file(path)
        except (OSError, NotFound):
            if not dropped:
                raise  # neither local bytes nor a reference existed

    def rename_file(self, src: str, dst: str) -> None:
        addr = self._ref_addr(src)
        if addr is not None:
            self.drop_ref(src)
            self.adopt(dst, addr)
        if self._base.file_exists(src):
            self._base.rename_file(src, dst)
        elif addr is None:
            self._base.rename_file(src, dst)  # raise the base's error

    def reuse_writable_file(self, old_path: str, new_path: str):
        self.drop_ref(old_path)
        self.drop_ref(new_path)
        return self._base.reuse_writable_file(old_path, new_path)

    def get_file_mtime(self, path: str) -> float | None:
        if self._base.file_exists(path):
            return self._base.get_file_mtime(path)
        return None  # a pure reference has no local mtime

    def create_dir(self, path: str) -> None:
        self._base.create_dir(path)

    def get_children(self, path: str) -> list[str]:
        try:
            names = [c for c in self._base.get_children(path)
                     if c != REFS_NAME and not c.startswith(REFS_NAME + ".")]
        except (OSError, NotFound):
            names = []
            if not self._load_refs(path):
                raise
        merged = set(names) | set(self._load_refs(path))
        return sorted(merged)

    def read_file(self, path: str) -> bytes:
        self._ensure_local(path)
        return self._base.read_file(path)

    def write_file(self, path: str, data: bytes, sync: bool = False) -> None:
        self.drop_ref(path)
        self._base.write_file(path, data, sync=sync)

    def now_micros(self) -> int:
        return self._base.now_micros()

    def status(self) -> dict:
        with self._mu:
            ref_dirs = {d: len(t) for d, t in self._refs.items() if t}
        doc = {"referenced": ref_dirs,
               "cache": self.tier.cache_stats()}
        if hasattr(self.store, "status"):
            try:
                doc["store"] = self.store.status()
            except Exception as e:  # noqa: BLE001
                _errors.swallow(reason="store-status-probe", exc=e)
                doc["store"] = {"error": repr(e)[:120]}
        return doc
