"""Content-addressed SST object store (the disaggregated-storage plane's
ground truth).

Topling's production dcompact reads and writes SSTs through shared storage
instead of shipping bytes per job (PAPER.md item 2). Our analogue keys every
object by the whole-file checksum the integrity plane (PR 5) already stamps
into FileMetaData and the MANIFEST:

    address = "<func>-<digest_hex>-<file_size>"   e.g. crc32c-9f01ab34-40960

The address IS the content checksum, so a fetched payload verifies against
its own name (`verify_payload`), dedup is free (same bytes -> same address),
and an adopted compaction output gets its MANIFEST checksum stamped without
re-reading a byte. `LocalObjectStore` is the directory backend (hardlink
publish when source and store share a posix filesystem); `StoreClient`
(storage/store_server.py) speaks the same interface over HTTP.

Deletion safety: objects are only removed by the mark-sweep GC
(storage/gc.py) against live manifests + the pin table kept here. Pins are
leases with a TTL — a publisher pins its outputs for the window between
publish and manifest install so a concurrent sweep can't reap an object
that is about to become live.
"""

from __future__ import annotations

import os
import uuid

from toplingdb_tpu.utils import concurrency as ccy
from toplingdb_tpu.utils.file_checksum import (
    DEFAULT_CHECKSUM_NAME,
    FileChecksumGenFactory,
    compute_file_checksum,
)
from toplingdb_tpu.utils.status import Corruption, InvalidArgument, NotFound

import json
import time


# ---------------------------------------------------------------------------
# Addresses
# ---------------------------------------------------------------------------


def object_address(func_name: str, digest: bytes, file_size: int) -> str:
    """The canonical store key for one SST's content."""
    if not digest:
        raise InvalidArgument("cannot address an object without a digest")
    return f"{func_name or DEFAULT_CHECKSUM_NAME}-{digest.hex()}-{file_size}"


def parse_address(addr: str) -> tuple[str, bytes, int]:
    """address -> (func_name, digest, file_size); raises InvalidArgument
    on anything that was not produced by object_address."""
    try:
        func, digest_hex, size = addr.rsplit("-", 2)
        return func, bytes.fromhex(digest_hex), int(size)
    except (ValueError, AttributeError) as e:
        raise InvalidArgument(f"bad object address {addr!r}") from e


def address_of_meta(meta) -> str | None:
    """Address for a FileMetaData, or None when the integrity plane never
    stamped it (file_checksum='off' / pre-upgrade files)."""
    if not getattr(meta, "file_checksum", None):
        return None
    return object_address(meta.file_checksum_func_name,
                          meta.file_checksum, meta.file_size)


def address_size(addr: str) -> int:
    return parse_address(addr)[2]


def verify_payload(addr: str, payload: bytes) -> None:
    """Self-verification: recompute the address's digest over the payload.
    Raises Corruption on any mismatch (wrong bytes, truncation, bitrot)."""
    func, digest, size = parse_address(addr)
    if len(payload) != size:
        raise Corruption(
            f"store object {addr}: payload is {len(payload)}B, "
            f"address says {size}B")
    gen = FileChecksumGenFactory(func).create()
    gen.update(payload)
    actual = gen.finalize()
    if actual != digest:
        raise Corruption(
            f"store object {addr}: digest mismatch "
            f"(recomputed {actual.hex()})")


def compute_address(env, path: str, func_name: str = DEFAULT_CHECKSUM_NAME,
                    ) -> str:
    """Address of an on-disk file (publish path for unstamped files)."""
    gen = FileChecksumGenFactory(func_name).create()
    digest = compute_file_checksum(env, path, gen)
    return object_address(func_name, digest, env.get_file_size(path))


# ---------------------------------------------------------------------------
# Local directory backend
# ---------------------------------------------------------------------------


class LocalObjectStore:
    """Directory-backed object store:

        <root>/objects/<digest_hex[:2]>/<addr>     immutable payloads
        <root>/pins/<addr>.pin                     JSON {holder, expires}

    Publishes are idempotent and safe under concurrent publishers: the
    payload lands under a unique temp name and is renamed into place, so
    two racers both succeed and the loser's rename atomically replaces
    identical bytes. Objects are immutable once present (content-addressed:
    a different payload would be a different address)."""

    DEFAULT_PIN_TTL = 300.0

    def __init__(self, root: str, env=None):
        if env is None:
            from toplingdb_tpu.env import default_env

            env = default_env()
        self.root = root
        self.env = env
        self._mu = ccy.Lock("object_store.LocalObjectStore._mu")
        env.create_dir(root)
        env.create_dir(f"{root}/objects")
        env.create_dir(f"{root}/pins")

    # -- layout --------------------------------------------------------

    def _obj_path(self, addr: str) -> str:
        _func, digest, _size = parse_address(addr)
        shard = digest.hex()[:2] or "00"
        return f"{self.root}/objects/{shard}/{addr}"

    def _pin_path(self, addr: str) -> str:
        return f"{self.root}/pins/{addr}.pin"

    # -- objects -------------------------------------------------------

    def contains(self, addr: str) -> bool:
        return self.env.file_exists(self._obj_path(addr))

    def fetch(self, addr: str) -> bytes:
        """Raw payload bytes (callers verify via verify_payload — the
        cache tier does, so a corrupt object can never be installed)."""
        path = self._obj_path(addr)
        if not self.env.file_exists(path):
            raise NotFound(f"store object {addr} not present")
        return self.env.read_file(path)

    def put(self, addr: str, payload: bytes) -> bool:
        """Store a payload under its address; returns False when the
        object was already present (dedup). The payload is verified
        BEFORE it becomes visible — a store never holds a lie."""
        if self.contains(addr):
            return False
        verify_payload(addr, payload)
        final = self._obj_path(addr)
        self._ensure_shard_dir(final)
        tmp = f"{final}.tmp-{uuid.uuid4().hex[:8]}"
        self.env.write_file(tmp, payload, sync=True)
        self.env.rename_file(tmp, final)
        return True

    def publish_file(self, src_path: str, addr: str, src_env=None) -> bool:
        """Publish a local file under `addr`; returns False on dedup.
        Hardlinks when the source and the store share a real posix
        filesystem (zero-copy publish); byte-copy otherwise."""
        if self.contains(addr):
            return False
        src_env = src_env or self.env
        final = self._obj_path(addr)
        self._ensure_shard_dir(final)
        from toplingdb_tpu.env.env import PosixEnv

        if type(self.env) is PosixEnv and type(src_env) is PosixEnv:
            tmp = f"{final}.tmp-{uuid.uuid4().hex[:8]}"
            try:
                os.link(src_path, tmp)
                os.replace(tmp, final)
                return True
            except OSError:
                pass  # cross-device / FS without links: fall through
        return self.put(addr, src_env.read_file(src_path))

    def delete(self, addr: str) -> bool:
        path = self._obj_path(addr)
        if not self.env.file_exists(path):
            return False
        self.env.delete_file(path)
        return True

    def object_mtime(self, addr: str) -> float | None:
        try:
            return self.env.get_file_mtime(self._obj_path(addr))
        except (OSError, NotFound):
            return None

    def list_addresses(self) -> list[str]:
        out = []
        try:
            shards = self.env.get_children(f"{self.root}/objects")
        except (OSError, NotFound):
            return out
        for shard in shards:
            try:
                names = self.env.get_children(
                    f"{self.root}/objects/{shard}")
            except (OSError, NotFound):
                continue  # a file where a shard dir should be: skip
            out.extend(n for n in names if ".tmp-" not in n)
        return sorted(out)

    def _ensure_shard_dir(self, obj_path: str) -> None:
        self.env.create_dir(obj_path.rsplit("/", 1)[0])

    # -- pins (sweep safety for not-yet-live objects) ------------------

    def pin(self, addr: str, holder: str, ttl: float | None = None) -> None:
        """Shield `addr` from the GC for `ttl` seconds (the publish ->
        manifest-install window). Re-pinning extends the lease."""
        ttl = self.DEFAULT_PIN_TTL if ttl is None else float(ttl)
        doc = {"holder": holder, "expires": time.time() + ttl}
        with self._mu:
            self.env.write_file(self._pin_path(addr),
                                json.dumps(doc).encode(), sync=True)

    def unpin(self, addr: str, holder: str | None = None) -> None:
        with self._mu:
            try:
                self.env.delete_file(self._pin_path(addr))
            except (OSError, NotFound):
                pass

    def pinned(self) -> set[str]:
        """Unexpired pinned addresses (expired pin files are reaped)."""
        now = time.time()
        out: set[str] = set()
        try:
            names = self.env.get_children(f"{self.root}/pins")
        except (OSError, NotFound):
            return out
        for name in names:
            if not name.endswith(".pin"):
                continue
            addr = name[:-4]
            path = self._pin_path(addr)
            try:
                doc = json.loads(self.env.read_file(path).decode())
                if float(doc.get("expires", 0)) >= now:
                    out.add(addr)
                    continue
            except (OSError, ValueError, NotFound):
                pass  # torn pin write: treat as expired
            with self._mu:
                try:
                    self.env.delete_file(path)
                except (OSError, NotFound):
                    pass
        return out

    # -- introspection -------------------------------------------------

    def status(self) -> dict:
        addrs = self.list_addresses()
        return {
            "backend": "local",
            "root": self.root,
            "objects": len(addrs),
            "bytes": sum(address_size(a) for a in addrs),
            "pinned": sorted(self.pinned()),
        }
