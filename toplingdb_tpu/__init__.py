"""toplingdb_tpu: a TPU-native LSM key-value storage framework.

A brand-new framework with the capabilities of ToplingDB (a RocksDB-fork LSM
engine, reference at /root/reference): WAL + memtable write path, versioned SST
levels with MANIFEST metadata, MVCC reads/iterators/snapshots, leveled/universal
compaction — with the compute-heavy compaction data plane (k-way merge, MVCC
garbage collection, merge-operand folding, SST block encoding) re-designed
TPU-first as JAX/XLA kernels over columnar key/value blocks, fanned out one
compaction job per TPU chip through a serializable distributed-compaction
boundary (the analogue of ToplingDB's dcompact, reference
db/compaction/compaction_executor.h:160-178).

Package layout:
  utils/      coding, crc32c, status, options, config registry, statistics
  db/         DB core: WAL, memtable, versions/MANIFEST, write path, iterators
  table/      SST formats: block-based builder/reader, table cache
  models/     pluggable format "model families" (table factories, memtable reps)
  compaction/ pickers, compaction iterator (MVCC GC), executor boundary
  ops/        JAX/Pallas kernels: sort-merge, visibility masking, encode
  parallel/   device-mesh fan-out (one job per chip; in-job range sharding)
  env/        filesystem/env abstraction (posix, in-memory)
  tools/      db_bench-style driver, sst_dump, ldb-style admin
  native/     C++ components (crc32c/xxhash, skiplist memtable) via ctypes
"""

__version__ = "0.1.0"

from toplingdb_tpu.utils.status import Status, NotFound, Corruption, InvalidArgument  # noqa: F401
