"""ShardRouter: the front door that turns one DB into a fleet.

Routes every read/write through the ShardMap to that key's shard, where a
per-shard serving stack — primary DB + follower set behind a
replication.router.ReplicaRouter — actually serves it. Composition rules:

  tokens     writes return a ShardToken(shard, epoch, seq). On a read the
             router re-resolves the key: if the shard NAME or EPOCH no
             longer matches (split/merge/migration happened), the token is
             rejected and the read re-routes to the CURRENT shard's
             primary — never silently served stale. When they match, the
             token degrades to a replication StalenessToken(seq, epoch)
             and the shard's ReplicaRouter enforces the same epoch rule
             against its follower set (epoch_provider = the live shard
             epoch from the map).
  fences     every shard has a write gate. Topology changes (migration
             cutover, cross-backend merge) fence the gate: new writers
             park (bounded by fence_timeout, then Busy), in-flight writers
             drain, and only then may the final WAL drain + cutover run —
             so no write can land on the old primary after the new one
             took over (the no-lost-write half of the chaos bar). Reads
             are never fenced.
  admission  per-tenant token buckets + stall shedding
             (sharding/admission.py), fed the target shard primary's LIVE
             write_stall_state() so a hot tenant sheds load instead of
             starving siblings.
"""

from __future__ import annotations

import dataclasses
import threading

from toplingdb_tpu.utils import concurrency as ccy
import time

from toplingdb_tpu.options import ReadOptions, WriteOptions
from toplingdb_tpu.replication.router import (
    ReplicaRouter,
    RouterOptions,
    StalenessToken,
)
from toplingdb_tpu.sharding.shard_map import Shard, ShardMap
from toplingdb_tpu.utils import statistics as stats_mod
from toplingdb_tpu.utils.status import Busy, InvalidArgument, NotFound
from toplingdb_tpu.utils import errors as _errors

_DEFAULT_READ = ReadOptions()
_DEFAULT_WRITE = WriteOptions()


@dataclasses.dataclass(frozen=True)
class ShardToken:
    """Read-your-writes token stamped with the shard identity + epoch the
    write was routed under. Either changing invalidates it (rejected and
    re-routed, never served stale)."""

    shard: str
    epoch: int
    seq: int


class _WriteGate:
    """Per-shard write fence: enter/exit bracket every routed write;
    fence() closes the gate AND drains in-flight writers, so after it
    returns no write can still be in the old primary's pipeline."""

    def __init__(self):
        self._cv = ccy.Condition("router._WriteGate._cv")
        self._open = True
        self._inflight = 0

    def enter(self, timeout: float):
        """True on entry, None on fence timeout; the truthy value is
        "waited" (the caller ticks SHARD_FENCE_WAITS on 2)."""
        from toplingdb_tpu.utils.sync_point import sync_point

        deadline = time.monotonic() + timeout
        waited = 1
        with self._cv:
            while not self._open:
                waited = 2
                # Interleaving seam: a writer is parked at a closed fence
                # (predecessor-only point — never blocks the gate).
                sync_point("ShardRouter::WriteGate:Parked")
                left = deadline - time.monotonic()
                if left <= 0:
                    return None
                self._cv.wait(left)
            self._inflight += 1
            return waited

    def exit(self) -> None:
        with self._cv:
            self._inflight -= 1
            if self._inflight <= 0:
                self._cv.notify_all()

    def fence(self, drain_timeout: float = 30.0) -> bool:
        with self._cv:
            self._open = False
            deadline = time.monotonic() + drain_timeout
            while self._inflight > 0:
                left = deadline - time.monotonic()
                if left <= 0:
                    return False
                self._cv.wait(left)
            return True

    def unfence(self) -> None:
        with self._cv:
            self._open = True
            self._cv.notify_all()

    @property
    def fenced(self) -> bool:
        return not self._open


class ShardServing:
    """One shard's serving stack: primary DB + follower set behind a
    ReplicaRouter whose epoch_provider is the LIVE shard epoch — so
    replication-level token checks stay correct across re-sharding without
    the replica router knowing the map exists."""

    def __init__(self, primary, followers=(), statistics=None,
                 router_options: RouterOptions | None = None,
                 epoch_provider=None):
        self.primary = primary
        self.followers = list(followers)
        self.replicas = ReplicaRouter(
            primary, self.followers, options=router_options,
            statistics=statistics, epoch_provider=epoch_provider)

    def stall_state(self) -> str:
        fn = getattr(self.primary, "write_stall_state", None)
        if fn is None:
            return "none"
        try:
            return fn()["state"]
        except Exception as e:
            _errors.swallow(reason="stall-state-probe", exc=e)
            return "none"

    def disk_pressure(self) -> str:
        fn = getattr(self.primary, "disk_pressure", None)
        if fn is None:
            return "ok"
        try:
            return fn()
        except Exception as e:
            _errors.swallow(reason="disk-pressure-probe", exc=e)
            return "ok"

    def health(self) -> dict:
        """This shard's health verdict (utils/slo.health_score rubric):
        stall state + the primary's SLO engine + open replica breakers.
        The balancer's hysteresis signal and /shards' at-a-glance row."""
        from toplingdb_tpu.utils import slo as _slo

        engine = getattr(self.primary, "slo_engine", None)
        slo_health, firing, last_alert = _slo.HEALTH_GREEN, [], None
        if engine is not None:
            s = engine.status()
            slo_health = s["health"]
            firing = sorted(n for n, r in s["specs"].items()
                            if r["firing"])
            alerts = engine.last_alerts()
            if alerts:
                # Most recent transition across the specs.
                last_alert = max(
                    alerts.values(),
                    key=lambda a: a.get("burn_rate_fast", 0)
                    if a.get("state") == "firing" else -1)
        breakers_open = 0
        try:
            regs = self.replicas.health._breakers
            breakers_open = sum(
                1 for b in regs.values() if b.state == "open")
        except Exception as e:
            _errors.swallow(reason="replica-breaker-probe", exc=e)
        return {
            "health": _slo.health_score(
                stall_state=self.stall_state(), slo_health=slo_health,
                breakers_open=breakers_open),
            "slo_firing": firing,
            "breakers_open": breakers_open,
            "last_slo_alert": last_alert,
        }


class ShardRouter:
    """Front-door router over a ShardMap. Serving stacks are attached per
    shard name; split shares the stack between the halves, migration swaps
    a fresh one in under the shard's fence."""

    def __init__(self, shard_map: ShardMap | None = None, statistics=None,
                 admission=None, fence_timeout: float = 5.0,
                 router_options: RouterOptions | None = None):
        self.map = shard_map or ShardMap()
        self.stats = statistics
        self.admission = admission
        self.fence_timeout = fence_timeout
        self.router_options = router_options
        self._mu = ccy.RLock("router.ShardRouter._mu")
        self._servings: dict[str, ShardServing] = {}
        self._gates: dict[str, _WriteGate] = {}
        self._traffic: dict[str, dict] = {}

    # -- wiring -----------------------------------------------------------

    def _shard_epoch(self, name: str) -> int:
        try:
            return self.map.get(name).epoch
        except NotFound:
            return -1  # shard merged/renamed away: no token matches again

    def _new_serving(self, name: str, primary, followers=()) -> ShardServing:
        return ShardServing(
            primary, followers, statistics=self.stats,
            router_options=self.router_options,
            epoch_provider=lambda n=name: self._shard_epoch(n))

    def attach_shard(self, name: str, primary, followers=()) -> None:
        """Bind a serving stack to a map shard (must exist in the map)."""
        self.map.get(name)  # raises NotFound for a name the map lacks
        with self._mu:
            self._servings[name] = self._new_serving(name, primary,
                                                     followers)
            self._gates.setdefault(name, _WriteGate())
            self._traffic.setdefault(name, {
                "reads": 0, "writes": 0, "read_keys": 0, "write_bytes": 0})

    def add_follower(self, name: str, follower) -> None:
        self._serving(name).replicas.add_follower(follower)

    def _serving(self, name: str) -> ShardServing:
        s = self._servings.get(name)
        if s is None:
            raise NotFound(f"no serving stack attached for shard {name!r}")
        return s

    def _gate(self, name: str) -> _WriteGate:
        # Lock-free on the hot path: a topology op holding _mu (e.g. a
        # cross-backend merge copy) must not block writers of OTHER shards.
        g = self._gates.get(name)
        if g is None:
            with self._mu:
                g = self._gates.setdefault(name, _WriteGate())
        return g

    def _tick(self, name: str, n: int = 1) -> None:
        if self.stats is not None:
            self.stats.record_tick(name, n)

    def _note_traffic(self, name: str, *, reads=0, writes=0, read_keys=0,
                      write_bytes=0) -> None:
        t = self._traffic.get(name)
        if t is None:
            with self._mu:
                t = self._traffic.setdefault(name, {
                    "reads": 0, "writes": 0, "read_keys": 0,
                    "write_bytes": 0})
        t["reads"] += reads
        t["writes"] += writes
        t["read_keys"] += read_keys
        t["write_bytes"] += write_bytes

    # -- write path -------------------------------------------------------

    def _enter_shard(self, key: bytes):
        """Resolve key → shard and enter its write gate, re-resolving when
        the topology changed while we were parked at a fence. Returns
        (shard, serving, gate) with the gate ENTERED."""
        for _ in range(16):
            shard = self.map.shard_for(key)
            gate = self._gate(shard.name)
            entered = gate.enter(self.fence_timeout)
            if entered is None:
                self._tick(stats_mod.SHARD_FENCE_WAITS)
                raise Busy(f"shard {shard.name!r} write-fenced "
                           f"(> {self.fence_timeout}s)")
            if entered == 2:
                self._tick(stats_mod.SHARD_FENCE_WAITS)
            cur = self.map.shard_for(key)
            serving = self._servings.get(cur.name)
            if cur.name == shard.name and cur.epoch == shard.epoch \
                    and serving is not None:
                return cur, serving, gate
            gate.exit()  # re-sharded while entering: route again
        raise Busy(f"shard routing for key {key!r} did not settle")

    def _admit(self, tenant, nbytes: int, serving: ShardServing) -> None:
        if self.admission is not None:
            self.admission.admit_write(
                tenant, nbytes, stall_state=serving.stall_state(),
                disk_pressure=serving.disk_pressure())

    def put(self, key: bytes, value: bytes,
            opts: WriteOptions = _DEFAULT_WRITE, tenant=None) -> ShardToken:
        shard, serving, gate = self._enter_shard(key)
        try:
            self._admit(tenant, len(key) + len(value), serving)
            seq = serving.replicas.put(key, value, opts)
        finally:
            gate.exit()
        self._tick(stats_mod.SHARD_ROUTED_WRITES)
        self._note_traffic(shard.name, writes=1,
                           write_bytes=len(key) + len(value))
        return ShardToken(shard=shard.name, epoch=shard.epoch, seq=seq)

    def delete(self, key: bytes, opts: WriteOptions = _DEFAULT_WRITE,
               tenant=None) -> ShardToken:
        shard, serving, gate = self._enter_shard(key)
        try:
            self._admit(tenant, len(key), serving)
            seq = serving.replicas.delete(key, opts)
        finally:
            gate.exit()
        self._tick(stats_mod.SHARD_ROUTED_WRITES)
        self._note_traffic(shard.name, writes=1, write_bytes=len(key))
        return ShardToken(shard=shard.name, epoch=shard.epoch, seq=seq)

    def merge(self, key: bytes, value: bytes,
              opts: WriteOptions = _DEFAULT_WRITE, tenant=None) -> ShardToken:
        shard, serving, gate = self._enter_shard(key)
        try:
            self._admit(tenant, len(key) + len(value), serving)
            seq = serving.replicas.merge(key, value, opts)
        finally:
            gate.exit()
        self._tick(stats_mod.SHARD_ROUTED_WRITES)
        self._note_traffic(shard.name, writes=1,
                           write_bytes=len(key) + len(value))
        return ShardToken(shard=shard.name, epoch=shard.epoch, seq=seq)

    def write(self, batch, opts: WriteOptions = _DEFAULT_WRITE, tenant=None,
              shard: str | None = None) -> list[ShardToken]:
        """Route a WriteBatch. With `shard` given (callers that pre-bucket
        their batches, e.g. bench fill loops) the whole batch goes to that
        shard with no per-record inspection. Otherwise records are grouped
        by shard — point records route by key, range deletions are clipped
        to each overlapping shard. Returns one token per touched shard."""
        from toplingdb_tpu.db.write_batch import WriteBatch
        from toplingdb_tpu.db.dbformat import ValueType

        if shard is not None:
            return [self._write_to_shard(shard, batch, opts, tenant)]
        groups: dict[str, WriteBatch] = {}
        for cf, t, k, v in batch.entries_cf():
            if t == ValueType.RANGE_DELETION:
                for sh in list(self.map.shards):
                    clipped = sh.clip(k, v)
                    if clipped is None:
                        continue
                    b, e = clipped
                    if b is None or e is None:
                        raise InvalidArgument(
                            "unbounded range deletion through the shard "
                            "router is not supported")
                    groups.setdefault(sh.name,
                                      WriteBatch()).delete_range(b, e, cf=cf)
                continue
            name = self.map.shard_for(k).name
            g = groups.setdefault(name, WriteBatch())
            if t == ValueType.VALUE:
                g.put(k, v, cf=cf)
            elif t == ValueType.MERGE:
                g.merge(k, v, cf=cf)
            elif t == ValueType.DELETION:
                g.delete(k, cf=cf)
            elif t == ValueType.SINGLE_DELETION:
                g.single_delete(k, cf=cf)
            elif t == ValueType.WIDE_COLUMN_ENTITY:
                g.put_entity(k, v, cf=cf)
            else:
                raise InvalidArgument(
                    f"record type {t} not routable through the shard router")
        return [self._write_to_shard(name, g, opts, tenant)
                for name, g in groups.items()]

    def _write_to_shard(self, name: str, batch, opts, tenant) -> ShardToken:
        # The gate is entered via a representative key resolve so a
        # concurrent re-shard still re-routes; the shard NAME the caller
        # targeted must still own the batch after entry.
        for _ in range(16):
            try:
                shard = self.map.get(name)
            except NotFound:
                raise InvalidArgument(f"shard {name!r} no longer exists")
            gate = self._gate(shard.name)
            entered = gate.enter(self.fence_timeout)
            if entered is None:
                self._tick(stats_mod.SHARD_FENCE_WAITS)
                raise Busy(f"shard {name!r} write-fenced")
            if entered == 2:
                self._tick(stats_mod.SHARD_FENCE_WAITS)
            cur = self.map.get(name)
            serving = self._servings.get(name)
            if cur.epoch == shard.epoch and serving is not None:
                try:
                    nbytes = batch.data_size()
                    self._admit(tenant, nbytes, serving)
                    seq = serving.replicas.write(batch, opts)
                finally:
                    gate.exit()
                self._tick(stats_mod.SHARD_ROUTED_WRITES)
                self._note_traffic(name, writes=batch.count(),
                                   write_bytes=nbytes)
                return ShardToken(shard=name, epoch=cur.epoch, seq=seq)
            gate.exit()
        raise Busy(f"shard {name!r} routing did not settle")

    # -- read path --------------------------------------------------------

    def _check_token(self, shard: Shard, token: ShardToken | None):
        """None → token-less read; StalenessToken → delegate to the shard's
        ReplicaRouter; the string "primary" → epoch/name mismatch, serve
        from the current primary (re-routed, never stale)."""
        if token is None:
            return None
        if token.shard != shard.name or token.epoch != shard.epoch:
            self._tick(stats_mod.SHARD_TOKEN_REJECTS)
            return "primary"
        return StalenessToken(seq=token.seq, epoch=token.epoch)

    def get(self, key: bytes, opts: ReadOptions = _DEFAULT_READ,
            token: ShardToken | None = None):
        shard = self.map.shard_for(key)
        serving = self._serving(shard.name)
        self._tick(stats_mod.SHARD_ROUTED_READS)
        self._note_traffic(shard.name, reads=1, read_keys=1)
        rt = self._check_token(shard, token)
        if rt == "primary":
            return serving.replicas.primary.get(key, opts)
        return serving.replicas.get(key, opts, token=rt)

    def multi_get(self, keys, opts: ReadOptions = _DEFAULT_READ,
                  token: ShardToken | None = None):
        """Group keys by shard, fan out one multi_get per shard, reassemble
        in input order. A single token applies to whichever shard it still
        matches (other shards read token-less)."""
        by_shard: dict[str, list[int]] = {}
        shards: dict[str, Shard] = {}
        for i, k in enumerate(keys):
            sh = self.map.shard_for(k)
            by_shard.setdefault(sh.name, []).append(i)
            shards[sh.name] = sh
        out = [None] * len(keys)
        # Fan the per-shard sub-batches out concurrently: each shard's
        # lookup becomes a future (DB.multi_get_async / the replica
        # router's async twin), so one request overlaps N shards' block
        # fetches instead of walking them shard-by-shard.  A single
        # shard keeps the plain sync call — no future overhead.
        pending: list[tuple[list[int], object]] = []
        for name, idxs in by_shard.items():
            sh = shards[name]
            serving = self._serving(name)
            sub = [keys[i] for i in idxs]
            rt = self._check_token(sh, token)
            if len(by_shard) == 1:
                if rt == "primary":
                    vals = serving.replicas.primary.multi_get(sub, opts)
                else:
                    vals = serving.replicas.multi_get(sub, opts, token=rt)
                for i, v in zip(idxs, vals):
                    out[i] = v
            elif rt == "primary":
                pending.append(
                    (idxs, serving.replicas.primary.multi_get_async(sub, opts)))
            else:
                pending.append(
                    (idxs, serving.replicas.multi_get_async(sub, opts,
                                                            token=rt)))
            self._note_traffic(name, reads=1, read_keys=len(sub))
        for idxs, fut in pending:
            for i, v in zip(idxs, fut.result()):
                out[i] = v
        self._tick(stats_mod.SHARD_ROUTED_READS, len(by_shard))
        return out

    def scan(self, begin: bytes | None = None, end: bytes | None = None,
             opts: ReadOptions = _DEFAULT_READ):
        """Ordered (key, value) iteration across the whole fleet: shards
        partition the keyspace and are stored sorted, so chaining per-shard
        iterators (each clipped to its shard ∩ [begin, end)) yields every
        live key exactly once, in order."""
        for name in self.map.names():
            try:
                shard = self.map.get(name)
            except NotFound:
                continue  # merged away mid-scan: successor covers it
            clipped = shard.clip(begin, end)
            if clipped is None:
                continue
            b, e = clipped
            serving = self._serving(name)
            it = serving.replicas.primary.new_iterator(opts)
            try:
                if b is None:
                    it.seek_to_first()
                else:
                    it.seek(b)
                while it.valid():
                    k = it.key()
                    if e is not None and k >= e:
                        break
                    yield k, it.value()
                    it.next()
            finally:
                close = getattr(it, "close", None)
                if close is not None:
                    close()

    # -- topology: split / merge -----------------------------------------

    def _span_root(self, db, name: str, **tags):
        tracer = getattr(db, "tracer", None)
        return tracer.start(name, **tags) if tracer is not None else None

    def split_shard(self, name: str, split_key: bytes,
                    right_name: str | None = None) -> tuple[Shard, Shard]:
        """Metadata split: both halves keep serving from the SAME stack
        (fresh epochs invalidate outstanding tokens); a later migration
        gives a half its own instance. No fence needed — in-flight writes
        commit to the shared primary either way."""
        with self._mu:
            serving = self._serving(name)
            sp = self._span_root(serving.primary, "shard.split", shard=name)
            try:
                left, right = self.map.split(name, split_key,
                                             right_name=right_name)
                # Left keeps its stack (same name, live epoch provider);
                # the right half gets its own serving entry over the SAME
                # primary/followers.
                self._servings[right.name] = self._new_serving(
                    right.name, serving.primary, serving.followers)
                self._gates.setdefault(right.name, _WriteGate())
                self._traffic.setdefault(right.name, {
                    "reads": 0, "writes": 0, "read_keys": 0,
                    "write_bytes": 0})
            finally:
                if sp is not None:
                    sp.finish()
        self._tick(stats_mod.SHARD_SPLITS)
        return left, right

    def merge_shards(self, left_name: str, right_name: str):
        """Merge two adjacent shards. Same backing primary → metadata-only.
        Different primaries → the right shard is write-fenced, its rows are
        copied into the left primary, then the map merges; the orphaned
        right serving stack is returned for the caller to close (None when
        the backends were shared)."""
        from toplingdb_tpu.db.write_batch import WriteBatch

        with self._mu:
            left_s = self._serving(left_name)
            right_s = self._serving(right_name)
            right_shard = self.map.get(right_name)
            sp = self._span_root(left_s.primary, "shard.merge",
                                 left=left_name, right=right_name)
            orphan = None
            gate = self._gate(right_name)
            fenced = False
            try:
                if right_s.primary is not left_s.primary:
                    if not gate.fence():
                        raise Busy(f"could not drain writers on "
                                   f"{right_name!r} for merge")
                    fenced = True
                    # Copy the right shard's rows (bounded to its range —
                    # the primary may physically hold more) into the left.
                    b = WriteBatch()
                    n = 0
                    it = right_s.replicas.primary.new_iterator()
                    if right_shard.start is None:
                        it.seek_to_first()
                    else:
                        it.seek(right_shard.start)
                    while it.valid():
                        k = it.key()
                        if right_shard.end is not None \
                                and k >= right_shard.end:
                            break
                        b.put(k, it.value())
                        n += 1
                        if n % 1000 == 0:
                            left_s.primary.write(b)
                            b = WriteBatch()
                        it.next()
                    if b.count():
                        left_s.primary.write(b)
                    orphan = right_s
                self.map.merge(left_name, right_name)
                self._servings.pop(right_name, None)
                self._traffic.pop(right_name, None)
            finally:
                if fenced:
                    gate.unfence()  # parked writers re-route to the merge
                if sp is not None:
                    sp.finish()
        self._tick(stats_mod.SHARD_MERGES)
        return orphan

    # -- topology: migration hooks (sharding/migration.py drives) ---------

    def fence_shard(self, name: str, drain_timeout: float = 30.0) -> float:
        """Close the shard's write gate and drain in-flight writers;
        returns the fence start time (for SHARD_FENCE_MICROS)."""
        t0 = time.monotonic()
        if not self._gate(name).fence(drain_timeout):
            self._gate(name).unfence()
            raise Busy(f"writers on shard {name!r} did not drain")
        self.map.set_state(name, "fenced")
        return t0

    def unfence_shard(self, name: str, t0: float | None = None) -> None:
        try:
            self.map.set_state(name, "serving")
        except NotFound:
            pass  # merged away while fenced
        self._gate(name).unfence()
        if t0 is not None and self.stats is not None:
            self.stats.record_in_histogram(
                stats_mod.SHARD_FENCE_MICROS,
                int((time.monotonic() - t0) * 1e6))

    def swap_serving(self, name: str, primary, followers=()) -> ShardServing:
        """Replace a shard's serving stack (migration cutover, under the
        fence) and bump its epoch so outstanding tokens die. Returns the
        OLD stack for the caller to retire."""
        with self._mu:
            old = self._serving(name)
            self._servings[name] = self._new_serving(name, primary,
                                                     followers)
            self.map.bump_epoch(name)
            return old

    # -- introspection ----------------------------------------------------

    def traffic(self) -> dict:
        with self._mu:
            return {k: dict(v) for k, v in self._traffic.items()}

    def status(self) -> dict:
        shards = []
        for s in list(self.map.shards):
            serving = self._servings.get(s.name)
            row = dict(s.to_config())
            row["fenced"] = self._gate(s.name).fenced
            row["traffic"] = dict(self._traffic.get(s.name, {}))
            if serving is not None:
                row["primary"] = getattr(serving.primary, "dbname", None)
                row["followers"] = len(serving.followers)
                row["stall"] = serving.stall_state()
                row.update(serving.health())
                try:
                    row["last_sequence"] = \
                        serving.primary.versions.last_sequence
                except Exception as e:
                    _errors.swallow(reason="status-last-sequence-probe", exc=e)
            shards.append(row)
        out = {
            "role": "shard-router",
            "map_version": self.map.version,
            "n_shards": len(shards),
            "shards": shards,
        }
        if self.admission is not None:
            out["admission"] = self.admission.status()
        return out

    def close(self) -> None:
        """Close every DISTINCT primary/follower referenced by the serving
        stacks (shared stacks after a split close once)."""
        with self._mu:
            servings = list(self._servings.values())
            self._servings.clear()
        seen: set[int] = set()
        for s in servings:
            for db in [*s.followers, s.primary]:
                if id(db) in seen:
                    continue
                seen.add(id(db))
                try:
                    db.close()
                except Exception as e:
                    _errors.swallow(reason="shard-close-on-shutdown", exc=e)
