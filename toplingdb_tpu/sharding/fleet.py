"""Out-of-process shard fleet: ShardServer processes, a leased front
door, and crash-safe supervision.

PR 8's sharding plane proved the split/merge/migration protocol with
every "process" as an in-process helper. This module promotes each
shard primary to a real OS process behind the HTTP control/data split
the repo already uses at the dcompact and replication seams:

  ShardServer      one process per shard: the shard's DB fronted by a
                   single-shard ShardRouter (reusing the `_WriteGate`
                   fence/drain and token machinery), a LogShipper behind
                   /replication/* (so followers and migrations pull WAL
                   frames exactly as PR 4 does), a lease heartbeat to the
                   coordinator, and SIGTERM-graceful shutdown:
                   fence → drain in-flight writes → flush → close.
  FleetRouter      the multi-process front door: routes by a CACHED
                   shard map validated against the lease coordinator; a
                   router that cannot re-validate within its map-lease
                   window fails writes CLOSED (Busy) instead of routing
                   on stale topology. Server-side epoch checks reject
                   anything the cache got wrong (409 → refresh → retry),
                   the cross-process analogue of `shard.token.rejects`.
  FleetSupervisor  spawns/watches the processes: heartbeat liveness,
                   kill -9 detection, automatic follower promotion on
                   primary death (coordinator `reassign` = epoch bump +
                   fresh fencing token), cross-process migration with
                   `ShardMigration.recover` invoked over HTTP when a
                   crash interrupts it mid-flight.

Safety invariants (chaos-soaked by tools/fleet_soak.py):
  - a write is acked iff it committed on the CURRENT epoch's primary
    under a live lease — never under a stale epoch or lapsed lease;
  - ownership moves only through the coordinator's fencing tokens, so
    two processes can never both accept writes for one shard;
  - kill -9 at any point loses nothing acked (WAL recovery on respawn;
    migration sources stay authoritative until the cutover grant).
"""

from __future__ import annotations

import argparse
import base64
import http.client
import json
import os
import shutil
import signal
import socket
import subprocess
import sys
import threading
import time
import urllib.error
import urllib.request
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from toplingdb_tpu.compaction.resilience import DcompactOptions
from toplingdb_tpu.replication.log_shipper import LogShipper, WalRetentionGone
from toplingdb_tpu.sharding.lease import LeaseClient, LeaseConflict
from toplingdb_tpu.sharding.migration import ShardMigration
from toplingdb_tpu.sharding.router import ShardRouter
from toplingdb_tpu.sharding.shard_map import Shard, ShardMap
from toplingdb_tpu.utils import concurrency as ccy
from toplingdb_tpu.utils import errors as _errors
from toplingdb_tpu.utils import statistics as stats_mod
from toplingdb_tpu.utils import telemetry as _tm
from toplingdb_tpu.utils.status import Busy, IOError_, NotSupported

DEFAULT_LEASE_TTL = 3.0


def find_free_port(host: str = "127.0.0.1") -> int:
    s = socket.socket()
    s.bind((host, 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _http_json(url: str, path: str, body: dict | None = None,
               timeout: float = 10.0) -> dict:
    """One JSON round-trip, no retries (callers own their retry loop)."""
    if body is None:
        req = urllib.request.Request(url.rstrip("/") + path)
    else:
        req = urllib.request.Request(
            url.rstrip("/") + path, data=json.dumps(body).encode(),
            headers={"Content-Type": "application/json"}, method="POST")
    with urllib.request.urlopen(req, timeout=timeout) as r:
        return json.loads(r.read())


class _StaleEpoch(Busy):
    """Server rejected the routed epoch (cutover happened): refresh the
    map and retry — the cross-process `shard.token.rejects`."""

    def __init__(self, msg: str, epoch: int | None = None):
        super().__init__(msg)
        self.epoch = epoch


class _Unavailable(Busy):
    """Server answered 503 (fenced / draining / lease lapsed / not the
    primary): transient by contract, retry after refresh."""


# ---------------------------------------------------------------------------
# ShardServer: one process per shard
# ---------------------------------------------------------------------------


class ShardServer:
    """One shard's serving process. Wraps the shard DB in a one-shard
    ShardRouter so the in-process fence/drain (`_WriteGate`), token and
    traffic machinery is reused verbatim; range clipping is the fleet
    router's job (this map spans the whole keyspace on purpose).

    Usable in-process for tests (`start()` / `shutdown()`), and as a
    process via `python -m toplingdb_tpu.sharding.fleet` (SIGTERM runs
    the same graceful shutdown)."""

    def __init__(self, shard: str, path: str, *, coordinator=None,
                 role: str = "primary", source_url: str | None = None,
                 holder: str | None = None,
                 lease_ttl: float = DEFAULT_LEASE_TTL,
                 options=None, statistics=None,
                 heartbeat_interval: float | None = None):
        from toplingdb_tpu.utils.statistics import Statistics

        self.shard = shard
        self.path = path
        self.coordinator = coordinator
        self.role = role
        self.source_url = source_url
        self.holder = holder or f"{shard}@{os.getpid()}"
        self.lease_ttl = lease_ttl
        self.options = options
        self.stats = statistics or Statistics()
        self.heartbeat_interval = heartbeat_interval or (lease_ttl / 3.0)
        self._mu = ccy.Lock("fleet.ShardServer._mu")
        self._lease: dict | None = None
        self._lease_valid_until = 0.0  # monotonic deadline (self-fence)
        self.router: ShardRouter | None = None
        self.db = None
        self.follower = None
        self.shipper: LogShipper | None = None
        self._http: ThreadingHTTPServer | None = None
        self._hb_thread = None
        self._hb_stop = threading.Event()
        self._down = False
        self.shutdown_requested = threading.Event()

    # -- lifecycle --------------------------------------------------------

    def start(self, port: int = 0, host: str = "127.0.0.1") -> int:
        if self.role == "primary":
            self._open_primary()
        else:
            self._open_follower()
        self._http = ThreadingHTTPServer((host, port), self._handler())
        ccy.spawn("fleet-shard-server", self._http.serve_forever,
                  owner=self, stop=self.shutdown)
        if self.coordinator is not None and self.role == "primary":
            self._acquire_lease_blocking()
            self._start_heartbeat()
        return self._http.server_address[1]

    @property
    def port(self) -> int:
        return self._http.server_address[1] if self._http else 0

    def _db_options(self, create: bool):
        from toplingdb_tpu.options import Options

        opts = self.options or Options()
        opts.create_if_missing = create
        if opts.statistics is None:
            opts.statistics = self.stats
        return opts

    def _open_primary(self) -> None:
        from toplingdb_tpu.db.db import DB

        epoch = 1
        if self.coordinator is not None:
            doc = self.coordinator.get_map()
            if doc.get("map"):
                m = ShardMap.from_config(doc["map"])
                epoch = m.epoch_of(self.shard)
        self.db = DB.open(self.path, self._db_options(create=True))
        self.router = ShardRouter(
            ShardMap([Shard(name=self.shard, start=None, end=None,
                            epoch=epoch)]),
            statistics=self.stats)
        self.router.attach_shard(self.shard, self.db)
        self.shipper = LogShipper(self.db, statistics=self.stats)

    def _open_follower(self) -> None:
        from toplingdb_tpu.replication.follower import FollowerDB
        from toplingdb_tpu.replication.log_shipper import HttpTransport

        if not self.source_url:
            raise NotSupported("follower role needs --source <primary url>")
        self.follower = FollowerDB.open(
            self.path, self._db_options(create=False),
            transport=HttpTransport(self.source_url),
            mode="standalone", bootstrap=True)
        self.follower.start_tailing()

    def promote(self, grant: dict) -> dict:
        """Follower → primary on the supervisor's order. `grant` is the
        coordinator's reassign result: the fresh fencing token + the
        bumped epoch that fences every pre-promotion write path."""
        if self.follower is None:
            raise NotSupported("promote: not a follower")
        t0 = time.monotonic()  # before the promotion work: conservative
        sp = _tm.span("fleet.promote")
        path = self.follower.promote()
        self.follower = None
        from toplingdb_tpu.db.db import DB

        # FollowerDB.open flipped these on the shared Options; a primary
        # must write (migration.py's cutover does the same reset).
        opts = self._db_options(create=False)
        opts.read_only = False
        opts.disable_auto_compactions = False
        self.db = DB.open(path, opts)
        epoch = int(grant.get("epoch", 1))
        self.router = ShardRouter(
            ShardMap([Shard(name=self.shard, start=None, end=None,
                            epoch=epoch)]),
            statistics=self.stats)
        self.router.attach_shard(self.shard, self.db)
        self.shipper = LogShipper(self.db, statistics=self.stats)
        self.role = "primary"
        self._adopt_grant(grant, t0)
        self.holder = grant.get("holder", self.holder)
        if self.coordinator is not None:
            self._start_heartbeat()
        self._tick(stats_mod.FLEET_PROMOTIONS)
        sp.finish()
        return {"role": self.role, "epoch": epoch,
                "applied_seq": self.db.versions.last_sequence}

    def shutdown(self) -> None:
        """Graceful teardown (SIGTERM handler and /fleet/shutdown): stop
        heartbeating, fence the shard and DRAIN in-flight writes through
        the _WriteGate, flush, close the DB, release the lease, stop
        HTTP. Idempotent; leaves zero owner-scoped threads behind."""
        with self._mu:
            if self._down:
                return
            self._down = True
        self._stop_heartbeat()
        if self.router is not None:
            try:
                self.router.fence_shard(self.shard, drain_timeout=5.0)
            except Busy as e:
                _errors.swallow(reason="fleet-shutdown-drain-timeout", exc=e)
        lease = self._lease
        if self.coordinator is not None and lease is not None:
            try:
                self.coordinator.release(self.shard, self.holder,
                                         lease["token"])
            except (LeaseConflict, IOError_, OSError) as e:
                _errors.swallow(reason="fleet-shutdown-lease-release", exc=e)
        self._lease = None
        if self.db is not None:
            try:
                self.db.flush()
            except Exception as e:
                _errors.swallow(reason="fleet-shutdown-flush", exc=e)
        if self.router is not None:
            self.router.close()  # closes the primary DB
            self.router = None
            self.db = None
        elif self.db is not None:
            self.db.close()
            self.db = None
        if self.follower is not None:
            self.follower.close()
            self.follower = None
        if self._http is not None:
            self._http.shutdown()
            self._http = None
        self.shutdown_requested.set()

    # -- lease machinery --------------------------------------------------

    def _tick(self, name: str) -> None:
        if self.stats is not None:
            self.stats.record_tick(name)

    def _acquire_lease_blocking(self, timeout: float = 30.0) -> None:
        """Primaries must hold the lease before serving a single write.
        A fresh grant may have to sit out the previous holder's expiry +
        grace (kill -9 respawn) — that wait IS the fencing protocol."""
        deadline = time.monotonic() + timeout
        while True:
            try:
                t0 = time.monotonic()
                grant = self.coordinator.acquire(self.shard, self.holder,
                                                 self.lease_ttl)
                self._adopt_grant(grant, t0)
                return
            except LeaseConflict as e:
                if time.monotonic() > deadline:
                    raise Busy(
                        f"could not acquire lease for {self.shard!r} "
                        f"within {timeout}s: {e}") from e
                time.sleep(0.1)
            except (IOError_, OSError) as e:
                self._tick(stats_mod.FLEET_HEARTBEAT_MISSES)
                if time.monotonic() > deadline:
                    raise IOError_(
                        f"lease coordinator unreachable: {e}") from e
                time.sleep(0.2)

    def _adopt_grant(self, grant: dict, t0: float | None = None) -> None:
        """Anchor the local self-fence deadline at `t0` — the monotonic
        clock captured IMMEDIATELY BEFORE the acquire/renew request was
        sent. The coordinator stamps expires = its_now + ttl while the
        request is in flight, so `t0 + ttl` is strictly conservative:
        a response delayed past the grace window (network, GC pause)
        can never leave this process believing in a lease the
        coordinator has already re-granted to a new holder."""
        if t0 is None:
            t0 = time.monotonic()
        with self._mu:
            self._lease = grant
            self._lease_valid_until = (
                t0 + float(grant.get("ttl", self.lease_ttl)))
        epoch = int(grant.get("epoch", 0))
        if self.router is not None \
                and epoch > self.router.map.epoch_of(self.shard):
            self.router.map.adopt_epoch(self.shard, epoch)

    def _start_heartbeat(self) -> None:
        if self._hb_thread is not None:
            return
        self._hb_stop.clear()
        self._hb_thread = ccy.spawn("fleet-lease-heartbeat",
                                    self._heartbeat_loop, owner=self,
                                    stop=self._hb_stop.set)

    def _stop_heartbeat(self) -> None:
        self._hb_stop.set()
        t = self._hb_thread
        if t is not None:
            t.join(timeout=5.0)
            self._hb_thread = None

    def _heartbeat_loop(self) -> None:
        while not self._hb_stop.wait(self.heartbeat_interval):
            with self._mu:
                lease = self._lease
            try:
                t0 = time.monotonic()
                if lease is None:
                    grant = self.coordinator.acquire(
                        self.shard, self.holder, self.lease_ttl)
                else:
                    grant = self.coordinator.renew(
                        self.shard, self.holder, lease["token"],
                        self.lease_ttl)
                if self._hb_stop.is_set():
                    # Released/retired while this beat was in flight:
                    # adopting now would resurrect a lease the server
                    # just surrendered (migration cutover).
                    return
                self._adopt_grant(grant, t0)
            except LeaseConflict as e:
                # Superseded or lapsed: SELF-FENCE — stop acking writes
                # now, re-acquire (fresh token) on a later beat.
                _errors.swallow(reason="fleet-lease-superseded", exc=e)
                with self._mu:
                    fenced_now = self._lease is not None
                    self._lease = None
                if fenced_now:
                    self._tick(stats_mod.FLEET_SELF_FENCES)
            except (IOError_, OSError) as e:
                # Coordinator unreachable: keep serving strictly within
                # the lease we already hold; local expiry self-fences.
                _errors.swallow(reason="fleet-heartbeat-miss", exc=e)
                self._tick(stats_mod.FLEET_HEARTBEAT_MISSES)

    def _lease_ok(self) -> bool:
        if self.coordinator is None:
            return True
        with self._mu:
            return (self._lease is not None
                    and time.monotonic() < self._lease_valid_until)

    def recover(self) -> dict:
        """Cross-process ShardMigration.recover: lift a fence left by a
        migration that died with the driver (kill -9 chaos). The source
        is still authoritative — cutover never happened — so unfencing
        restores service on the old epoch."""
        ShardMigration.recover(self.router, self.shard)
        self._tick(stats_mod.FLEET_MIGRATIONS_RECOVERED)
        return {"recovered": True, "shard": self.shard,
                "epoch": self.router.map.epoch_of(self.shard)}

    # -- request handling -------------------------------------------------

    def _current_epoch(self) -> int:
        return self.router.map.epoch_of(self.shard)

    def handle_write(self, req: dict) -> tuple[int, dict]:
        """The data-plane hot path, and the safety choke point: a write
        is admitted iff this process is the primary, holds a live lease,
        and the router stamped the CURRENT epoch. 409/503 are answers,
        not errors — the fleet router refreshes and retries."""
        if self.role != "primary" or self.router is None:
            return 503, {"error": "not_primary"}
        if not self._lease_ok():
            self._tick(stats_mod.FLEET_WRITE_REJECTS)
            return 503, {"error": "lease_expired"}
        db = self.db
        if db is not None and db.disk_pressure() == "red":
            # Red storage pressure: shed the write BEFORE it reaches the
            # WAL. A 503 is retryable — the fleet router backs off while
            # the reclaim ladder frees space; reads keep serving.
            self._tick(stats_mod.NO_SPACE_WRITES_SHED)
            self._tick(stats_mod.FLEET_WRITE_REJECTS)
            return 503, {"error": "disk_pressure", "level": "red"}
        epoch = self._current_epoch()
        if int(req.get("epoch", -1)) != epoch:
            self._tick(stats_mod.FLEET_STALE_EPOCH_REJECTS)
            return 409, {"error": "stale_epoch", "epoch": epoch}
        from toplingdb_tpu.db.write_batch import WriteBatch

        batch = WriteBatch(base64.b64decode(req["batch_b64"]))
        try:
            tokens = self.router.write(batch, shard=self.shard)
        except Busy as e:
            return 503, {"error": "fenced", "detail": str(e)}
        tok = tokens[0]
        return 200, {"seq": tok.seq, "epoch": tok.epoch, "shard": self.shard}

    def handle_get(self, req: dict) -> tuple[int, dict]:
        key = base64.b64decode(req["key_b64"])
        if self.follower is not None:
            v = self.follower.get(key)
        elif self.router is not None:
            v = self.router.get(key)
        else:
            return 503, {"error": "not_serving"}
        return 200, {"value_b64":
                     base64.b64encode(v).decode() if v is not None else None}

    def handle_multiget(self, req: dict) -> tuple[int, dict]:
        if self.router is None:
            return 503, {"error": "not_primary"}
        keys = [base64.b64decode(k) for k in req["keys_b64"]]
        vals = self.router.multi_get(keys)
        return 200, {"values_b64": [
            base64.b64encode(v).decode() if v is not None else None
            for v in vals]}

    def handle_scan(self, req: dict) -> tuple[int, dict]:
        if self.router is None:
            return 503, {"error": "not_primary"}
        begin = base64.b64decode(req["begin_b64"]) \
            if req.get("begin_b64") else None
        end = base64.b64decode(req["end_b64"]) if req.get("end_b64") else None
        limit = int(req.get("limit", 10000))
        rows = []
        truncated = False
        for k, v in self.router.scan(begin, end):
            if len(rows) >= limit:
                truncated = True
                break
            rows.append([base64.b64encode(k).decode(),
                         base64.b64encode(v).decode()])
        return 200, {"rows": rows, "truncated": truncated}

    def status(self) -> dict:
        with self._mu:
            lease = dict(self._lease) if self._lease else None
        doc = {
            "shard": self.shard, "role": self.role, "holder": self.holder,
            "pid": os.getpid(), "lease": lease,
            "lease_ok": self._lease_ok(),
        }
        if self.router is not None:
            doc["epoch"] = self._current_epoch()
            doc["applied_seq"] = self.db.versions.last_sequence
            doc["fenced"] = self.router._gate(self.shard).fenced
            doc["stale_epoch_rejects"] = self.stats.get_ticker_count(
                stats_mod.FLEET_STALE_EPOCH_REJECTS) \
                if self.stats is not None else 0
        elif self.follower is not None:
            doc.update(self.follower.replication_status())
            doc["applied_seq"] = self.follower.applied_sequence()
        return doc

    def _handler(self):
        srv = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *a):
                pass

            def _reply(self, code: int, body: dict):
                data = json.dumps(body).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(data)))
                self.end_headers()
                self.wfile.write(data)

            def do_GET(self):
                if self.path == "/health":
                    doc = {"ok": True, "shard": srv.shard, "role": srv.role,
                           "pid": os.getpid()}
                    if srv.router is not None:
                        doc["epoch"] = srv._current_epoch()
                        doc["fenced"] = srv.router._gate(srv.shard).fenced
                    self._reply(200, doc)
                elif self.path == "/fleet/status":
                    self._reply(200, srv.status())
                elif self.path == "/metrics":
                    text = srv.stats.to_prometheus(
                        labels=f'shard="{srv.shard}"') \
                        if srv.stats is not None else ""
                    data = text.encode()
                    self.send_response(200)
                    self.send_header("Content-Type",
                                     "text/plain; version=0.0.4")
                    self.send_header("Content-Length", str(len(data)))
                    self.end_headers()
                    self.wfile.write(data)
                else:
                    self._reply(404, {"error": "not found"})

            def do_POST(self):
                n = int(self.headers.get("Content-Length", 0))
                try:
                    req = json.loads(self.rfile.read(n) or b"{}")
                except ValueError:
                    self._reply(400, {"error": "bad json"})
                    return
                try:
                    self._route(req)
                except WalRetentionGone as e:
                    self._reply(410, {"error": "wal_retention_gone",
                                      "detail": str(e)})
                except Exception as e:  # transport must answer, not die
                    self._reply(500, {"error": repr(e)[:300]})

            def _route(self, req: dict):
                p = self.path
                if p == "/fleet/write":
                    self._reply(*srv.handle_write(req))
                elif p == "/fleet/get":
                    self._reply(*srv.handle_get(req))
                elif p == "/fleet/multiget":
                    self._reply(*srv.handle_multiget(req))
                elif p == "/fleet/scan":
                    self._reply(*srv.handle_scan(req))
                elif p == "/fleet/fence":
                    srv.router.fence_shard(
                        srv.shard,
                        drain_timeout=float(req.get("drain_timeout", 30.0)))
                    self._reply(200, {
                        "fenced": True,
                        "applied_seq": srv.db.versions.last_sequence})
                elif p == "/fleet/unfence":
                    srv.router.unfence_shard(srv.shard)
                    self._reply(200, {"fenced": False})
                elif p == "/fleet/recover":
                    self._reply(200, srv.recover())
                elif p == "/fleet/epoch":
                    with srv._mu:
                        lease = srv._lease
                    if lease is not None and \
                            int(req.get("token", -1)) != lease["token"]:
                        self._reply(409, {"error": "stale_token"})
                        return
                    srv.router.map.adopt_epoch(srv.shard,
                                               int(req["epoch"]))
                    self._reply(200, {"epoch": srv._current_epoch()})
                elif p == "/fleet/promote":
                    self._reply(200, srv.promote(req))
                elif p == "/fleet/release_lease":
                    # Stop the heartbeat FIRST: a beat landing between
                    # this release and the supervisor's reassign would
                    # re-acquire the lease and make the cutover fail
                    # spuriously (aborting a caught-up migration).
                    srv._stop_heartbeat()
                    with srv._mu:
                        lease = srv._lease
                        srv._lease = None
                    if lease is not None and srv.coordinator is not None:
                        try:
                            srv.coordinator.release(srv.shard, srv.holder,
                                                    lease["token"])
                        except (LeaseConflict, IOError_, OSError) as e:
                            # Best-effort: the caller's reassign carries
                            # the token and settles ownership either way.
                            _errors.swallow(
                                reason="fleet-release-lease", exc=e)
                    self._reply(200, {
                        "released": lease is not None,
                        "token": lease["token"] if lease else None})
                elif p == "/fleet/flush":
                    srv.db.flush()
                    self._reply(200, {"flushed": True})
                elif p == "/fleet/shutdown":
                    self._reply(200, {"stopping": True})
                    srv.shutdown_requested.set()
                elif p == "/replication/pull":
                    if req.get("spans"):
                        srv.shipper.accept_spans(req["spans"])
                    frames, state = srv.shipper.frames_since(
                        req.get("since_seq"),
                        max_bytes=int(req.get("max_bytes", 1 << 22)))
                    self._reply(200, {
                        "frames_b64": [
                            base64.b64encode(f.encode()).decode()
                            for f in frames],
                        "state": state,
                    })
                elif p == "/replication/checkpoint":
                    from toplingdb_tpu.utilities.checkpoint import (
                        create_checkpoint,
                    )

                    create_checkpoint(srv.db, req["dest"])
                    self._reply(200, {"dest": req["dest"]})
                else:
                    self._reply(404, {"error": "not found"})

        return Handler


# ---------------------------------------------------------------------------
# FleetRouter: the multi-process front door
# ---------------------------------------------------------------------------


class FleetRouter:
    """Routes keys to ShardServer processes by a cached, lease-validated
    shard map. Fail-closed: if the coordinator has been unreachable for
    longer than `map_lease` seconds, writes raise Busy rather than
    routing on possibly-stale topology (the soak's partition scenario).
    Stale-epoch 409s from servers trigger refresh + bounded retry and
    tick `shard.token.rejects` — parity with the in-process router."""

    def __init__(self, coordinator, *, statistics=None,
                 map_lease: float = 3.0, request_timeout: float = 10.0,
                 write_deadline: float = 15.0,
                 options: DcompactOptions | None = None):
        self.coordinator = coordinator
        self.stats = statistics
        self.map_lease = map_lease
        self.request_timeout = request_timeout
        self.write_deadline = write_deadline
        self.options = options or DcompactOptions(
            max_attempts=3, backoff_base=0.05,
            attempt_timeout=request_timeout)
        self._mu = ccy.Lock("fleet.FleetRouter._mu")
        self.map: ShardMap | None = None
        self.placement: dict[str, str] = {}
        self._synced_at = 0.0
        self.refresh()

    def _tick(self, name: str, n: int = 1) -> None:
        if self.stats is not None:
            self.stats.record_tick(name, n)

    def refresh(self) -> None:
        doc = self.coordinator.get_map()
        if not doc.get("map"):
            raise IOError_("coordinator has no shard map installed")
        m = ShardMap.from_config(doc["map"])
        with self._mu:
            self.map = m
            self.placement = dict(doc.get("placement", {}))
            self._synced_at = time.monotonic()
        self._tick(stats_mod.FLEET_MAP_REFRESHES)

    def _ensure_fresh(self) -> None:
        with self._mu:
            age = time.monotonic() - self._synced_at
            stale = self.map is None or age > self.map_lease
        if not stale:
            return
        try:
            self.refresh()
        except (IOError_, OSError) as e:
            self._tick(stats_mod.FLEET_WRITE_REJECTS)
            raise Busy(
                f"shard map lease expired ({age:.1f}s > "
                f"{self.map_lease}s) and the coordinator is "
                f"unreachable: {e}") from e

    def _route(self, key: bytes) -> tuple[Shard, str]:
        with self._mu:
            shard = self.map.shard_for(key)
            url = self.placement.get(shard.name)
        if url is None:
            raise IOError_(f"no placement for shard {shard.name!r}")
        return shard, url

    def _server_post(self, url: str, path: str, body: dict,
                     timeout: float | None = None) -> dict:
        try:
            return _http_json(url, path, body,
                              timeout=timeout or self.request_timeout)
        except urllib.error.HTTPError as e:
            try:
                payload = json.loads(e.read())
            except ValueError:
                payload = {}
            if e.code == 409:
                raise _StaleEpoch(payload.get("error", "stale_epoch"),
                                  payload.get("epoch")) from e
            if e.code == 503:
                raise _Unavailable(payload.get("error", "busy")) from e
            raise IOError_(
                f"shard server {url}{path}: HTTP {e.code}") from e
        except (OSError, http.client.HTTPException) as e:
            # HTTPException covers a peer killed MID-response
            # (IncompleteRead): same retryable class as a refused connect.
            raise IOError_(f"shard server {url}{path}: {e}") from e

    # -- writes -----------------------------------------------------------

    def put(self, key: bytes, value: bytes):
        from toplingdb_tpu.db.write_batch import WriteBatch

        b = WriteBatch()
        b.put(key, value)
        return self._write_routed(key, b)

    def delete(self, key: bytes):
        from toplingdb_tpu.db.write_batch import WriteBatch

        b = WriteBatch()
        b.delete(key)
        return self._write_routed(key, b)

    def _write_routed(self, key: bytes, batch):
        self._ensure_fresh()
        shard, _url = self._route(key)
        return self.write(batch, shard=shard.name)

    def write(self, batch, shard: str | None = None):
        """Send a (pre-bucketed) WriteBatch to `shard`'s primary. The
        retry loop converges through topology changes: 409 → the epoch
        moved (refresh, restamp, retry); 503 → fenced or lease-lapsed
        (cutover or failover in progress — back off and retry); network
        error → the primary may have died (refresh picks up the
        respawned/promoted placement)."""
        from toplingdb_tpu.sharding.router import ShardToken

        if shard is None:
            raise NotSupported(
                "FleetRouter.write routes pre-bucketed batches; "
                "use put()/delete() for by-key routing")
        payload_b64 = base64.b64encode(batch.data()).decode()
        deadline = time.monotonic() + self.write_deadline
        delay = 0.05
        while True:
            self._ensure_fresh()
            with self._mu:
                epoch = self.map.epoch_of(shard)
                url = self.placement.get(shard)
            if url is None:
                raise IOError_(f"no placement for shard {shard!r}")
            try:
                out = self._server_post(url, "/fleet/write", {
                    "epoch": epoch, "batch_b64": payload_b64})
                self._tick(stats_mod.SHARD_ROUTED_WRITES)
                return [ShardToken(shard=shard, epoch=int(out["epoch"]),
                                   seq=int(out["seq"]))]
            except _StaleEpoch as e:
                self._tick(stats_mod.SHARD_TOKEN_REJECTS)
                err: Busy = e
            except (_Unavailable, IOError_) as e:
                err = e
            if time.monotonic() > deadline:
                raise Busy(
                    f"write to shard {shard!r} did not converge within "
                    f"{self.write_deadline}s: {err}") from err
            time.sleep(delay)
            delay = min(delay * 2, 0.5)
            try:
                self.refresh()
            except (IOError_, OSError) as e2:
                _errors.swallow(reason="fleet-write-refresh-miss", exc=e2)

    # -- reads ------------------------------------------------------------

    def get(self, key: bytes):
        self._ensure_fresh()
        deadline = time.monotonic() + self.write_deadline
        while True:
            shard, url = self._route(key)
            try:
                out = self._server_post(url, "/fleet/get", {
                    "key_b64": base64.b64encode(key).decode()})
                self._tick(stats_mod.SHARD_ROUTED_READS)
                v = out.get("value_b64")
                return base64.b64decode(v) if v is not None else None
            except (_Unavailable, IOError_) as e:
                if time.monotonic() > deadline:
                    raise Busy(f"read of {key!r} did not converge: "
                               f"{e}") from e
                time.sleep(0.05)
                try:
                    self.refresh()
                except (IOError_, OSError) as e2:
                    _errors.swallow(reason="fleet-read-refresh-miss",
                                    exc=e2)

    def multi_get(self, keys):
        """Batched read across the fleet: group keys by shard, POST one
        `/fleet/multiget` per shard — concurrently when the batch spans
        more than one shard — and reassemble values in input order.
        Each shard's POST keeps `_shard_post`'s refresh-and-retry
        convergence, so a mid-batch migration only stalls that shard's
        sub-batch, not the whole request."""
        self._ensure_fresh()
        by_shard: dict[str, list[int]] = {}
        for i, k in enumerate(keys):
            with self._mu:
                shard = self.map.shard_for(k)
            by_shard.setdefault(shard.name, []).append(i)
        out: list[bytes | None] = [None] * len(keys)

        def _fetch(name: str, idxs: list[int]):
            resp = self._shard_post(name, "/fleet/multiget", {
                "keys_b64": [base64.b64encode(keys[i]).decode()
                             for i in idxs]})
            return [base64.b64decode(v) if v is not None else None
                    for v in resp["values_b64"]]

        if len(by_shard) == 1:
            ((name, idxs),) = by_shard.items()
            for i, v in zip(idxs, _fetch(name, idxs)):
                out[i] = v
        else:
            from concurrent.futures import ThreadPoolExecutor

            with ThreadPoolExecutor(
                    max_workers=len(by_shard),
                    thread_name_prefix="tpulsm-fleet-mget") as pool:
                futs = [(idxs, pool.submit(_fetch, name, idxs))
                        for name, idxs in by_shard.items()]
                for idxs, fut in futs:
                    for i, v in zip(idxs, fut.result()):
                        out[i] = v
        self._tick(stats_mod.SHARD_ROUTED_READS, len(by_shard))
        return out

    def _shard_post(self, shard: str, path: str, body: dict) -> dict:
        """POST to `shard`'s current placement with refresh-and-retry on
        transport errors — a migrated/promoted shard's old address gives
        connection-refused until the next refresh picks up the move."""
        deadline = time.monotonic() + self.write_deadline
        while True:
            with self._mu:
                url = self.placement.get(shard)
            try:
                if url is None:
                    raise IOError_(f"no placement for shard {shard!r}")
                return self._server_post(url, path, body)
            except (_Unavailable, IOError_) as e:
                if time.monotonic() > deadline:
                    raise Busy(f"shard {shard!r} {path} did not "
                               f"converge: {e}") from e
                time.sleep(0.05)
                try:
                    self.refresh()
                except (IOError_, OSError) as e2:
                    _errors.swallow(reason="fleet-shard-refresh-miss",
                                    exc=e2)

    def scan(self, begin: bytes | None = None, end: bytes | None = None,
             page: int = 5000):
        """Ordered iteration across every shard process (merged-oracle
        parity checks): shards tile the keyspace, so chaining per-shard
        paged scans yields each live key exactly once, in order."""
        self._ensure_fresh()
        with self._mu:
            shards = list(self.map.shards)
        for s in shards:
            clipped = s.clip(begin, end)
            if clipped is None:
                continue
            lo, hi = clipped
            while True:
                out = self._shard_post(s.name, "/fleet/scan", {
                    "begin_b64":
                        base64.b64encode(lo).decode() if lo else None,
                    "end_b64":
                        base64.b64encode(hi).decode() if hi else None,
                    "limit": page,
                })
                rows = out.get("rows", [])
                for k64, v64 in rows:
                    yield base64.b64decode(k64), base64.b64decode(v64)
                if not out.get("truncated"):
                    break
                lo = base64.b64decode(rows[-1][0]) + b"\x00"

    def status(self) -> dict:
        with self._mu:
            age = time.monotonic() - self._synced_at
            return {
                "map_version": self.map.version if self.map else 0,
                "map_age_sec": round(age, 3),
                "map_lease_sec": self.map_lease,
                "placement": dict(self.placement),
            }

    def close(self) -> None:
        pass  # no background threads: freshness is checked per request


# ---------------------------------------------------------------------------
# FleetSupervisor: process supervision
# ---------------------------------------------------------------------------


class _Member:
    """One supervised ShardServer process."""

    def __init__(self, holder: str, shard: str, path: str, port: int,
                 role: str, proc: subprocess.Popen, cmd: list[str],
                 source_url: str | None = None):
        self.holder = holder
        self.shard = shard
        self.path = path
        self.port = port
        self.role = role
        self.proc = proc
        self.cmd = cmd
        self.source_url = source_url

    @property
    def url(self) -> str:
        return f"http://127.0.0.1:{self.port}"

    def alive(self) -> bool:
        return self.proc is not None and self.proc.poll() is None


class FleetSupervisor:
    """Spawns and watches the fleet's processes. The supervisor is the
    failure DETECTOR (waitpid + /health probes); the coordinator stays
    the failure ARBITER — every ownership change goes through its
    fencing tokens, so a confused supervisor cannot create two
    primaries."""

    def __init__(self, coordinator_url: str, *, statistics=None,
                 python: str = sys.executable,
                 lease_ttl: float = DEFAULT_LEASE_TTL):
        self.coordinator_url = coordinator_url
        self.coordinator = LeaseClient(coordinator_url)
        self.stats = statistics
        self.python = python
        self.lease_ttl = lease_ttl
        self._mu = ccy.Lock("fleet.FleetSupervisor._mu")
        self.members: dict[str, _Member] = {}
        self._seq = 0

    def _tick(self, name: str) -> None:
        if self.stats is not None:
            self.stats.record_tick(name)

    @staticmethod
    def _proc_env() -> dict:
        env = dict(os.environ)
        pkg_root = os.path.dirname(os.path.dirname(
            os.path.dirname(os.path.abspath(__file__))))
        env["PYTHONPATH"] = pkg_root + os.pathsep + env.get("PYTHONPATH", "")
        env.setdefault("JAX_PLATFORMS", "cpu")
        return env

    @staticmethod
    def _read_ready(proc: subprocess.Popen, what: str,
                    timeout: float) -> int:
        """Read the child's `READY <port>` line under a deadline: a
        child wedged BEFORE its READY print (hung DB open, unreachable
        coordinator inside start()) must fail the spawn, not hang the
        supervisor thread on a bare readline forever."""
        box: list[bytes] = []
        t = ccy.spawn("fleet-ready-reader",
                      lambda: box.append(proc.stdout.readline()))
        t.join(timeout)
        line = box[0].decode().strip() if box else ""
        if not line.startswith("READY "):
            proc.kill()  # unblocks the reader thread too (pipe EOF)
            proc.wait()
            t.join(timeout=5.0)
            raise IOError_(
                f"{what} did not come up within {timeout}s "
                f"(last stdout line: {line!r})")
        return int(line.split()[1])

    @staticmethod
    def start_coordinator(log_path: str, port: int = 0,
                          ttl: float = DEFAULT_LEASE_TTL,
                          grace: float = 1.0,
                          python: str = sys.executable,
                          wait_ready: float = 30.0
                          ) -> tuple[subprocess.Popen, str]:
        """Spawn the lease-coordinator process; returns (proc, url)."""
        cmd = [python, "-m", "toplingdb_tpu.sharding.lease",
               "--log", log_path, "--port", str(port),
               "--ttl", str(ttl), "--grace", str(grace)]
        proc = subprocess.Popen(cmd, env=FleetSupervisor._proc_env(),
                                stdout=subprocess.PIPE,
                                stderr=subprocess.DEVNULL)
        real_port = FleetSupervisor._read_ready(
            proc, "lease coordinator", wait_ready)
        return proc, f"http://127.0.0.1:{real_port}"

    def spawn_server(self, shard: str, path: str, port: int = 0, *,
                     role: str = "primary", source_url: str | None = None,
                     holder: str | None = None,
                     wait_ready: float = 30.0) -> _Member:
        with self._mu:
            self._seq += 1
            holder = holder or f"{shard}-p{self._seq}"
        cmd = [self.python, "-m", "toplingdb_tpu.sharding.fleet",
               "--shard", shard, "--path", path, "--port", str(port),
               "--coordinator", self.coordinator_url, "--role", role,
               "--holder", holder, "--ttl", str(self.lease_ttl)]
        if source_url:
            cmd += ["--source", source_url]
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        logf = open(path + ".log", "ab")  # noqa: SIM115 - process log
        proc = subprocess.Popen(cmd, env=self._proc_env(),
                                stdout=subprocess.PIPE, stderr=logf)
        logf.close()  # the child inherited the descriptor
        real_port = self._read_ready(
            proc, f"shard server {holder} (see {path}.log)", wait_ready)
        m = _Member(holder, shard, path, real_port, role, proc, cmd,
                    source_url)
        self._wait_healthy(m, timeout=wait_ready)
        with self._mu:
            self.members[holder] = m
        return m

    @staticmethod
    def _wait_healthy(m: _Member, timeout: float = 30.0) -> None:
        deadline = time.monotonic() + timeout
        while True:
            try:
                doc = _http_json(m.url, "/health", timeout=2.0)
                if doc.get("ok"):
                    return
            except (OSError, http.client.HTTPException) as e:
                if not m.alive():
                    raise IOError_(
                        f"{m.holder} died during startup "
                        f"(see {m.path}.log)") from e
            if time.monotonic() > deadline:
                raise IOError_(f"{m.holder} not healthy after {timeout}s")
            time.sleep(0.05)

    # -- liveness + failover ----------------------------------------------

    def poll(self) -> dict:
        """One supervision pass: process liveness + /health probes.
        Returns {holder: "ok" | "dead" | "unhealthy"}."""
        out = {}
        with self._mu:
            members = list(self.members.values())
        for m in members:
            if not m.alive():
                out[m.holder] = "dead"
                continue
            try:
                doc = _http_json(m.url, "/health", timeout=2.0)
                out[m.holder] = "ok" if doc.get("ok") else "unhealthy"
            except (OSError, http.client.HTTPException):
                out[m.holder] = "unhealthy"
        return out

    def handle_death(self, holder: str) -> _Member:
        """Failover for a dead primary: promote its follower if one is
        attached, else respawn on the same data directory (WAL recovery
        — kill -9 loses nothing acked). Either path goes through the
        coordinator: promotion bumps the epoch + issues a fresh fencing
        token; a respawn re-acquires a lease (sitting out the dead
        process's expiry + grace)."""
        with self._mu:
            m = self.members.pop(holder)
            follower = next(
                (f for f in self.members.values()
                 if f.shard == m.shard and f.role == "follower"), None)
        if m.alive():
            m.proc.kill()
            m.proc.wait()
        if follower is not None:
            return self.promote(follower.holder)
        self._tick(stats_mod.FLEET_RESTARTS)
        return self.spawn_server(m.shard, m.path, 0, role="primary",
                                 holder=None)

    def promote(self, follower_holder: str) -> _Member:
        """Follower → primary through the coordinator's reassign (the
        dead holder's lease is force-revoked — the supervisor positively
        observed the death — and the epoch bump fences stragglers)."""
        sp = _tm.span("fleet.promote")
        with self._mu:
            m = self.members[follower_holder]
        grant = self.coordinator.reassign(m.shard, m.holder, force=True,
                                          url=m.url, ttl=self.lease_ttl)
        _http_json(m.url, "/fleet/promote", grant, timeout=30.0)
        with self._mu:
            m.role = "primary"
        self._tick(stats_mod.FLEET_PROMOTIONS)
        sp.finish()
        return m

    # -- migration (cross-process) ----------------------------------------

    def migrate(self, shard: str, dest_path: str, *,
                catchup_timeout: float = 30.0,
                fault_hook=None) -> _Member:
        """Move `shard` to a new process: bootstrap a follower process
        off the source's /replication seam, catch up, fence + final
        drain, then hand ownership over through the coordinator (the
        source surrenders its lease; the grant to the dest bumps the
        epoch). The source stays authoritative until that grant: a crash
        anywhere before it is recovered by `recover_migration` with zero
        lost keys. `fault_hook(phase)` is the chaos seam."""
        sp = _tm.span("fleet.migrate")
        hook = fault_hook or (lambda phase: None)
        with self._mu:
            src = next(m for m in self.members.values()
                       if m.shard == shard and m.role == "primary")
        hook("bootstrap")
        dest = self.spawn_server(shard, dest_path, 0, role="follower",
                                 source_url=src.url)
        try:
            hook("catchup")
            self._await_catchup(src, dest, catchup_timeout)
            hook("fence")
            _http_json(src.url, "/fleet/fence", {"drain_timeout": 10.0},
                       timeout=30.0)
            self._await_catchup(src, dest, catchup_timeout)
            hook("cutover")
            # The source surrenders: release_lease stops its heartbeat
            # (so the lease can never be re-acquired behind our back)
            # and hands back its fencing token for a COOPERATIVE
            # reassign — the cutover admits on the token, not on a
            # racy released-lease window.
            rel = _http_json(src.url, "/fleet/release_lease", {},
                             timeout=10.0)
            grant = self.coordinator.reassign(shard, dest.holder,
                                              token=rel.get("token"),
                                              url=dest.url,
                                              ttl=self.lease_ttl)
            _http_json(dest.url, "/fleet/promote", grant, timeout=30.0)
            with self._mu:
                dest.role = "primary"
        except BaseException:
            # Source is still authoritative (ownership never moved):
            # tear the half-built dest down and restore the source.
            self._abort_migration(src, dest)
            raise
        self.retire(src.holder)
        sp.finish()
        return dest

    @staticmethod
    def _await_catchup(src: _Member, dest: _Member,
                       timeout: float) -> None:
        deadline = time.monotonic() + timeout
        while True:
            s = _http_json(src.url, "/fleet/status", timeout=5.0)
            d = _http_json(dest.url, "/fleet/status", timeout=5.0)
            if d.get("applied_seq", -1) >= s.get("applied_seq", 0):
                return
            if time.monotonic() > deadline:
                raise Busy(
                    f"migration catch-up stuck: dest "
                    f"{d.get('applied_seq')} < src {s.get('applied_seq')}")
            time.sleep(0.05)

    def _abort_migration(self, src: _Member, dest: _Member) -> None:
        with self._mu:
            self.members.pop(dest.holder, None)
        if dest.alive():
            dest.proc.kill()
            dest.proc.wait()
        shutil.rmtree(dest.path, ignore_errors=True)
        if src.alive():
            try:
                _http_json(src.url, "/fleet/recover", {}, timeout=10.0)
            except OSError as e:
                _errors.swallow(reason="fleet-migration-abort-recover",
                                exc=e)

    def recover_migration(self, shard: str) -> _Member:
        """Recovery after a kill -9 mid-migration: respawn the source if
        the crash took it down, invoke ShardMigration.recover ACROSS the
        process boundary (unfence; the source never stopped being the
        owner), and discard any half-bootstrapped dest follower."""
        with self._mu:
            src = next((m for m in self.members.values()
                        if m.shard == shard and m.role == "primary"), None)
            dests = [m for m in self.members.values()
                     if m.shard == shard and m.role == "follower"]
        for d in dests:
            with self._mu:
                self.members.pop(d.holder, None)
            if d.alive():
                d.proc.kill()
                d.proc.wait()
            shutil.rmtree(d.path, ignore_errors=True)
        if src is None:
            raise Busy(f"no primary member recorded for {shard!r}")
        if not src.alive():
            with self._mu:
                self.members.pop(src.holder, None)
            self._tick(stats_mod.FLEET_RESTARTS)
            src = self.spawn_server(shard, src.path, 0, role="primary")
        _http_json(src.url, "/fleet/recover", {}, timeout=10.0)
        return src

    # -- teardown ---------------------------------------------------------

    def retire(self, holder: str, timeout: float = 10.0) -> None:
        """Graceful stop (SIGTERM → fence/drain/flush/close) with a
        kill -9 escalation if the process does not exit in time."""
        with self._mu:
            m = self.members.pop(holder, None)
        if m is None or not m.alive():
            return
        m.proc.terminate()
        try:
            m.proc.wait(timeout=timeout)
        except subprocess.TimeoutExpired as e:
            _errors.swallow(reason="fleet-retire-sigterm-timeout", exc=e)
            m.proc.kill()
            m.proc.wait()

    def stop_all(self, timeout: float = 10.0) -> None:
        with self._mu:
            holders = list(self.members)
        for h in holders:
            self.retire(h, timeout=timeout)

    def status(self) -> dict:
        with self._mu:
            members = list(self.members.values())
        rows = []
        for m in members:
            row = {"holder": m.holder, "shard": m.shard, "role": m.role,
                   "url": m.url, "pid": m.proc.pid,
                   "alive": m.alive()}
            try:
                row.update(_http_json(m.url, "/fleet/status", timeout=2.0))
            except OSError as e:
                row["error"] = str(e)[:120]
            rows.append(row)
        return {"members": rows}


# ---------------------------------------------------------------------------
# Process entry point: python -m toplingdb_tpu.sharding.fleet ...
# ---------------------------------------------------------------------------


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="shard-server")
    ap.add_argument("--shard", required=True)
    ap.add_argument("--path", required=True)
    ap.add_argument("--port", type=int, default=0)
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--coordinator", default=None,
                    help="lease coordinator base URL")
    ap.add_argument("--role", choices=["primary", "follower"],
                    default="primary")
    ap.add_argument("--source", default=None,
                    help="primary URL to tail (follower role)")
    ap.add_argument("--holder", default=None)
    ap.add_argument("--ttl", type=float, default=DEFAULT_LEASE_TTL)
    args = ap.parse_args(argv)

    from toplingdb_tpu.utils.statistics import Statistics

    coordinator = LeaseClient(args.coordinator) if args.coordinator else None
    server = ShardServer(args.shard, args.path, coordinator=coordinator,
                         role=args.role, source_url=args.source,
                         holder=args.holder, lease_ttl=args.ttl,
                         statistics=Statistics())

    def _term(signum, frame):
        server.shutdown_requested.set()

    signal.signal(signal.SIGTERM, _term)
    signal.signal(signal.SIGINT, _term)
    port = server.start(args.port, host=args.host)
    print(f"READY {port}", flush=True)
    server.shutdown_requested.wait()
    server.shutdown()
    return 0


if __name__ == "__main__":
    sys.exit(main())
