"""Live shard migration, assembled from pieces the repo already owns.

State machine (each arrow is a phase boundary where `fault_hook` fires —
the chaos soak kills migrations exactly there and at every catch-up round):

  bootstrap   Checkpoint.restore_to seeds the destination from a source
              checkpoint (replication/follower.py's standalone bootstrap:
              FollowerDB.open(transport=...) requests the checkpoint over
              the migration transport and restores it).
  catchup     the destination tails the source's WAL through a LogShipper
              until its applied sequence is within `catchup_lag` of the
              source — the DUAL-WRITE window: the source keeps serving
              writes, every one of which also lands on the destination via
              shipping. Transport faults (drop/delay/truncate) only slow
              this phase down; a torn frame never half-applies.
  fence       the router closes the shard's write gate and DRAINS in-flight
              writers, then the destination pulls the final frames until
              applied == source.last_sequence. Bounded: a drain that cannot
              complete aborts the migration with the source untouched.
  cutover     FollowerDB.promote() → DB.open on the destination, the router
              swaps the serving stack and bumps the shard epoch (every
              outstanding token for the shard now re-routes), the fence
              lifts. Writers parked at the fence re-resolve and land on the
              NEW primary.

Abort safety: until the swap inside `cutover` the source is authoritative
and untouched — any failure (or a hard kill) leaves a correct cluster; the
destination directory is garbage to delete and retry. A HARD-killed
migration can leave the fence closed; `ShardMigration.recover(router,
shard)` is the supervisor-side cleanup (lift fence, reset state), after
which writes flow to the source again.
"""

from __future__ import annotations

import time

from toplingdb_tpu.replication.follower import FollowerDB
from toplingdb_tpu.replication.log_shipper import LocalTransport, LogShipper
from toplingdb_tpu.utils import statistics as stats_mod
from toplingdb_tpu.utils import telemetry as _tm
from toplingdb_tpu.utils.status import Busy, IOError_
from toplingdb_tpu.utils.sync_point import sync_point
from toplingdb_tpu.utils import errors as _errors


class MigrationAborted(Exception):
    """Raised when a migration gives up before cutover; the source shard
    is still authoritative and serving."""


class ShardMigration:
    """One shard → one new DB instance at `dest_path`.

    `transport_factory` wraps the LocalTransport built over the source's
    LogShipper (tests wrap FaultyTransport for chaos); `fault_hook(phase)`
    is called at every phase boundary and each catch-up round — raising
    from it aborts the migration exactly there."""

    PHASES = ("bootstrap", "catchup", "fence", "cutover")

    def __init__(self, router, shard_name: str, dest_path: str,
                 options=None, transport_factory=None,
                 catchup_lag: int = 0, catchup_timeout: float = 60.0,
                 fence_drain_timeout: float = 30.0, fault_hook=None):
        self.router = router
        self.shard_name = shard_name
        self.dest_path = dest_path
        self.options = options
        self.transport_factory = transport_factory
        self.catchup_lag = max(0, catchup_lag)
        self.catchup_timeout = catchup_timeout
        self.fence_drain_timeout = fence_drain_timeout
        self.fault_hook = fault_hook
        self.phase = "idle"
        self.rounds = 0

    def _hook(self, phase: str) -> None:
        self.phase = phase
        if self.fault_hook is not None:
            self.fault_hook(phase)

    def _tick(self, name: str) -> None:
        if self.router.stats is not None:
            self.router.stats.record_tick(name)

    def run(self) -> dict:
        router = self.router
        serving = router._serving(self.shard_name)
        src = serving.primary
        self._tick(stats_mod.SHARD_MIGRATIONS)
        t_start = time.monotonic()
        tracer = getattr(src, "tracer", None)
        root = tracer.start("shard.migrate", shard=self.shard_name,
                            dest=self.dest_path) if tracer else None
        router.map.set_state(self.shard_name, "migrating")
        follower = None
        fence_t0 = None
        try:
            # -- bootstrap: checkpoint restore into dest ------------------
            self._hook("bootstrap")
            sp = _tm.span("shard.migrate.bootstrap")
            shipper = LogShipper(src, statistics=router.stats)
            transport = LocalTransport(shipper)
            if self.transport_factory is not None:
                transport = self.transport_factory(transport)
            follower = FollowerDB.open(
                self.dest_path, self.options, env=src.env,
                transport=transport, mode="standalone", bootstrap=True)
            sp.finish()

            # -- catchup: the dual-write window ---------------------------
            sp = _tm.span("shard.migrate.catchup")
            deadline = time.monotonic() + self.catchup_timeout
            while True:
                self._hook("catchup")
                self.rounds += 1
                follower.catch_up()
                lag = (src.versions.last_sequence
                       - follower.applied_sequence())
                if lag <= self.catchup_lag:
                    break
                if time.monotonic() > deadline:
                    raise MigrationAborted(
                        f"catch-up stuck {lag} sequences behind after "
                        f"{self.catchup_timeout}s")
            sp.finish()

            # -- fence: drain writers, pull the last frames ---------------
            self._hook("fence")
            sp = _tm.span("shard.migrate.fence")
            fence_t0 = router.fence_shard(
                self.shard_name, drain_timeout=self.fence_drain_timeout)
            drain_deadline = time.monotonic() + self.fence_drain_timeout
            while follower.applied_sequence() < src.versions.last_sequence:
                follower.catch_up()
                if time.monotonic() > drain_deadline:
                    raise MigrationAborted(
                        "final drain did not converge under the fence")
            sp.finish()

            # -- cutover: promote + swap + epoch bump ---------------------
            # Interleaving seam: tests order the cutover against writers
            # parked at the fence (WriteGate:Parked -> BeforeCutover) to
            # pin that parked writers re-resolve onto the NEW primary.
            sync_point("ShardMigration::BeforeCutover")
            self._hook("cutover")
            sp = _tm.span("shard.migrate.cutover")
            from toplingdb_tpu.db.db import DB
            from toplingdb_tpu.options import Options

            path = follower.promote()  # final catch-up + close
            follower = None
            new_opts = self.options or Options()
            new_opts.read_only = False
            new_opts.create_if_missing = False
            new_opts.disable_auto_compactions = False
            if new_opts.statistics is None:
                new_opts.statistics = router.stats
            new_db = DB.open(path, new_opts, env=src.env)
            old = router.swap_serving(self.shard_name, new_db)
            router.unfence_shard(self.shard_name, fence_t0)
            fence_t0 = None
            # Retire the replaced stack (swap_serving hands it back for
            # exactly this): after cutover the old directory serves
            # nothing, and an unclosed primary pins its shared-store env
            # (cache + prefetch threads) forever.
            for db in [*old.followers, old.primary]:
                if db is new_db:
                    continue
                try:
                    db.close()
                except Exception as e2:
                    _errors.swallow(reason="cutover-retire-old", exc=e2)
            sp.finish()
            if router.stats is not None:
                router.stats.record_in_histogram(
                    stats_mod.SHARD_MIGRATION_MICROS,
                    int((time.monotonic() - t_start) * 1e6))
            self.phase = "done"
            return {
                "shard": self.shard_name,
                "dest": path,
                "rounds": self.rounds,
                "epoch": router.map.epoch_of(self.shard_name),
                "last_sequence": new_db.versions.last_sequence,
            }
        except BaseException as e:
            # Source stays authoritative: lift the fence, reset the state,
            # retire the half-built destination. A retry starts clean.
            self.phase = "aborted"
            self._tick(stats_mod.SHARD_MIGRATION_FAILURES)
            if fence_t0 is not None:
                try:
                    router.unfence_shard(self.shard_name, fence_t0)
                except Exception as e2:
                    _errors.swallow(reason="abort-unfence", exc=e2)
            else:
                try:
                    router.map.set_state(self.shard_name, "serving")
                except Exception as e2:
                    _errors.swallow(reason="abort-state-restore", exc=e2)
            if follower is not None:
                try:
                    follower.close()
                except Exception as e2:
                    _errors.swallow(reason="abort-follower-close", exc=e2)
            if isinstance(e, (MigrationAborted, Busy)):
                raise
            raise MigrationAborted(f"migration of {self.shard_name!r} "
                                   f"failed in {self.phase}: {e!r}") from e
        finally:
            if root is not None:
                root.finish()

    @staticmethod
    def recover(router, shard_name: str) -> None:
        """Supervisor-side cleanup after a HARD-killed migration (the
        process died holding the fence): lift the fence and return the
        shard to serving — the source was never demoted, so this restores
        full service; the destination directory is garbage to remove
        before a retry."""
        try:
            router.unfence_shard(shard_name)
        except Exception as e:  # pragma: no cover - map gone entirely
            raise IOError_(f"cannot recover shard {shard_name!r}: {e}")
