"""Lease-based shard-map consensus: the fleet's single coordinator.

The multi-process fleet (sharding/fleet.py) needs every router and every
shard server to agree on WHO owns each shard and under WHICH epoch —
during splits, merges, migrations and crash-promotions. Full Paxos/Raft
is overkill for one small map; the reference deployment runs exactly
this shape: a single lightweight metadata coordinator whose state is a
durable log, with *leases + fencing tokens* carrying the safety story:

  - Every shard primary holds a time-bounded lease stamped with a
    monotonically increasing **fencing token**. Tokens are never reused,
    survive coordinator restarts (the grant is fsynced before it is
    acked) and strictly order ownership: any request carrying an older
    token than the current grant is rejected.
  - Every shard-map mutation is an **epoch CAS**: the caller presents
    the map version it read; a concurrent mutation wins and the loser
    retries against the fresh map. Epochs themselves are allocated by
    the map (never reused), so a router that routed under a pre-cutover
    map is rejected by the shard server's epoch check — the same
    `shard.token.rejects` contract the in-process plane proves.
  - Routers cache the map under a read lease: a router that cannot
    re-validate its map within the lease window fails writes CLOSED
    (Busy) instead of routing on possibly-stale topology.
  - Expiry honours a **clock-skew grace window**: a holder may renew
    slightly past nominal expiry (its clock may run behind), but a NEW
    holder is only granted after expiry + grace — the two windows
    cannot overlap, so two primaries can never both believe they hold
    the shard.

Durability: an append-only JSONL log, fsynced per mutation, replayed on
restart. Map records are full snapshots (the map is small), lease
records are deltas; `next fencing token = max(seen) + 1` keeps token
monotonicity across restarts, which is what makes double grants
impossible even when the coordinator loses its memory.
"""

from __future__ import annotations

import argparse
import http.client
import json
import os
import signal
import sys
import threading
import time
import urllib.error
import urllib.request
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from toplingdb_tpu.compaction.resilience import DcompactOptions
from toplingdb_tpu.sharding.shard_map import ShardMap
from toplingdb_tpu.utils import concurrency as ccy
from toplingdb_tpu.utils import statistics as stats_mod
from toplingdb_tpu.utils.status import Busy, IOError_, InvalidArgument

DEFAULT_TTL = 5.0      # seconds a grant/renewal is valid
DEFAULT_GRACE = 1.0    # clock-skew allowance around expiry


class LeaseConflict(Busy):
    """Lease or CAS refused: held by another holder, stale fencing
    token, expired lease, or a lost map-version CAS."""


class LeaseCoordinator:
    """The fleet's metadata authority: shard map + placement + leases,
    all behind one durable log. Thread-safe; single-writer by design
    (one coordinator process per fleet)."""

    def __init__(self, log_path: str, *, default_ttl: float = DEFAULT_TTL,
                 grace: float = DEFAULT_GRACE, clock=time.time,
                 statistics=None):
        self.log_path = log_path
        self.default_ttl = default_ttl
        self.grace = grace
        self._clock = clock
        self.stats = statistics
        self._mu = ccy.RLock("lease.LeaseCoordinator._mu")
        self.map: ShardMap | None = None
        self.placement: dict[str, str] = {}
        # shard -> {"holder", "token", "expires", "ttl"}
        self.leases: dict[str, dict] = {}
        self._next_token = 1
        self._log = None
        self._replay()
        self._log = open(self.log_path, "ab")  # noqa: SIM115 - held open

    # -- durability -------------------------------------------------------

    def _replay(self) -> None:
        """Fold the log back into memory. Absolute expiry times survive
        the restart, so an unexpired grant is still binding on the
        restarted coordinator — the double-grant-impossibility proof.

        A torn tail (crash mid-append) is TRUNCATED away, not merely
        skipped: reopening in append mode behind a partial line would
        weld the next record onto the fragment, and on the following
        restart that one corrupt merged line would poison every fsynced
        record after it — acked grants silently lost, fencing tokens
        replayed to an old value."""
        if not os.path.exists(self.log_path):
            return
        good = 0  # byte offset just past the last parseable record
        with open(self.log_path, "rb") as f:
            pos = 0
            for line in f:
                pos += len(line)
                if not line.endswith(b"\n"):
                    # Unterminated final write: record + newline go out
                    # in ONE append, fsynced before the ack — a missing
                    # newline means the mutation was never acked, so it
                    # is safe (and necessary) to drop it.
                    break
                stripped = line.strip()
                if not stripped:
                    good = pos
                    continue
                try:
                    rec = json.loads(stripped)
                except ValueError:
                    break  # torn tail from a crash mid-append
                self._apply(rec)
                good = pos
            size = f.seek(0, os.SEEK_END)
        if good < size:
            with open(self.log_path, "r+b") as f:
                f.truncate(good)

    def _apply(self, rec: dict) -> None:
        op = rec.get("op")
        if op == "map":
            if rec.get("cfg") is not None:
                self.map = ShardMap.from_config(rec["cfg"])
            self.placement = dict(rec.get("placement", {}))
        elif op == "grant":
            self.leases[rec["shard"]] = {
                "holder": rec["holder"], "token": int(rec["token"]),
                "expires": float(rec["expires"]),
                "ttl": float(rec.get("ttl", self.default_ttl)),
            }
            self._next_token = max(self._next_token, int(rec["token"]) + 1)
        elif op == "renew":
            l = self.leases.get(rec["shard"])
            if l is not None and l["token"] == int(rec["token"]):
                l["expires"] = float(rec["expires"])
        elif op == "release":
            l = self.leases.get(rec["shard"])
            if l is not None and l["token"] == int(rec["token"]):
                del self.leases[rec["shard"]]

    def _append(self, rec: dict) -> None:
        """fsync-before-ack: a grant that was ever visible to a caller
        is in the log, so a restarted coordinator still honours it."""
        data = json.dumps(rec, separators=(",", ":")).encode() + b"\n"
        self._log.write(data)
        self._log.flush()
        os.fsync(self._log.fileno())

    def _tick(self, name: str) -> None:
        if self.stats is not None:
            self.stats.record_tick(name)

    # -- shard map: epoch CAS ---------------------------------------------

    def install_map(self, cfg: dict, placement: dict | None = None) -> dict:
        """Bootstrap the fleet's first map (version CAS against 0)."""
        return self.cas_map(0, cfg, placement)

    def cas_map(self, expected_version: int, cfg: dict,
                placement: dict | None = None) -> dict:
        with self._mu:
            cur = self.map.version if self.map is not None else 0
            if int(expected_version) != cur:
                self._tick(stats_mod.LEASE_CAS_CONFLICTS)
                raise LeaseConflict(
                    f"map CAS lost: expected version {expected_version}, "
                    f"coordinator has {cur}")
            m = ShardMap.from_config(cfg)
            m.validate()
            m.version = max(m.version, cur + 1)
            new_placement = dict(placement if placement is not None
                                 else self.placement)
            self._append({"op": "map", "cfg": m.to_config(),
                          "placement": new_placement})
            self.map = m
            self.placement = new_placement
            return {"version": m.version}

    def get_map(self) -> dict:
        with self._mu:
            if self.map is None:
                return {"map": None, "placement": {}, "version": 0}
            return {"map": self.map.to_config(),
                    "placement": dict(self.placement),
                    "version": self.map.version}

    def bump_epoch(self, shard: str, token: int) -> dict:
        """Cutover: a fresh epoch for `shard`, fenced by the holder's
        token so a deposed primary cannot bump behind the new one."""
        with self._mu:
            self._check_token(shard, token)
            epoch = self.map.bump_epoch(shard)
            self._append({"op": "map", "cfg": self.map.to_config(),
                          "placement": dict(self.placement)})
            return {"epoch": epoch, "version": self.map.version}

    # -- leases -----------------------------------------------------------

    def _check_token(self, shard: str, token: int) -> dict:
        l = self.leases.get(shard)
        if l is None or l["token"] != int(token):
            self._tick(stats_mod.LEASE_REJECTS)
            raise LeaseConflict(
                f"stale fencing token {token} for {shard!r} "
                f"(current: {l['token'] if l else None})")
        return l

    def acquire(self, shard: str, holder: str,
                ttl: float | None = None) -> dict:
        """Grant `shard` to `holder` with a fresh fencing token. Refused
        while another holder's lease could still be live (expiry +
        grace). The same holder may re-acquire at any time (it gets a
        NEW, higher token — its old one is thereby fenced)."""
        ttl = float(ttl or self.default_ttl)
        with self._mu:
            if self.map is not None and shard not in set(self.map.names()):
                raise InvalidArgument(f"unknown shard {shard!r}")
            now = self._clock()
            l = self.leases.get(shard)
            if l is not None and l["holder"] != holder:
                if now < l["expires"] + self.grace:
                    self._tick(stats_mod.LEASE_REJECTS)
                    raise LeaseConflict(
                        f"shard {shard!r} leased to {l['holder']!r} until "
                        f"{l['expires']:.3f} (+{self.grace}s grace)")
                self._tick(stats_mod.LEASE_EXPIRIES)
            return self._grant(shard, holder, ttl, now)

    def _grant(self, shard: str, holder: str, ttl: float,
               now: float) -> dict:
        token = self._next_token
        self._next_token += 1
        expires = now + ttl
        self._append({"op": "grant", "shard": shard, "holder": holder,
                      "token": token, "expires": expires, "ttl": ttl})
        self.leases[shard] = {"holder": holder, "token": token,
                              "expires": expires, "ttl": ttl}
        self._tick(stats_mod.LEASE_GRANTS)
        epoch = self.map.epoch_of(shard) if self.map is not None else 0
        return {"shard": shard, "holder": holder, "token": token,
                "expires": expires, "ttl": ttl, "epoch": epoch}

    def renew(self, shard: str, holder: str, token: int,
              ttl: float | None = None) -> dict:
        """Extend a live lease. The holder's clock may lag: renewals are
        honoured up to `grace` past nominal expiry, which is exactly the
        window a competing acquire must also sit out."""
        ttl = float(ttl or self.default_ttl)
        with self._mu:
            now = self._clock()
            l = self._check_token(shard, token)
            if l["holder"] != holder:
                self._tick(stats_mod.LEASE_REJECTS)
                raise LeaseConflict(
                    f"lease for {shard!r} held by {l['holder']!r}, "
                    f"not {holder!r}")
            if now >= l["expires"] + self.grace:
                self._tick(stats_mod.LEASE_EXPIRIES)
                self._tick(stats_mod.LEASE_REJECTS)
                raise LeaseConflict(
                    f"lease for {shard!r} expired at {l['expires']:.3f} "
                    f"(now {now:.3f}, grace {self.grace}s)")
            expires = now + ttl
            self._append({"op": "renew", "shard": shard, "token": token,
                          "expires": expires})
            l["expires"] = expires
            self._tick(stats_mod.LEASE_RENEWALS)
            epoch = self.map.epoch_of(shard) if self.map is not None else 0
            return {"shard": shard, "holder": holder, "token": token,
                    "expires": expires, "ttl": ttl, "epoch": epoch}

    def release(self, shard: str, holder: str, token: int) -> dict:
        with self._mu:
            l = self._check_token(shard, token)
            if l["holder"] != holder:
                self._tick(stats_mod.LEASE_REJECTS)
                raise LeaseConflict(
                    f"lease for {shard!r} held by {l['holder']!r}")
            self._append({"op": "release", "shard": shard, "token": token})
            del self.leases[shard]
            return {"shard": shard, "released": True}

    def reassign(self, shard: str, holder: str, *, token: int | None = None,
                 url: str | None = None, force: bool = False,
                 ttl: float | None = None) -> dict:
        """Move ownership of `shard` to `holder` and bump its epoch — the
        promotion/cutover primitive. Three admission paths:
          - cooperative: `token` is the CURRENT holder's fencing token
            (migration cutover — the source surrenders);
          - supervised: `force=True` when the supervisor has positively
            observed the holder's death (waitpid, kill -9);
          - expiry: otherwise the old lease must be past expiry + grace.
        The epoch bump is what fences stragglers: writes routed under
        the old epoch are rejected by the new primary's epoch check."""
        ttl = float(ttl or self.default_ttl)
        with self._mu:
            now = self._clock()
            l = self.leases.get(shard)
            if l is not None and token is not None:
                self._check_token(shard, token)
            elif l is not None and not force \
                    and now < l["expires"] + self.grace:
                self._tick(stats_mod.LEASE_REJECTS)
                raise LeaseConflict(
                    f"shard {shard!r} leased to {l['holder']!r} until "
                    f"{l['expires']:.3f}; need its token, its expiry, "
                    f"or force")
            epoch = None
            if self.map is not None and shard in set(self.map.names()):
                epoch = self.map.bump_epoch(shard)
            if url is not None:
                self.placement[shard] = url
            self._append({"op": "map",
                          "cfg": self.map.to_config()
                          if self.map is not None else None,
                          "placement": dict(self.placement)})
            out = self._grant(shard, holder, ttl, now)
            if epoch is not None:
                out["epoch"] = epoch
            out["version"] = self.map.version if self.map is not None else 0
            return out

    def status(self) -> dict:
        with self._mu:
            now = self._clock()
            return {
                "map_version": self.map.version if self.map else 0,
                "n_shards": len(self.map.shards) if self.map else 0,
                "next_token": self._next_token,
                "placement": dict(self.placement),
                "leases": {
                    s: {**l, "remaining": round(l["expires"] - now, 3)}
                    for s, l in self.leases.items()
                },
            }

    def close(self) -> None:
        with self._mu:
            if self._log is not None:
                self._log.close()
                self._log = None


# ---------------------------------------------------------------------------
# HTTP service (the dcompact_service / ReplicationServer transport shape)
# ---------------------------------------------------------------------------


class LeaseCoordinatorServer:
    """The coordinator behind HTTP: POST /lease/{acquire,renew,release,
    cas_map,bump_epoch,reassign}, GET /lease/{map,status} and /health.
    Lease/CAS refusals answer 409 so clients can tell policy from
    transport failure."""

    def __init__(self, coordinator: LeaseCoordinator):
        self.coordinator = coordinator
        self._server: ThreadingHTTPServer | None = None

    def start(self, port: int = 0, host: str = "127.0.0.1") -> int:
        co = self.coordinator

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *a):
                pass

            def _reply(self, code: int, body: dict):
                data = json.dumps(body).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(data)))
                self.end_headers()
                self.wfile.write(data)

            def do_GET(self):
                if self.path == "/lease/map":
                    self._reply(200, co.get_map())
                elif self.path == "/lease/status":
                    self._reply(200, co.status())
                elif self.path == "/health":
                    self._reply(200, {"ok": True, "role": "coordinator"})
                else:
                    self._reply(404, {"error": "not found"})

            def do_POST(self):
                n = int(self.headers.get("Content-Length", 0))
                try:
                    req = json.loads(self.rfile.read(n) or b"{}")
                except ValueError:
                    self._reply(400, {"error": "bad json"})
                    return
                try:
                    if self.path == "/lease/acquire":
                        self._reply(200, co.acquire(
                            req["shard"], req["holder"], req.get("ttl")))
                    elif self.path == "/lease/renew":
                        self._reply(200, co.renew(
                            req["shard"], req["holder"],
                            int(req["token"]), req.get("ttl")))
                    elif self.path == "/lease/release":
                        self._reply(200, co.release(
                            req["shard"], req["holder"], int(req["token"])))
                    elif self.path == "/lease/cas_map":
                        self._reply(200, co.cas_map(
                            int(req["expected_version"]), req["map"],
                            req.get("placement")))
                    elif self.path == "/lease/bump_epoch":
                        self._reply(200, co.bump_epoch(
                            req["shard"], int(req["token"])))
                    elif self.path == "/lease/reassign":
                        tok = req.get("token")
                        self._reply(200, co.reassign(
                            req["shard"], req["holder"],
                            token=int(tok) if tok is not None else None,
                            url=req.get("url"),
                            force=bool(req.get("force", False)),
                            ttl=req.get("ttl")))
                    else:
                        self._reply(404, {"error": "not found"})
                except LeaseConflict as e:
                    self._reply(409, {"error": "lease_conflict",
                                      "detail": str(e)})
                except Exception as e:  # transport must answer, not die
                    self._reply(500, {"error": repr(e)[:300]})

        self._server = ThreadingHTTPServer((host, port), Handler)
        ccy.spawn("lease-coordinator-server", self._server.serve_forever,
                  owner=self, stop=self.stop)
        return self._server.server_address[1]

    def stop(self) -> None:
        if self._server is not None:
            self._server.shutdown()
            self._server = None


# ---------------------------------------------------------------------------
# Client
# ---------------------------------------------------------------------------


class LeaseClient:
    """HTTP client for a LeaseCoordinatorServer: per-request timeouts +
    bounded retry/backoff on transport errors (a hung coordinator must
    not wedge a router thread), 409 mapped back to LeaseConflict (never
    retried — a refusal is an answer). Duck-type compatible with
    LeaseCoordinator so routers/servers take either.

    Transport retries are restricted to IDEMPOTENT paths (GETs, renew,
    release — replay-safe: a duplicate is a no-op or a clean 409). The
    mutating POSTs (acquire, cas_map, bump_epoch, reassign) are NOT
    retried: a connection dropped after the server applied the mutation
    would make a blind retry double-bump an epoch or report a CAS
    conflict for an install that actually landed. Those fail fast with
    IOError_ and the caller — whose retry loops re-read the map first —
    decides the true outcome.

    `partition` is an optional env/fault_injection.PartitionGate: while
    engaged, every call fails fast with IOError_ — the chaos soak's
    router-partitioned-from-lease-store scenario."""

    def __init__(self, url: str, *, timeout: float = 5.0,
                 options: DcompactOptions | None = None, partition=None):
        self.url = url.rstrip("/")
        self.timeout = timeout
        self.options = options or DcompactOptions(
            max_attempts=3, backoff_base=0.05, attempt_timeout=timeout)
        self.partition = partition

    # Replay-safe POSTs: renew/release against a moved token answer a
    # deterministic 409, so a duplicate delivery cannot corrupt state.
    _RETRY_SAFE_POSTS = ("/lease/renew", "/lease/release")

    def _call(self, method: str, path: str, body: dict | None = None) -> dict:
        if self.partition is not None:
            self.partition.check(f"{method} {path}")
        retryable = method == "GET" or path in self._RETRY_SAFE_POSTS
        last: Exception | None = None
        for attempt in range(1, self.options.max_attempts + 1):
            if attempt > 1:
                time.sleep(self.options.backoff_delay(attempt - 1))
                if self.partition is not None:
                    self.partition.check(f"{method} {path}")
            try:
                if body is None:
                    req = urllib.request.Request(self.url + path)
                else:
                    req = urllib.request.Request(
                        self.url + path, data=json.dumps(body).encode(),
                        headers={"Content-Type": "application/json"},
                        method="POST")
                with urllib.request.urlopen(req,
                                            timeout=self.timeout) as r:
                    return json.loads(r.read())
            except urllib.error.HTTPError as e:
                try:
                    payload = json.loads(e.read())
                except ValueError:
                    payload = {}
                if e.code == 409:
                    raise LeaseConflict(
                        payload.get("detail", "lease conflict")) from e
                raise IOError_(
                    f"coordinator {path}: HTTP {e.code} "
                    f"{payload.get('error', '')}") from e
            except (OSError, http.client.HTTPException) as e:
                # a coordinator killed mid-response (IncompleteRead) is
                # the same retryable class as a refused connect
                last = e
                if not retryable:
                    raise IOError_(
                        f"coordinator {path} failed in transit (not "
                        f"retried: the request is not idempotent and may "
                        f"have been applied; re-read the map to learn the "
                        f"outcome): {e}") from e
        raise IOError_(
            f"coordinator {path} unreachable after "
            f"{self.options.max_attempts} attempts: {last}") from last

    def install_map(self, cfg, placement=None):
        return self._call("POST", "/lease/cas_map",
                          {"expected_version": 0, "map": cfg,
                           "placement": placement})

    def cas_map(self, expected_version, cfg, placement=None):
        return self._call("POST", "/lease/cas_map",
                          {"expected_version": expected_version, "map": cfg,
                           "placement": placement})

    def get_map(self):
        return self._call("GET", "/lease/map")

    def bump_epoch(self, shard, token):
        return self._call("POST", "/lease/bump_epoch",
                          {"shard": shard, "token": token})

    def acquire(self, shard, holder, ttl=None):
        return self._call("POST", "/lease/acquire",
                          {"shard": shard, "holder": holder, "ttl": ttl})

    def renew(self, shard, holder, token, ttl=None):
        return self._call("POST", "/lease/renew",
                          {"shard": shard, "holder": holder, "token": token,
                           "ttl": ttl})

    def release(self, shard, holder, token):
        return self._call("POST", "/lease/release",
                          {"shard": shard, "holder": holder, "token": token})

    def reassign(self, shard, holder, *, token=None, url=None, force=False,
                 ttl=None):
        return self._call("POST", "/lease/reassign",
                          {"shard": shard, "holder": holder, "token": token,
                           "url": url, "force": force, "ttl": ttl})

    def status(self):
        return self._call("GET", "/lease/status")


# ---------------------------------------------------------------------------
# Process entry point: python -m toplingdb_tpu.sharding.lease ...
# ---------------------------------------------------------------------------


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="lease-coordinator")
    ap.add_argument("--log", required=True, help="durable JSONL log path")
    ap.add_argument("--port", type=int, default=0)
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--ttl", type=float, default=DEFAULT_TTL)
    ap.add_argument("--grace", type=float, default=DEFAULT_GRACE)
    args = ap.parse_args(argv)

    from toplingdb_tpu.utils.statistics import Statistics

    co = LeaseCoordinator(args.log, default_ttl=args.ttl, grace=args.grace,
                          statistics=Statistics())
    srv = LeaseCoordinatorServer(co)
    port = srv.start(args.port, host=args.host)
    done = threading.Event()

    def _term(signum, frame):
        done.set()

    signal.signal(signal.SIGTERM, _term)
    signal.signal(signal.SIGINT, _term)
    print(f"READY {port}", flush=True)
    done.wait()
    srv.stop()
    co.close()
    return 0


if __name__ == "__main__":
    sys.exit(main())
