"""ShardBalancer: split/merge decisions from per-shard size + traffic.

The decision loop is deliberately mechanical (no ML, no history beyond one
snapshot): a shard whose approximate on-disk+memtable size exceeds
`split_bytes`, or whose write traffic since the last tick exceeds
`split_writes`, is split at a median key; two ADJACENT shards that are
both tiny (< `merge_bytes`) and share a backing primary are merged
metadata-only. run_once() returns the actions it took so operators (and
tools/shard_admin.py --balance) can audit every topology change.

Split-key selection prefers SST boundary keys (free — they already live in
the version metadata and land near the data's real mass), falling back to
an iterator sample for memtable-only shards.
"""

from __future__ import annotations

import dataclasses

from toplingdb_tpu.db import dbformat
from toplingdb_tpu.utils import errors as _errors


@dataclasses.dataclass
class BalancerOptions:
    split_bytes: int = 256 << 20
    split_writes: int = 0          # writes/tick; 0 = size-only splits
    merge_bytes: int = 8 << 20
    max_shards: int = 64
    min_shards: int = 1


class ShardBalancer:
    def __init__(self, router, options: BalancerOptions | None = None):
        self.router = router
        self.options = options or BalancerOptions()
        self._last_traffic: dict[str, dict] = {}

    # -- measurements -----------------------------------------------------

    def shard_size(self, name: str) -> int:
        """Approximate bytes owned by the shard: SST bytes in its range
        plus the primary's memtable usage scaled by nothing (cheap upper
        bound — the memtable may hold other shards' keys when stacks are
        shared post-split)."""
        shard = self.router.map.get(name)
        db = self.router._serving(name).primary
        lo = shard.start if shard.start is not None else b""
        hi = shard.end
        if hi is None:
            # An effectively-infinite upper bound: past any real user key.
            hi = (lo or b"") + b"\xff" * 64
        try:
            size = db.get_approximate_sizes([(lo, hi)])[0]
        except Exception as e:
            _errors.swallow(reason="shard-size-probe", exc=e)
            size = 0
        try:
            cfs = getattr(db, "_cfs", {})
            size += sum(c.mem.approximate_memory_usage()
                        for c in cfs.values())
        except Exception as e:
            _errors.swallow(reason="shard-mem-size-probe", exc=e)
        return size

    def pick_split_key(self, name: str) -> bytes | None:
        """A key strictly inside the shard's range, near its data median:
        SST file boundary user keys inside the range when available, else
        an iterator sample (every 16th key, capped)."""
        shard = self.router.map.get(name)
        db = self.router._serving(name).primary
        candidates: list[bytes] = []
        try:
            version = db.versions.current
            for level in range(version.num_levels):
                for f in version.files[level]:
                    for ik in (f.smallest, f.largest):
                        uk = dbformat.extract_user_key(ik)
                        if shard.contains(uk) and uk != shard.start:
                            candidates.append(uk)
        except Exception as e:
            _errors.swallow(reason="split-key-file-scan", exc=e)
            candidates = []
        if len(candidates) < 3:
            it = db.new_iterator()
            try:
                if shard.start is None:
                    it.seek_to_first()
                else:
                    it.seek(shard.start)
                n = 0
                while it.valid() and n < 4096:
                    k = it.key()
                    if shard.end is not None and k >= shard.end:
                        break
                    if n % 16 == 0 and k != shard.start:
                        candidates.append(k)
                    n += 1
                    it.next()
            finally:
                close = getattr(it, "close", None)
                if close is not None:
                    close()
        candidates = sorted(set(candidates))
        if not candidates:
            return None
        key = candidates[len(candidates) // 2]
        if (shard.start is not None and key <= shard.start) or \
                (shard.end is not None and key >= shard.end):
            return None
        return key

    def _write_delta(self, name: str, traffic: dict) -> int:
        cur = traffic.get(name, {}).get("writes", 0)
        prev = self._last_traffic.get(name, {}).get("writes", 0)
        return max(0, cur - prev)

    # -- the decision loop ------------------------------------------------

    def run_once(self) -> list[dict]:
        """One balancing pass: at most one split and one merge (topology
        changes are rare and should be observable one at a time)."""
        opts = self.options
        router = self.router
        actions: list[dict] = []
        traffic = router.traffic()
        names = router.map.names()

        if len(names) < opts.max_shards:
            for name in names:
                size = self.shard_size(name)
                hot = (opts.split_writes > 0
                       and self._write_delta(name, traffic)
                       >= opts.split_writes)
                if size < opts.split_bytes and not hot:
                    continue
                key = self.pick_split_key(name)
                if key is None:
                    continue
                left, right = router.split_shard(name, key)
                actions.append({
                    "action": "split", "shard": name,
                    "split_key_hex": key.hex(), "bytes": size,
                    "hot": hot, "left": left.name, "right": right.name,
                })
                break

        if len(router.map.names()) > opts.min_shards:
            shards = list(router.map.shards)
            for a, b in zip(shards, shards[1:]):
                sa = router._servings.get(a.name)
                sb = router._servings.get(b.name)
                if sa is None or sb is None or sa.primary is not sb.primary:
                    continue  # cross-backend merges are an operator call
                if self.shard_size(a.name) >= opts.merge_bytes or \
                        self.shard_size(b.name) >= opts.merge_bytes:
                    continue
                router.merge_shards(a.name, b.name)
                actions.append({"action": "merge", "left": a.name,
                                "right": b.name})
                break

        self._last_traffic = traffic
        return actions
