"""Per-tenant / per-shard admission control for the front-door router.

Two cooperating mechanisms (the ROADMAP's "one hot tenant can't starve the
rest" bar):

  rate limits      one token bucket per (tenant, dimension) reusing
                   utils/rate_limiter.py's RateLimiter — bytes/sec and
                   ops/sec, enforced with a BOUNDED wait
                   (TenantQuota.max_wait) after which the write is shed
                   with Busy instead of queueing unboundedly.
  stall shedding   when the target shard's primary reports
                   write_stall_state() == "stopped" (L0 past the stop
                   trigger), a tenant whose bucket is EMPTY is shed
                   immediately — zero wait — so the stalled shard's
                   capacity drains to in-quota tenants and siblings keep
                   serving. In-quota writes still pass through (and then
                   block inside _maybe_stall_writes like any other write):
                   backpressure, not a brownout.

The controller is deliberately router-agnostic: admit_write(tenant,
nbytes, stall_state) is the whole contract, so tests can drive it directly
and the ShardRouter just forwards the shard's live stall state.
"""

from __future__ import annotations

import dataclasses
import threading

from toplingdb_tpu.utils import concurrency as ccy
import time

from toplingdb_tpu.utils import statistics as stats_mod
from toplingdb_tpu.utils.rate_limiter import RateLimiter
from toplingdb_tpu.utils.status import Busy


@dataclasses.dataclass
class TenantQuota:
    """0 = unlimited for either dimension."""

    write_bytes_per_sec: int = 0
    write_ops_per_sec: int = 0
    # Bounded bucket wait before the write is shed with Busy.
    max_wait: float = 0.25
    # Shed with zero wait while the target shard is stall-stopped.
    shed_on_stall: bool = True


class AdmissionController:
    """Token-bucket admission with stall-aware shedding. One instance per
    ShardRouter; quotas are keyed by tenant name (None = the anonymous
    tenant, governed by `default_quota` when set)."""

    def __init__(self, default_quota: TenantQuota | None = None,
                 statistics=None):
        self.default_quota = default_quota
        self.stats = statistics
        self._mu = ccy.Lock("admission.AdmissionController._mu")
        self._quotas: dict[str | None, TenantQuota] = {}
        # (tenant, "bytes"|"ops") → RateLimiter
        self._buckets: dict[tuple, RateLimiter] = {}
        self.shed_count = 0
        self.waited_count = 0

    def set_quota(self, tenant: str | None, quota: TenantQuota) -> None:
        with self._mu:
            self._quotas[tenant] = quota
            # Rate changes rebuild the buckets lazily.
            self._buckets.pop((tenant, "bytes"), None)
            self._buckets.pop((tenant, "ops"), None)

    def quota_for(self, tenant: str | None) -> TenantQuota | None:
        with self._mu:
            return self._quotas.get(tenant, self.default_quota)

    def _bucket(self, tenant, dim: str, rate: int) -> RateLimiter:
        with self._mu:
            b = self._buckets.get((tenant, dim))
            if b is None or b.rate != rate:
                b = RateLimiter(rate)
                self._buckets[(tenant, dim)] = b
            return b

    def _tick(self, name: str) -> None:
        if self.stats is not None:
            self.stats.record_tick(name)

    def admit_write(self, tenant: str | None, nbytes: int,
                    stall_state: str = "none",
                    disk_pressure: str = "ok") -> float:
        """Admit or shed one write of `nbytes` from `tenant` against a
        shard currently in `stall_state`. Returns the seconds spent
        waiting on buckets (0.0 for the fast path); raises Busy when shed.

        `disk_pressure` is the target shard's storage-pressure level
        (DB.disk_pressure()): at "red" EVERY write is shed immediately,
        quota or not — accepting it would push the shard into the ENOSPC
        latch and take reads down with it. Shedding here keeps the shard
        serving reads while the reclaim ladder frees space; callers
        retry against the 503/Busy like any stall shed."""
        if disk_pressure == "red":
            self.shed_count += 1
            self._tick(stats_mod.NO_SPACE_WRITES_SHED)
            self._tick(stats_mod.SHARD_WRITES_SHED)
            raise Busy(
                f"tenant {tenant!r} shed: shard at red disk pressure")
        quota = self.quota_for(tenant)
        if quota is None:
            return 0.0
        budget = (0.0 if (stall_state == "stopped" and quota.shed_on_stall)
                  else quota.max_wait)
        t0 = time.monotonic()
        for dim, rate, n in (("ops", quota.write_ops_per_sec, 1),
                             ("bytes", quota.write_bytes_per_sec, nbytes)):
            if rate <= 0:
                continue
            remaining = max(0.0, budget - (time.monotonic() - t0))
            if not self._bucket(tenant, dim, rate).try_request(
                    n, timeout=remaining):
                self.shed_count += 1
                self._tick(stats_mod.SHARD_WRITES_SHED)
                raise Busy(
                    f"tenant {tenant!r} over {dim} quota "
                    f"({rate}/s, stall_state={stall_state})")
        waited = time.monotonic() - t0
        if waited > 0.001:
            self.waited_count += 1
            self._tick(stats_mod.SHARD_ADMISSION_WAITS)
        return waited

    def status(self) -> dict:
        with self._mu:
            quotas = {
                str(t): dataclasses.asdict(q)
                for t, q in sorted(self._quotas.items(),
                                   key=lambda kv: str(kv[0]))
            }
        return {
            "default_quota": (dataclasses.asdict(self.default_quota)
                              if self.default_quota else None),
            "quotas": quotas,
            "shed_count": self.shed_count,
            "waited_count": self.waited_count,
        }
