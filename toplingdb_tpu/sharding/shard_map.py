"""ShardMap: versioned key-range → shard metadata, epoch-stamped.

The map is PURE metadata — names, [start, end) user-key ranges, per-shard
epochs, and serving state — deliberately free of any backend wiring so it
can be JSON-persisted (the utils/config.py SidePlugin shape), shipped over
the HTTP control plane, and diffed between processes. The ShardRouter
resolves names to serving stacks separately.

Invariants (checked by validate(), enforced by every mutator):

  - shards are sorted by start key and EXACTLY partition the keyspace:
    the first shard starts at -inf (None), the last ends at +inf (None),
    and every shard's end equals the next shard's start — no gaps, no
    overlap, so a key routes to exactly ONE shard (the no-double-serve
    half of the chaos-soak acceptance bar).
  - epochs are allocated from a map-wide monotonic counter and NEVER
    reused: any topology change (split/merge/migration cutover) gives the
    affected shards fresh epochs, so a staleness token stamped under the
    old epoch can never compare equal again.
  - `version` increments on every mutation — cheap "did anything change"
    probe for caches and the HTTP view.
"""

from __future__ import annotations

import dataclasses
import json
import threading

from toplingdb_tpu.utils import concurrency as ccy

from toplingdb_tpu.utils.status import InvalidArgument, NotFound

# Serving states a shard moves through (migration.py drives the cycle).
SHARD_STATES = ("serving", "migrating", "fenced")


@dataclasses.dataclass
class Shard:
    """One key-range: [start, end) with None as -inf/+inf open bounds."""

    name: str
    start: bytes | None  # inclusive; None = -inf
    end: bytes | None    # exclusive; None = +inf
    epoch: int = 1
    state: str = "serving"

    def contains(self, key: bytes) -> bool:
        if self.start is not None and key < self.start:
            return False
        if self.end is not None and key >= self.end:
            return False
        return True

    def clip(self, begin: bytes | None, end: bytes | None):
        """Intersection of [begin, end) with this shard's range, as a
        (begin, end) pair with the same None-as-infinity convention, or
        None when the ranges are disjoint."""
        b = self.start if begin is None else (
            begin if self.start is None else max(begin, self.start))
        e = self.end if end is None else (
            end if self.end is None else min(end, self.end))
        if b is not None and e is not None and b >= e:
            return None
        return b, e

    def to_config(self) -> dict:
        return {
            "name": self.name,
            # hex keeps arbitrary key bytes JSON-safe; null = open bound
            "start_hex": self.start.hex() if self.start is not None else None,
            "end_hex": self.end.hex() if self.end is not None else None,
            "epoch": self.epoch,
            "state": self.state,
        }

    @staticmethod
    def from_config(cfg: dict) -> "Shard":
        sh = cfg.get("start_hex")
        eh = cfg.get("end_hex")
        return Shard(
            name=cfg["name"],
            start=bytes.fromhex(sh) if sh is not None else None,
            end=bytes.fromhex(eh) if eh is not None else None,
            epoch=int(cfg.get("epoch", 1)),
            state=cfg.get("state", "serving"),
        )


class ShardMap:
    """Sorted, contiguous, epoch-stamped shard table. All mutators bump
    `version`; epoch allocation is monotonic across the map's lifetime
    (persisted, so a reloaded map cannot re-issue an old epoch)."""

    def __init__(self, shards: list[Shard] | None = None):
        self._mu = ccy.RLock("shard_map.ShardMap._mu")
        self.shards: list[Shard] = list(shards) if shards else [
            Shard(name="s0", start=None, end=None, epoch=1)
        ]
        self.version = 1
        self._next_epoch = max(s.epoch for s in self.shards) + 1
        self._name_seq = len(self.shards)
        self.validate()

    @staticmethod
    def from_bounds(bounds: list[tuple[str, bytes | None, bytes | None]]
                    ) -> "ShardMap":
        """Build from explicit (name, start, end) rows (cluster setup)."""
        return ShardMap([Shard(name=n, start=s, end=e, epoch=i + 1)
                         for i, (n, s, e) in enumerate(bounds)])

    @staticmethod
    def uniform(n: int, key_width: int = 16, prefix: str = "s") -> "ShardMap":
        """n equal-width shards over fixed-width big-endian byte keys —
        the bench/README "4-shard local cluster" shape. Split points are
        the top byte of the key space scaled by i/n."""
        if n < 1:
            raise InvalidArgument("uniform shard count must be >= 1")
        bounds = []
        for i in range(n):
            start = None if i == 0 else \
                bytes([256 * i // n]) + b"\x00" * (key_width - 1)
            end = None if i == n - 1 else \
                bytes([256 * (i + 1) // n]) + b"\x00" * (key_width - 1)
            bounds.append((f"{prefix}{i}", start, end))
        return ShardMap.from_bounds(bounds)

    # -- introspection ----------------------------------------------------

    def validate(self) -> None:
        with self._mu:
            if not self.shards:
                raise InvalidArgument("shard map is empty")
            names = [s.name for s in self.shards]
            if len(set(names)) != len(names):
                raise InvalidArgument(f"duplicate shard names: {names}")
            if self.shards[0].start is not None:
                raise InvalidArgument("first shard must start at -inf")
            if self.shards[-1].end is not None:
                raise InvalidArgument("last shard must end at +inf")
            for a, b in zip(self.shards, self.shards[1:]):
                if a.end is None or b.start is None or a.end != b.start:
                    raise InvalidArgument(
                        f"shards {a.name}/{b.name} do not tile: "
                        f"{a.end!r} != {b.start!r}")

    def get(self, name: str) -> Shard:
        with self._mu:
            for s in self.shards:
                if s.name == name:
                    return s
        raise NotFound(f"no shard named {name!r}")

    def shard_for(self, key: bytes) -> Shard:
        """The unique shard whose range contains `key` (binary search on
        the sorted start bounds)."""
        with self._mu:
            shards = self.shards
            lo, hi = 0, len(shards) - 1
            while lo < hi:  # last shard with start <= key
                mid = (lo + hi + 1) // 2
                st = shards[mid].start
                if st is not None and key < st:
                    hi = mid - 1
                else:
                    lo = mid
            return shards[lo]

    def epoch_of(self, name: str) -> int:
        return self.get(name).epoch

    def names(self) -> list[str]:
        with self._mu:
            return [s.name for s in self.shards]

    # -- mutation ---------------------------------------------------------

    def _alloc_epoch(self) -> int:
        e = self._next_epoch
        self._next_epoch += 1
        return e

    def _alloc_name(self, hint: str | None = None) -> str:
        with self._mu:
            taken = {s.name for s in self.shards}
            if hint and hint not in taken:
                return hint
            while True:
                name = f"s{self._name_seq}"
                self._name_seq += 1
                if name not in taken:
                    return name

    def bump_epoch(self, name: str) -> int:
        """Fresh epoch for one shard (migration cutover): every token
        stamped under the old epoch is now rejected by the routers."""
        with self._mu:
            s = self.get(name)
            s.epoch = self._alloc_epoch()
            self.version += 1
            return s.epoch

    def adopt_epoch(self, name: str, epoch: int) -> None:
        """Adopt a coordinator-assigned epoch (fleet cutover/promotion).
        The map-wide allocator floor rises past it so locally allocated
        epochs can never collide with coordinator-issued ones."""
        with self._mu:
            s = self.get(name)
            if epoch < s.epoch:
                raise InvalidArgument(
                    f"epoch for {name!r} may not move backwards "
                    f"({s.epoch} -> {epoch})")
            s.epoch = epoch
            self._next_epoch = max(self._next_epoch, epoch + 1)
            self.version += 1

    def set_state(self, name: str, state: str) -> None:
        if state not in SHARD_STATES:
            raise InvalidArgument(f"unknown shard state {state!r}")
        with self._mu:
            self.get(name).state = state
            self.version += 1

    def split(self, name: str, split_key: bytes,
              right_name: str | None = None) -> tuple[Shard, Shard]:
        """Split one shard at `split_key` (strictly inside its range):
        the left half keeps the name (fresh epoch), the right half gets
        `right_name` or a generated one. Returns (left, right)."""
        with self._mu:
            s = self.get(name)
            if (s.start is not None and split_key <= s.start) or \
                    (s.end is not None and split_key >= s.end):
                raise InvalidArgument(
                    f"split key {split_key!r} outside shard {name!r} "
                    f"range [{s.start!r}, {s.end!r})")
            idx = self.shards.index(s)
            left = Shard(name=s.name, start=s.start, end=split_key,
                         epoch=self._alloc_epoch(), state=s.state)
            right = Shard(name=self._alloc_name(right_name),
                          start=split_key, end=s.end,
                          epoch=self._alloc_epoch(), state=s.state)
            self.shards[idx:idx + 1] = [left, right]
            self.version += 1
            self.validate()
            return left, right

    def merge(self, left_name: str, right_name: str) -> Shard:
        """Merge two ADJACENT shards into one carrying the left name and a
        fresh epoch."""
        with self._mu:
            l, r = self.get(left_name), self.get(right_name)
            li = self.shards.index(l)
            if li + 1 >= len(self.shards) or self.shards[li + 1] is not r:
                raise InvalidArgument(
                    f"shards {left_name!r}/{right_name!r} are not adjacent")
            merged = Shard(name=l.name, start=l.start, end=r.end,
                           epoch=self._alloc_epoch())
            self.shards[li:li + 2] = [merged]
            self.version += 1
            self.validate()
            return merged

    # -- persistence (the utils/config.py JSON shape) ---------------------

    def to_config(self) -> dict:
        with self._mu:
            return {
                "version": self.version,
                "next_epoch": self._next_epoch,
                "shards": [s.to_config() for s in self.shards],
            }

    @staticmethod
    def from_config(cfg: dict) -> "ShardMap":
        m = ShardMap([Shard.from_config(s) for s in cfg["shards"]])
        m.version = int(cfg.get("version", m.version))
        # Epoch monotonicity must survive reload: never below what the
        # persisted map had already handed out.
        m._next_epoch = max(m._next_epoch, int(cfg.get("next_epoch", 0)))
        return m

    def save(self, path: str, env=None) -> None:
        """Crash-atomic: the new map is written (and fsynced) to a side
        file, then renamed over `path` — a kill at any instant leaves
        either the complete old map or the complete new one, never a
        torn prefix. Readers must ignore stray `.tmp` files."""
        if env is None:
            from toplingdb_tpu.env import default_env

            env = default_env()
        tmp = path + ".tmp"
        env.write_file(tmp, json.dumps(self.to_config(), indent=1).encode(),
                       sync=True)
        env.rename_file(tmp, path)

    @staticmethod
    def load(path: str, env=None) -> "ShardMap":
        if env is None:
            from toplingdb_tpu.env import default_env

            env = default_env()
        return ShardMap.from_config(json.loads(env.read_file(path).decode()))
