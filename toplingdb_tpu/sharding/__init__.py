"""Sharding plane: many primaries behind one front door.

PR 4's replication plane scaled reads vertically (one primary, N
followers); this package scales the write axis horizontally — the layer
that turns a single DB into a fleet (ROADMAP item 3):

  shard_map   versioned key-range → shard metadata: epoch-stamped,
              JSON-persistable, gap/overlap-free by construction.
  router      ShardRouter — the front door: routes by key range, composes
              with replication.router.ReplicaRouter per shard (each shard
              owns its follower set and read-your-writes tokens; tokens
              carry the shard epoch so a split/merge/migration invalidates
              them cleanly), write fences for topology changes, and
              per-tenant admission control.
  admission   token-bucket rate limits (utils/rate_limiter.py) + write-
              stall shedding fed by DB.write_stall_state().
  migration   live shard migration: checkpoint bootstrap → WAL-shipping
              catch-up (the dual-write window) → fence/drain →
              promote-style cutover with an epoch bump.
  balancer    split/merge decisions from per-shard size/traffic stats.
  lease       the fleet's consensus substrate: a single-coordinator lease
              store with monotonic fencing tokens, epoch CAS on map
              mutations, and a durable replayable log (out-of-process
              deployments; PR 16).
  fleet       the out-of-process deployment: ShardServer processes behind
              HTTP, the lease-validated FleetRouter front door, and the
              crash-safe FleetSupervisor (heartbeats, promotion on
              primary death, cross-process migration + recovery).
"""

from toplingdb_tpu.sharding.admission import AdmissionController, TenantQuota
from toplingdb_tpu.sharding.balancer import BalancerOptions, ShardBalancer
from toplingdb_tpu.sharding.fleet import (
    FleetRouter,
    FleetSupervisor,
    ShardServer,
)
from toplingdb_tpu.sharding.lease import (
    LeaseClient,
    LeaseConflict,
    LeaseCoordinator,
    LeaseCoordinatorServer,
)
from toplingdb_tpu.sharding.migration import MigrationAborted, ShardMigration
from toplingdb_tpu.sharding.router import ShardRouter, ShardServing, ShardToken
from toplingdb_tpu.sharding.shard_map import Shard, ShardMap

__all__ = [
    "AdmissionController",
    "BalancerOptions",
    "FleetRouter",
    "FleetSupervisor",
    "LeaseClient",
    "LeaseConflict",
    "LeaseCoordinator",
    "LeaseCoordinatorServer",
    "MigrationAborted",
    "Shard",
    "ShardBalancer",
    "ShardMap",
    "ShardMigration",
    "ShardRouter",
    "ShardServer",
    "ShardServing",
    "ShardToken",
    "TenantQuota",
    "open_local_cluster",
]


def open_local_cluster(base_dir: str, bounds, options_factory=None,
                       statistics=None, admission=None,
                       fence_timeout: float = 5.0) -> ShardRouter:
    """Stand up one DB instance per shard under `base_dir` and return the
    ShardRouter fronting them — the README/bench "4-shard local cluster"
    in one call. `bounds` is a list of (name, start, end) rows (None =
    open bound) or an int N for N uniform shards over fixed-width keys.
    `options_factory(shard_name)` builds each primary's Options (default:
    fresh Options(create_if_missing=True)). Close with router.close()."""
    import os

    from toplingdb_tpu.db.db import DB
    from toplingdb_tpu.options import Options

    if isinstance(bounds, int):
        shard_map = ShardMap.uniform(bounds)
    else:
        shard_map = ShardMap.from_bounds(list(bounds))
    router = ShardRouter(shard_map, statistics=statistics,
                         admission=admission, fence_timeout=fence_timeout)
    for name in shard_map.names():
        if options_factory is not None:
            opts = options_factory(name)
        else:
            opts = Options(create_if_missing=True)
            opts.statistics = statistics
        db = DB.open(os.path.join(base_dir, name), opts)
        router.attach_shard(name, db)
    return router
