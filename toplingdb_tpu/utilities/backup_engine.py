"""BackupEngine: incremental backups with shared-file dedup
(reference utilities/backup/backup_engine.cc in /root/reference).

Layout under backup_dir:
  shared/<file_size>_<crc32c>_<name>.sst    content-addressed SSTs
  meta/<backup_id>.json                     manifest of one backup
  private/<backup_id>/                      per-backup MANIFEST/CURRENT copy
Restore rebuilds a DB dir from a backup id.
"""

from __future__ import annotations

import json
import os
import shutil

from toplingdb_tpu.db import filename
from toplingdb_tpu.utils import crc32c
from toplingdb_tpu.utils.status import InvalidArgument, NotFound


class BackupEngine:
    def __init__(self, backup_dir: str):
        from toplingdb_tpu.utils import concurrency as ccy

        self.dir = backup_dir
        # Serializes create/delete/purge/GC: shared files and private dirs
        # land BEFORE their meta json, so an unsynchronized GC could sweep
        # a half-created backup's files as unreferenced garbage.
        self._mu = ccy.Lock("backup_engine.BackupEngine._mu")
        os.makedirs(os.path.join(backup_dir, "shared"), exist_ok=True)
        os.makedirs(os.path.join(backup_dir, "meta"), exist_ok=True)
        os.makedirs(os.path.join(backup_dir, "private"), exist_ok=True)

    # ------------------------------------------------------------------

    def _next_backup_id(self) -> int:
        ids = [int(f.split(".")[0]) for f in os.listdir(os.path.join(self.dir, "meta"))
               if f.split(".")[0].isdigit()]
        return max(ids, default=0) + 1

    def create_backup(self, db, app_metadata: str | None = None) -> int:
        """Snapshot the DB (checkpoint = atomic consistent view), then dedup
        its SSTs into shared/ — the file list and the MANIFEST come from the
        SAME checkpoint, so concurrent compactions can't skew them.
        app_metadata: reference CreateNewBackupWithMetadata."""
        from toplingdb_tpu.utilities.checkpoint import create_checkpoint

        with self._mu:
            return self._create_backup_locked(db, app_metadata,
                                              create_checkpoint)

    def _create_backup_locked(self, db, app_metadata, create_checkpoint):
        backup_id = self._next_backup_id()
        private = os.path.join(self.dir, "private", str(backup_id))
        os.makedirs(private, exist_ok=True)
        tmp_ckpt = private + ".ckpt"
        if os.path.exists(tmp_ckpt):
            shutil.rmtree(tmp_ckpt)
        create_checkpoint(db, tmp_ckpt)
        files = []
        for name in sorted(os.listdir(tmp_ckpt)):
            ftype, num = filename.parse_file_name(name)
            path = os.path.join(tmp_ckpt, name)
            if ftype != filename.FileType.TABLE:
                shutil.copy2(path, os.path.join(private, name))
                continue
            with open(path, "rb") as s:
                data = s.read()
            crc = crc32c.value(data)
            shared_name = f"{len(data)}_{crc:08x}_{num:06d}.sst"
            shared_path = os.path.join(self.dir, "shared", shared_name)
            if not os.path.exists(shared_path):
                with open(shared_path + ".tmp", "wb") as d:
                    d.write(data)
                os.replace(shared_path + ".tmp", shared_path)
            files.append({
                "number": num, "shared": shared_name,
                "size": len(data), "crc32c": crc,
            })
        shutil.rmtree(tmp_ckpt)
        import time as _time

        meta = {"backup_id": backup_id, "files": files,
                "timestamp": int(_time.time()),
                "app_metadata": app_metadata}
        meta_path = os.path.join(self.dir, "meta", f"{backup_id}.json")
        with open(meta_path + ".tmp", "w") as f:
            json.dump(meta, f, indent=1)
        os.replace(meta_path + ".tmp", meta_path)
        return backup_id

    def get_backup_info(self) -> list[dict]:
        out = []
        meta_dir = os.path.join(self.dir, "meta")
        ids = sorted(
            int(name[:-5]) for name in os.listdir(meta_dir)
            if name.endswith(".json") and name[:-5].isdigit()
        )
        for bid in ids:  # numeric order: purge must drop OLDEST first
            with open(os.path.join(meta_dir, f"{bid}.json")) as f:
                m = json.load(f)
            out.append({
                "backup_id": m["backup_id"],
                "num_files": len(m["files"]),
                "size": sum(f["size"] for f in m["files"]),
                "timestamp": m.get("timestamp", 0),
                "app_metadata": m.get("app_metadata"),
            })
        return out

    def delete_backup(self, backup_id: int) -> None:
        """Drop ONE backup (reference DeleteBackup); shared files still
        referenced by other backups survive."""
        with self._mu:
            meta_path = os.path.join(self.dir, "meta", f"{backup_id}.json")
            if not os.path.exists(meta_path):
                raise NotFound(f"backup {backup_id}")
            os.remove(meta_path)
            shutil.rmtree(os.path.join(self.dir, "private", str(backup_id)),
                          ignore_errors=True)
            self._garbage_collect_locked()

    def verify_backup(self, backup_id: int) -> None:
        """Check every file of one backup exists with the recorded size +
        crc32c (reference VerifyBackup with verify_with_checksum=true);
        raises Corruption/NotFound on any divergence."""
        from toplingdb_tpu.utils.status import Corruption

        meta_path = os.path.join(self.dir, "meta", f"{backup_id}.json")
        if not os.path.exists(meta_path):
            raise NotFound(f"backup {backup_id}")
        with open(meta_path) as f:
            meta = json.load(f)
        for fi in meta["files"]:
            path = os.path.join(self.dir, "shared", fi["shared"])
            if not os.path.exists(path):
                raise Corruption(f"backup {backup_id}: missing {fi['shared']}")
            with open(path, "rb") as s_:
                data = s_.read()
            if len(data) != fi["size"]:
                raise Corruption(
                    f"backup {backup_id}: size mismatch {fi['shared']}")
            if crc32c.value(data) != fi["crc32c"]:
                raise Corruption(
                    f"backup {backup_id}: checksum mismatch {fi['shared']}")
        private = os.path.join(self.dir, "private", str(backup_id))
        if not os.path.isdir(private):
            raise Corruption(f"backup {backup_id}: private dir missing")

    def garbage_collect(self) -> int:
        """Remove shared files and private dirs no live backup references
        (reference BackupEngine::GarbageCollect — cleanup after aborted
        or deleted backups). Returns the number of entries removed."""
        with self._mu:
            return self._garbage_collect_locked()

    def _garbage_collect_locked(self) -> int:
        live = set()
        meta_dir = os.path.join(self.dir, "meta")
        ids = set()
        for name in os.listdir(meta_dir):
            if name.endswith(".json") and name[:-5].isdigit():
                ids.add(int(name[:-5]))
                with open(os.path.join(meta_dir, name)) as f:
                    for fi in json.load(f)["files"]:
                        live.add(fi["shared"])
        removed = 0
        for name in os.listdir(os.path.join(self.dir, "shared")):
            if name not in live:
                os.remove(os.path.join(self.dir, "shared", name))
                removed += 1
        for name in os.listdir(os.path.join(self.dir, "private")):
            if name.isdigit() and int(name) not in ids:
                shutil.rmtree(os.path.join(self.dir, "private", name),
                              ignore_errors=True)
                removed += 1
        return removed

    def restore_db_from_backup(self, backup_id: int, db_dir: str) -> None:
        meta_path = os.path.join(self.dir, "meta", f"{backup_id}.json")
        if not os.path.exists(meta_path):
            raise NotFound(f"backup {backup_id}")
        with open(meta_path) as f:
            meta = json.load(f)
        os.makedirs(db_dir, exist_ok=True)
        for f in meta["files"]:
            src = os.path.join(self.dir, "shared", f["shared"])
            with open(src, "rb") as s:
                data = s.read()
            if crc32c.value(data) != f["crc32c"]:
                from toplingdb_tpu.utils.status import Corruption

                raise Corruption(f"backup file {f['shared']} checksum mismatch")
            dst = filename.table_file_name(db_dir, f["number"])
            with open(dst, "wb") as d:
                d.write(data)
        private = os.path.join(self.dir, "private", str(backup_id))
        for name in os.listdir(private):
            shutil.copy2(os.path.join(private, name), os.path.join(db_dir, name))

    def purge_old_backups(self, num_to_keep: int) -> None:
        with self._mu:
            infos = self.get_backup_info()
            for info in infos[: max(0, len(infos) - num_to_keep)]:
                bid = info["backup_id"]
                os.remove(os.path.join(self.dir, "meta", f"{bid}.json"))
                shutil.rmtree(os.path.join(self.dir, "private", str(bid)),
                              ignore_errors=True)
            self._garbage_collect_locked()
