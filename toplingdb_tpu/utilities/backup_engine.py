"""BackupEngine: incremental backups with shared-file dedup
(reference utilities/backup/backup_engine.cc in /root/reference).

Layout under backup_dir:
  shared/<file_size>_<crc32c>_<name>.sst    content-addressed SSTs
  meta/<backup_id>.json                     manifest of one backup
  private/<backup_id>/                      per-backup MANIFEST/CURRENT copy
Restore rebuilds a DB dir from a backup id.
"""

from __future__ import annotations

import json
import os
import shutil

from toplingdb_tpu.db import filename
from toplingdb_tpu.utils import crc32c
from toplingdb_tpu.utils.status import InvalidArgument, NotFound


class BackupEngine:
    def __init__(self, backup_dir: str):
        self.dir = backup_dir
        os.makedirs(os.path.join(backup_dir, "shared"), exist_ok=True)
        os.makedirs(os.path.join(backup_dir, "meta"), exist_ok=True)
        os.makedirs(os.path.join(backup_dir, "private"), exist_ok=True)

    # ------------------------------------------------------------------

    def _next_backup_id(self) -> int:
        ids = [int(f.split(".")[0]) for f in os.listdir(os.path.join(self.dir, "meta"))
               if f.split(".")[0].isdigit()]
        return max(ids, default=0) + 1

    def create_backup(self, db) -> int:
        """Snapshot the DB (checkpoint = atomic consistent view), then dedup
        its SSTs into shared/ — the file list and the MANIFEST come from the
        SAME checkpoint, so concurrent compactions can't skew them."""
        from toplingdb_tpu.utilities.checkpoint import create_checkpoint

        backup_id = self._next_backup_id()
        private = os.path.join(self.dir, "private", str(backup_id))
        os.makedirs(private, exist_ok=True)
        tmp_ckpt = private + ".ckpt"
        if os.path.exists(tmp_ckpt):
            shutil.rmtree(tmp_ckpt)
        create_checkpoint(db, tmp_ckpt)
        files = []
        for name in sorted(os.listdir(tmp_ckpt)):
            ftype, num = filename.parse_file_name(name)
            path = os.path.join(tmp_ckpt, name)
            if ftype != filename.FileType.TABLE:
                shutil.copy2(path, os.path.join(private, name))
                continue
            with open(path, "rb") as s:
                data = s.read()
            crc = crc32c.value(data)
            shared_name = f"{len(data)}_{crc:08x}_{num:06d}.sst"
            shared_path = os.path.join(self.dir, "shared", shared_name)
            if not os.path.exists(shared_path):
                with open(shared_path + ".tmp", "wb") as d:
                    d.write(data)
                os.replace(shared_path + ".tmp", shared_path)
            files.append({
                "number": num, "shared": shared_name,
                "size": len(data), "crc32c": crc,
            })
        shutil.rmtree(tmp_ckpt)
        meta = {"backup_id": backup_id, "files": files}
        meta_path = os.path.join(self.dir, "meta", f"{backup_id}.json")
        with open(meta_path + ".tmp", "w") as f:
            json.dump(meta, f, indent=1)
        os.replace(meta_path + ".tmp", meta_path)
        return backup_id

    def get_backup_info(self) -> list[dict]:
        out = []
        meta_dir = os.path.join(self.dir, "meta")
        ids = sorted(
            int(name[:-5]) for name in os.listdir(meta_dir)
            if name.endswith(".json") and name[:-5].isdigit()
        )
        for bid in ids:  # numeric order: purge must drop OLDEST first
            with open(os.path.join(meta_dir, f"{bid}.json")) as f:
                m = json.load(f)
            out.append({
                "backup_id": m["backup_id"],
                "num_files": len(m["files"]),
                "size": sum(f["size"] for f in m["files"]),
            })
        return out

    def restore_db_from_backup(self, backup_id: int, db_dir: str) -> None:
        meta_path = os.path.join(self.dir, "meta", f"{backup_id}.json")
        if not os.path.exists(meta_path):
            raise NotFound(f"backup {backup_id}")
        with open(meta_path) as f:
            meta = json.load(f)
        os.makedirs(db_dir, exist_ok=True)
        for f in meta["files"]:
            src = os.path.join(self.dir, "shared", f["shared"])
            with open(src, "rb") as s:
                data = s.read()
            if crc32c.value(data) != f["crc32c"]:
                from toplingdb_tpu.utils.status import Corruption

                raise Corruption(f"backup file {f['shared']} checksum mismatch")
            dst = filename.table_file_name(db_dir, f["number"])
            with open(dst, "wb") as d:
                d.write(data)
        private = os.path.join(self.dir, "private", str(backup_id))
        for name in os.listdir(private):
            shutil.copy2(os.path.join(private, name), os.path.join(db_dir, name))

    def purge_old_backups(self, num_to_keep: int) -> None:
        infos = self.get_backup_info()
        to_drop = infos[: max(0, len(infos) - num_to_keep)]
        keep_ids = {i["backup_id"] for i in infos} - {i["backup_id"] for i in to_drop}
        # Collect shared files still referenced.
        referenced = set()
        for bid in keep_ids:
            with open(os.path.join(self.dir, "meta", f"{bid}.json")) as f:
                for fi in json.load(f)["files"]:
                    referenced.add(fi["shared"])
        for info in to_drop:
            bid = info["backup_id"]
            os.remove(os.path.join(self.dir, "meta", f"{bid}.json"))
            shutil.rmtree(os.path.join(self.dir, "private", str(bid)),
                          ignore_errors=True)
        for name in os.listdir(os.path.join(self.dir, "shared")):
            if name not in referenced:
                os.remove(os.path.join(self.dir, "shared", name))
