"""Option-change migration.

Analogue of the reference's option_change_migration
(utilities/option_change_migration/option_change_migration.cc): reshape an
existing DB's file layout so a different compaction style's invariants hold
before reopening with the new options:

  * → leveled: any layout is legal; a full manual compaction tidies it.
  * → universal: the picker sees L0 runs + one base run in the last level;
    a full compaction leaves exactly that shape.
  * → fifo: ALL files must live in L0 (fifo only ever looks there); after
    compacting, every file is MOVED to L0 (overlap-legal).
"""

from __future__ import annotations

from toplingdb_tpu.db.db import DB
from toplingdb_tpu.db.version_edit import VersionEdit
from toplingdb_tpu.options import Options


def migrate_options(dbname: str, from_options: Options, to_options: Options,
                    env=None) -> None:
    """Run the migration and persist the new options. The DB must be closed;
    it is reopened briefly twice (old options to reshape, new to validate)."""
    with DB.open(dbname, from_options, env=env) as db:
        db.compact_range()  # one sorted run at the bottom
        if to_options.compaction_style == "fifo":
            moved = False
            with db._mutex:
                for cf_id in db.versions.column_families:
                    v = db.versions.cf_current(cf_id)
                    edit = VersionEdit(column_family=cf_id)
                    any_move = False
                    for level in range(1, v.num_levels):
                        for f in v.files[level]:
                            edit.delete_file(level, f.number)
                            edit.add_file(0, f)
                            any_move = True
                    if any_move:
                        db.versions.log_and_apply(edit)
                        moved = True
            if moved:
                db.event_logger.log("option_migration_moved_to_l0")
    # Validate + persist the new options (writes a fresh OPTIONS file).
    DB.open(dbname, to_options, env=env).close()
