"""Transactions: pessimistic (2PL) and optimistic, over WriteBatchWithIndex.

Reference utilities/transactions/ in /root/reference:
  * PointLockManager — striped lock maps + deadlock detection
    (point_lock_manager.cc:64-98; the Topling fork rebuilds it on terark
    hash maps for 5x — ours uses striped dicts, the Python-native analogue).
  * PessimisticTransactionDB (WriteCommitted policy): writes take point locks
    at write time; commit applies the indexed batch atomically; supports 2PC
    prepare/commit.
  * OptimisticTransactionDB: conflict check at commit via per-key sequence
    validation (optimistic_transaction_db_impl.cc).
"""

from __future__ import annotations

import threading

from toplingdb_tpu.utils import concurrency as ccy
import time

from toplingdb_tpu.db.db import DB
from toplingdb_tpu.options import Options, ReadOptions, WriteOptions
from toplingdb_tpu.utilities.write_batch_with_index import WriteBatchWithIndex
from toplingdb_tpu.utils.status import Busy, Expired, InvalidArgument, TryAgain
from toplingdb_tpu.utils import errors as _errors

NUM_STRIPES = 16


class DeadlockError(Busy):
    pass


def _has_wait_cycle(waits_for: dict, waiter: int, holder: int,
                    max_steps: int = 256) -> bool:
    """Would waiter→holder close a cycle? DFS over the wait-for graph;
    values may be a single txn id (point locks: one holder per key) or a
    set of ids (range locks: many holders per interval). Callers hold
    their own lock around waits_for."""
    seen = set()
    stack = [holder]
    steps = 0
    while stack and steps < max_steps:
        cur = stack.pop()
        steps += 1
        if cur == waiter:
            return True
        if cur in seen:
            continue
        seen.add(cur)
        nxt = waits_for.get(cur)
        if nxt is None:
            continue
        if isinstance(nxt, (set, frozenset)):
            stack.extend(nxt)
        else:
            stack.append(nxt)
    return False


class PointLockManager:
    """Striped exclusive point locks with wait-for-graph deadlock detection."""

    def __init__(self, num_stripes: int = NUM_STRIPES):
        self._stripes = [
            {"mu": ccy.Lock("transactions.PointLockManager.stripe_mu"), "cv": ccy.Condition("transactions.PointLockManager.stripe_cv"),
             "locks": {}}
            for _ in range(num_stripes)
        ]
        self._n = num_stripes
        self._waits_for: dict[int, int] = {}   # txn id → txn id it waits on
        self._wf_mu = ccy.Lock("transactions.PointLockManager._wf_mu")

    def _stripe(self, key: bytes):
        return self._stripes[hash(key) % self._n]

    def _would_deadlock(self, waiter: int, holder: int) -> bool:
        with self._wf_mu:
            return _has_wait_cycle(self._waits_for, waiter, holder)

    def try_lock(self, txn_id: int, key: bytes, timeout: float = 1.0) -> None:
        s = self._stripe(key)
        deadline = time.time() + timeout
        with s["cv"]:
            while True:
                holder = s["locks"].get(key)
                if holder is None or holder == txn_id:
                    s["locks"][key] = txn_id
                    with self._wf_mu:
                        self._waits_for.pop(txn_id, None)
                    return
                if self._would_deadlock(txn_id, holder):
                    raise DeadlockError(
                        f"deadlock: txn {txn_id} → txn {holder} on {key!r}"
                    )
                with self._wf_mu:
                    self._waits_for[txn_id] = holder
                remain = deadline - time.time()
                if remain <= 0:
                    with self._wf_mu:
                        self._waits_for.pop(txn_id, None)
                    raise Busy(f"lock timeout on {key!r} (held by txn {holder})")
                s["cv"].wait(min(remain, 0.05))

    def unlock_all(self, txn_id: int, keys) -> None:
        by_stripe: dict[int, list[bytes]] = {}
        for k in keys:
            by_stripe.setdefault(hash(k) % self._n, []).append(k)
        for si, ks in by_stripe.items():
            s = self._stripes[si]
            with s["cv"]:
                for k in ks:
                    if s["locks"].get(k) == txn_id:
                        del s["locks"][k]
                s["cv"].notify_all()
        with self._wf_mu:
            self._waits_for.pop(txn_id, None)


class RangeLockManager:
    """Range (gap) locks — the role of the reference's Toku `locktree`
    (utilities/transactions/lock/range/range_tree/): a transaction can lock
    a whole user-key interval [begin, end] (closed), blocking writers to
    ANY key inside it, with the same wait-for-graph deadlock detection as
    point locks and Toku-style lock escalation (when one transaction holds
    more than max_ranges_per_txn ranges, adjacent owned ranges merge into
    their hull — over-locking is safe, unbounded memory is not).

    Point locks are single-key ranges, so this manager is a drop-in for
    PointLockManager (try_lock / unlock_all have the same shape)."""

    def __init__(self, max_ranges_per_txn: int = 1024):
        self._cv = ccy.Condition("transactions.RangeLockManager._cv")
        self._ranges: list[list] = []  # [begin, end, owner], sorted by begin
        self._max_per_txn = max_ranges_per_txn
        self._counts: dict[int, int] = {}
        self._waits_for: dict[int, set[int]] = {}

    # -- internals (all under self._cv) --------------------------------

    def _overlaps(self, b: bytes, e: bytes):
        # Linear scan: a begin-sorted list cannot bound the scan start
        # (an early range may extend past b), and escalation already
        # bounds the list length.
        return [r for r in self._ranges if r[0] <= e and r[1] >= b]

    def _insert(self, txn_id: int, b: bytes, e: bytes) -> None:
        import bisect

        # Merge with owned overlapping/adjacent ranges into one hull.
        merged_b, merged_e = b, e
        keep = []
        for r in self._overlaps(b, e):
            if r[2] == txn_id:
                merged_b = min(merged_b, r[0])
                merged_e = max(merged_e, r[1])
                keep.append(r)
        for r in keep:
            self._ranges.remove(r)
            self._counts[txn_id] -= 1
        bisect.insort(self._ranges, [merged_b, merged_e, txn_id])
        self._counts[txn_id] = self._counts.get(txn_id, 0) + 1
        if self._counts[txn_id] > self._max_per_txn:
            self._escalate(txn_id)

    def _escalate(self, txn_id: int) -> None:
        """Merge CONSECUTIVE ranges owned by txn_id (no other owner's range
        between them) into their hull — Toku lock escalation: widens the
        lock footprint (safe) to bound memory."""
        out = []
        for r in self._ranges:
            if (out and r[2] == txn_id and out[-1][2] == txn_id):
                out[-1][1] = max(out[-1][1], r[1])
            else:
                out.append(r)
        freed = len(self._ranges) - len(out)
        if freed:
            self._ranges = out
            self._counts[txn_id] -= freed

    # -- public surface --------------------------------------------------

    def try_lock_range(self, txn_id: int, begin: bytes, end: bytes,
                       timeout: float = 1.0) -> None:
        if begin > end:
            raise InvalidArgument("range lock begin > end")
        deadline = time.time() + timeout
        with self._cv:
            while True:
                holders = {
                    r[2] for r in self._overlaps(begin, end)
                    if r[2] != txn_id
                }
                if not holders:
                    self._insert(txn_id, begin, end)
                    self._waits_for.pop(txn_id, None)
                    return
                # A range waits on EVERY holder of an overlapping range:
                # single-edge tracking would miss cycles through the rest.
                for holder in holders:
                    if _has_wait_cycle(self._waits_for, txn_id, holder):
                        self._waits_for.pop(txn_id, None)  # no stale edge
                        raise DeadlockError(
                            f"deadlock: txn {txn_id} → txn {holder} on "
                            f"[{begin!r}, {end!r}]"
                        )
                self._waits_for[txn_id] = set(holders)
                remain = deadline - time.time()
                if remain <= 0:
                    self._waits_for.pop(txn_id, None)
                    raise Busy(
                        f"range lock timeout on [{begin!r}, {end!r}] "
                        f"(held by {len(holders)} txns)"
                    )
                self._cv.wait(min(remain, 0.05))

    def try_lock(self, txn_id: int, key: bytes, timeout: float = 1.0) -> None:
        self.try_lock_range(txn_id, key, key, timeout)

    def unlock_all(self, txn_id: int, keys=None) -> None:
        """Release EVERY range owned by txn_id (ranges may cover many keys,
        so per-key release would leak; the reference's locktree likewise
        releases by owner at commit/rollback)."""
        with self._cv:
            self._ranges = [r for r in self._ranges if r[2] != txn_id]
            self._counts.pop(txn_id, None)
            self._waits_for.pop(txn_id, None)
            self._cv.notify_all()


class _TxnBase:
    _next_id = [1]
    _id_lock = ccy.Lock("transactions._TxnBase._id_lock")

    def __init__(self, db: DB, write_options: WriteOptions):
        with self._id_lock:
            self.id = self._next_id[0]
            self._next_id[0] += 1
        self._db = db
        self._wo = write_options
        self.wbwi = WriteBatchWithIndex(db.options.merge_operator)
        self._snapshot = None
        self.state = "started"

    def set_snapshot(self) -> None:
        self._snapshot = self._db.get_snapshot()

    def _read_opts(self) -> ReadOptions:
        return ReadOptions(snapshot=self._snapshot)

    def get(self, key: bytes) -> bytes | None:
        return self.wbwi.get_from_batch_and_db(self._db, key, self._read_opts())

    def put(self, key: bytes, value: bytes) -> None:
        self._before_write(key)
        self.wbwi.put(key, value)

    def delete(self, key: bytes) -> None:
        self._before_write(key)
        self.wbwi.delete(key)

    def merge(self, key: bytes, value: bytes) -> None:
        self._before_write(key)
        self.wbwi.merge(key, value)

    def _before_write(self, key: bytes) -> None:
        raise NotImplementedError

    def rollback(self) -> None:
        self.wbwi.clear()
        self._cleanup()
        self.state = "rolledback"

    def _cleanup(self) -> None:
        if self._snapshot is not None:
            self._snapshot.release()
            self._snapshot = None


class PessimisticTransaction(_TxnBase):
    def __init__(self, txn_db: "TransactionDB", write_options: WriteOptions,
                 lock_timeout: float = 1.0):
        super().__init__(txn_db.db, write_options)
        self._txn_db = txn_db
        self._locked: set[bytes] = set()
        self._locked_ranges: list[tuple[bytes, bytes]] = []
        self._lock_timeout = lock_timeout

    def _before_write(self, key: bytes) -> None:
        if key not in self._locked:
            self._txn_db.lock_manager.try_lock(self.id, key, self._lock_timeout)
            self._locked.add(key)

    def get_for_update(self, key: bytes) -> bytes | None:
        self._before_write(key)
        return self.get(key)

    def get_range_lock(self, begin: bytes, end: bytes) -> None:
        """Lock the whole user-key interval [begin, end] (reference
        Transaction::GetRangeLock — range-locking TransactionDBs only)."""
        mgr = self._txn_db.lock_manager
        if not isinstance(mgr, RangeLockManager):
            raise InvalidArgument(
                "get_range_lock requires TransactionDB.open("
                "use_range_locking=True)"
            )
        mgr.try_lock_range(self.id, begin, end, self._lock_timeout)
        self._locked_ranges.append((begin, end))

    def undo_get_for_update(self, key: bytes) -> None:
        # The reference keeps the lock until commit if the key was written;
        # we match: only unwritten keys are released. Under RANGE locking
        # partial release is unsupported (the locktree frees by owner at
        # commit/rollback) — keeping the lock is safe over-locking.
        if isinstance(self._txn_db.lock_manager, RangeLockManager):
            return
        written = bool(self.wbwi._batch_view(key))  # one seek, not a scan
        if key in self._locked and not written:
            self._txn_db.lock_manager.unlock_all(self.id, [key])
            self._locked.discard(key)

    def set_name(self, name: str) -> None:
        """Name for 2PC (reference Transaction::SetName — required before
        Prepare so recovery can identify the transaction). Names are unique
        among undecided transactions and immutable once set."""
        if not name or "/" in name or name.startswith("."):
            raise InvalidArgument(f"bad transaction name {name!r}")
        if name.startswith("rb."):
            # Reserved: 'txn.' + 'rb.X' would collide with the rollback
            # marker of transaction 'X' (TransactionDB._RB_PREFIX).
            raise InvalidArgument(
                f"transaction names may not start with 'rb.': {name!r}"
            )
        if self.state != "started":
            raise InvalidArgument(f"cannot rename in state {self.state}")
        if getattr(self, "name", None) is not None:
            raise InvalidArgument("transaction already named")
        self._txn_db._register_name(name)
        self.name = name

    def prepare(self) -> None:
        """2PC phase 1 (reference Transaction::Prepare): persist the batch
        durably so a crash between prepare and commit leaves the transaction
        recoverable via TransactionDB.get_prepared_transactions()."""
        if self.state != "started":
            raise InvalidArgument(f"cannot prepare from state {self.state}")
        if getattr(self, "name", None) is None:
            raise InvalidArgument("set_name() required before prepare()")
        self._txn_db._persist_prepared(self)
        self.state = "prepared"
        self._tick("TXN_PREPARE")

    def commit(self) -> None:
        if self.state not in ("started", "prepared"):
            raise InvalidArgument(f"cannot commit from state {self.state}")
        # Locks release only on SUCCESS: a failed commit of a prepared txn
        # must stay prepared with its keys locked, or a retry/recovery
        # commit would stomp newer writes (lost update).
        if self.state == "prepared":
            self._txn_db._commit_prepared(self)
        else:
            if not self.wbwi.batch.is_empty():
                self._db.write(self.wbwi.batch, self._wo)
            if getattr(self, "name", None) is not None:
                self._txn_db._release_name(self.name)
        self.state = "committed"
        self._release()
        self._tick("TXN_COMMIT")

    def rollback(self) -> None:
        if self.state == "prepared":
            self._txn_db._discard_prepared(self)
        elif getattr(self, "name", None) is not None:
            self._txn_db._release_name(self.name)
        super().rollback()
        self._release()
        self._tick("TXN_ROLLBACK")

    def _tick(self, which: str) -> None:
        stats = getattr(self._db, "stats", None)
        if stats is not None:
            from toplingdb_tpu.utils import statistics as st

            stats.record_tick(getattr(st, which))

    def _release(self) -> None:
        self._txn_db.lock_manager.unlock_all(self.id, self._locked)
        self._locked.clear()
        self._cleanup()


class TransactionDB:
    """Pessimistic transaction DB (reference PessimisticTransactionDB,
    WriteCommitted policy). 2PC: prepared transactions persist in
    `<db>/txns/<name>.prep` (batch + lock set, fsynced); commit appends a
    hidden marker key in the same atomic batch so recovery can tell a
    crash-after-commit from a still-prepared transaction (the reference
    uses WAL Prepare/Commit markers for the same purpose)."""

    _MARKER_PREFIX = b"txn."
    _TXN_CF = "__tpulsm_txn__"

    def __init__(self, db: DB, use_range_locking: bool = False,
                 write_policy: str = "write_committed"):
        if write_policy not in ("write_committed", "write_prepared",
                                "write_unprepared"):
            raise InvalidArgument(f"unknown write policy {write_policy!r}")
        self.db = db
        self.write_policy = write_policy
        # Reference TransactionDBOptions::lock_mgr_handle: "point" (default)
        # or the range-capable locktree manager.
        self.lock_manager = (
            RangeLockManager() if use_range_locking else PointLockManager()
        )
        self._txn_dir = f"{db.dbname}/txns"
        self._recovered: list[PessimisticTransaction] = []
        self._names: set[str] = set()
        self._names_mu = ccy.Lock("transactions.TransactionDB._names_mu")
        # WritePrepared/WriteUnprepared: seqno ranges of in-DB data belonging
        # to undecided transactions (name → [(lo, hi), ...]). Exposed to the
        # engine's read paths via DB._undecided_provider (the reference's
        # SnapshotChecker / commit-cache visibility role).
        self._undecided: dict[str, list] = {}
        self._undecided_mu = ccy.Lock("transactions.TransactionDB._undecided_mu")
        self._parked_guards: list = []  # (guard snapshot, ranges) — see
        #                                 _wp_release_guard
        db._undecided_provider = self._undecided_ranges
        # Commit markers live in their own column family so user-keyspace
        # scans never see them (the reference keeps its markers in the WAL).
        cf = db.get_column_family(self._TXN_CF)
        self._txn_cf = cf if cf is not None else \
            db.create_column_family(self._TXN_CF)
        try:
            db.env.create_dir(self._txn_dir)
        except Exception as e:
            _errors.swallow(reason="txn-dir-create-exists", exc=e)
        try:
            self._recover_prepared()
        except BaseException:
            # A recovery refusal (e.g. prepared range locks without
            # use_range_locking) must not leak the fully-opened DB.
            db.close()
            raise

    def _register_name(self, name: str) -> None:
        with self._names_mu:
            if name in self._names or self.db.env.file_exists(
                    self._prep_path(name)):
                raise InvalidArgument(
                    f"transaction name {name!r} already in use"
                )
            self._names.add(name)

    def _release_name(self, name: str) -> None:
        with self._names_mu:
            self._names.discard(name)

    @staticmethod
    def open(path: str, options: Options | None = None,
             use_range_locking: bool = False,
             write_policy: str = "write_committed") -> "TransactionDB":
        return TransactionDB(DB.open(path, options), use_range_locking,
                             write_policy)

    # -- 2PC journal ----------------------------------------------------

    def _prep_path(self, name: str) -> str:
        return f"{self._txn_dir}/{name}.prep"

    def _persist_prepared(self, txn) -> None:
        import json as _json

        doc = _json.dumps({
            "name": txn.name,
            "batch": txn.wbwi.batch.data().hex(),
            "locks": [k.hex() for k in txn._locked],
            "range_locks": [
                [b.hex(), e.hex()] for b, e in txn._locked_ranges
            ],
        })
        self.db.env.write_file(self._prep_path(txn.name), doc.encode(),
                               sync=True)

    def _commit_prepared(self, txn) -> None:
        from toplingdb_tpu.db.write_batch import WriteBatch

        marker = self._MARKER_PREFIX + txn.name.encode()
        batch = WriteBatch(txn.wbwi.batch.data())
        batch.put(marker, b"1", cf=self._txn_cf.id)
        self.db.write(batch, txn._wo)
        try:
            self.db.env.delete_file(self._prep_path(txn.name))
        except Exception as e:
            _errors.swallow(reason="prepared-journal-cleanup", exc=e)
        self.db.delete(marker, cf=self._txn_cf)
        if txn in self._recovered:
            self._recovered.remove(txn)
        self._release_name(txn.name)

    def _discard_prepared(self, txn) -> None:
        try:
            self.db.env.delete_file(self._prep_path(txn.name))
        except Exception as e:
            _errors.swallow(reason="prepared-journal-cleanup", exc=e)
        if txn in self._recovered:
            self._recovered.remove(txn)
        self._release_name(txn.name)

    def _recover_prepared(self) -> None:
        import json as _json

        from toplingdb_tpu.utils.status import NotFound

        try:
            children = self.db.env.get_children(self._txn_dir)
        except NotFound:
            return
        live_names: set[str] = set()
        for child in sorted(children):
            if not child.endswith(".prep"):
                continue
            # IO errors PROPAGATE (hiding a prepared txn loses its locks);
            # only unparseable content counts as a torn prepare.
            raw = self.db.env.read_file(f"{self._txn_dir}/{child}")
            try:
                doc = _json.loads(raw.decode())
                name = doc["name"]
                if doc.get("policy") in ("write_prepared",
                                         "write_unprepared"):
                    self._recover_wp(name, doc)
                    live_names.add(name)
                    continue
                batch_data = bytes.fromhex(doc["batch"])
                locks = [bytes.fromhex(kh) for kh in doc["locks"]]
                range_locks = [
                    (bytes.fromhex(b), bytes.fromhex(e))
                    for b, e in doc.get("range_locks", [])
                ]
            except (ValueError, KeyError, UnicodeDecodeError):
                # Torn prepare: quarantine so it can't be re-read forever.
                self.db.env.rename_file(
                    f"{self._txn_dir}/{child}",
                    f"{self._txn_dir}/{child}.corrupt",
                )
                continue
            marker = self._MARKER_PREFIX + name.encode()
            if self.db.get(marker, cf=self._txn_cf) is not None:
                # Crashed between commit-write and prep-file delete: the
                # batch is already durable — finish the bookkeeping.
                try:
                    self.db.env.delete_file(self._prep_path(name))
                except NotFound:
                    pass
                self.db.delete(marker, cf=self._txn_cf)
                continue
            txn = PessimisticTransaction(self, WriteOptions())
            txn.name = name
            self._names.add(name)
            live_names.add(name)
            from toplingdb_tpu.db.write_batch import WriteBatch

            txn.wbwi.batch = WriteBatch(batch_data)
            for k in locks:
                self.lock_manager.try_lock(txn.id, k, 0.0)
                txn._locked.add(k)
            if range_locks and not isinstance(self.lock_manager,
                                              RangeLockManager):
                raise InvalidArgument(
                    f"prepared transaction {name!r} holds range locks; "
                    f"reopen with use_range_locking=True"
                )
            for b, e in range_locks:
                self.lock_manager.try_lock_range(txn.id, b, e, 0.0)
                txn._locked_ranges.append((b, e))
            txn.state = "prepared"
            self._recovered.append(txn)
        # Sweep orphan markers (crash between prep delete and marker
        # delete): any marker without a surviving .prep is garbage.
        it = self.db.new_iterator(cf=self._txn_cf)
        it.seek(self._MARKER_PREFIX)
        orphans = []
        while it.valid() and it.key().startswith(self._MARKER_PREFIX):
            name = it.key()[len(self._MARKER_PREFIX):].decode(errors="replace")
            if name not in live_names:
                orphans.append(it.key())
            it.next()
        for k in orphans:
            self.db.delete(k, cf=self._txn_cf)

    def get_prepared_transactions(self) -> list:
        """Recovered prepared-but-undecided transactions (reference
        GetAllPreparedTransactions); commit() or rollback() each."""
        return list(self._recovered)

    # -- WritePrepared / WriteUnprepared machinery ----------------------
    #
    # Reference write_prepared_txn_db.cc / write_unprepared_txn_db.cc: data
    # reaches the DB (WAL + memtable) at Prepare time — commit is a tiny
    # marker write, not a second copy of the batch. Visibility is enforced
    # by the engine: every read excludes the seqno ranges of undecided
    # transactions (DB._undecided_provider; snapshots capture the set at
    # creation, the old_commit_map role). Rollback follows the reference's
    # design: write compensating records restoring each key's pre-prepare
    # value, then let the whole range become visible — the compensation is
    # newer, so the observable state is the rollback.

    _RB_PREFIX = b"txn.rb."

    def _undecided_ranges(self) -> tuple:
        with self._undecided_mu:
            return tuple(r for rs in self._undecided.values() for r in rs)

    def _wp_unregister(self, name: str) -> None:
        with self._undecided_mu:
            self._undecided.pop(name, None)

    def _wp_write_batch(self, txn, batch) -> None:
        """Write `batch` into the DB invisibly, recording the new seqno
        range on the transaction. The exclusion registers via the write
        path's on_sequenced hook — inside the commit critical section,
        before the group's last_sequence publishes — so no reader can ever
        observe the data unexcluded."""
        if batch.is_empty():
            return
        db = self.db

        def on_sequenced(lo: int, hi: int) -> None:
            with self._undecided_mu:
                self._undecided.setdefault(txn.name, []).append((lo, hi))
            txn._wp_ranges.append((lo, hi))
            if txn._guard_snap is None:
                # Compaction guard: a visibility boundary below the
                # undecided data so background GC never folds/drops across
                # it (the reference excludes the snapshot-checker from
                # compaction similarly conservatively).
                txn._guard_snap = db.snapshots.new_snapshot(lo - 1)

        # Prepare durability: the reference syncs the WAL at prepare.
        db.write(batch, WriteOptions(sync=True), on_sequenced=on_sequenced)

    def _wp_journal(self, txn, finalized: bool) -> None:
        """Persist the transaction's WP journal (.prep file). Written with
        finalized=False BEFORE any data write (intent: a crash rolls the
        transaction back) and rewritten with finalized=True at Prepare.

        lo_hint: a lower bound on any seqno this transaction's data can
        occupy, taken BEFORE the data write. If we crash after the data hits
        the WAL but before the journal records the actual ranges, recovery
        still compensates correctly by reading each key just below lo_hint —
        sound because the transaction holds locks on every written key, so
        no other writer can touch them in between."""
        import json as _json

        if txn._wp_lo_hint is None:
            txn._wp_lo_hint = self.db.versions.last_sequence + 1
        doc = _json.dumps({
            "policy": "write_prepared",
            "name": txn.name,
            "finalized": finalized,
            "lo_hint": txn._wp_lo_hint,
            "ranges": [[lo, hi] for lo, hi in txn._wp_ranges],
            "keys": [k.hex() for k in sorted(txn._wp_keys)],
            "locks": [k.hex() for k in txn._locked],
            "range_locks": [
                [b.hex(), e.hex()] for b, e in txn._locked_ranges
            ],
        })
        self.db.env.write_file(self._prep_path(txn.name), doc.encode(),
                               sync=True)

    def _wp_prepare(self, txn) -> None:
        txn._wp_keys.update(txn.wbwi.key_set())
        self._wp_journal(txn, finalized=False)   # intent first: crash = abort
        self._wp_write_batch(txn, txn._wp_pending_batch())
        self._wp_journal(txn, finalized=True)

    def _wp_commit(self, txn) -> None:
        from toplingdb_tpu.db.write_batch import WriteBatch

        marker = self._MARKER_PREFIX + txn.name.encode()
        b = WriteBatch()
        b.put(marker, b"1", cf=self._txn_cf.id)
        self.db.write(b, WriteOptions(sync=True))  # the commit point
        self._wp_unregister(txn.name)              # data becomes visible
        self._wp_release_guard(txn)
        try:
            self.db.env.delete_file(self._prep_path(txn.name))
        except Exception as e:
            _errors.swallow(reason="prepared-journal-cleanup", exc=e)
        self.db.delete(marker, cf=self._txn_cf)
        if txn in self._recovered:
            self._recovered.remove(txn)
        self._release_name(txn.name)

    def _wp_rollback(self, txn) -> None:
        from toplingdb_tpu.db.write_batch import WriteBatch

        rb_marker = self._RB_PREFIX + txn.name.encode()
        mb = WriteBatch()
        mb.put(rb_marker, b"1", cf=self._txn_cf.id)
        self.db.write(mb, WriteOptions(sync=True))  # rollback decision point
        # Compensating records: each written key's value just below the
        # transaction's first seqno (reference WritePreparedTxn::
        # RollbackInternal reads prior versions the same way). When the
        # ranges were never journaled (crash mid-prepare), lo_hint bounds
        # them from below — see _wp_journal.
        lo0 = (min(lo for lo, _ in txn._wp_ranges) if txn._wp_ranges
               else txn._wp_lo_hint)
        if lo0 is not None and txn._wp_keys:
            snap = self.db.snapshots.new_snapshot(
                lo0 - 1, excluded_ranges=self._undecided_ranges()
            )
            comp = WriteBatch()
            try:
                for k in sorted(txn._wp_keys):
                    v = self.db.get(k, ReadOptions(snapshot=snap))
                    if v is None:
                        comp.delete(k)
                    else:
                        comp.put(k, v)
            finally:
                snap.release()
            self.db.write(comp, WriteOptions(sync=True))
        self._wp_unregister(txn.name)  # original + compensation now visible
        self._wp_release_guard(txn)
        try:
            self.db.env.delete_file(self._prep_path(txn.name))
        except Exception as e:
            _errors.swallow(reason="prepared-journal-cleanup", exc=e)
        self.db.delete(rb_marker, cf=self._txn_cf)
        if txn in self._recovered:
            self._recovered.remove(txn)
        self._release_name(txn.name)

    def _wp_release_guard(self, txn) -> None:
        g = txn._guard_snap
        txn._guard_snap = None
        if g is None:
            return
        ranges = tuple(txn._wp_ranges)
        if ranges and self.db.snapshots.any_excluding(
            min(lo for lo, _ in ranges), max(hi for _, hi in ranges)
        ):
            # A live snapshot captured this transaction's exclusion:
            # compaction must keep the pre-transaction versions that
            # snapshot reads, so the guard is PARKED until every such
            # snapshot dies (swept opportunistically).
            with self._undecided_mu:
                self._parked_guards.append((g, ranges))
            return
        g.release()

    def _sweep_parked_guards(self) -> None:
        with self._undecided_mu:
            parked, self._parked_guards = self._parked_guards, []
        keep = []
        for g, ranges in parked:
            if self.db.snapshots.any_excluding(
                min(lo for lo, _ in ranges), max(hi for _, hi in ranges)
            ):
                keep.append((g, ranges))
            else:
                g.release()
        if keep:
            with self._undecided_mu:
                self._parked_guards.extend(keep)

    def _recover_wp(self, name: str, doc: dict) -> None:
        """Recovery for a WritePrepared/WriteUnprepared journal file."""
        marker = self._MARKER_PREFIX + name.encode()
        if self.db.get(marker, cf=self._txn_cf) is not None:
            # Committed; crash before cleanup. Data is visible already.
            try:
                self.db.env.delete_file(self._prep_path(name))
            except Exception as e:
                _errors.swallow(reason="prepared-journal-cleanup", exc=e)
            self.db.delete(marker, cf=self._txn_cf)
            return
        txn = WritePreparedTransaction(self, WriteOptions())
        txn.name = name
        txn._wp_ranges = [(lo, hi) for lo, hi in doc.get("ranges", [])]
        txn._wp_lo_hint = doc.get("lo_hint")
        txn._wp_keys = {bytes.fromhex(k) for k in doc.get("keys", [])}
        with self._names_mu:
            self._names.add(name)
        with self._undecided_mu:
            self._undecided[name] = list(txn._wp_ranges)
        if txn._wp_ranges:
            txn._guard_snap = self.db.snapshots.new_snapshot(
                min(lo for lo, _ in txn._wp_ranges) - 1
            )
        rb = self.db.get(self._RB_PREFIX + name.encode(), cf=self._txn_cf)
        if rb is not None or not doc.get("finalized", False):
            # Mid-rollback, or crashed before Prepare finished: the
            # transaction never became durable-prepared — roll it back
            # (idempotent: compensation re-reads below the first seqno).
            self._wp_rollback(txn)
            return
        for kh in doc.get("locks", []):
            k = bytes.fromhex(kh)
            self.lock_manager.try_lock(txn.id, k, 0.0)
            txn._locked.add(k)
        range_locks = [
            (bytes.fromhex(b), bytes.fromhex(e))
            for b, e in doc.get("range_locks", [])
        ]
        if range_locks and not isinstance(self.lock_manager, RangeLockManager):
            raise InvalidArgument(
                f"prepared transaction {name!r} holds range locks; "
                f"reopen with use_range_locking=True"
            )
        for b, e in range_locks:
            self.lock_manager.try_lock_range(txn.id, b, e, 0.0)
            txn._locked_ranges.append((b, e))
        txn.state = "prepared"
        self._recovered.append(txn)

    def begin_transaction(self, write_options: WriteOptions = WriteOptions(),
                          lock_timeout: float = 1.0) -> PessimisticTransaction:
        self._sweep_parked_guards()
        if self.write_policy == "write_prepared":
            return WritePreparedTransaction(self, write_options, lock_timeout)
        if self.write_policy == "write_unprepared":
            return WriteUnpreparedTransaction(self, write_options, lock_timeout)
        return PessimisticTransaction(self, write_options, lock_timeout)

    # Non-transactional access locks implicitly (reference WriteCommitted
    # TransactionDB::Put): a degenerate single-op transaction.
    def put(self, key: bytes, value: bytes,
            opts: WriteOptions = WriteOptions()) -> None:
        txn = self.begin_transaction(opts)
        txn.put(key, value)
        txn.commit()

    def get(self, key: bytes, opts: ReadOptions = ReadOptions()):
        return self.db.get(key, opts)

    def close(self) -> None:
        with self._undecided_mu:
            parked, self._parked_guards = self._parked_guards, []
        for g, _ in parked:
            g.release()
        self.db.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


class WritePreparedTransaction(PessimisticTransaction):
    """WritePrepared policy (reference write_prepared_txn_db.cc): Prepare
    writes the batch into the DB (WAL + memtable, synced) so Commit is a
    marker write — no second copy of a large batch at the commit point. The
    data stays invisible to every reader until the commit marker lands (see
    TransactionDB._wp_* and DB._undecided_provider)."""

    def __init__(self, txn_db: "TransactionDB", write_options: WriteOptions,
                 lock_timeout: float = 1.0):
        super().__init__(txn_db, write_options, lock_timeout)
        self._wp_ranges: list[tuple[int, int]] = []  # in-DB undecided seqnos
        self._wp_keys: set[bytes] = set()            # for rollback records
        self._wp_lo_hint: int | None = None          # see _wp_journal
        self._guard_snap = None                      # compaction guard

    def _wp_pending_batch(self):
        """The batch portion not yet written to the DB (everything, for
        plain WritePrepared; the unprepared subclass spills early)."""
        return self.wbwi.batch

    def prepare(self) -> None:
        if self.state != "started":
            raise InvalidArgument(f"cannot prepare from state {self.state}")
        if getattr(self, "name", None) is None:
            raise InvalidArgument("set_name() required before prepare()")
        self._txn_db._wp_prepare(self)
        self.state = "prepared"

    def commit(self) -> None:
        if self.state == "started":
            # Commit without Prepare: a single atomic batch write IS the
            # commit point — identical to the WriteCommitted fast path.
            super().commit()
            return
        if self.state != "prepared":
            raise InvalidArgument(f"cannot commit from state {self.state}")
        self._txn_db._wp_commit(self)
        self.state = "committed"
        self._release()

    def rollback(self) -> None:
        if self.state == "prepared":
            self._txn_db._wp_rollback(self)
            self.wbwi.clear()
            self.state = "rolledback"
            self._release()
            return
        super().rollback()


class WriteUnpreparedTransaction(WritePreparedTransaction):
    """WriteUnprepared policy (reference write_unprepared_txn_db.cc): batch
    fragments SPILL into the DB while the transaction is still running, so a
    transaction larger than memory never materializes its full batch. Each
    spill extends the undecided seqno ranges; Prepare flushes the remainder
    and finalizes the journal. The WBWI index is retained for
    read-your-own-writes across spills."""

    #: spill once the unflushed batch bytes exceed this (reference
    #: TransactionOptions::write_batch_flush_threshold).
    spill_threshold: int = 64 * 1024

    def __init__(self, txn_db: "TransactionDB", write_options: WriteOptions,
                 lock_timeout: float = 1.0,
                 spill_threshold: int | None = None):
        super().__init__(txn_db, write_options, lock_timeout)
        if spill_threshold is not None:
            self.spill_threshold = spill_threshold
        self._spill_off = None  # byte offset of unspilled tail in the batch
        self._spill_count = 0

    def _unspilled(self):
        from toplingdb_tpu.db.write_batch import HEADER_SIZE, WriteBatch

        if self._spill_off is None:
            return self.wbwi.batch
        full = self.wbwi.batch
        part = WriteBatch()
        part._rep = bytearray(part._rep[:HEADER_SIZE])
        part._rep += full._rep[self._spill_off:]
        part.set_count(full.count() - self._spill_count)
        part._simple = False  # sliced bytes: decode when applying
        return part

    def _wp_pending_batch(self):
        return self._unspilled()

    def _maybe_spill(self) -> None:
        pending = self._unspilled()
        if pending.data_size() <= self.spill_threshold:
            return
        if getattr(self, "name", None) is None:
            # Spills need a recoverable identity before any data hits the
            # WAL (the reference assigns XIDs internally).
            self.set_name(f"__unprep.{self.id}")
        self._wp_keys.update(self.wbwi.key_set())
        self._txn_db._wp_journal(self, finalized=False)  # intent first
        self._txn_db._wp_write_batch(self, pending)
        self._txn_db._wp_journal(self, finalized=False)  # record the range
        self._spill_off = len(self.wbwi.batch._rep)
        self._spill_count = self.wbwi.batch.count()

    def put(self, key: bytes, value: bytes) -> None:
        super().put(key, value)
        self._maybe_spill()

    def delete(self, key: bytes) -> None:
        super().delete(key)
        self._maybe_spill()

    def merge(self, key: bytes, value: bytes) -> None:
        super().merge(key, value)
        self._maybe_spill()

    def commit(self) -> None:
        if self.state == "started" and self._spill_off is not None:
            # Data is already partially in the DB: a commit must go through
            # the marker protocol (implicit prepare, as the reference does).
            self.prepare()
        super().commit()

    def rollback(self) -> None:
        if self.state == "started" and self._spill_off is not None:
            self._txn_db._wp_rollback(self)
            self.wbwi.clear()
            self.state = "rolledback"
            self._release()
            return
        super().rollback()


class OptimisticTransaction(_TxnBase):
    def __init__(self, txn_db: "OptimisticTransactionDB",
                 write_options: WriteOptions):
        super().__init__(txn_db.db, write_options)
        self._txn_db = txn_db
        self._tracked: dict[bytes, int] = {}  # key → seqno when first read/written
        self.set_snapshot()

    def _before_write(self, key: bytes) -> None:
        # Track at the SNAPSHOT sequence: reads are served at the snapshot,
        # so any write after it is a conflict (tracking at last_sequence
        # would silently admit lost updates for writes that landed between
        # snapshot and track — reference TransactionUtil::CheckKey).
        self._tracked.setdefault(key, self._snapshot.sequence)

    def get_for_update(self, key: bytes) -> bytes | None:
        self._before_write(key)
        return self.get(key)

    def commit(self) -> None:
        if self.state != "started":
            raise InvalidArgument(f"cannot commit from state {self.state}")
        db = self._db
        with db._mutex:  # validation + write must be atomic
            for key, seq_at_track in self._tracked.items():
                if self._conflicts(key, seq_at_track):
                    self._cleanup()
                    self.state = "aborted"
                    raise Busy(f"write conflict on {key!r}")
            if not self.wbwi.batch.is_empty():
                db.write(self.wbwi.batch, self._wo)
        self.state = "committed"
        self._cleanup()

    def _conflicts(self, key: bytes, seq_at_track: int) -> bool:
        """Did anyone write `key` after we tracked it? Checked via a read at
        latest vs read at tracked seqno (reference checks memtable seqnos;
        ours inspects the newest visible version's seqno)."""
        ctx_seq = self._latest_write_seqno(key)
        return ctx_seq is not None and ctx_seq > seq_at_track

    def _latest_write_seqno(self, key: bytes):
        db = self._db
        snap = db.versions.last_sequence
        for mem in [db.mem] + db.imm:
            for seq, t, val in mem.entries_for_key(key, snap):
                return seq
            ts = mem.covering_tombstone_seq(key, snap)
            if ts:
                return ts
        version = db.versions.current
        for level, f in version.files_for_get(key):
            reader = db.table_cache.get_reader(f.number)
            if not reader.key_may_match(key):
                continue
            from toplingdb_tpu.db import dbformat

            it = reader.new_iterator()
            it.seek(dbformat.make_internal_key(
                key, snap, dbformat.VALUE_TYPE_FOR_SEEK
            ))
            while it.valid():
                uk, seq, t = dbformat.split_internal_key(it.key())
                if uk != key:
                    break
                return seq
            # L0 files are newest-first; the first hit is the latest version.
        return None


class OptimisticTransactionDB:
    def __init__(self, db: DB):
        self.db = db

    @staticmethod
    def open(path: str, options: Options | None = None) -> "OptimisticTransactionDB":
        return OptimisticTransactionDB(DB.open(path, options))

    def begin_transaction(self, write_options: WriteOptions = WriteOptions()
                          ) -> OptimisticTransaction:
        return OptimisticTransaction(self, write_options)

    def get(self, key: bytes, opts: ReadOptions = ReadOptions()):
        return self.db.get(key, opts)

    def close(self) -> None:
        self.db.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
