"""Transactions: pessimistic (2PL) and optimistic, over WriteBatchWithIndex.

Reference utilities/transactions/ in /root/reference:
  * PointLockManager — striped lock maps + deadlock detection
    (point_lock_manager.cc:64-98; the Topling fork rebuilds it on terark
    hash maps for 5x — ours uses striped dicts, the Python-native analogue).
  * PessimisticTransactionDB (WriteCommitted policy): writes take point locks
    at write time; commit applies the indexed batch atomically; supports 2PC
    prepare/commit.
  * OptimisticTransactionDB: conflict check at commit via per-key sequence
    validation (optimistic_transaction_db_impl.cc).
"""

from __future__ import annotations

import threading
import time

from toplingdb_tpu.db.db import DB
from toplingdb_tpu.options import Options, ReadOptions, WriteOptions
from toplingdb_tpu.utilities.write_batch_with_index import WriteBatchWithIndex
from toplingdb_tpu.utils.status import Busy, Expired, InvalidArgument, TryAgain

NUM_STRIPES = 16


class DeadlockError(Busy):
    pass


class PointLockManager:
    """Striped exclusive point locks with wait-for-graph deadlock detection."""

    def __init__(self, num_stripes: int = NUM_STRIPES):
        self._stripes = [
            {"mu": threading.Lock(), "cv": threading.Condition(threading.Lock()),
             "locks": {}}
            for _ in range(num_stripes)
        ]
        self._n = num_stripes
        self._waits_for: dict[int, int] = {}   # txn id → txn id it waits on
        self._wf_mu = threading.Lock()

    def _stripe(self, key: bytes):
        return self._stripes[hash(key) % self._n]

    def _would_deadlock(self, waiter: int, holder: int) -> bool:
        with self._wf_mu:
            cur = holder
            for _ in range(64):
                nxt = self._waits_for.get(cur)
                if nxt is None:
                    return False
                if nxt == waiter:
                    return True
                cur = nxt
        return False

    def try_lock(self, txn_id: int, key: bytes, timeout: float = 1.0) -> None:
        s = self._stripe(key)
        deadline = time.time() + timeout
        with s["cv"]:
            while True:
                holder = s["locks"].get(key)
                if holder is None or holder == txn_id:
                    s["locks"][key] = txn_id
                    with self._wf_mu:
                        self._waits_for.pop(txn_id, None)
                    return
                if self._would_deadlock(txn_id, holder):
                    raise DeadlockError(
                        f"deadlock: txn {txn_id} → txn {holder} on {key!r}"
                    )
                with self._wf_mu:
                    self._waits_for[txn_id] = holder
                remain = deadline - time.time()
                if remain <= 0:
                    with self._wf_mu:
                        self._waits_for.pop(txn_id, None)
                    raise Busy(f"lock timeout on {key!r} (held by txn {holder})")
                s["cv"].wait(min(remain, 0.05))

    def unlock_all(self, txn_id: int, keys) -> None:
        by_stripe: dict[int, list[bytes]] = {}
        for k in keys:
            by_stripe.setdefault(hash(k) % self._n, []).append(k)
        for si, ks in by_stripe.items():
            s = self._stripes[si]
            with s["cv"]:
                for k in ks:
                    if s["locks"].get(k) == txn_id:
                        del s["locks"][k]
                s["cv"].notify_all()
        with self._wf_mu:
            self._waits_for.pop(txn_id, None)


class _TxnBase:
    _next_id = [1]
    _id_lock = threading.Lock()

    def __init__(self, db: DB, write_options: WriteOptions):
        with self._id_lock:
            self.id = self._next_id[0]
            self._next_id[0] += 1
        self._db = db
        self._wo = write_options
        self.wbwi = WriteBatchWithIndex(db.options.merge_operator)
        self._snapshot = None
        self.state = "started"

    def set_snapshot(self) -> None:
        self._snapshot = self._db.get_snapshot()

    def _read_opts(self) -> ReadOptions:
        return ReadOptions(snapshot=self._snapshot)

    def get(self, key: bytes) -> bytes | None:
        return self.wbwi.get_from_batch_and_db(self._db, key, self._read_opts())

    def put(self, key: bytes, value: bytes) -> None:
        self._before_write(key)
        self.wbwi.put(key, value)

    def delete(self, key: bytes) -> None:
        self._before_write(key)
        self.wbwi.delete(key)

    def merge(self, key: bytes, value: bytes) -> None:
        self._before_write(key)
        self.wbwi.merge(key, value)

    def _before_write(self, key: bytes) -> None:
        raise NotImplementedError

    def rollback(self) -> None:
        self.wbwi.clear()
        self._cleanup()
        self.state = "rolledback"

    def _cleanup(self) -> None:
        if self._snapshot is not None:
            self._snapshot.release()
            self._snapshot = None


class PessimisticTransaction(_TxnBase):
    def __init__(self, txn_db: "TransactionDB", write_options: WriteOptions,
                 lock_timeout: float = 1.0):
        super().__init__(txn_db.db, write_options)
        self._txn_db = txn_db
        self._locked: set[bytes] = set()
        self._lock_timeout = lock_timeout

    def _before_write(self, key: bytes) -> None:
        if key not in self._locked:
            self._txn_db.lock_manager.try_lock(self.id, key, self._lock_timeout)
            self._locked.add(key)

    def get_for_update(self, key: bytes) -> bytes | None:
        self._before_write(key)
        return self.get(key)

    def undo_get_for_update(self, key: bytes) -> None:
        # The reference keeps the lock until commit if the key was written;
        # we match: only unwritten keys are released.
        batch_keys = {e[0] for e in self.wbwi._items}
        if key in self._locked and key not in batch_keys:
            self._txn_db.lock_manager.unlock_all(self.id, [key])
            self._locked.discard(key)

    def prepare(self) -> None:
        """2PC phase 1: persist the batch to the WAL as a prepared record
        (simplified: the batch is staged durably in the txn registry)."""
        if self.state != "started":
            raise InvalidArgument(f"cannot prepare from state {self.state}")
        self.state = "prepared"

    def commit(self) -> None:
        if self.state not in ("started", "prepared"):
            raise InvalidArgument(f"cannot commit from state {self.state}")
        try:
            if not self.wbwi.batch.is_empty():
                self._db.write(self.wbwi.batch, self._wo)
            self.state = "committed"
        finally:
            self._release()

    def rollback(self) -> None:
        super().rollback()
        self._release()

    def _release(self) -> None:
        self._txn_db.lock_manager.unlock_all(self.id, self._locked)
        self._locked.clear()
        self._cleanup()


class TransactionDB:
    """Pessimistic transaction DB (reference PessimisticTransactionDB,
    WriteCommitted policy)."""

    def __init__(self, db: DB):
        self.db = db
        self.lock_manager = PointLockManager()

    @staticmethod
    def open(path: str, options: Options | None = None) -> "TransactionDB":
        return TransactionDB(DB.open(path, options))

    def begin_transaction(self, write_options: WriteOptions = WriteOptions(),
                          lock_timeout: float = 1.0) -> PessimisticTransaction:
        return PessimisticTransaction(self, write_options, lock_timeout)

    # Non-transactional access locks implicitly (reference WriteCommitted
    # TransactionDB::Put): a degenerate single-op transaction.
    def put(self, key: bytes, value: bytes,
            opts: WriteOptions = WriteOptions()) -> None:
        txn = self.begin_transaction(opts)
        txn.put(key, value)
        txn.commit()

    def get(self, key: bytes, opts: ReadOptions = ReadOptions()):
        return self.db.get(key, opts)

    def close(self) -> None:
        self.db.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


class OptimisticTransaction(_TxnBase):
    def __init__(self, txn_db: "OptimisticTransactionDB",
                 write_options: WriteOptions):
        super().__init__(txn_db.db, write_options)
        self._txn_db = txn_db
        self._tracked: dict[bytes, int] = {}  # key → seqno when first read/written
        self.set_snapshot()

    def _before_write(self, key: bytes) -> None:
        # Track at the SNAPSHOT sequence: reads are served at the snapshot,
        # so any write after it is a conflict (tracking at last_sequence
        # would silently admit lost updates for writes that landed between
        # snapshot and track — reference TransactionUtil::CheckKey).
        self._tracked.setdefault(key, self._snapshot.sequence)

    def get_for_update(self, key: bytes) -> bytes | None:
        self._before_write(key)
        return self.get(key)

    def commit(self) -> None:
        if self.state != "started":
            raise InvalidArgument(f"cannot commit from state {self.state}")
        db = self._db
        with db._mutex:  # validation + write must be atomic
            for key, seq_at_track in self._tracked.items():
                if self._conflicts(key, seq_at_track):
                    self._cleanup()
                    self.state = "aborted"
                    raise Busy(f"write conflict on {key!r}")
            if not self.wbwi.batch.is_empty():
                db.write(self.wbwi.batch, self._wo)
        self.state = "committed"
        self._cleanup()

    def _conflicts(self, key: bytes, seq_at_track: int) -> bool:
        """Did anyone write `key` after we tracked it? Checked via a read at
        latest vs read at tracked seqno (reference checks memtable seqnos;
        ours inspects the newest visible version's seqno)."""
        ctx_seq = self._latest_write_seqno(key)
        return ctx_seq is not None and ctx_seq > seq_at_track

    def _latest_write_seqno(self, key: bytes):
        db = self._db
        snap = db.versions.last_sequence
        for mem in [db.mem] + db.imm:
            for seq, t, val in mem.entries_for_key(key, snap):
                return seq
            ts = mem.covering_tombstone_seq(key, snap)
            if ts:
                return ts
        version = db.versions.current
        for level, f in version.files_for_get(key):
            reader = db.table_cache.get_reader(f.number)
            if not reader.key_may_match(key):
                continue
            from toplingdb_tpu.db import dbformat

            it = reader.new_iterator()
            it.seek(dbformat.make_internal_key(
                key, snap, dbformat.VALUE_TYPE_FOR_SEEK
            ))
            while it.valid():
                uk, seq, t = dbformat.split_internal_key(it.key())
                if uk != key:
                    break
                return seq
            # L0 files are newest-first; the first hit is the latest version.
        return None


class OptimisticTransactionDB:
    def __init__(self, db: DB):
        self.db = db

    @staticmethod
    def open(path: str, options: Options | None = None) -> "OptimisticTransactionDB":
        return OptimisticTransactionDB(DB.open(path, options))

    def begin_transaction(self, write_options: WriteOptions = WriteOptions()
                          ) -> OptimisticTransaction:
        return OptimisticTransaction(self, write_options)

    def get(self, key: bytes, opts: ReadOptions = ReadOptions()):
        return self.db.get(key, opts)

    def close(self) -> None:
        self.db.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
