"""SstFileWriter/Reader + external file ingestion.

Reference table/sst_file_writer.cc, sst_file_reader.cc and
db/external_sst_file_ingestion_job.cc in /root/reference: build SSTs outside
a DB, then ingest them atomically at the lowest level that doesn't overlap.
"""

from __future__ import annotations

import os

from toplingdb_tpu.db import dbformat, filename
from toplingdb_tpu.db.dbformat import InternalKeyComparator, ValueType
from toplingdb_tpu.db.version_edit import FileMetaData, VersionEdit
from toplingdb_tpu.env import default_env
from toplingdb_tpu.options import Options
from toplingdb_tpu.table.builder import TableOptions
from toplingdb_tpu.table.factory import new_table_builder, open_table
from toplingdb_tpu.utils.status import InvalidArgument


class SstFileWriter:
    """Build a standalone SST with ascending user keys; entries get seqno 0
    (rewritten at ingestion via the global seqno the same way the reference
    assigns the ingested file a single seqno)."""

    def __init__(self, options: Options | None = None):
        self.options = options or Options()
        self.icmp = InternalKeyComparator(self.options.comparator)
        self._builder = None
        self._wfile = None
        self._path = None
        self._last_user_key: bytes | None = None

    def open(self, path: str) -> None:
        self._path = path
        self._wfile = default_env().new_writable_file(path)
        self._builder = new_table_builder(
            self._wfile, self.icmp, self.options.table_options
        )

    def _add(self, user_key: bytes, value: bytes, t: ValueType) -> None:
        if self._builder is None:
            raise InvalidArgument("writer not open")
        if (self._last_user_key is not None
                and self.icmp.user_comparator.compare(
                    self._last_user_key, user_key) >= 0):
            raise InvalidArgument("keys must be added in strictly ascending order")
        self._builder.add(dbformat.make_internal_key(user_key, 0, t), value)
        self._last_user_key = user_key

    def put(self, user_key: bytes, value: bytes) -> None:
        self._add(user_key, value, ValueType.VALUE)

    def merge(self, user_key: bytes, value: bytes) -> None:
        self._add(user_key, value, ValueType.MERGE)

    def delete(self, user_key: bytes) -> None:
        self._add(user_key, b"", ValueType.DELETION)

    def delete_range(self, begin: bytes, end: bytes) -> None:
        self._builder.add_tombstone(
            dbformat.make_internal_key(begin, 0, ValueType.RANGE_DELETION), end
        )

    def finish(self):
        props = self._builder.finish()
        self._wfile.sync()
        self._wfile.close()
        smallest, largest = self._builder.smallest_key, self._builder.largest_key
        self._builder = None
        return props, smallest, largest


class SstFileReader:
    """Read a standalone SST (reference table/sst_file_reader.cc)."""

    def __init__(self, path: str, options: Options | None = None):
        self.options = options or Options()
        icmp = InternalKeyComparator(self.options.comparator)
        self._reader = open_table(
            default_env().new_random_access_file(path), icmp,
            self.options.table_options,
        )
        self.properties = self._reader.properties

    def iterate(self):
        it = self._reader.new_iterator()
        it.seek_to_first()
        for ikey, v in it.entries():
            uk, seq, t = dbformat.split_internal_key(ikey)
            yield uk, seq, t, v

    def verify_checksums(self) -> None:
        for _ in self.iterate():
            pass


def ingest_external_file(db, external_path: str, move: bool = False) -> int:
    """Ingest an SstFileWriter-produced file into the DB at the lowest level
    with no overlap (reference ExternalSstFileIngestionJob). Returns the
    level. The file's entries must not overlap the memtable (flushed first
    if they do)."""
    opts = db.options
    reader = open_table(
        db.env.new_random_access_file(external_path), db.icmp,
        opts.table_options,
    )
    it = reader.new_iterator()
    it.seek_to_first()
    if not it.valid() and not reader.range_del_entries():
        raise InvalidArgument("cannot ingest an empty file")
    with db._mutex:
        # Assign one global seqno to the whole file and REWRITE entries with
        # it, so snapshots taken before the ingestion don't see them (the
        # reference patches a global_seqno field in place; we rebuild —
        # correctness first, zero-rewrite is a later optimization).
        seq = db.versions.last_sequence + 1
        db.versions.last_sequence = seq
        db.flush()
        fnum = db.versions.new_file_number()
        dst = filename.table_file_name(db.dbname, fnum)
        w = db.env.new_writable_file(dst)
        b = new_table_builder(w, db.icmp, opts.table_options)
        it.seek_to_first()
        for ikey, v in it.entries():
            uk, _, t = dbformat.split_internal_key(ikey)
            b.add(dbformat.make_internal_key(uk, seq, t), v)
        for bk, e in reader.range_del_entries():
            uk, _, t = dbformat.split_internal_key(bk)
            b.add_tombstone(
                dbformat.make_internal_key(uk, seq, ValueType.RANGE_DELETION), e
            )
        props = b.finish()
        w.sync()
        w.close()
        smallest, largest = b.smallest_key, b.largest_key
        su = dbformat.extract_user_key(smallest)
        lu = dbformat.extract_user_key(largest)
        # Lowest level with no overlap at-or-above it.
        version = db.versions.current
        target = 0
        for lvl in range(1, version.num_levels):
            if version.overlapping_files(lvl, su, lu):
                break
            if any(version.overlapping_files(l2, su, lu) for l2 in range(lvl)):
                break
            target = lvl
        meta = FileMetaData(
            number=fnum,
            file_size=db.env.get_file_size(dst),
            smallest=smallest, largest=largest,
            smallest_seqno=seq, largest_seqno=seq,
            num_entries=props.num_entries,
            num_deletions=props.num_deletions,
            num_range_deletions=props.num_range_deletions,
        )
        edit = VersionEdit()
        edit.add_file(target, meta)
        db.versions.log_and_apply(edit)
        if move:
            os.remove(external_path)
    from toplingdb_tpu.utils.listener import IngestionInfo, notify

    notify(opts.listeners, "on_external_file_ingested", db, IngestionInfo(
        db_name=db.dbname, external_file_path=external_path,
        internal_file_number=fnum, level=target,
    ))
    return target
