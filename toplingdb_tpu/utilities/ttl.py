"""TTL DB: per-record expiry (reference utilities/ttl/ in /root/reference).

Values carry a 4-byte little-endian unix write-timestamp suffix; reads strip
it and hide expired records; a compaction filter physically drops them.
"""

from __future__ import annotations

import struct
import time

from toplingdb_tpu.db.db import DB
from toplingdb_tpu.options import FlushOptions, Options, ReadOptions, WriteOptions
from toplingdb_tpu.utils.compaction_filter import CompactionFilter, Decision
from toplingdb_tpu.utils.status import Corruption

_TS = struct.Struct("<I")


class TtlCompactionFilter(CompactionFilter):
    def __init__(self, ttl: int, clock=time.time, user_filter=None):
        self.ttl = ttl
        self.clock = clock
        self.user_filter = user_filter

    def name(self) -> str:
        return f"TtlCompactionFilter:{self.ttl}"

    def filter(self, level, key, value):
        if len(value) < 4:
            return Decision.KEEP, None
        ts = _TS.unpack_from(value, len(value) - 4)[0]
        if self.ttl > 0 and ts + self.ttl <= int(self.clock()):
            return Decision.REMOVE, None
        if self.user_filter is not None:
            d, nv = self.user_filter.filter(level, key, value[:-4])
            if d == Decision.CHANGE_VALUE:
                return d, (nv or b"") + value[-4:]
            return d, None
        return Decision.KEEP, None


class TtlDB:
    """StackableDB-style wrapper (reference DBWithTTLImpl)."""

    def __init__(self, db: DB, ttl: int, clock=time.time):
        self._db = db
        self.ttl = ttl
        self._clock = clock

    @staticmethod
    def open(path: str, ttl: int, options: Options | None = None,
             clock=time.time) -> "TtlDB":
        options = options or Options()
        options.compaction_filter = TtlCompactionFilter(
            ttl, clock, options.compaction_filter
        )
        return TtlDB(DB.open(path, options), ttl, clock)

    def put(self, key: bytes, value: bytes,
            opts: WriteOptions = WriteOptions()) -> None:
        ts = _TS.pack(int(self._clock()) & 0xFFFFFFFF)
        self._db.put(key, value + ts, opts)

    def get(self, key: bytes, opts: ReadOptions = ReadOptions()) -> bytes | None:
        v = self._db.get(key, opts)
        if v is None:
            return None
        if len(v) < 4:
            raise Corruption("TTL value missing timestamp suffix")
        ts = _TS.unpack_from(v, len(v) - 4)[0]
        if self.ttl > 0 and ts + self.ttl <= int(self._clock()):
            return None  # logically expired but not yet compacted away
        return v[:-4]

    def delete(self, key: bytes, opts: WriteOptions = WriteOptions()) -> None:
        self._db.delete(key, opts)

    def compact_range(self, *a, **kw) -> None:
        self._db.compact_range(*a, **kw)

    def flush(self, fopts: FlushOptions = FlushOptions()) -> None:
        self._db.flush(fopts)

    def close(self) -> None:
        self._db.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    @property
    def db(self) -> DB:
        return self._db
