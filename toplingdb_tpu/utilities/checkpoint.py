"""Checkpoint: consistent openable snapshot of a live DB in a new directory
(reference utilities/checkpoint/checkpoint_impl.cc in /root/reference):
hard-link SSTs (copy on filesystems without links), write a fresh MANIFEST
snapshot + OPTIONS + CURRENT, flush first so no WAL tail is needed.

CURRENT is written LAST: a directory without CURRENT is not a checkpoint,
so a crash mid-copy can never leave a half-snapshot that opens."""

from __future__ import annotations

import os

from toplingdb_tpu.db import filename
from toplingdb_tpu.db.import_column_family_job import (  # noqa: F401
    ExportImportFilesMetaData,
    export_column_family,
)
from toplingdb_tpu.db.log import LogWriter
from toplingdb_tpu.db.version_edit import VersionEdit
from toplingdb_tpu.utils.status import InvalidArgument
from toplingdb_tpu.utils import errors as _errors


def create_checkpoint(db, dest: str) -> None:
    env = db.env
    if env.file_exists(dest):
        try:
            if env.get_children(dest):
                raise InvalidArgument(
                    f"checkpoint dir {dest} exists and is not empty"
                )
        except InvalidArgument:
            raise
        except Exception as e:
            _errors.swallow(reason="checkpoint-dest-probe", exc=e)
    env.create_dir(dest)
    # Pin the file set (reference DisableFileDeletions during checkpoint);
    # the mutex already excludes GC, but the pin also protects any future
    # restructuring that copies outside the lock.
    db.disable_file_deletions()
    try:
        _checkpoint_locked(db, env, dest)
    finally:
        db.enable_file_deletions()


def _checkpoint_locked(db, env, dest: str) -> None:
    with db._mutex:
        db.flush()
        last_seq = db.versions.last_sequence
        # EVERY column family's files (a checkpoint is a whole-DB snapshot).
        cf_files: dict[int, list] = {}
        files = []
        for cf_id, st in sorted(db.versions.column_families.items()):
            cur = [(lvl, f) for lvl, f in st.current.all_files()]
            cf_files[cf_id] = cur
            files.extend(cur)
        # Hard-link when the env is the real posix FS; copy through the Env
        # otherwise (MemEnv / fault injection stay in the loop).
        from toplingdb_tpu.env.env import PosixEnv

        def link_or_copy(src: str, dst: str) -> None:
            if type(env) is PosixEnv:
                try:
                    os.link(src, dst)
                    return
                except OSError:
                    pass
            env.write_file(dst, env.read_file(src), sync=True)

        from toplingdb_tpu.utils.file_checksum import (
            verify_recorded_checksum,
        )

        # Reference mode (storage/shared_env.py): when the DB runs on a
        # SharedSstEnv, a checkpoint holds its SSTs as store references —
        # publish (idempotent; install already did) + adopt, no bytes.
        # Unstamped files (file_checksum='off' / pre-upgrade) still copy.
        ref_env = hasattr(env, "publish_sst") and hasattr(env, "adopt")
        for _, f in files:
            src = filename.table_file_name(db.dbname, f.number)
            dst = filename.table_file_name(dest, f.number)
            if ref_env and f.file_checksum:
                from toplingdb_tpu.storage.object_store import (
                    address_of_meta,
                )

                try:
                    addr = address_of_meta(f)
                    if not env.store.contains(addr):
                        # Install already published (idempotent); this
                        # only fires for pre-store tables.
                        env.publish_sst(src, f)
                    if env.store.contains(addr):
                        env.adopt(dst, addr)
                        continue  # self-verifying: checked at first fetch
                except Exception as e:  # noqa: BLE001 — store outage
                    # A flaky/unreachable store must not abort the
                    # checkpoint: degrade this file to the byte path.
                    _errors.swallow(reason="checkpoint-ref-fallback", exc=e)
            link_or_copy(src, dst)
            # A checkpoint must not propagate corruption: the copy is
            # re-read and compared against the MANIFEST-recorded checksum
            # (no-op for pre-upgrade files without one).
            verify_recorded_checksum(db.env, dst, f)
        # Blob files too: all present ones (deletions are excluded for the
        # duration, so every LIVE blob is here; extra not-yet-GC'd ones are
        # harmless dead weight in the snapshot).
        for child in env.get_children(db.dbname):
            if child.endswith(".blob"):
                link_or_copy(f"{db.dbname}/{child}", f"{dest}/{child}")
        # Fresh MANIFEST snapshot: one edit per column family.
        manifest_number = 1
        w = LogWriter(db.env.new_writable_file(
            filename.manifest_file_name(dest, manifest_number)
        ))
        for cf_id in sorted(cf_files):
            st = db.versions.column_families[cf_id]
            edit = VersionEdit(
                column_family=cf_id,
                column_family_add=st.name,
                max_column_family=db.versions.max_column_family,
            )
            if cf_id == 0:
                edit.comparator = db.icmp.user_comparator.name()
                edit.log_number = 0
                edit.next_file_number = db.versions.next_file_number
                edit.last_sequence = last_seq
            for lvl, f in cf_files[cf_id]:
                edit.add_file(lvl, f)
            w.add_record(edit.encode())
        w.sync()
        w.close()
        db.env.write_file(
            filename.identity_file_name(dest), db.identity.encode()
        )
        # OPTIONS ride in the snapshot (reference checkpoints link the
        # OPTIONS file): a restored DB / follower bootstrap reopens with
        # the same comparator/merge-operator/table config it was built
        # with instead of whatever the caller defaults to.
        try:
            from toplingdb_tpu.utils.config import options_to_config

            import json as _json

            db.env.write_file(
                filename.options_file_name(dest, manifest_number + 1),
                _json.dumps(options_to_config(db.options), indent=1).encode(),
                sync=True,
            )
        except Exception as e:
            # unregistered custom plugin objects: OPTIONS best-effort
            _errors.swallow(reason="options-manifest-best-effort", exc=e)
        # CURRENT last — this write is what MAKES dest a checkpoint.
        filename.set_current_file(db.env, dest, manifest_number)


class Checkpoint:
    """Handle on a checkpoint directory. `Checkpoint.create(db, dest)`
    snapshots a live DB; `Checkpoint(path, env).restore_to(dest)` copies a
    checkpoint into a fresh directory (the follower-bootstrap path in
    replication/follower.py) after verifying it is complete."""

    def __init__(self, path: str, env=None):
        if env is None:
            from toplingdb_tpu.env import default_env

            env = default_env()
        self.path = path
        self.env = env

    @staticmethod
    def create(db, dest: str) -> "Checkpoint":
        create_checkpoint(db, dest)
        return Checkpoint(dest, db.env)

    def verify(self) -> None:
        """A complete checkpoint has CURRENT pointing at a present MANIFEST
        (CURRENT was written last, so its presence implies the rest)."""
        env = self.env
        cur = filename.current_file_name(self.path)
        if not env.file_exists(cur):
            raise InvalidArgument(
                f"{self.path} is not a checkpoint (no CURRENT — "
                f"an interrupted create never writes one)"
            )
        name = env.read_file(cur).decode().strip()
        if not env.file_exists(f"{self.path}/{name}"):
            raise InvalidArgument(
                f"{self.path}: CURRENT points at missing {name}"
            )

    def restore_to(self, dest: str) -> str:
        """Copy this checkpoint into `dest` (must not exist or be empty) and
        return dest, openable as a DB. CURRENT again lands last so an
        interrupted restore is never mistaken for a database."""
        env = self.env
        self.verify()
        if env.file_exists(dest):
            try:
                if env.get_children(dest):
                    raise InvalidArgument(
                        f"restore target {dest} exists and is not empty"
                    )
            except InvalidArgument:
                raise
            except Exception as e:
                _errors.swallow(reason="restore-dest-probe", exc=e)
        env.create_dir(dest)
        # Reference mode (storage/shared_env.py): SSTs the checkpoint
        # holds by reference restore as references — the bootstrap becomes
        # a metadata swap and the bytes arrive lazily through the cache
        # tier on first read (or eagerly via warm_refs below).
        refs = dict(env.refs_of(self.path)) if hasattr(env, "refs_of") \
            else {}
        for name, addr in sorted(refs.items()):
            env.adopt(f"{dest}/{name}", addr)
        children = [c for c in env.get_children(self.path)
                    if c != "CURRENT" and c not in refs]
        # Hard-link fast path: same-filesystem restore of a real posix
        # tree links instead of copying (EXDEV or any link failure falls
        # back to the byte copy, so cross-device restores still work).
        # (Fault-injection wrappers also expose .base — only the shared
        # env may unwrap, or injected read faults would be linked around.)
        from toplingdb_tpu.env.env import PosixEnv
        base = env.base if hasattr(env, "refs_of") else env
        can_link = type(base) is PosixEnv
        for child in children:
            if can_link:
                try:
                    os.link(f"{self.path}/{child}", f"{dest}/{child}")
                    continue
                except OSError:
                    pass
            try:
                data = env.read_file(f"{self.path}/{child}")
            except (OSError, IsADirectoryError):
                continue  # stray subdirectory: checkpoints hold only files
            env.write_file(f"{dest}/{child}", data, sync=True)
        env.write_file(f"{dest}/CURRENT",
                       env.read_file(f"{self.path}/CURRENT"), sync=True)
        # Deep integrity check on the restored copy (the replication
        # follower's bootstrap path rides through here): every
        # MANIFEST-recorded SST checksum is recomputed on the copy, so a
        # truncated/bit-rotted restore fails HERE, not hours later.
        # Referenced SSTs are exempt: their address IS the checksum and
        # the cache tier verifies every fetch, so recomputing here would
        # force the full download the reference mode exists to avoid.
        if not refs:
            try:
                from toplingdb_tpu.utils.file_checksum import (
                    verify_dir_file_checksums,
                )

                verify_dir_file_checksums(dest, env)
            except ImportError:  # pragma: no cover
                pass
        elif hasattr(env, "warm_refs"):
            env.warm_refs(dest)  # fire-and-forget cache warm
        return dest
