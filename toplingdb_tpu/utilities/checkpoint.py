"""Checkpoint: consistent openable snapshot of a live DB in a new directory
(reference utilities/checkpoint/checkpoint_impl.cc in /root/reference):
hard-link SSTs (copy on filesystems without links), write a fresh MANIFEST
snapshot + OPTIONS + CURRENT, flush first so no WAL tail is needed.

CURRENT is written LAST: a directory without CURRENT is not a checkpoint,
so a crash mid-copy can never leave a half-snapshot that opens."""

from __future__ import annotations

import os

from toplingdb_tpu.db import filename
from toplingdb_tpu.db.import_column_family_job import (  # noqa: F401
    ExportImportFilesMetaData,
    export_column_family,
)
from toplingdb_tpu.db.log import LogWriter
from toplingdb_tpu.db.version_edit import VersionEdit
from toplingdb_tpu.utils.status import InvalidArgument
from toplingdb_tpu.utils import errors as _errors


def create_checkpoint(db, dest: str) -> None:
    env = db.env
    if env.file_exists(dest):
        try:
            if env.get_children(dest):
                raise InvalidArgument(
                    f"checkpoint dir {dest} exists and is not empty"
                )
        except InvalidArgument:
            raise
        except Exception as e:
            _errors.swallow(reason="checkpoint-dest-probe", exc=e)
    env.create_dir(dest)
    # Pin the file set (reference DisableFileDeletions during checkpoint);
    # the mutex already excludes GC, but the pin also protects any future
    # restructuring that copies outside the lock.
    db.disable_file_deletions()
    try:
        _checkpoint_locked(db, env, dest)
    finally:
        db.enable_file_deletions()


def _checkpoint_locked(db, env, dest: str) -> None:
    with db._mutex:
        db.flush()
        last_seq = db.versions.last_sequence
        # EVERY column family's files (a checkpoint is a whole-DB snapshot).
        cf_files: dict[int, list] = {}
        files = []
        for cf_id, st in sorted(db.versions.column_families.items()):
            cur = [(lvl, f) for lvl, f in st.current.all_files()]
            cf_files[cf_id] = cur
            files.extend(cur)
        # Hard-link when the env is the real posix FS; copy through the Env
        # otherwise (MemEnv / fault injection stay in the loop).
        from toplingdb_tpu.env.env import PosixEnv

        def link_or_copy(src: str, dst: str) -> None:
            if type(env) is PosixEnv:
                try:
                    os.link(src, dst)
                    return
                except OSError:
                    pass
            env.write_file(dst, env.read_file(src), sync=True)

        from toplingdb_tpu.utils.file_checksum import (
            verify_recorded_checksum,
        )

        for _, f in files:
            link_or_copy(filename.table_file_name(db.dbname, f.number),
                         filename.table_file_name(dest, f.number))
            # A checkpoint must not propagate corruption: the copy is
            # re-read and compared against the MANIFEST-recorded checksum
            # (no-op for pre-upgrade files without one).
            verify_recorded_checksum(
                db.env, filename.table_file_name(dest, f.number), f)
        # Blob files too: all present ones (deletions are excluded for the
        # duration, so every LIVE blob is here; extra not-yet-GC'd ones are
        # harmless dead weight in the snapshot).
        for child in env.get_children(db.dbname):
            if child.endswith(".blob"):
                link_or_copy(f"{db.dbname}/{child}", f"{dest}/{child}")
        # Fresh MANIFEST snapshot: one edit per column family.
        manifest_number = 1
        w = LogWriter(db.env.new_writable_file(
            filename.manifest_file_name(dest, manifest_number)
        ))
        for cf_id in sorted(cf_files):
            st = db.versions.column_families[cf_id]
            edit = VersionEdit(
                column_family=cf_id,
                column_family_add=st.name,
                max_column_family=db.versions.max_column_family,
            )
            if cf_id == 0:
                edit.comparator = db.icmp.user_comparator.name()
                edit.log_number = 0
                edit.next_file_number = db.versions.next_file_number
                edit.last_sequence = last_seq
            for lvl, f in cf_files[cf_id]:
                edit.add_file(lvl, f)
            w.add_record(edit.encode())
        w.sync()
        w.close()
        db.env.write_file(
            filename.identity_file_name(dest), db.identity.encode()
        )
        # OPTIONS ride in the snapshot (reference checkpoints link the
        # OPTIONS file): a restored DB / follower bootstrap reopens with
        # the same comparator/merge-operator/table config it was built
        # with instead of whatever the caller defaults to.
        try:
            from toplingdb_tpu.utils.config import options_to_config

            import json as _json

            db.env.write_file(
                filename.options_file_name(dest, manifest_number + 1),
                _json.dumps(options_to_config(db.options), indent=1).encode(),
                sync=True,
            )
        except Exception as e:
            # unregistered custom plugin objects: OPTIONS best-effort
            _errors.swallow(reason="options-manifest-best-effort", exc=e)
        # CURRENT last — this write is what MAKES dest a checkpoint.
        filename.set_current_file(db.env, dest, manifest_number)


class Checkpoint:
    """Handle on a checkpoint directory. `Checkpoint.create(db, dest)`
    snapshots a live DB; `Checkpoint(path, env).restore_to(dest)` copies a
    checkpoint into a fresh directory (the follower-bootstrap path in
    replication/follower.py) after verifying it is complete."""

    def __init__(self, path: str, env=None):
        if env is None:
            from toplingdb_tpu.env import default_env

            env = default_env()
        self.path = path
        self.env = env

    @staticmethod
    def create(db, dest: str) -> "Checkpoint":
        create_checkpoint(db, dest)
        return Checkpoint(dest, db.env)

    def verify(self) -> None:
        """A complete checkpoint has CURRENT pointing at a present MANIFEST
        (CURRENT was written last, so its presence implies the rest)."""
        env = self.env
        cur = filename.current_file_name(self.path)
        if not env.file_exists(cur):
            raise InvalidArgument(
                f"{self.path} is not a checkpoint (no CURRENT — "
                f"an interrupted create never writes one)"
            )
        name = env.read_file(cur).decode().strip()
        if not env.file_exists(f"{self.path}/{name}"):
            raise InvalidArgument(
                f"{self.path}: CURRENT points at missing {name}"
            )

    def restore_to(self, dest: str) -> str:
        """Copy this checkpoint into `dest` (must not exist or be empty) and
        return dest, openable as a DB. CURRENT again lands last so an
        interrupted restore is never mistaken for a database."""
        env = self.env
        self.verify()
        if env.file_exists(dest):
            try:
                if env.get_children(dest):
                    raise InvalidArgument(
                        f"restore target {dest} exists and is not empty"
                    )
            except InvalidArgument:
                raise
            except Exception as e:
                _errors.swallow(reason="restore-dest-probe", exc=e)
        env.create_dir(dest)
        children = [c for c in env.get_children(self.path)
                    if c != "CURRENT"]
        for child in children:
            try:
                data = env.read_file(f"{self.path}/{child}")
            except (OSError, IsADirectoryError):
                continue  # stray subdirectory: checkpoints hold only files
            env.write_file(f"{dest}/{child}", data, sync=True)
        env.write_file(f"{dest}/CURRENT",
                       env.read_file(f"{self.path}/CURRENT"), sync=True)
        # Deep integrity check on the restored copy (the replication
        # follower's bootstrap path rides through here): every
        # MANIFEST-recorded SST checksum is recomputed on the copy, so a
        # truncated/bit-rotted restore fails HERE, not hours later.
        try:
            from toplingdb_tpu.utils.file_checksum import (
                verify_dir_file_checksums,
            )

            verify_dir_file_checksums(dest, env)
        except ImportError:  # pragma: no cover
            pass
        return dest
