"""WriteBatchWithIndex: an indexed, uncommitted write buffer.

Reference utilities/write_batch_with_index/ in /root/reference — the
structure backing transactions: every update is both appended to a WriteBatch
(for atomic commit) and indexed in a sorted in-memory view so the
transaction can read its own writes (`get_from_batch_and_db`) and iterate
batch+DB merged (`iterator_with_base`). The pluggable rep mirrors the
WBWIFactory hook (write_batch_with_index.h:313 — where the reference's
20x-faster CSPP_WBWI plugs in).
"""

from __future__ import annotations

import bisect

from toplingdb_tpu.db.dbformat import ValueType
from toplingdb_tpu.db.write_batch import WriteBatch
from toplingdb_tpu.options import ReadOptions
from toplingdb_tpu.utils.status import MergeInProgress


class _ListIndex:
    """Sorted-list index (the baseline WBWI rep)."""

    def __init__(self):
        self._items: list[tuple[bytes, int, int, bytes | None]] = []
        self._order = 0

    def insert(self, t: int, key: bytes, value: bytes | None) -> None:
        self._order += 1
        entry = (key, self._order, t, value)
        bisect.insort(self._items, entry, key=lambda e: (e[0], e[1]))

    def newest_first(self, key: bytes) -> list[tuple[int, bytes | None]]:
        i = bisect.bisect_left(self._items, (key, 0),
                               key=lambda e: (e[0], e[1]))
        out = []
        while i < len(self._items) and self._items[i][0] == key:
            out.append((self._items[i][2], self._items[i][3]))
            i += 1
        out.reverse()
        return out

    def keys(self) -> list[bytes]:
        out = []
        for k, _, _, _ in self._items:
            if not out or out[-1] != k:
                out.append(k)
        return out

    def clear(self) -> None:
        self._items.clear()
        self._order = 0


class _SkipIndex:
    """Native arena-skiplist index — the CSPP_WBWI analogue (reference
    README.md:46 claims 20x over the std::skiplist WBWI; ours reuses the
    same native rep the memtable runs on). Entries order newest-first per
    key via an inverted insertion counter."""

    _DELETES = (int(ValueType.DELETION), int(ValueType.SINGLE_DELETION))

    def __init__(self):
        from toplingdb_tpu.db.memtable import NativeSkipListRep

        self._rep = NativeSkipListRep()
        self._order = 0

    def insert(self, t: int, key: bytes, value: bytes | None) -> None:
        self._order += 1
        inv = (1 << 64) - 1 - self._order  # newest sorts first
        self._rep.insert((key, inv),
                         bytes([t]) + (value if value is not None else b""))

    def newest_first(self, key: bytes) -> list[tuple[int, bytes | None]]:
        out = []
        for (uk, _inv), v in self._rep.iter_from((key, 0)):
            if uk != key:
                break
            t = v[0]
            # value-absence is derivable from the type — no marker byte.
            out.append((t, None if t in self._DELETES else bytes(v[1:])))
        return out

    def keys(self) -> list[bytes]:
        out = []
        for (uk, _inv), _v in self._rep.iter_all():
            if not out or out[-1] != uk:
                out.append(uk)
        return out

    def clear(self) -> None:
        from toplingdb_tpu.db.memtable import NativeSkipListRep

        self._rep = NativeSkipListRep()
        self._order = 0


def _make_index(rep: str):
    if rep == "list":
        return _ListIndex()
    if rep in ("skiplist", "auto"):
        try:
            return _SkipIndex()
        except Exception:
            if rep == "skiplist":
                raise
            return _ListIndex()  # auto: no native toolchain
    from toplingdb_tpu.utils.status import InvalidArgument

    raise InvalidArgument(f"unknown WBWI rep {rep!r}")


class WriteBatchWithIndex:
    def __init__(self, merge_operator=None, rep: str = "auto"):
        self.batch = WriteBatch()
        self._merge_op = merge_operator
        self._idx = _make_index(rep)

    # -- writes ---------------------------------------------------------

    def _index(self, t: ValueType, key: bytes, value: bytes | None) -> None:
        self._idx.insert(int(t), key, value)

    def put(self, key: bytes, value: bytes) -> None:
        self.batch.put(key, value)
        self._index(ValueType.VALUE, key, value)

    def delete(self, key: bytes) -> None:
        self.batch.delete(key)
        self._index(ValueType.DELETION, key, None)

    def single_delete(self, key: bytes) -> None:
        self.batch.single_delete(key)
        self._index(ValueType.SINGLE_DELETION, key, None)

    def merge(self, key: bytes, value: bytes) -> None:
        self.batch.merge(key, value)
        self._index(ValueType.MERGE, key, value)

    def clear(self) -> None:
        self.batch.clear()
        self._idx.clear()

    def key_set(self) -> list[bytes]:
        """Distinct keys written through this batch, sorted."""
        return self._idx.keys()

    def count(self) -> int:
        return self.batch.count()

    # -- reads ----------------------------------------------------------

    def _batch_view(self, key: bytes):
        """Newest-first updates for key in this batch: [(type, value)]."""
        return self._idx.newest_first(key)

    def get_from_batch(self, key: bytes):
        """(found, value_or_None) from the batch alone; found=False means the
        batch says nothing conclusive (no entry, or an open merge chain)."""
        operands = []
        for t, v in self._batch_view(key):
            if t == int(ValueType.VALUE):
                if operands:
                    v = self._fold(key, v, operands)
                return True, v
            if t in (int(ValueType.DELETION), int(ValueType.SINGLE_DELETION)):
                if operands:
                    return True, self._fold(key, None, operands)
                return True, None
            if t == int(ValueType.MERGE):
                operands.append(v)
        if operands:
            return False, operands  # open chain: caller folds with DB value
        return False, None

    def _fold(self, key, base, operands):
        if self._merge_op is None:
            raise MergeInProgress("merge in batch but no merge_operator")
        return self._merge_op.full_merge(key, base, list(reversed(operands)))

    def get_from_batch_and_db(self, db, key: bytes,
                              opts: ReadOptions = ReadOptions()):
        found, v = self.get_from_batch(key)
        if found:
            return v
        if isinstance(v, list):  # open merge chain
            base = db.get(key, opts)
            return self._fold(key, base, v)
        return db.get(key, opts)

    def iterator_with_base(self, db, opts: ReadOptions = ReadOptions()):
        """Merged forward iteration over batch + DB (newest batch state wins;
        reference BaseDeltaIterator)."""
        db_it = db.new_iterator(opts)
        db_it.seek_to_first()
        db_pairs = list(db_it.entries())
        # Batch resolved view per key.
        batch_keys = self._idx.keys()
        merged = []
        bi = di = 0
        while bi < len(batch_keys) or di < len(db_pairs):
            if di >= len(db_pairs) or (
                bi < len(batch_keys) and batch_keys[bi] <= db_pairs[di][0]
            ):
                k = batch_keys[bi]
                skip_db = di < len(db_pairs) and db_pairs[di][0] == k
                found, v = self.get_from_batch(k)
                if found:
                    if v is not None:
                        merged.append((k, v))
                elif isinstance(v, list):
                    base = db_pairs[di][1] if skip_db else None
                    merged.append((k, self._fold(k, base, v)))
                if skip_db:
                    di += 1
                bi += 1
            else:
                merged.append(db_pairs[di])
                di += 1
        return merged
