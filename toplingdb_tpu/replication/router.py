"""ReplicaRouter: bounded-staleness read fan-out over follower DBs.

Read-your-writes without synchronous replication: every write through the
router returns a staleness token (the batch's last published sequence —
DB.write's return value); a token-carrying read is served only by replicas
whose applied sequence has reached the token, falling back to the primary
when none has. Token-less reads accept any healthy follower, optionally
bounded by `max_lag_seq` behind the primary.

Replica health reuses the dcompact resilience primitives
(compaction/resilience.py): one CircuitBreaker per follower via a
WorkerHealthRegistry — a follower that throws on reads trips its breaker
after `breaker_failure_threshold` consecutive failures, gets skipped until
the reset timeout, and is re-admitted through a half-open probe read.
"""

from __future__ import annotations

import dataclasses
import threading

from toplingdb_tpu.utils import concurrency as ccy

from toplingdb_tpu.compaction.resilience import (
    DcompactOptions,
    WorkerHealthRegistry,
)
from toplingdb_tpu.options import ReadOptions, WriteOptions
from toplingdb_tpu.utils import errors as _errors
from toplingdb_tpu.utils import statistics as stats_mod

_DEFAULT_READ = ReadOptions()
_DEFAULT_WRITE = WriteOptions()


@dataclasses.dataclass
class RouterOptions:
    # Token-less reads skip followers more than this many sequences behind
    # the primary (None = any applied watermark is acceptable).
    max_lag_seq: int | None = None
    # Breaker policy for follower read errors.
    breaker_failure_threshold: int = 3
    breaker_reset_timeout: float = 5.0


@dataclasses.dataclass(frozen=True)
class StalenessToken:
    """Read-your-writes token: the write's last published sequence plus the
    epoch of the replica set that produced it. A token whose epoch no
    longer matches the router's current epoch is REJECTED — the read is
    re-routed to the primary — never silently served by a follower whose
    applied watermark happens to satisfy the (now meaningless) sequence.
    The sharding plane stamps shard epochs here, so a split/merge/migration
    invalidates every outstanding token for the moved range cleanly."""

    seq: int
    epoch: int = 0


class ReplicaRouter:
    """Fans reads across followers; writes go to the primary and return
    staleness tokens. Pass the token back into get/multi_get/new_iterator
    for read-your-writes. `epoch_provider` (a callable returning the
    replica set's current epoch) arms the StalenessToken epoch check; when
    None, bare integer sequence tokens keep their original meaning."""

    def __init__(self, primary, followers=(), options: RouterOptions | None
                 = None, statistics=None, epoch_provider=None):
        self.primary = primary
        self.options = options or RouterOptions()
        self.stats = statistics if statistics is not None else primary.stats
        self._mu = ccy.Lock("router.ReplicaRouter._mu")
        self._followers: list = list(followers)
        self._rr = 0
        self._epoch_provider = epoch_provider
        self.health = WorkerHealthRegistry(DcompactOptions(
            breaker_failure_threshold=self.options.breaker_failure_threshold,
            breaker_reset_timeout=self.options.breaker_reset_timeout,
        ))

    # -- membership ------------------------------------------------------

    def add_follower(self, follower) -> None:
        with self._mu:
            self._followers.append(follower)

    def remove_follower(self, follower) -> None:
        with self._mu:
            self._followers = [f for f in self._followers
                               if f is not follower]

    def _label(self, follower) -> str:
        return f"replica-{id(follower):x}"

    # -- write path (primary) -------------------------------------------

    def put(self, key: bytes, value: bytes,
            opts: WriteOptions = _DEFAULT_WRITE, cf=None) -> int:
        return self.primary.put(key, value, opts, cf=cf)

    def delete(self, key: bytes, opts: WriteOptions = _DEFAULT_WRITE,
               cf=None) -> int:
        return self.primary.delete(key, opts, cf=cf)

    def merge(self, key: bytes, value: bytes,
              opts: WriteOptions = _DEFAULT_WRITE, cf=None) -> int:
        return self.primary.merge(key, value, opts, cf=cf)

    def write(self, batch, opts: WriteOptions = _DEFAULT_WRITE) -> int:
        return self.primary.write(batch, opts)

    def latest_token(self) -> int:
        return self.primary.latest_sequence_number()

    def current_epoch(self) -> int:
        ep = self._epoch_provider
        return int(ep()) if ep is not None else 0

    def token(self, seq: int) -> StalenessToken:
        """Epoch-stamp a write's returned sequence into a StalenessToken."""
        return StalenessToken(seq=seq, epoch=self.current_epoch())

    # -- replica selection ----------------------------------------------

    def _tick(self, name, n=1):
        if self.stats is not None:
            self.stats.record_tick(name, n)

    def _candidates(self, token):
        """Breaker- and staleness-filtered followers, round-robin order.
        `token` is an int sequence, a StalenessToken, or None. An
        epoch-mismatched StalenessToken yields NO followers (the caller
        then re-routes to the primary, which is never stale)."""
        if isinstance(token, StalenessToken):
            if token.epoch != self.current_epoch():
                self._tick(stats_mod.ROUTER_EPOCH_REJECTS)
                return
            token = token.seq
        with self._mu:
            followers = list(self._followers)
            start = self._rr
            self._rr += 1
        n = len(followers)
        max_lag = self.options.max_lag_seq
        primary_seq = (self.primary.versions.last_sequence
                       if max_lag is not None else 0)
        for i in range(n):
            f = followers[(start + i) % n]
            applied = f.applied_sequence()
            if token is not None and applied < token:
                self._tick(stats_mod.ROUTER_STALE_SKIPS)
                continue
            if max_lag is not None and primary_seq - applied > max_lag:
                self._tick(stats_mod.ROUTER_STALE_SKIPS)
                continue
            label = self._label(f)
            if not self.health.breaker(label).allow():
                self._tick(stats_mod.ROUTER_BREAKER_SKIPS)
                continue
            yield f, label

    # -- read path -------------------------------------------------------

    def get(self, key: bytes, opts: ReadOptions = _DEFAULT_READ,
            cf=None, token=None):
        for f, label in self._candidates(token):
            try:
                v = f.get(key, opts, cf=cf)
            except Exception as e:
                _errors.swallow(reason="replica-get-failover", exc=e)
                self.health.record_failure(label)
                continue
            self.health.record_success(label)
            self._tick(stats_mod.ROUTER_FOLLOWER_READS)
            return v
        self._tick(stats_mod.ROUTER_PRIMARY_READS)
        return self.primary.get(key, opts, cf=cf)

    def multi_get(self, keys, opts: ReadOptions = _DEFAULT_READ,
                  cf=None, token=None):
        for f, label in self._candidates(token):
            try:
                out = f.multi_get(keys, opts, cf=cf)
            except Exception as e:
                _errors.swallow(reason="replica-multiget-failover", exc=e)
                self.health.record_failure(label)
                continue
            self.health.record_success(label)
            self._tick(stats_mod.ROUTER_FOLLOWER_READS, len(keys))
            return out
        self._tick(stats_mod.ROUTER_PRIMARY_READS, len(keys))
        return self.primary.multi_get(keys, opts, cf=cf)

    def multi_get_async(self, keys, opts: ReadOptions = _DEFAULT_READ,
                        cf=None, token=None):
        """Future-returning multi_get: the whole replica-routed walk
        (candidate failover + health accounting) runs on the primary DB's
        async-read executor, so a shard front door can fan sub-batches
        across many shards concurrently (env/async_reads.py)."""
        keys = list(keys)
        return self.primary._submit_async(
            lambda: self.multi_get(keys, opts, cf=cf, token=token))

    def new_iterator(self, opts: ReadOptions = _DEFAULT_READ,
                     cf=None, token=None):
        """An iterator over one token-eligible replica (an iterator is a
        point-in-time view, so it binds to a single DB). Creation errors
        trip the replica's breaker; the primary always serves as backstop."""
        for f, label in self._candidates(token):
            try:
                it = f.new_iterator(opts, cf=cf)
            except Exception as e:
                _errors.swallow(reason="replica-iter-failover", exc=e)
                self.health.record_failure(label)
                continue
            self.health.record_success(label)
            self._tick(stats_mod.ROUTER_FOLLOWER_READS)
            return it
        self._tick(stats_mod.ROUTER_PRIMARY_READS)
        return self.primary.new_iterator(opts, cf=cf)

    # -- introspection ---------------------------------------------------

    def status(self) -> dict:
        with self._mu:
            followers = list(self._followers)
        primary_seq = self.primary.versions.last_sequence
        return {
            "role": "router",
            "primary_sequence": primary_seq,
            "followers": [
                {
                    "label": self._label(f),
                    "applied_sequence": f.applied_sequence(),
                    "lag_seq": max(0, primary_seq - f.applied_sequence()),
                }
                for f in followers
            ],
            "health": self.health.snapshot(),
        }
