"""FollowerDB: a read replica that tails shipped WAL frames.

Extends SecondaryDB (db/db_readonly.py) with a continuous tail/apply loop:
shipped batches land in the follower's memtables at their original
sequence numbers; when the primary's MANIFEST epoch advances (flush or
compaction installed a new version) a directory-sharing follower swaps in
the new version set and re-pulls the WAL tail; when lag outruns WAL
retention, the follower bootstraps from a fresh primary checkpoint through
utilities/checkpoint.py's Checkpoint.restore_to.

Two deployment modes:

  shared      dbname IS the primary's directory (the reference secondary
              instance shape): SSTs and MANIFEST are read in place; only
              the WAL tail travels as frames. Epoch changes trigger a
              MANIFEST re-read; retention gaps resolve the same way
              (the new MANIFEST's SSTs cover the GC'd WALs).
  standalone  dbname is the follower's own directory, seeded by a
              checkpoint restore over the shared filesystem (the dcompact
              data-plane assumption): frames accumulate in the memtable;
              retention gaps trigger a full re-bootstrap.

The applied-sequence watermark (`applied_sequence()`) only advances AFTER
a batch's entries are visible, so the router's token rule — serve a
token-carrying read only from replicas with applied >= token — yields
read-your-writes with no locks on the read path.
"""

from __future__ import annotations

import threading

from toplingdb_tpu.utils import concurrency as ccy
import time

from toplingdb_tpu.db import filename
from toplingdb_tpu.db.db_readonly import SecondaryDB
from toplingdb_tpu.db.write_batch import WriteBatch
from toplingdb_tpu.options import Options
from toplingdb_tpu.replication.log_shipper import WalRetentionGone
from toplingdb_tpu.utils import statistics as stats_mod
from toplingdb_tpu.utils.status import Corruption, IOError_
from toplingdb_tpu.utils import errors as _errors


class FollowerDB(SecondaryDB):
    """A SecondaryDB fed by a ReplicationTransport instead of (only) the
    shared directory. Use FollowerDB.open(); then either call catch_up()
    on your own cadence or start_tailing() for a background loop."""

    @staticmethod
    def open(dbname: str, options: Options | None = None, env=None,
             transport=None, mode: str = "shared",
             bootstrap: bool = True) -> "FollowerDB":
        options = options or Options()
        options.create_if_missing = False
        options.disable_auto_compactions = True
        options.read_only = True
        from toplingdb_tpu.env import default_env

        env = env or default_env()
        if (mode == "standalone" and bootstrap and transport is not None
                and not env.file_exists(filename.current_file_name(dbname))):
            FollowerDB._restore_checkpoint_into(dbname, env, transport)
        db = FollowerDB(dbname, options, env)
        db._mode = mode
        db._transport = transport
        db._epoch = None
        db._applied_seq = None  # None = pull from the retention head
        db._tail_stop = threading.Event()
        db._tail_thread = None
        db.tail_errors = 0
        # Telemetry: finished apply-span dicts awaiting the next pull (the
        # ship-frame ack channel). Fire-and-forget and bounded — a dead
        # primary or a dropped pull must neither error nor leak.
        db._span_outbox = []
        db._journal = None  # standalone-mode frame journal (local WAL)
        db.versions.recover(readonly=True)
        db._compaction_scheduler = None
        if mode == "shared":
            db._replay_wals_into_mem()
            db._applied_seq = db.versions.last_sequence
            db._epoch = db._local_epoch()
        else:
            # Checkpoint-restored: SSTs carry everything up to the
            # checkpoint sequence; frames take it from there. The frame
            # JOURNAL (a local WAL of every applied rep) makes applied
            # frames durable in OUR directory: re-opens resume from it,
            # and promote() → DB.open replays it — without it every frame
            # applied after the checkpoint lived only in the memtable and
            # silently vanished at promote (the migration-cutover
            # data-loss hole the sharding chaos soak caught).
            db._materialize_cfs()
            db._replay_wals_into_mem()  # prior journals, on re-open
            db._applied_seq = db.versions.last_sequence
            db._open_frame_journal()
        db._repl_status_provider = db.replication_status
        return db

    # -- bootstrap -------------------------------------------------------

    @staticmethod
    def _restore_checkpoint_into(dbname: str, env, transport) -> None:
        from toplingdb_tpu.utilities.checkpoint import Checkpoint

        ckpt = f"{dbname}.bootstrap-ckpt"
        transport.request_checkpoint(ckpt)
        Checkpoint(ckpt, env).restore_to(dbname)
        _rm_tree(env, ckpt)

    def _bootstrap(self) -> None:
        """Standalone follower fell behind WAL retention: wipe and restore
        from a fresh primary checkpoint (reference secondaries re-open)."""
        if self.stats is not None:
            self.stats.record_tick(stats_mod.REPLICATION_BOOTSTRAPS)
        if self._transport is None:
            raise IOError_("follower lag exceeds WAL retention and no "
                           "transport is attached to bootstrap from")
        from toplingdb_tpu.db.table_cache import TableCache
        from toplingdb_tpu.db.version_set import VersionSet
        from toplingdb_tpu.utilities.checkpoint import Checkpoint

        ckpt = f"{self.dbname}.bootstrap-ckpt"
        _rm_tree(self.env, ckpt)
        self._transport.request_checkpoint(ckpt)
        with self._mutex:
            self._close_frame_journal(sync=False)  # wiped with the rest
            self.table_cache.close()
            for child in list(self.env.get_children(self.dbname)):
                try:
                    self.env.delete_file(f"{self.dbname}/{child}")
                except Exception as e:
                    # subdirectories (archive/) stay; files go
                    _errors.swallow(reason="wipe-db-file-delete", exc=e)
            Checkpoint(ckpt, self.env).restore_to(self.dbname)
            _rm_tree(self.env, ckpt)
            vs = VersionSet(self.env, self.dbname, self.icmp,
                            self.options.num_levels)
            vs.recover(readonly=True)
            self.versions = vs
            self.table_cache = TableCache(
                self.env, self.dbname, self.icmp, self.options.table_options,
                block_cache=self.options.block_cache)
            self.table_cache.stats = self.options.statistics
            for cf_id in list(self._cfs):
                if cf_id != 0:
                    del self._cfs[cf_id]
            self._cfs[0].mem = self._fresh_memtable()
            self._cfs[0].imm = []
            self._materialize_cfs()
            self._applied_seq = vs.last_sequence
            self._epoch = None  # next state observation resets it
            self._open_frame_journal()

    # -- frame journal (standalone durability) ---------------------------

    def _open_frame_journal(self) -> None:
        """A fresh local WAL for applied frame reps (standalone mode owns
        its directory, so writing one is safe — shared mode must never:
        dbname is the PRIMARY's directory). Reps carry their original
        sequence numbers, so DB recovery replays them verbatim."""
        from toplingdb_tpu.db.log import LogWriter

        num = self.versions.new_file_number()
        self._journal = LogWriter(self.env.new_writable_file(
            filename.log_file_name(self.dbname, num)))

    def _close_frame_journal(self, sync: bool) -> None:
        j = self._journal
        self._journal = None
        if j is None:
            return
        try:
            if sync:
                j.sync()
            j.close()
        except Exception as e:
            # a broken journal close must not block shutdown
            _errors.swallow(reason="frame-journal-close-on-shutdown", exc=e)

    # -- epoch / version swap -------------------------------------------

    def _local_epoch(self) -> int:
        from toplingdb_tpu.replication.log_shipper import pack_epoch

        return pack_epoch(self.versions.manifest_file_number,
                          getattr(self.versions, "edit_seq", 0))

    def _reload_versions(self) -> None:
        """Shared-directory version swap: the primary flushed/compacted.
        Fresh memtables + applied=None forces the next pull to restart at
        the retention head; everything below it is covered by the SSTs the
        new MANIFEST installed. Readers between the swap and the re-pull
        see the (consistent) manifest view."""
        if self.stats is not None:
            self.stats.record_tick(stats_mod.REPLICATION_EPOCH_RELOADS)
        with self._mutex:
            self._reload_manifest_view()
            self._applied_seq = None

    # -- tail/apply loop -------------------------------------------------

    def applied_sequence(self) -> int:
        """Router-facing watermark: every sequence <= this is visible to
        reads. 0 while a reload/bootstrap is repositioning the cursor (the
        router then treats this replica as arbitrarily stale)."""
        s = self._applied_seq
        return 0 if s is None else s

    def catch_up(self, max_bytes: int = 1 << 22) -> int:
        """One pull/apply round. Returns the number of batches applied."""
        tr = self._transport
        if tr is None:
            # Pure shared-directory mode: behave like SecondaryDB.
            self.try_catch_up_with_primary()
            self._applied_seq = self.versions.last_sequence
            self._epoch = self._local_epoch()
            return 0
        outbox = None
        if self._span_outbox:
            # Hand the pending apply spans to this pull (the ack). The
            # outbox clears regardless of outcome: a dropped exchange
            # degrades the primary's trace to primary-only, nothing leaks.
            outbox, self._span_outbox = self._span_outbox, []
        try:
            frames, state = tr.pull(self._applied_seq, max_bytes=max_bytes,
                                    span_export=outbox)
        except Corruption:
            # Truncated/bitflipped frame: nothing applied; re-pull later.
            if self.stats is not None:
                self.stats.record_tick(stats_mod.REPLICATION_FRAME_CORRUPT)
            return 0
        except WalRetentionGone:
            if self._mode == "shared":
                # The MANIFEST that advanced past those WALs is in our
                # directory: re-read it instead of copying a checkpoint.
                self._reload_versions()
            else:
                self._bootstrap()
            return 0
        epoch = state.get("epoch")
        if self._mode == "shared" and epoch is not None \
                and epoch != self._epoch:
            self._reload_versions()
            self._epoch = epoch
            return 0  # re-pull from the retention head next round
        self._epoch = epoch
        t_ap = time.monotonic()
        applied = self._apply_frames(frames)
        if applied:
            self._bank_apply_spans(state.get("trace_ctxs"),
                                   (time.monotonic() - t_ap) * 1e6)
        if self._applied_seq is None and state.get("wal_floor_seq") is None:
            # From-head pull and the primary retains NO WAL records: every
            # published sequence is durable in the SSTs our MANIFEST view
            # already covers — adopt the primary's watermark.
            self._applied_seq = state.get(
                "last_sequence", self.versions.last_sequence)
        return applied

    def _bank_apply_spans(self, ctxs, dur_us: float) -> None:
        """Record one finished `follower.apply` span per propagated write
        context this round actually covered; they ride the NEXT pull back
        to the primary and stitch into the write's trace."""
        if not ctxs:
            return
        aseq = self.applied_sequence()
        for c in ctxs:
            if not c.get("trace_id") or c.get("seq", 0) > aseq:
                continue
            self._span_outbox.append({
                "name": "follower.apply",
                "trace_id": c["trace_id"],
                "parent_id": c.get("span_id", 0),
                "span_id": 0,
                "start_us": 0,
                "dur_us": int(dur_us),
                "proc": "follower",
                "tags": {"seq": c.get("seq"), "mode": self._mode,
                         "db": self.dbname},
            })
        if len(self._span_outbox) > 256:
            del self._span_outbox[: len(self._span_outbox) - 256]

    def _apply_frames(self, frames) -> int:
        applied = 0
        now_us = int(time.time() * 1e6)
        for frame in frames:
            if self._applied_seq is not None \
                    and frame.last_seq <= self._applied_seq:
                continue  # duplicate delivery
            if self._applied_seq is not None \
                    and frame.first_seq > self._applied_seq + 1 \
                    and self.stats is not None:
                # Sequences absent from the WAL (disable_wal writes) or an
                # upstream anomaly: observable either way.
                self.stats.record_tick(stats_mod.REPLICATION_FRAME_GAPS)
            mems = {cf_id: cfd.mem for cf_id, cfd in self._cfs.items()}
            for rep in frame.batches:
                b = WriteBatch(rep)
                cnt = b.count()
                if cnt == 0:
                    continue
                end = b.sequence() + cnt - 1
                if self._applied_seq is not None \
                        and end <= self._applied_seq:
                    continue
                if self._journal is not None:
                    # Journal-first (WAL discipline): a crash between the
                    # append and the insert replays the rep on re-open.
                    self._journal.add_record(rep)
                b.insert_into(mems)
                # Publish order: entries first, then the watermark — a
                # router read that saw applied>=token is guaranteed the
                # token's entries are in the view it snapshots.
                if end > self.versions.last_sequence:
                    self.versions.last_sequence = end
                self._applied_seq = end
                applied += 1
            if self.stats is not None:
                self.stats.record_tick(stats_mod.REPLICATION_FRAMES_APPLIED)
                lag = max(0, now_us - frame.shipped_unix_us)
                self.stats.record_in_histogram(
                    stats_mod.REPLICATION_LAG_MICROS, lag)
        if applied and self.stats is not None:
            self.stats.record_tick(
                stats_mod.REPLICATION_RECORDS_APPLIED, applied)
        return applied

    # -- background tailing ---------------------------------------------

    def start_tailing(self, interval: float = 0.05) -> None:
        if self._tail_thread is not None:
            return
        self._tail_stop.clear()

        def loop():
            while not self._tail_stop.is_set():
                try:
                    self.catch_up()
                except Exception as e:
                    # The loop must survive transient primary restarts /
                    # transport outages; the next round retries.
                    _errors.swallow(reason="tail-loop-retry", exc=e)
                    self.tail_errors += 1
                if self._tail_stop.wait(interval):
                    return

        self._tail_thread = ccy.spawn("follower-tail", loop, owner=self,
                                      stop=self.stop_tailing)

    def stop_tailing(self) -> None:
        self._tail_stop.set()
        t = self._tail_thread
        if t is not None:
            t.join(timeout=5.0)
            self._tail_thread = None

    def close(self) -> None:
        self.stop_tailing()
        self._close_frame_journal(sync=True)
        super().close()

    # -- admin ----------------------------------------------------------

    def promote(self) -> str:
        """Detach from the (dead) primary: final best-effort catch-up, stop
        tailing, close, and return the path — reopen it with DB.open() for
        read-write service (tools/repl_admin.py drives this)."""
        self.stop_tailing()
        try:
            self.catch_up()
        except Exception as e:
            # primary is gone; serve what we have
            _errors.swallow(reason="promote-final-catch-up", exc=e)
        path = self.dbname
        self.close()
        return path

    def replication_status(self) -> dict:
        return {
            "role": "follower",
            "mode": self._mode,
            "applied_sequence": self.applied_sequence(),
            "epoch": self._epoch,
            "tailing": self._tail_thread is not None,
            "tail_errors": self.tail_errors,
        }


def _rm_tree(env, path: str) -> None:
    """Best-effort recursive delete through the Env (checkpoint staging)."""
    try:
        for child in env.get_children(path):
            try:
                env.delete_file(f"{path}/{child}")
            except Exception as e:
                _errors.swallow(reason="rm-tree-recurse-dir", exc=e)
                _rm_tree(env, f"{path}/{child}")
    except Exception as e:
        _errors.swallow(reason="rm-tree-best-effort", exc=e)
