"""Replication plane: WAL shipping, follower DBs, bounded-staleness routing.

The serving-scale counterpart of distributed compaction (ROADMAP north
star): dcompact moves compaction work OFF the primary; this package moves
read traffic off it. Three pieces:

  log_shipper   primary side — tails the live WAL(s) into sequence-tagged,
                CRC-framed batches; serves them to followers over a local
                call or the dcompact-style HTTP control plane; tracks the
                MANIFEST epoch so followers know when to re-read it.
  follower      FollowerDB(SecondaryDB) — continuous tail/apply loop with
                version swap on primary flush/compaction and automatic
                checkpoint bootstrap when lag outruns WAL retention.
  router        ReplicaRouter — fans get/multi_get/iterators across
                followers under read-your-writes staleness tokens, with
                breaker/health-aware replica selection reusing
                compaction/resilience.py primitives.
"""

from toplingdb_tpu.replication.follower import FollowerDB
from toplingdb_tpu.replication.log_shipper import (
    FaultyTransport,
    HttpTransport,
    LocalTransport,
    LogShipper,
    ReplicationServer,
    ShipFrame,
    WalRetentionGone,
)
from toplingdb_tpu.replication.router import (
    ReplicaRouter,
    RouterOptions,
    StalenessToken,
)

__all__ = [
    "FaultyTransport",
    "FollowerDB",
    "HttpTransport",
    "LocalTransport",
    "LogShipper",
    "ReplicaRouter",
    "ReplicationServer",
    "RouterOptions",
    "ShipFrame",
    "StalenessToken",
    "WalRetentionGone",
]
