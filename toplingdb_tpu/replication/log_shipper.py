"""Primary-side WAL shipping: sequence-tagged, CRC-framed batch transport.

The data being shipped is exactly what the primary's group commit wrote to
the WAL — WriteBatch reps, each carrying its own first sequence and count —
so followers apply bit-identical mutations at identical sequence numbers.
The shipper tails the retained WAL set (live + archived) through
db/log.py's TailingLogReader, which distinguishes a torn in-flight append
(retry next poll) from real corruption (raise), and serves any follower
from any acknowledged sequence as long as the covering WALs are retained.
When they are not, the follower gets WalRetentionGone and bootstraps from a
checkpoint (utilities/checkpoint.py), mirroring how the reference's
secondary instances fall back to a full re-open.

Frames also carry the primary's MANIFEST epoch — (manifest_file_number,
edit_seq) packed into 64 bits — so a follower sharing the directory knows
the instant it must re-read the MANIFEST (flush/compaction installed a new
version) instead of polling it.

Transport layers, smallest to largest:

  LocalTransport   direct function calls (tests; same-process replicas)
  HttpTransport    pulls frames from a ReplicationServer over HTTP with
                   the same control-plane/shared-data-plane split as
                   compaction/dcompact_service.py
  FaultyTransport  chaos wrapper driven by env/fault_injection.py's
                   ShipFaultInjector (drop/delay/truncate)
"""

from __future__ import annotations

import base64
import dataclasses
import json
import os
import threading

from toplingdb_tpu.utils import concurrency as ccy
import time
import urllib.request
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from toplingdb_tpu.db.log import TailingLogReader
from toplingdb_tpu.db.write_batch import WriteBatch
from toplingdb_tpu.utils import coding, crc32c
from toplingdb_tpu.utils import statistics as stats_mod
from toplingdb_tpu.utils.status import Corruption, IOError_, NotFound
from toplingdb_tpu.utils import errors as _errors

FRAME_MAGIC = b"TSHP"
FRAME_VERSION = 1
# magic(4) version(1) reserved(1) epoch(8) first(8) last(8) shipped_us(8)
# n_batches(4) payload_len(4) masked_crc(4)
FRAME_HEADER_SIZE = 50


class WalRetentionGone(Exception):
    """The requested sequence range is no longer covered by retained WALs:
    the follower must bootstrap from a checkpoint (or, sharing the
    directory, re-read the MANIFEST whose SSTs cover the gap)."""


@dataclasses.dataclass
class ShipFrame:
    """One shipped unit: consecutive WriteBatch reps covering
    [first_seq, last_seq], CRC-framed as a whole so a truncated or bitflipped
    transport payload is detected before ANY batch applies."""

    epoch: int
    first_seq: int
    last_seq: int
    shipped_unix_us: int
    batches: list[bytes]

    def encode(self) -> bytes:
        payload = b"".join(
            coding.encode_fixed32(len(b)) + b for b in self.batches
        )
        crc = crc32c.mask(crc32c.value(payload))
        return (
            FRAME_MAGIC
            + bytes([FRAME_VERSION, 0])
            + coding.encode_fixed64(self.epoch)
            + coding.encode_fixed64(self.first_seq)
            + coding.encode_fixed64(self.last_seq)
            + coding.encode_fixed64(self.shipped_unix_us)
            + coding.encode_fixed32(len(self.batches))
            + coding.encode_fixed32(len(payload))
            + coding.encode_fixed32(crc)
            + payload
        )

    @staticmethod
    def decode(buf: bytes) -> "ShipFrame":
        if len(buf) < FRAME_HEADER_SIZE:
            raise Corruption(
                f"ship frame shorter than header ({len(buf)} bytes)"
            )
        if buf[:4] != FRAME_MAGIC:
            raise Corruption("ship frame bad magic")
        if buf[4] != FRAME_VERSION:
            raise Corruption(f"ship frame unknown version {buf[4]}")
        epoch = coding.decode_fixed64(buf, 6)
        first = coding.decode_fixed64(buf, 14)
        last = coding.decode_fixed64(buf, 22)
        shipped = coding.decode_fixed64(buf, 30)
        n_batches = coding.decode_fixed32(buf, 38)
        payload_len = coding.decode_fixed32(buf, 42)
        stored_crc = coding.decode_fixed32(buf, 46)
        payload = buf[FRAME_HEADER_SIZE : FRAME_HEADER_SIZE + payload_len]
        if len(payload) != payload_len:
            raise Corruption("ship frame truncated payload")
        if crc32c.unmask(stored_crc) != crc32c.value(payload):
            raise Corruption("ship frame checksum mismatch")
        batches: list[bytes] = []
        off = 0
        for _ in range(n_batches):
            if off + 4 > payload_len:
                raise Corruption("ship frame batch count overruns payload")
            ln = coding.decode_fixed32(payload, off)
            off += 4
            if off + ln > payload_len:
                raise Corruption("ship frame batch length overruns payload")
            batches.append(bytes(payload[off : off + ln]))
            off += ln
        return ShipFrame(epoch=epoch, first_seq=first, last_seq=last,
                         shipped_unix_us=shipped, batches=batches)


def pack_epoch(manifest_file_number: int, edit_seq: int) -> int:
    return ((manifest_file_number & 0xFFFFFFFF) << 32) | (
        edit_seq & 0xFFFFFFFF)


class LogShipper:
    """Tails the primary's retained WALs into an in-order cache of
    (first_seq, last_seq, rep) batch records and cuts ShipFrames from it.
    The cache holds only records whose source WAL is still retained, so
    its memory is bounded by WAL retention — and so `frames_since` fails
    with WalRetentionGone exactly when the WALs could no longer serve the
    request either."""

    def __init__(self, db, statistics=None, max_frame_bytes: int = 1 << 20):
        self.db = db
        self.stats = statistics if statistics is not None else db.stats
        self.max_frame_bytes = max_frame_bytes
        self._mu = ccy.Lock("log_shipper.LogShipper._mu")
        self._tails: dict[int, TailingLogReader] = {}
        # (first_seq, last_seq, rep, wal_number), ascending by sequence.
        self._records: list[tuple[int, int, bytes, int]] = []
        self.frames_shipped = 0
        self.bytes_shipped = 0
        db._repl_status_provider = self.status

    # -- epoch ----------------------------------------------------------

    def epoch(self) -> int:
        vs = self.db.versions
        return pack_epoch(vs.manifest_file_number,
                          getattr(vs, "edit_seq", 0))

    def state(self) -> dict:
        return {
            "epoch": self.epoch(),
            "last_sequence": self.db.versions.last_sequence,
            "wal_floor_seq": self._records[0][0] if self._records else None,
        }

    # -- WAL tailing ----------------------------------------------------

    def _poll_wals(self) -> None:
        wals = self.db.get_wal_files()  # (number, path, archived), sorted
        live = {num for num, _, _ in wals}
        for num in list(self._tails):
            if num not in live:
                del self._tails[num]
        if self._records and any(r[3] not in live for r in self._records):
            self._records = [r for r in self._records if r[3] in live]
        newest = max(live) if live else None
        last_cached = self._records[-1][1] if self._records else 0
        for num, path, archived in wals:
            tr = self._tails.get(num)
            if tr is None:
                tr = TailingLogReader(self.db.env, path, log_number=num)
                self._tails[num] = tr
            # A WAL below the newest number (or archived) will never grow:
            # a torn tail there is a dead tail, not an in-flight append.
            final = archived or num != newest
            try:
                recs = tr.poll(final=final)
            except NotFound:
                self._tails.pop(num, None)  # GC'd mid-poll: drop the tail
                continue
            for rec in recs:
                b = WriteBatch(rec)
                cnt = b.count()
                if cnt == 0:
                    continue
                s0 = b.sequence()
                s1 = s0 + cnt - 1
                if s1 <= last_cached:
                    continue  # duplicate coverage (recycled-file residue)
                self._records.append((s0, s1, rec, num))
                last_cached = s1

    # -- frame service ---------------------------------------------------

    def frames_since(self, since_seq: int | None,
                     max_bytes: int = 1 << 22) -> tuple[list[ShipFrame], dict]:
        """Frames covering every retained batch with last_seq > since_seq
        (bounded by max_bytes), plus the primary state. `since_seq=None`
        means 'from the oldest retained record' — the follower just
        reloaded the MANIFEST, whose SSTs cover everything older.
        Raises WalRetentionGone when sequences after since_seq have been
        GC'd from the WAL set."""
        with self._mu:
            self._poll_wals()
            state = self.state()
            recs = self._records
            if since_seq is None:
                start = 0
            else:
                lo, hi = 0, len(recs)
                while lo < hi:  # first record with last_seq > since_seq
                    mid = (lo + hi) // 2
                    if recs[mid][1] <= since_seq:
                        lo = mid + 1
                    else:
                        hi = mid
                start = lo
                if start == len(recs):
                    if since_seq < state["last_sequence"] and not recs:
                        # Everything newer was flushed AND its WALs GC'd.
                        raise WalRetentionGone(
                            f"no retained WAL covers seq > {since_seq}"
                        )
                    return [], state
                if recs[start][0] > since_seq + 1:
                    raise WalRetentionGone(
                        f"WAL retention starts at seq {recs[start][0]}, "
                        f"follower needs {since_seq + 1}"
                    )
            frames: list[ShipFrame] = []
            shipped_us = int(time.time() * 1e6)
            batches: list[bytes] = []
            first = last = None
            size = 0
            total = 0

            def cut() -> None:
                nonlocal batches, first, last, size
                if batches:
                    frames.append(ShipFrame(
                        epoch=state["epoch"], first_seq=first, last_seq=last,
                        shipped_unix_us=shipped_us, batches=batches))
                    batches, first, last, size = [], None, None, 0

            for s0, s1, rep, _num in recs[start:]:
                if total + len(rep) > max_bytes and total > 0:
                    break
                if size + len(rep) > self.max_frame_bytes and batches:
                    cut()
                if first is None:
                    first = s0
                last = s1
                batches.append(rep)
                size += len(rep)
                total += len(rep)
            cut()
            if frames:
                self.frames_shipped += len(frames)
                self.bytes_shipped += total
                if self.stats is not None:
                    self.stats.record_tick(
                        stats_mod.REPLICATION_FRAMES_SHIPPED, len(frames))
                    self.stats.record_tick(
                        stats_mod.REPLICATION_BYTES_SHIPPED, total)
                tracer = getattr(self.db, "tracer", None)
                if tracer is not None:
                    # Telemetry propagation: contexts of sampled writes
                    # covered by these frames ride the pull state, so the
                    # follower can record its apply spans under them.
                    ctxs = tracer.ctxs_in_range(frames[0].first_seq,
                                                frames[-1].last_seq)
                    if ctxs:
                        state["trace_ctxs"] = ctxs
            return frames, state

    def accept_spans(self, spans) -> int:
        """Follower-ack half of the telemetry plane: finished follower
        span dicts arriving with a later pull stitch into the primary's
        originating traces. Unknown/evicted trace ids drop silently."""
        tracer = getattr(self.db, "tracer", None)
        if tracer is None or not spans:
            return 0
        return tracer.attach_remote(spans)

    def status(self) -> dict:
        return {
            "role": "primary",
            "last_sequence": self.db.versions.last_sequence,
            "epoch": self.epoch(),
            "frames_shipped": self.frames_shipped,
            "bytes_shipped": self.bytes_shipped,
            "retained_records": len(self._records),
            "wal_floor_seq": self._records[0][0] if self._records else None,
        }


# ---------------------------------------------------------------------------
# Transports
# ---------------------------------------------------------------------------


class ReplicationTransport:
    """Follower-side view of a primary: pull frames, ask for checkpoints.
    `span_export` carries the follower's finished telemetry spans back to
    the primary piggybacked on the pull (the ship-frame ack channel) —
    fire-and-forget: a dropped pull drops the spans with it."""

    def pull(self, since_seq: int | None, max_bytes: int = 1 << 22,
             span_export=None) -> tuple[list[ShipFrame], dict]:
        raise NotImplementedError

    def request_checkpoint(self, dest: str) -> str:
        raise NotImplementedError


class LocalTransport(ReplicationTransport):
    """Same-process primary (tests; co-located replicas on shared fs)."""

    def __init__(self, shipper: LogShipper):
        self.shipper = shipper

    def pull(self, since_seq, max_bytes: int = 1 << 22, span_export=None):
        if span_export:
            self.shipper.accept_spans(span_export)
        return self.shipper.frames_since(since_seq, max_bytes=max_bytes)

    def request_checkpoint(self, dest: str) -> str:
        from toplingdb_tpu.utilities.checkpoint import create_checkpoint

        create_checkpoint(self.shipper.db, dest)
        return dest


class HttpTransport(ReplicationTransport):
    """Pulls frames from a ReplicationServer. Control plane over HTTP,
    bulk data (checkpoints) over the shared filesystem — the same split as
    the dcompact service.

    Failure policy reuses the dcompact boundary's (resilience.py): every
    request carries a per-attempt timeout (a hung peer can no longer wedge
    the calling router thread indefinitely), network-level failures get a
    bounded exponential-backoff retry, and a per-URL CircuitBreaker makes
    a dead primary fail FAST after `breaker_failure_threshold` strikes
    instead of paying the timeout on every pull. HTTP-level answers are
    authoritative (the peer is alive): 410 maps to WalRetentionGone, other
    codes to IOError_ — neither is retried here."""

    def __init__(self, url: str, timeout: float = 30.0, options=None):
        from toplingdb_tpu.compaction.resilience import (
            CircuitBreaker,
            DcompactOptions,
        )

        self.url = url.rstrip("/")
        self.timeout = timeout
        self.options = options or DcompactOptions(
            max_attempts=3, backoff_base=0.05, attempt_timeout=timeout,
            breaker_reset_timeout=5.0)
        self.breaker = CircuitBreaker(
            failure_threshold=self.options.breaker_failure_threshold,
            reset_timeout=self.options.breaker_reset_timeout)

    def _post(self, path: str, body: dict) -> dict:
        data = json.dumps(body).encode()
        last_err: Exception | None = None
        for attempt in range(1, self.options.max_attempts + 1):
            if not self.breaker.allow():
                raise IOError_(
                    f"replication peer {self.url} circuit open "
                    f"(consecutive failures "
                    f">= {self.breaker.failure_threshold})")
            req = urllib.request.Request(
                self.url + path, data=data,
                headers={"Content-Type": "application/json"},
                method="POST")
            try:
                with urllib.request.urlopen(
                        req, timeout=min(self.timeout,
                                         self.options.attempt_timeout)) as r:
                    out = json.loads(r.read())
                self.breaker.on_success()
                return out
            except urllib.error.HTTPError as e:
                # The peer ANSWERED: it is alive (breaker success), and the
                # answer is deterministic — retrying cannot change it.
                self.breaker.on_success()
                try:
                    payload = json.loads(e.read())
                except Exception as e2:
                    _errors.swallow(reason="http-error-body-parse", exc=e2)
                    payload = {}
                if e.code == 410 or \
                        payload.get("error") == "wal_retention_gone":
                    raise WalRetentionGone(payload.get("detail", "")) from e
                raise IOError_(
                    f"replication POST {path} to {self.url}: HTTP {e.code}"
                ) from e
            except OSError as e:
                # Network-level (refused / reset / timeout): the retryable
                # class — back off and try again, up to the bound.
                self.breaker.on_failure()
                last_err = e
                if attempt < self.options.max_attempts:
                    time.sleep(self.options.backoff_delay(attempt))
        raise IOError_(
            f"replication POST {path} to {self.url} failed after "
            f"{self.options.max_attempts} attempts: {last_err}"
        ) from last_err

    def pull(self, since_seq, max_bytes: int = 1 << 22, span_export=None):
        req = {"since_seq": since_seq, "max_bytes": max_bytes}
        if span_export:
            req["spans"] = span_export
        body = self._post("/replication/pull", req)
        frames = [ShipFrame.decode(base64.b64decode(f))
                  for f in body.get("frames_b64", [])]
        return frames, body.get("state", {})

    def request_checkpoint(self, dest: str) -> str:
        body = self._post("/replication/checkpoint", {"dest": dest})
        return body.get("dest", dest)


class FaultyTransport(ReplicationTransport):
    """Chaos wrapper: injects drop/delay/truncate on pulled frames via an
    env/fault_injection.py ShipFaultInjector. Truncation is applied to the
    encoded frame bytes and re-decoded so the follower's CRC/short-frame
    detection path is what gets exercised — exactly what a flaky network
    or a crashed relay would produce."""

    def __init__(self, inner: ReplicationTransport, injector):
        self.inner = inner
        self.injector = injector

    def pull(self, since_seq, max_bytes: int = 1 << 22, span_export=None):
        plan = self.injector.plan()
        if plan == "delay":
            time.sleep(self.injector.delay_sec)
        if plan == "drop":
            # The whole exchange is lost — the ack's span export with it
            # (the primary keeps a primary-only trace; no error, no leak).
            span_export = None
        frames, state = self.inner.pull(since_seq, max_bytes=max_bytes,
                                        span_export=span_export)
        if plan == "drop":
            return [], state
        if plan == "truncate" and frames:
            mangled = self.injector.truncate_bytes(frames[0].encode())
            # Decode raises Corruption — the follower counts it and
            # re-pulls; no half-applied batch can exist.
            frames = [ShipFrame.decode(mangled)] + frames[1:]
        return frames, state

    def request_checkpoint(self, dest: str) -> str:
        return self.inner.request_checkpoint(dest)


# ---------------------------------------------------------------------------
# Primary-side HTTP service
# ---------------------------------------------------------------------------


class ReplicationServer:
    """Embeds a LogShipper behind HTTP (the dcompact_service transport
    shape): POST /replication/pull {"since_seq": N|null, "max_bytes": M} →
    {"frames_b64": [...], "state": {...}}; 410 when WAL retention can no
    longer serve the range. POST /replication/checkpoint {"dest": path}
    creates a bootstrap checkpoint on the shared filesystem. GET
    /replication/status for introspection; GET /replication/health for
    the fleet aggregator's health doc and GET /metrics for Prometheus
    text — the health plane's per-member scrape points."""

    def __init__(self, db, shipper: LogShipper | None = None):
        self.db = db
        self.shipper = shipper or LogShipper(db)
        self._server: ThreadingHTTPServer | None = None

    def _label(self) -> str:
        """Member identity for /metrics labels and the health doc: the DB
        directory's basename (the full path would bloat every series)."""
        return os.path.basename(
            str(getattr(self.db, "dbname", "")).rstrip("/")) or "primary"

    def start(self, port: int = 0, host: str = "127.0.0.1") -> int:
        srv = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *a):
                pass

            def _reply(self, code: int, body: dict):
                data = json.dumps(body).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(data)))
                self.end_headers()
                self.wfile.write(data)

            def do_GET(self):
                if self.path == "/replication/status":
                    self._reply(200, srv.shipper.status())
                elif self.path == "/replication/health":
                    from toplingdb_tpu.utils.slo import health_doc

                    try:
                        doc = health_doc(srv.db, srv._label(),
                                         role="primary")
                        doc["replication"] = srv.shipper.status()
                        self._reply(200, doc)
                    except Exception as e:
                        self._reply(500, {"error": repr(e)[:300]})
                elif self.path == "/metrics":
                    stats = getattr(srv.db, "stats", None)
                    text = stats.to_prometheus(
                        labels=f'db="{srv._label()}"'
                    ) if stats is not None else ""
                    data = text.encode()
                    self.send_response(200)
                    self.send_header("Content-Type",
                                     "text/plain; version=0.0.4")
                    self.send_header("Content-Length", str(len(data)))
                    self.end_headers()
                    self.wfile.write(data)
                else:
                    self._reply(404, {"error": "not found"})

            def do_POST(self):
                n = int(self.headers.get("Content-Length", 0))
                try:
                    req = json.loads(self.rfile.read(n) or b"{}")
                except ValueError:
                    self._reply(400, {"error": "bad json"})
                    return
                try:
                    if self.path == "/replication/pull":
                        if req.get("spans"):
                            srv.shipper.accept_spans(req["spans"])
                        frames, state = srv.shipper.frames_since(
                            req.get("since_seq"),
                            max_bytes=int(req.get("max_bytes", 1 << 22)))
                        self._reply(200, {
                            "frames_b64": [
                                base64.b64encode(f.encode()).decode()
                                for f in frames
                            ],
                            "state": state,
                        })
                    elif self.path == "/replication/checkpoint":
                        from toplingdb_tpu.utilities.checkpoint import (
                            create_checkpoint,
                        )

                        dest = req["dest"]
                        create_checkpoint(srv.db, dest)
                        self._reply(200, {"dest": dest})
                    else:
                        self._reply(404, {"error": "not found"})
                except WalRetentionGone as e:
                    self._reply(410, {"error": "wal_retention_gone",
                                      "detail": str(e)})
                except Exception as e:  # transport must answer, not die
                    self._reply(500, {"error": repr(e)[:300]})

        self._server = ThreadingHTTPServer((host, port), Handler)
        ccy.spawn("replication-server", self._server.serve_forever,
                  owner=self, stop=self.stop)
        return self._server.server_address[1]

    def stop(self) -> None:
        if self._server is not None:
            self._server.shutdown()
            self._server = None
