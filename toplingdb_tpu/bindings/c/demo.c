/* Smoke demo for the C binding: open → put → get → delete → flush →
 * reopen-visible. Exits 0 on success, nonzero with a message otherwise. */
#include <stdio.h>
#include <stdlib.h>
#include <string.h>

#include "tpulsm_c.h"

#define CHECK(err)                                         \
    if (err) {                                             \
        fprintf(stderr, "FAIL: %s\n", err);                \
        return 1;                                          \
    }

int main(int argc, char** argv) {
    const char* path = argc > 1 ? argv[1] : "/tmp/tpulsm_c_demo";
    char* err = NULL;
    tpulsm_init();
    tpulsm_db_t* db = tpulsm_open(path, 1, &err);
    CHECK(err);
    tpulsm_put(db, "hello", 5, "world", 5, &err);
    CHECK(err);
    size_t n = 0;
    char* v = tpulsm_get(db, "hello", 5, &n, &err);
    CHECK(err);
    if (!v || n != 5 || memcmp(v, "world", 5) != 0) {
        fprintf(stderr, "FAIL: get mismatch\n");
        return 1;
    }
    tpulsm_free(v);
    v = tpulsm_get(db, "missing", 7, &n, &err);
    CHECK(err);
    if (v) {
        fprintf(stderr, "FAIL: missing key returned a value\n");
        return 1;
    }
    tpulsm_delete(db, "hello", 5, &err);
    CHECK(err);
    tpulsm_put(db, "durable", 7, "yes", 3, &err);
    CHECK(err);
    tpulsm_flush(db, &err);
    CHECK(err);
    tpulsm_close(db);

    db = tpulsm_open(path, 0, &err); /* reopen: recovery path */
    CHECK(err);
    v = tpulsm_get(db, "durable", 7, &n, &err);
    CHECK(err);
    if (!v || n != 3 || memcmp(v, "yes", 3) != 0) {
        fprintf(stderr, "FAIL: durability\n");
        return 1;
    }
    tpulsm_free(v);
    v = tpulsm_get(db, "hello", 5, &n, &err);
    CHECK(err);
    if (v) {
        fprintf(stderr, "FAIL: deleted key resurrected\n");
        return 1;
    }
    tpulsm_close(db);
    tpulsm_shutdown();
    printf("C-API-OK\n");
    return 0;
}
