/* Smoke demo for the C binding: open → put → get → delete → flush →
 * reopen-visible. Exits 0 on success, nonzero with a message otherwise. */
#include <stdio.h>
#include <stdlib.h>
#include <string.h>

#include "tpulsm_c.h"

#define CHECK(err)                                         \
    if (err) {                                             \
        fprintf(stderr, "FAIL: %s\n", err);                \
        return 1;                                          \
    }

int main(int argc, char** argv) {
    const char* path = argc > 1 ? argv[1] : "/tmp/tpulsm_c_demo";
    char* err = NULL;
    tpulsm_init();
    tpulsm_db_t* db = tpulsm_open(path, 1, &err);
    CHECK(err);
    tpulsm_put(db, "hello", 5, "world", 5, &err);
    CHECK(err);
    size_t n = 0;
    char* v = tpulsm_get(db, "hello", 5, &n, &err);
    CHECK(err);
    if (!v || n != 5 || memcmp(v, "world", 5) != 0) {
        fprintf(stderr, "FAIL: get mismatch\n");
        return 1;
    }
    tpulsm_free(v);
    v = tpulsm_get(db, "missing", 7, &n, &err);
    CHECK(err);
    if (v) {
        fprintf(stderr, "FAIL: missing key returned a value\n");
        return 1;
    }
    tpulsm_delete(db, "hello", 5, &err);
    CHECK(err);
    tpulsm_put(db, "durable", 7, "yes", 3, &err);
    CHECK(err);

    /* write batch: atomic multi-op */
    tpulsm_writebatch_t* wb = tpulsm_writebatch_create();
    if (!wb) { fprintf(stderr, "FAIL: writebatch_create\n"); return 1; }
    tpulsm_writebatch_put(wb, "wb1", 3, "a", 1, &err);
    CHECK(err);
    tpulsm_writebatch_put(wb, "wb2", 3, "b", 1, &err);
    CHECK(err);
    tpulsm_writebatch_delete(wb, "wb1", 3, &err);
    CHECK(err);
    tpulsm_write(db, wb, &err);
    CHECK(err);
    tpulsm_writebatch_destroy(wb);
    v = tpulsm_get(db, "wb2", 3, &n, &err);
    CHECK(err);
    if (!v || n != 1 || v[0] != 'b') {
        fprintf(stderr, "FAIL: writebatch apply\n");
        return 1;
    }
    tpulsm_free(v);
    v = tpulsm_get(db, "wb1", 3, &n, &err);
    CHECK(err);
    if (v) {
        fprintf(stderr, "FAIL: batch delete did not apply\n");
        return 1;
    }

    /* iterator: full forward scan + seek + reverse step */
    tpulsm_iterator_t* it = tpulsm_create_iterator(db, &err);
    CHECK(err);
    int count = 0;
    for (tpulsm_iter_seek_to_first(it); tpulsm_iter_valid(it);
         tpulsm_iter_next(it)) {
        size_t kl = 0, vl = 0;
        char* k = tpulsm_iter_key(it, &kl);
        char* val2 = tpulsm_iter_value(it, &vl);
        if (!k || !val2 || kl == 0) {
            fprintf(stderr, "FAIL: iter key/value\n");
            return 1;
        }
        tpulsm_free(k);
        tpulsm_free(val2);
        count++;
    }
    if (count != 2) { /* durable + wb2 */
        fprintf(stderr, "FAIL: iter count %d != 2\n", count);
        return 1;
    }
    tpulsm_iter_seek(it, "wb", 2);
    if (!tpulsm_iter_valid(it)) {
        fprintf(stderr, "FAIL: iter seek\n");
        return 1;
    }
    tpulsm_iter_seek_to_last(it);
    tpulsm_iter_prev(it);
    if (!tpulsm_iter_valid(it)) {
        fprintf(stderr, "FAIL: iter prev\n");
        return 1;
    }
    tpulsm_iter_destroy(it);

    /* property introspection */
    char* prop = tpulsm_property_value(db, "tpulsm.estimate-num-keys");
    if (!prop) {
        fprintf(stderr, "FAIL: property_value\n");
        return 1;
    }
    tpulsm_free(prop);
    if (tpulsm_property_value(db, "tpulsm.no-such-prop") != NULL) {
        fprintf(stderr, "FAIL: unknown property not NULL\n");
        return 1;
    }

    tpulsm_flush(db, &err);
    CHECK(err);
    tpulsm_close(db);

    db = tpulsm_open(path, 0, &err); /* reopen: recovery path */
    CHECK(err);
    v = tpulsm_get(db, "durable", 7, &n, &err);
    CHECK(err);
    if (!v || n != 3 || memcmp(v, "yes", 3) != 0) {
        fprintf(stderr, "FAIL: durability\n");
        return 1;
    }
    tpulsm_free(v);
    v = tpulsm_get(db, "hello", 5, &n, &err);
    CHECK(err);
    if (v) {
        fprintf(stderr, "FAIL: deleted key resurrected\n");
        return 1;
    }
    tpulsm_close(db);
    tpulsm_shutdown();
    printf("C-API-OK\n");
    return 0;
}
