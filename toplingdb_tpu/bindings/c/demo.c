/* Smoke demo for the C binding: open → put → get → delete → flush →
 * reopen-visible. Exits 0 on success, nonzero with a message otherwise. */
#include <stdio.h>
#include <stdlib.h>
#include <string.h>

#include "tpulsm_c.h"

#define CHECK(err)                                         \
    if (err) {                                             \
        fprintf(stderr, "FAIL: %s\n", err);                \
        return 1;                                          \
    }

int main(int argc, char** argv) {
    const char* path = argc > 1 ? argv[1] : "/tmp/tpulsm_c_demo";
    char* err = NULL;
    tpulsm_init();
    tpulsm_db_t* db = tpulsm_open(path, 1, &err);
    CHECK(err);
    tpulsm_put(db, "hello", 5, "world", 5, &err);
    CHECK(err);
    size_t n = 0;
    char* v = tpulsm_get(db, "hello", 5, &n, &err);
    CHECK(err);
    if (!v || n != 5 || memcmp(v, "world", 5) != 0) {
        fprintf(stderr, "FAIL: get mismatch\n");
        return 1;
    }
    tpulsm_free(v);
    v = tpulsm_get(db, "missing", 7, &n, &err);
    CHECK(err);
    if (v) {
        fprintf(stderr, "FAIL: missing key returned a value\n");
        return 1;
    }
    tpulsm_delete(db, "hello", 5, &err);
    CHECK(err);
    tpulsm_put(db, "durable", 7, "yes", 3, &err);
    CHECK(err);

    /* write batch: atomic multi-op */
    tpulsm_writebatch_t* wb = tpulsm_writebatch_create();
    if (!wb) { fprintf(stderr, "FAIL: writebatch_create\n"); return 1; }
    tpulsm_writebatch_put(wb, "wb1", 3, "a", 1, &err);
    CHECK(err);
    tpulsm_writebatch_put(wb, "wb2", 3, "b", 1, &err);
    CHECK(err);
    tpulsm_writebatch_delete(wb, "wb1", 3, &err);
    CHECK(err);
    tpulsm_write(db, wb, &err);
    CHECK(err);
    tpulsm_writebatch_destroy(wb);
    v = tpulsm_get(db, "wb2", 3, &n, &err);
    CHECK(err);
    if (!v || n != 1 || v[0] != 'b') {
        fprintf(stderr, "FAIL: writebatch apply\n");
        return 1;
    }
    tpulsm_free(v);
    v = tpulsm_get(db, "wb1", 3, &n, &err);
    CHECK(err);
    if (v) {
        fprintf(stderr, "FAIL: batch delete did not apply\n");
        return 1;
    }

    /* iterator: full forward scan + seek + reverse step */
    tpulsm_iterator_t* it = tpulsm_create_iterator(db, &err);
    CHECK(err);
    int count = 0;
    for (tpulsm_iter_seek_to_first(it); tpulsm_iter_valid(it);
         tpulsm_iter_next(it)) {
        size_t kl = 0, vl = 0;
        char* k = tpulsm_iter_key(it, &kl);
        char* val2 = tpulsm_iter_value(it, &vl);
        if (!k || !val2 || kl == 0) {
            fprintf(stderr, "FAIL: iter key/value\n");
            return 1;
        }
        tpulsm_free(k);
        tpulsm_free(val2);
        count++;
    }
    if (count != 2) { /* durable + wb2 */
        fprintf(stderr, "FAIL: iter count %d != 2\n", count);
        return 1;
    }
    tpulsm_iter_seek(it, "wb", 2);
    if (!tpulsm_iter_valid(it)) {
        fprintf(stderr, "FAIL: iter seek\n");
        return 1;
    }
    tpulsm_iter_seek_to_last(it);
    tpulsm_iter_prev(it);
    if (!tpulsm_iter_valid(it)) {
        fprintf(stderr, "FAIL: iter prev\n");
        return 1;
    }
    tpulsm_iter_destroy(it);

    /* property introspection */
    char* prop = tpulsm_property_value(db, "tpulsm.estimate-num-keys");
    if (!prop) {
        fprintf(stderr, "FAIL: property_value\n");
        return 1;
    }
    tpulsm_free(prop);
    if (tpulsm_property_value(db, "tpulsm.no-such-prop") != NULL) {
        fprintf(stderr, "FAIL: unknown property not NULL\n");
        return 1;
    }

    /* snapshots: read-your-history */
    tpulsm_put(db, "snapkey", 7, "v1", 2, &err);
    CHECK(err);
    tpulsm_snapshot_t* snap = tpulsm_create_snapshot(db, &err);
    CHECK(err);
    tpulsm_put(db, "snapkey", 7, "v2", 2, &err);
    CHECK(err);
    v = tpulsm_get_at_snapshot(db, snap, "snapkey", 7, &n, &err);
    CHECK(err);
    if (!v || n != 2 || memcmp(v, "v1", 2) != 0) {
        fprintf(stderr, "FAIL: snapshot read\n");
        return 1;
    }
    tpulsm_free(v);
    tpulsm_release_snapshot(snap);

    /* delete_range */
    tpulsm_put(db, "rka", 3, "1", 1, &err); CHECK(err);
    tpulsm_put(db, "rkb", 3, "2", 1, &err); CHECK(err);
    tpulsm_delete_range(db, "rka", 3, "rkb", 3, &err); CHECK(err);
    v = tpulsm_get(db, "rka", 3, &n, &err); CHECK(err);
    if (v) { fprintf(stderr, "FAIL: delete_range\n"); return 1; }
    v = tpulsm_get(db, "rkb", 3, &n, &err); CHECK(err);
    if (!v) { fprintf(stderr, "FAIL: delete_range end excl\n"); return 1; }
    tpulsm_free(v);

    /* column families */
    tpulsm_cf_t* cf = tpulsm_create_column_family(db, "aux", &err);
    CHECK(err);
    tpulsm_put_cf(db, cf, "cfk", 3, "cfv", 3, &err);
    CHECK(err);
    v = tpulsm_get_cf(db, cf, "cfk", 3, &n, &err);
    CHECK(err);
    if (!v || n != 3 || memcmp(v, "cfv", 3) != 0) {
        fprintf(stderr, "FAIL: cf get\n");
        return 1;
    }
    tpulsm_free(v);
    v = tpulsm_get(db, "cfk", 3, &n, &err);
    CHECK(err);
    if (v) { fprintf(stderr, "FAIL: cf leaked to default\n"); return 1; }
    tpulsm_delete_cf(db, cf, "cfk", 3, &err);
    CHECK(err);
    tpulsm_cf_t* cf2 = tpulsm_column_family_handle(db, "aux", &err);
    CHECK(err);
    tpulsm_cf_handle_destroy(cf2);
    tpulsm_cf_handle_destroy(cf);

    /* checkpoint + backup engine */
    char aux[1024];
    snprintf(aux, sizeof aux, "%s_ckpt", path);
    tpulsm_checkpoint_create(db, aux, &err);
    CHECK(err);
    snprintf(aux, sizeof aux, "%s_backups", path);
    tpulsm_backup_engine_t* be = tpulsm_backup_engine_open(aux, &err);
    CHECK(err);
    int bid = tpulsm_backup_engine_create_backup(be, db, &err);
    CHECK(err);
    if (bid <= 0 || tpulsm_backup_engine_count(be) != 1) {
        fprintf(stderr, "FAIL: backup create/count\n");
        return 1;
    }
    snprintf(aux, sizeof aux, "%s_restored", path);
    tpulsm_backup_engine_restore(be, 0, aux, &err);
    CHECK(err);
    tpulsm_backup_engine_close(be);

    /* external SST build + ingest */
    snprintf(aux, sizeof aux, "%s_ext.sst", path);
    tpulsm_sstwriter_t* sw = tpulsm_sstfilewriter_create(aux, &err);
    CHECK(err);
    tpulsm_sstfilewriter_put(sw, "zzz-ext", 7, "ingested", 8, &err);
    CHECK(err);
    tpulsm_sstfilewriter_finish(sw, &err);
    CHECK(err);
    tpulsm_sstfilewriter_destroy(sw);
    tpulsm_ingest_external_file(db, aux, &err);
    CHECK(err);
    v = tpulsm_get(db, "zzz-ext", 7, &n, &err);
    CHECK(err);
    if (!v || n != 8) { fprintf(stderr, "FAIL: ingest\n"); return 1; }
    tpulsm_free(v);

    tpulsm_flush(db, &err);
    CHECK(err);
    tpulsm_close(db);

    /* transactions (separate DB dir) */
    snprintf(aux, sizeof aux, "%s_txn", path);
    tpulsm_txndb_t* tdb = tpulsm_txndb_open(aux, 1, &err);
    CHECK(err);
    tpulsm_txn_t* txn = tpulsm_txn_begin(tdb, &err);
    CHECK(err);
    tpulsm_txn_put(txn, "tk", 2, "tv", 2, &err);
    CHECK(err);
    v = tpulsm_txn_get(txn, "tk", 2, &n, &err);
    CHECK(err);
    if (!v || n != 2) { fprintf(stderr, "FAIL: txn read-own-write\n"); return 1; }
    tpulsm_free(v);
    tpulsm_txn_commit(txn, &err);
    CHECK(err);
    tpulsm_txn_destroy(txn);
    v = tpulsm_txndb_get(tdb, "tk", 2, &n, &err);
    CHECK(err);
    if (!v || n != 2 || memcmp(v, "tv", 2) != 0) {
        fprintf(stderr, "FAIL: txn commit visible\n");
        return 1;
    }
    tpulsm_free(v);
    tpulsm_txn_t* txn2 = tpulsm_txn_begin(tdb, &err);
    CHECK(err);
    tpulsm_txn_put(txn2, "tk2", 3, "x", 1, &err);
    CHECK(err);
    tpulsm_txn_rollback(txn2, &err);
    CHECK(err);
    tpulsm_txn_destroy(txn2);
    v = tpulsm_txndb_get(tdb, "tk2", 3, &n, &err);
    CHECK(err);
    if (v) { fprintf(stderr, "FAIL: rolled-back write visible\n"); return 1; }
    tpulsm_txndb_close(tdb);

    db = tpulsm_open(path, 0, &err); /* reopen: recovery path */
    CHECK(err);
    v = tpulsm_get(db, "durable", 7, &n, &err);
    CHECK(err);
    if (!v || n != 3 || memcmp(v, "yes", 3) != 0) {
        fprintf(stderr, "FAIL: durability\n");
        return 1;
    }
    tpulsm_free(v);
    v = tpulsm_get(db, "hello", 5, &n, &err);
    CHECK(err);
    if (v) {
        fprintf(stderr, "FAIL: deleted key resurrected\n");
        return 1;
    }
    tpulsm_close(db);
    tpulsm_shutdown();
    printf("C-API-OK\n");
    return 0;
}
