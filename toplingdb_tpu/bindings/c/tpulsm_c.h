/* tpulsm flat C API.
 *
 * Role of the reference's C binding (db/c.cc, include/rocksdb/c.h in the
 * upstream): a stable C ABI for foreign-language consumers. The engine runs
 * embedded (libpython); call tpulsm_init() once per process before any
 * other function (it boots the interpreter; PYTHONPATH must reach the
 * toplingdb_tpu package).
 *
 * Error convention mirrors rocksdb_*: every fallible call takes char** errptr;
 * on failure *errptr is a malloc'd message the caller frees with
 * tpulsm_free(); on success it is left untouched.
 */
#ifndef TPULSM_C_H
#define TPULSM_C_H

#include <stddef.h>

#ifdef __cplusplus
extern "C" {
#endif

typedef struct tpulsm_db_t tpulsm_db_t;

/* Process-wide init/teardown of the embedded engine runtime. */
int tpulsm_init(void);
void tpulsm_shutdown(void);

tpulsm_db_t* tpulsm_open(const char* path, int create_if_missing,
                         char** errptr);
void tpulsm_close(tpulsm_db_t* db);

void tpulsm_put(tpulsm_db_t* db, const char* key, size_t keylen,
                const char* val, size_t vallen, char** errptr);
/* Returns a malloc'd value (caller frees with tpulsm_free) or NULL when the
 * key is absent (with *errptr untouched) or on error (with *errptr set). */
char* tpulsm_get(tpulsm_db_t* db, const char* key, size_t keylen,
                 size_t* vallen, char** errptr);
void tpulsm_delete(tpulsm_db_t* db, const char* key, size_t keylen,
                   char** errptr);
void tpulsm_flush(tpulsm_db_t* db, char** errptr);
void tpulsm_compact_range(tpulsm_db_t* db, char** errptr);

void tpulsm_free(void* ptr);

#ifdef __cplusplus
}
#endif
#endif /* TPULSM_C_H */
