/* tpulsm flat C API.
 *
 * Role of the reference's C binding (db/c.cc, include/rocksdb/c.h in the
 * upstream): a stable C ABI for foreign-language consumers. The engine runs
 * embedded (libpython); call tpulsm_init() once per process before any
 * other function (it boots the interpreter; PYTHONPATH must reach the
 * toplingdb_tpu package).
 *
 * Error convention mirrors rocksdb_*: every fallible call takes char** errptr;
 * on failure *errptr is a malloc'd message the caller frees with
 * tpulsm_free(); on success it is left untouched.
 */
#ifndef TPULSM_C_H
#define TPULSM_C_H

#include <stddef.h>

#ifdef __cplusplus
extern "C" {
#endif

typedef struct tpulsm_db_t tpulsm_db_t;

/* Process-wide init/teardown of the embedded engine runtime. */
int tpulsm_init(void);
void tpulsm_shutdown(void);

tpulsm_db_t* tpulsm_open(const char* path, int create_if_missing,
                         char** errptr);
void tpulsm_close(tpulsm_db_t* db);

void tpulsm_put(tpulsm_db_t* db, const char* key, size_t keylen,
                const char* val, size_t vallen, char** errptr);
/* Returns a malloc'd value (caller frees with tpulsm_free) or NULL when the
 * key is absent (with *errptr untouched) or on error (with *errptr set). */
char* tpulsm_get(tpulsm_db_t* db, const char* key, size_t keylen,
                 size_t* vallen, char** errptr);
void tpulsm_delete(tpulsm_db_t* db, const char* key, size_t keylen,
                   char** errptr);
void tpulsm_flush(tpulsm_db_t* db, char** errptr);
void tpulsm_compact_range(tpulsm_db_t* db, char** errptr);

void tpulsm_free(void* ptr);

/* -- write batches (reference rocksdb_writebatch_*) ---------------------- */
typedef struct tpulsm_writebatch_t tpulsm_writebatch_t;
tpulsm_writebatch_t* tpulsm_writebatch_create(void);
void tpulsm_writebatch_destroy(tpulsm_writebatch_t* wb);
void tpulsm_writebatch_put(tpulsm_writebatch_t* wb, const char* key,
                           size_t keylen, const char* val, size_t vallen,
                           char** errptr);
void tpulsm_writebatch_delete(tpulsm_writebatch_t* wb, const char* key,
                              size_t keylen, char** errptr);
/* Atomic apply of the whole batch. */
void tpulsm_write(tpulsm_db_t* db, tpulsm_writebatch_t* wb, char** errptr);

/* -- iterators (reference rocksdb_iter_*) -------------------------------- */
typedef struct tpulsm_iterator_t tpulsm_iterator_t;
tpulsm_iterator_t* tpulsm_create_iterator(tpulsm_db_t* db, char** errptr);
void tpulsm_iter_destroy(tpulsm_iterator_t* it);
void tpulsm_iter_seek_to_first(tpulsm_iterator_t* it);
void tpulsm_iter_seek_to_last(tpulsm_iterator_t* it);
void tpulsm_iter_seek(tpulsm_iterator_t* it, const char* key, size_t keylen);
int tpulsm_iter_valid(tpulsm_iterator_t* it);
void tpulsm_iter_next(tpulsm_iterator_t* it);
void tpulsm_iter_prev(tpulsm_iterator_t* it);
/* Key/value of the current position: malloc'd copies (tpulsm_free).
 * NULL while valid() means an ERROR (OOM or engine failure), never an
 * empty key — check tpulsm_iter_get_error. */
char* tpulsm_iter_key(tpulsm_iterator_t* it, size_t* klen);
char* tpulsm_iter_value(tpulsm_iterator_t* it, size_t* vlen);
/* Last key/value error on this iterator (rocksdb_iter_get_error role):
 * sets *errptr to a malloc'd message, or leaves it untouched if none. */
void tpulsm_iter_get_error(tpulsm_iterator_t* it, char** errptr);

/* -- introspection (reference rocksdb_property_value) -------------------- */
/* malloc'd property string (tpulsm_free), or NULL when unknown. */
char* tpulsm_property_value(tpulsm_db_t* db, const char* name);

#ifdef __cplusplus
}
#endif
#endif /* TPULSM_C_H */
