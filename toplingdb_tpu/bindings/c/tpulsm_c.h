/* tpulsm flat C API.
 *
 * Role of the reference's C binding (db/c.cc, include/rocksdb/c.h in the
 * upstream): a stable C ABI for foreign-language consumers. The engine runs
 * embedded (libpython); call tpulsm_init() once per process before any
 * other function (it boots the interpreter; PYTHONPATH must reach the
 * toplingdb_tpu package).
 *
 * Error convention mirrors rocksdb_*: every fallible call takes char** errptr;
 * on failure *errptr is a malloc'd message the caller frees with
 * tpulsm_free(); on success it is left untouched.
 */
#ifndef TPULSM_C_H
#define TPULSM_C_H

#include <stddef.h>

#ifdef __cplusplus
extern "C" {
#endif

typedef struct tpulsm_db_t tpulsm_db_t;

/* Process-wide init/teardown of the embedded engine runtime. */
int tpulsm_init(void);
void tpulsm_shutdown(void);

tpulsm_db_t* tpulsm_open(const char* path, int create_if_missing,
                         char** errptr);
void tpulsm_close(tpulsm_db_t* db);

void tpulsm_put(tpulsm_db_t* db, const char* key, size_t keylen,
                const char* val, size_t vallen, char** errptr);
/* Returns a malloc'd value (caller frees with tpulsm_free) or NULL when the
 * key is absent (with *errptr untouched) or on error (with *errptr set). */
char* tpulsm_get(tpulsm_db_t* db, const char* key, size_t keylen,
                 size_t* vallen, char** errptr);
void tpulsm_delete(tpulsm_db_t* db, const char* key, size_t keylen,
                   char** errptr);
void tpulsm_flush(tpulsm_db_t* db, char** errptr);
void tpulsm_compact_range(tpulsm_db_t* db, char** errptr);

void tpulsm_free(void* ptr);

/* -- write batches (reference rocksdb_writebatch_*) ---------------------- */
typedef struct tpulsm_writebatch_t tpulsm_writebatch_t;
tpulsm_writebatch_t* tpulsm_writebatch_create(void);
void tpulsm_writebatch_destroy(tpulsm_writebatch_t* wb);
void tpulsm_writebatch_put(tpulsm_writebatch_t* wb, const char* key,
                           size_t keylen, const char* val, size_t vallen,
                           char** errptr);
void tpulsm_writebatch_delete(tpulsm_writebatch_t* wb, const char* key,
                              size_t keylen, char** errptr);
/* Atomic apply of the whole batch. */
void tpulsm_write(tpulsm_db_t* db, tpulsm_writebatch_t* wb, char** errptr);

/* -- iterators (reference rocksdb_iter_*) -------------------------------- */
typedef struct tpulsm_iterator_t tpulsm_iterator_t;
tpulsm_iterator_t* tpulsm_create_iterator(tpulsm_db_t* db, char** errptr);
void tpulsm_iter_destroy(tpulsm_iterator_t* it);
void tpulsm_iter_seek_to_first(tpulsm_iterator_t* it);
void tpulsm_iter_seek_to_last(tpulsm_iterator_t* it);
void tpulsm_iter_seek(tpulsm_iterator_t* it, const char* key, size_t keylen);
int tpulsm_iter_valid(tpulsm_iterator_t* it);
void tpulsm_iter_next(tpulsm_iterator_t* it);
void tpulsm_iter_prev(tpulsm_iterator_t* it);
/* Key/value of the current position: malloc'd copies (tpulsm_free).
 * NULL while valid() means an ERROR (OOM or engine failure), never an
 * empty key — check tpulsm_iter_get_error. */
char* tpulsm_iter_key(tpulsm_iterator_t* it, size_t* klen);
char* tpulsm_iter_value(tpulsm_iterator_t* it, size_t* vlen);
/* Last key/value error on this iterator (rocksdb_iter_get_error role):
 * sets *errptr to a malloc'd message, or leaves it untouched if none. */
void tpulsm_iter_get_error(tpulsm_iterator_t* it, char** errptr);

/* -- introspection (reference rocksdb_property_value) -------------------- */
/* malloc'd property string (tpulsm_free), or NULL when unknown. */
char* tpulsm_property_value(tpulsm_db_t* db, const char* name);

/* -- more point ops (reference rocksdb_merge / rocksdb_delete_range) ----- */
void tpulsm_merge(tpulsm_db_t* db, const char* key, size_t keylen,
                  const char* val, size_t vallen, char** errptr);
void tpulsm_delete_range(tpulsm_db_t* db, const char* begin, size_t blen,
                         const char* end, size_t elen, char** errptr);
void tpulsm_writebatch_merge(tpulsm_writebatch_t* wb, const char* key,
                             size_t keylen, const char* val, size_t vallen,
                             char** errptr);
void tpulsm_writebatch_delete_range(tpulsm_writebatch_t* wb,
                                    const char* begin, size_t blen,
                                    const char* end, size_t elen,
                                    char** errptr);
void tpulsm_writebatch_clear(tpulsm_writebatch_t* wb);
int tpulsm_writebatch_count(tpulsm_writebatch_t* wb);

/* -- snapshots (reference rocksdb_create_snapshot / read at snapshot) ---- */
typedef struct tpulsm_snapshot_t tpulsm_snapshot_t;
tpulsm_snapshot_t* tpulsm_create_snapshot(tpulsm_db_t* db, char** errptr);
void tpulsm_release_snapshot(tpulsm_snapshot_t* snap);
char* tpulsm_get_at_snapshot(tpulsm_db_t* db, tpulsm_snapshot_t* snap,
                             const char* key, size_t keylen, size_t* vallen,
                             char** errptr);

/* -- column families (reference rocksdb_column_family_handle_t) ---------- */
typedef struct tpulsm_cf_t tpulsm_cf_t;
tpulsm_cf_t* tpulsm_create_column_family(tpulsm_db_t* db, const char* name,
                                         char** errptr);
/* Existing CF by name (from DB.list_column_families), or NULL + error. */
tpulsm_cf_t* tpulsm_column_family_handle(tpulsm_db_t* db, const char* name,
                                         char** errptr);
void tpulsm_drop_column_family(tpulsm_db_t* db, tpulsm_cf_t* cf,
                               char** errptr);
void tpulsm_cf_handle_destroy(tpulsm_cf_t* cf);
void tpulsm_put_cf(tpulsm_db_t* db, tpulsm_cf_t* cf, const char* key,
                   size_t keylen, const char* val, size_t vallen,
                   char** errptr);
char* tpulsm_get_cf(tpulsm_db_t* db, tpulsm_cf_t* cf, const char* key,
                    size_t keylen, size_t* vallen, char** errptr);
void tpulsm_delete_cf(tpulsm_db_t* db, tpulsm_cf_t* cf, const char* key,
                      size_t keylen, char** errptr);

/* -- checkpoint (reference rocksdb_checkpoint_create) -------------------- */
void tpulsm_checkpoint_create(tpulsm_db_t* db, const char* dest,
                              char** errptr);

/* -- backup engine (reference rocksdb_backup_engine_*) ------------------- */
typedef struct tpulsm_backup_engine_t tpulsm_backup_engine_t;
tpulsm_backup_engine_t* tpulsm_backup_engine_open(const char* dir,
                                                  char** errptr);
void tpulsm_backup_engine_close(tpulsm_backup_engine_t* be);
/* Returns the new backup id (>0), or 0 on error. */
int tpulsm_backup_engine_create_backup(tpulsm_backup_engine_t* be,
                                       tpulsm_db_t* db, char** errptr);
int tpulsm_backup_engine_count(tpulsm_backup_engine_t* be);
void tpulsm_backup_engine_restore(tpulsm_backup_engine_t* be, int backup_id,
                                  const char* target_dir, char** errptr);
void tpulsm_backup_engine_purge_old(tpulsm_backup_engine_t* be,
                                    int num_to_keep, char** errptr);

/* -- pessimistic transactions (reference rocksdb_transactiondb_*) -------- */
typedef struct tpulsm_txndb_t tpulsm_txndb_t;
typedef struct tpulsm_txn_t tpulsm_txn_t;
tpulsm_txndb_t* tpulsm_txndb_open(const char* path, int create_if_missing,
                                  char** errptr);
void tpulsm_txndb_close(tpulsm_txndb_t* tdb);
tpulsm_txn_t* tpulsm_txn_begin(tpulsm_txndb_t* tdb, char** errptr);
void tpulsm_txn_put(tpulsm_txn_t* txn, const char* key, size_t keylen,
                    const char* val, size_t vallen, char** errptr);
char* tpulsm_txn_get(tpulsm_txn_t* txn, const char* key, size_t keylen,
                     size_t* vallen, char** errptr);
void tpulsm_txn_delete(tpulsm_txn_t* txn, const char* key, size_t keylen,
                       char** errptr);
void tpulsm_txn_commit(tpulsm_txn_t* txn, char** errptr);
void tpulsm_txn_rollback(tpulsm_txn_t* txn, char** errptr);
void tpulsm_txn_destroy(tpulsm_txn_t* txn);
/* Point reads through the txn DB outside any transaction. */
char* tpulsm_txndb_get(tpulsm_txndb_t* tdb, const char* key, size_t keylen,
                       size_t* vallen, char** errptr);

/* -- external SSTs (reference rocksdb_sstfilewriter / ingest) ------------ */
typedef struct tpulsm_sstwriter_t tpulsm_sstwriter_t;
tpulsm_sstwriter_t* tpulsm_sstfilewriter_create(const char* path,
                                                char** errptr);
void tpulsm_sstfilewriter_put(tpulsm_sstwriter_t* w, const char* key,
                              size_t keylen, const char* val, size_t vallen,
                              char** errptr);
void tpulsm_sstfilewriter_finish(tpulsm_sstwriter_t* w, char** errptr);
void tpulsm_sstfilewriter_destroy(tpulsm_sstwriter_t* w);
void tpulsm_ingest_external_file(tpulsm_db_t* db, const char* path,
                                 char** errptr);

/* -- SidePluginRepo: open DBs from JSON config + HTTP introspection
 *    (the reference's java SidePluginRepo.java:10-104 role). DB handles
 *    returned by tpulsm_repo_open_db may be released with tpulsm_close
 *    (DB.close is idempotent) or left to tpulsm_repo_close_all; after
 *    close_all every repo-opened handle is CLOSED but still must be
 *    freed by tpulsm_close if it was not already. ------------------- */
typedef struct tpulsm_repo_t tpulsm_repo_t;

tpulsm_repo_t* tpulsm_repo_create(char** errptr);
/* config_json: {"path": ..., "name": ..., "options": {...}} */
tpulsm_db_t* tpulsm_repo_open_db(tpulsm_repo_t* repo,
                                 const char* config_json, char** errptr);
/* Serves /dbs /stats/<name> /levels/<name> /config/<name> /metrics.
 * Returns the bound port (pass 0 to auto-pick), or -1 + error. */
int tpulsm_repo_start_http(tpulsm_repo_t* repo, int port, char** errptr);
void tpulsm_repo_stop_http(tpulsm_repo_t* repo);
/* Stops HTTP, closes every repo-opened DB, and DESTROYS the repo handle
 * itself — `repo` is invalid after this call. */
void tpulsm_repo_close_all(tpulsm_repo_t* repo);

#ifdef __cplusplus
}
#endif
#endif /* TPULSM_C_H */
