#!/bin/sh
# Build libtpulsm_c.so (the embedded-engine C binding) and the demo binary.
# Consumers need PYTHONPATH to reach the toplingdb_tpu package at runtime.
set -e
cd "$(dirname "$0")"
g++ -shared -fPIC -O2 tpulsm_c.c -o libtpulsm_c.so \
    $(python3-config --includes) $(python3-config --ldflags --embed)
gcc -O2 demo.c -o tpulsm_demo -I. -L. -ltpulsm_c -Wl,-rpath,"$PWD"
echo "built libtpulsm_c.so + tpulsm_demo"
