/* SidePluginRepo C-API demo: open a DB from a JSON config document, write
 * through it, start the HTTP introspection endpoint, fetch /dbs, close.
 * Mirrors the open-from-config flow of the reference's
 * java/src/main/java/org/rocksdb/SidePluginRepo.java:10-104. */
#include <stdio.h>
#include <stdlib.h>
#include <string.h>

#include <netinet/in.h>
#include <sys/socket.h>
#include <unistd.h>

#include "tpulsm_c.h"

static int http_get_dbs(int port, char* buf, size_t cap) {
    int fd = socket(AF_INET, SOCK_STREAM, 0);
    if (fd < 0) return -1;
    struct sockaddr_in a;
    memset(&a, 0, sizeof(a));
    a.sin_family = AF_INET;
    a.sin_port = htons((unsigned short)port);
    a.sin_addr.s_addr = htonl(INADDR_LOOPBACK);
    if (connect(fd, (struct sockaddr*)&a, sizeof(a)) != 0) {
        close(fd);
        return -1;
    }
    const char* req = "GET /dbs HTTP/1.0\r\n\r\n";
    if (write(fd, req, strlen(req)) < 0) {
        close(fd);
        return -1;
    }
    size_t got = 0;
    ssize_t r;
    while (got + 1 < cap && (r = read(fd, buf + got, cap - got - 1)) > 0)
        got += (size_t)r;
    buf[got] = 0;
    close(fd);
    return (int)got;
}

int main(int argc, char** argv) {
    if (argc < 2) {
        fprintf(stderr, "usage: repo_demo <dbdir>\n");
        return 2;
    }
    char cfg[1024];
    snprintf(cfg, sizeof(cfg),
             "{\"path\": \"%s\", \"name\": \"repo-db\", "
             "\"options\": {\"create_if_missing\": true}}",
             argv[1]);
    tpulsm_init();
    char* err = NULL;
    tpulsm_repo_t* repo = tpulsm_repo_create(&err);
    if (!repo) {
        fprintf(stderr, "repo_create: %s\n", err ? err : "?");
        return 1;
    }
    tpulsm_db_t* db = tpulsm_repo_open_db(repo, cfg, &err);
    if (!db) {
        fprintf(stderr, "repo_open_db: %s\n", err ? err : "?");
        return 1;
    }
    tpulsm_put(db, "rk", 2, "rv", 2, &err);
    if (err) {
        fprintf(stderr, "put: %s\n", err);
        return 1;
    }
    size_t vlen = 0;
    char* v = tpulsm_get(db, "rk", 2, &vlen, &err);
    if (!v || vlen != 2 || memcmp(v, "rv", 2) != 0) {
        fprintf(stderr, "get mismatch\n");
        return 1;
    }
    tpulsm_free(v);

    int port = tpulsm_repo_start_http(repo, 0, &err);
    if (port <= 0) {
        fprintf(stderr, "start_http: %s\n", err ? err : "?");
        return 1;
    }
    char body[4096];
    if (http_get_dbs(port, body, sizeof(body)) <= 0 ||
        strstr(body, "repo-db") == NULL) {
        fprintf(stderr, "http /dbs missing repo-db: %s\n", body);
        return 1;
    }
    tpulsm_repo_stop_http(repo);
    tpulsm_repo_close_all(repo);
    tpulsm_close(db); /* idempotent after close_all; frees the handle */
    printf("REPO-C-API-OK\n");
    return 0;
}
