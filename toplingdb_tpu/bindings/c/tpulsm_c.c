/* Flat C binding over the embedded engine (see tpulsm_c.h).
 *
 * Uses the CPython C API directly (no pybind11 in this toolchain). All
 * entry points take the GIL via PyGILState_Ensure, so the library is safe
 * to call from multiple C threads; the engine's own locking provides the
 * DB-level thread safety.
 */
#define PY_SSIZE_T_CLEAN
#include <Python.h>
#include <stdlib.h>
#include <string.h>

#include "tpulsm_c.h"

struct tpulsm_db_t {
    PyObject* obj; /* toplingdb_tpu.db.db.DB instance */
};

static char* dup_cstr(const char* s) {
    size_t n = strlen(s) + 1;
    char* out = (char*)malloc(n);
    if (out) memcpy(out, s, n);
    return out;
}

static void set_err_from_python(char** errptr) {
    PyObject *type, *value, *tb;
    PyErr_Fetch(&type, &value, &tb);
    if (errptr) {
        PyObject* s = value ? PyObject_Str(value) : NULL;
        const char* msg = s ? PyUnicode_AsUTF8(s) : "unknown python error";
        *errptr = dup_cstr(msg ? msg : "unknown python error");
        Py_XDECREF(s);
    }
    Py_XDECREF(type);
    Py_XDECREF(value);
    Py_XDECREF(tb);
}

static PyThreadState* g_main_tstate = NULL;
static int g_owns_interpreter = 0;

int tpulsm_init(void) {
    if (Py_IsInitialized()) return 0; /* host already embeds Python */
    Py_InitializeEx(0);
    g_owns_interpreter = 1;
    /* Release the GIL so worker threads can take it via PyGILState. */
    g_main_tstate = PyEval_SaveThread();
    return 0;
}

void tpulsm_shutdown(void) {
    /* Only tear down an interpreter WE created; finalizing a host's
     * interpreter (or calling Py_FinalizeEx without a thread state) would
     * abort the process. */
    if (!g_owns_interpreter || !Py_IsInitialized()) return;
    PyEval_RestoreThread(g_main_tstate);
    Py_FinalizeEx();
    g_main_tstate = NULL;
    g_owns_interpreter = 0;
}

tpulsm_db_t* tpulsm_open(const char* path, int create_if_missing,
                         char** errptr) {
    PyGILState_STATE g = PyGILState_Ensure();
    tpulsm_db_t* out = NULL;
    PyObject* mod = PyImport_ImportModule("toplingdb_tpu.db.db");
    if (!mod) { set_err_from_python(errptr); goto done; }
    {
        PyObject* omod = PyImport_ImportModule("toplingdb_tpu.options");
        if (!omod) { Py_DECREF(mod); set_err_from_python(errptr); goto done; }
        PyObject* opts = PyObject_CallMethod(
            omod, "Options", NULL);
        if (opts) {
            PyObject* flag = create_if_missing ? Py_True : Py_False;
            PyObject_SetAttrString(opts, "create_if_missing", flag);
        }
        PyObject* dbcls = opts ? PyObject_GetAttrString(mod, "DB") : NULL;
        PyObject* db = dbcls ? PyObject_CallMethod(
            dbcls, "open", "sO", path, opts) : NULL;
        if (db) {
            out = (tpulsm_db_t*)malloc(sizeof(*out));
            if (out) {
                out->obj = db;
            } else {
                Py_DECREF(db);
                if (errptr) *errptr = dup_cstr("out of memory");
            }
        } else {
            set_err_from_python(errptr);
        }
        Py_XDECREF(dbcls);
        Py_XDECREF(opts);
        Py_DECREF(omod);
        Py_DECREF(mod);
    }
done:
    PyGILState_Release(g);
    return out;
}

void tpulsm_close(tpulsm_db_t* db) {
    if (!db) return;
    PyGILState_STATE g = PyGILState_Ensure();
    PyObject* r = PyObject_CallMethod(db->obj, "close", NULL);
    if (!r) PyErr_Clear();
    Py_XDECREF(r);
    Py_DECREF(db->obj);
    PyGILState_Release(g);
    free(db);
}

void tpulsm_put(tpulsm_db_t* db, const char* key, size_t keylen,
                const char* val, size_t vallen, char** errptr) {
    if (!db) {
        if (errptr) *errptr = dup_cstr("null db handle");
        return;
    }
    PyGILState_STATE g = PyGILState_Ensure();
    PyObject* r = PyObject_CallMethod(
        db->obj, "put", "y#y#", key, (Py_ssize_t)keylen,
        val, (Py_ssize_t)vallen);
    if (!r) set_err_from_python(errptr);
    Py_XDECREF(r);
    PyGILState_Release(g);
}

char* tpulsm_get(tpulsm_db_t* db, const char* key, size_t keylen,
                 size_t* vallen, char** errptr) {
    if (!db) {
        if (errptr) *errptr = dup_cstr("null db handle");
        if (vallen) *vallen = 0;
        return NULL;
    }
    PyGILState_STATE g = PyGILState_Ensure();
    char* out = NULL;
    if (vallen) *vallen = 0;
    PyObject* r = PyObject_CallMethod(
        db->obj, "get", "y#", key, (Py_ssize_t)keylen);
    if (!r) {
        set_err_from_python(errptr);
    } else if (r != Py_None) {
        char* buf = NULL;
        Py_ssize_t n = 0;
        if (PyBytes_AsStringAndSize(r, &buf, &n) == 0) {
            out = (char*)malloc(n > 0 ? (size_t)n : 1);
            if (out) {
                memcpy(out, buf, (size_t)n);
                if (vallen) *vallen = (size_t)n;
            } else if (errptr) {
                /* NULL + untouched errptr means "absent" — OOM must NOT
                 * masquerade as a missing key. */
                *errptr = dup_cstr("out of memory");
            }
        } else {
            set_err_from_python(errptr);
        }
    }
    Py_XDECREF(r);
    PyGILState_Release(g);
    return out;
}

void tpulsm_delete(tpulsm_db_t* db, const char* key, size_t keylen,
                   char** errptr) {
    if (!db) {
        if (errptr) *errptr = dup_cstr("null db handle");
        return;
    }
    PyGILState_STATE g = PyGILState_Ensure();
    PyObject* r = PyObject_CallMethod(
        db->obj, "delete", "y#", key, (Py_ssize_t)keylen);
    if (!r) set_err_from_python(errptr);
    Py_XDECREF(r);
    PyGILState_Release(g);
}

void tpulsm_flush(tpulsm_db_t* db, char** errptr) {
    if (!db) {
        if (errptr) *errptr = dup_cstr("null db handle");
        return;
    }
    PyGILState_STATE g = PyGILState_Ensure();
    PyObject* r = PyObject_CallMethod(db->obj, "flush", NULL);
    if (!r) set_err_from_python(errptr);
    Py_XDECREF(r);
    PyGILState_Release(g);
}

void tpulsm_compact_range(tpulsm_db_t* db, char** errptr) {
    if (!db) {
        if (errptr) *errptr = dup_cstr("null db handle");
        return;
    }
    PyGILState_STATE g = PyGILState_Ensure();
    PyObject* r = PyObject_CallMethod(db->obj, "compact_range", NULL);
    if (!r) set_err_from_python(errptr);
    Py_XDECREF(r);
    PyGILState_Release(g);
}

void tpulsm_free(void* ptr) { free(ptr); }

/* -- write batches ------------------------------------------------------ */

struct tpulsm_writebatch_t {
    PyObject* obj; /* toplingdb_tpu.db.write_batch.WriteBatch */
};

tpulsm_writebatch_t* tpulsm_writebatch_create(void) {
    PyGILState_STATE g = PyGILState_Ensure();
    tpulsm_writebatch_t* out = NULL;
    PyObject* mod = PyImport_ImportModule("toplingdb_tpu.db.write_batch");
    PyObject* wb = mod ? PyObject_CallMethod(mod, "WriteBatch", NULL) : NULL;
    if (wb) {
        out = (tpulsm_writebatch_t*)malloc(sizeof(*out));
        if (out) out->obj = wb;
        else Py_DECREF(wb);
    } else {
        PyErr_Clear();
    }
    Py_XDECREF(mod);
    PyGILState_Release(g);
    return out;
}

void tpulsm_writebatch_destroy(tpulsm_writebatch_t* wb) {
    if (!wb) return;
    PyGILState_STATE g = PyGILState_Ensure();
    Py_DECREF(wb->obj);
    PyGILState_Release(g);
    free(wb);
}

void tpulsm_writebatch_put(tpulsm_writebatch_t* wb, const char* key,
                           size_t keylen, const char* val, size_t vallen,
                           char** errptr) {
    if (!wb) {
        if (errptr) *errptr = dup_cstr("null writebatch handle");
        return;
    }
    PyGILState_STATE g = PyGILState_Ensure();
    PyObject* r = PyObject_CallMethod(
        wb->obj, "put", "y#y#", key, (Py_ssize_t)keylen,
        val, (Py_ssize_t)vallen);
    if (!r) set_err_from_python(errptr);
    Py_XDECREF(r);
    PyGILState_Release(g);
}

void tpulsm_writebatch_delete(tpulsm_writebatch_t* wb, const char* key,
                              size_t keylen, char** errptr) {
    if (!wb) {
        if (errptr) *errptr = dup_cstr("null writebatch handle");
        return;
    }
    PyGILState_STATE g = PyGILState_Ensure();
    PyObject* r = PyObject_CallMethod(
        wb->obj, "delete", "y#", key, (Py_ssize_t)keylen);
    if (!r) set_err_from_python(errptr);
    Py_XDECREF(r);
    PyGILState_Release(g);
}

void tpulsm_write(tpulsm_db_t* db, tpulsm_writebatch_t* wb, char** errptr) {
    if (!db || !wb) {
        if (errptr) *errptr = dup_cstr("null handle");
        return;
    }
    PyGILState_STATE g = PyGILState_Ensure();
    PyObject* r = PyObject_CallMethod(db->obj, "write", "O", wb->obj);
    if (!r) set_err_from_python(errptr);
    Py_XDECREF(r);
    PyGILState_Release(g);
}

/* -- iterators ----------------------------------------------------------- */

struct tpulsm_iterator_t {
    PyObject* obj;   /* DBIter */
    char* last_err;  /* sticky key/value failure (tpulsm_iter_get_error) */
};

tpulsm_iterator_t* tpulsm_create_iterator(tpulsm_db_t* db, char** errptr) {
    if (!db) {
        if (errptr) *errptr = dup_cstr("null db handle");
        return NULL;
    }
    PyGILState_STATE g = PyGILState_Ensure();
    tpulsm_iterator_t* out = NULL;
    PyObject* it = PyObject_CallMethod(db->obj, "new_iterator", NULL);
    if (it) {
        out = (tpulsm_iterator_t*)malloc(sizeof(*out));
        if (out) {
            out->obj = it;
            out->last_err = NULL;
        } else {
            Py_DECREF(it);
            if (errptr) *errptr = dup_cstr("out of memory");
        }
    } else {
        set_err_from_python(errptr);
    }
    PyGILState_Release(g);
    return out;
}

void tpulsm_iter_destroy(tpulsm_iterator_t* it) {
    if (!it) return;
    PyGILState_STATE g = PyGILState_Ensure();
    Py_DECREF(it->obj);
    PyGILState_Release(g);
    free(it->last_err);
    free(it);
}

static void iter_call0(tpulsm_iterator_t* it, const char* name) {
    if (!it) return;
    PyGILState_STATE g = PyGILState_Ensure();
    PyObject* r = PyObject_CallMethod(it->obj, name, NULL);
    if (!r) PyErr_Clear(); /* position ops surface via valid() */
    Py_XDECREF(r);
    PyGILState_Release(g);
}

void tpulsm_iter_seek_to_first(tpulsm_iterator_t* it) {
    iter_call0(it, "seek_to_first");
}

void tpulsm_iter_seek_to_last(tpulsm_iterator_t* it) {
    iter_call0(it, "seek_to_last");
}

void tpulsm_iter_seek(tpulsm_iterator_t* it, const char* key, size_t keylen) {
    if (!it) return;
    PyGILState_STATE g = PyGILState_Ensure();
    PyObject* r = PyObject_CallMethod(
        it->obj, "seek", "y#", key, (Py_ssize_t)keylen);
    if (!r) PyErr_Clear();
    Py_XDECREF(r);
    PyGILState_Release(g);
}

int tpulsm_iter_valid(tpulsm_iterator_t* it) {
    if (!it) return 0;
    PyGILState_STATE g = PyGILState_Ensure();
    int out = 0;
    PyObject* r = PyObject_CallMethod(it->obj, "valid", NULL);
    if (r) out = PyObject_IsTrue(r) == 1;
    else PyErr_Clear();
    Py_XDECREF(r);
    PyGILState_Release(g);
    return out;
}

void tpulsm_iter_next(tpulsm_iterator_t* it) { iter_call0(it, "next"); }

void tpulsm_iter_prev(tpulsm_iterator_t* it) { iter_call0(it, "prev"); }

static void iter_set_err(tpulsm_iterator_t* it, const char* msg) {
    free(it->last_err);
    it->last_err = dup_cstr(msg);
}

static char* iter_bytes(tpulsm_iterator_t* it, const char* name,
                        size_t* lenp) {
    if (lenp) *lenp = 0;
    if (!it) return NULL;
    PyGILState_STATE g = PyGILState_Ensure();
    char* out = NULL;
    PyObject* r = PyObject_CallMethod(it->obj, name, NULL);
    if (r) {
        char* buf = NULL;
        Py_ssize_t n = 0;
        if (PyBytes_AsStringAndSize(r, &buf, &n) == 0) {
            out = (char*)malloc(n > 0 ? (size_t)n : 1);
            if (out) {
                memcpy(out, buf, (size_t)n);
                if (lenp) *lenp = (size_t)n;
            } else {
                /* NULL must not read as "no data": record the OOM. */
                iter_set_err(it, "out of memory");
            }
        } else {
            char* e = NULL;
            set_err_from_python(&e);
            iter_set_err(it, e ? e : "non-bytes iterator result");
            free(e);
        }
    } else {
        char* e = NULL;
        set_err_from_python(&e);
        iter_set_err(it, e ? e : "iterator access failed");
        free(e);
    }
    Py_XDECREF(r);
    PyGILState_Release(g);
    return out;
}

void tpulsm_iter_get_error(tpulsm_iterator_t* it, char** errptr) {
    if (it && it->last_err && errptr) *errptr = dup_cstr(it->last_err);
}

char* tpulsm_iter_key(tpulsm_iterator_t* it, size_t* klen) {
    return iter_bytes(it, "key", klen);
}

char* tpulsm_iter_value(tpulsm_iterator_t* it, size_t* vlen) {
    return iter_bytes(it, "value", vlen);
}

/* -- introspection ------------------------------------------------------- */

char* tpulsm_property_value(tpulsm_db_t* db, const char* name) {
    if (!db) return NULL;
    PyGILState_STATE g = PyGILState_Ensure();
    char* out = NULL;
    PyObject* r = PyObject_CallMethod(db->obj, "get_property", "s", name);
    if (r && r != Py_None) {
        const char* s = PyUnicode_AsUTF8(r);
        if (s) out = dup_cstr(s);
        else PyErr_Clear();
    } else if (!r) {
        PyErr_Clear();
    }
    Py_XDECREF(r);
    PyGILState_Release(g);
    return out;
}
