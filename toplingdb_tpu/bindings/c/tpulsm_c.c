/* Flat C binding over the embedded engine (see tpulsm_c.h).
 *
 * Uses the CPython C API directly (no pybind11 in this toolchain). All
 * entry points take the GIL via PyGILState_Ensure, so the library is safe
 * to call from multiple C threads; the engine's own locking provides the
 * DB-level thread safety.
 */
#define PY_SSIZE_T_CLEAN
#include <Python.h>
#include <stdlib.h>
#include <string.h>

#include "tpulsm_c.h"

struct tpulsm_db_t {
    PyObject* obj; /* toplingdb_tpu.db.db.DB instance */
};

static char* dup_cstr(const char* s) {
    size_t n = strlen(s) + 1;
    char* out = (char*)malloc(n);
    if (out) memcpy(out, s, n);
    return out;
}

static void set_err_from_python(char** errptr) {
    PyObject *type, *value, *tb;
    PyErr_Fetch(&type, &value, &tb);
    if (errptr) {
        PyObject* s = value ? PyObject_Str(value) : NULL;
        const char* msg = s ? PyUnicode_AsUTF8(s) : "unknown python error";
        *errptr = dup_cstr(msg ? msg : "unknown python error");
        Py_XDECREF(s);
    }
    Py_XDECREF(type);
    Py_XDECREF(value);
    Py_XDECREF(tb);
}

static PyThreadState* g_main_tstate = NULL;
static int g_owns_interpreter = 0;

int tpulsm_init(void) {
    if (Py_IsInitialized()) return 0; /* host already embeds Python */
    Py_InitializeEx(0);
    g_owns_interpreter = 1;
    /* Release the GIL so worker threads can take it via PyGILState. */
    g_main_tstate = PyEval_SaveThread();
    return 0;
}

void tpulsm_shutdown(void) {
    /* Only tear down an interpreter WE created; finalizing a host's
     * interpreter (or calling Py_FinalizeEx without a thread state) would
     * abort the process. */
    if (!g_owns_interpreter || !Py_IsInitialized()) return;
    PyEval_RestoreThread(g_main_tstate);
    Py_FinalizeEx();
    g_main_tstate = NULL;
    g_owns_interpreter = 0;
}

tpulsm_db_t* tpulsm_open(const char* path, int create_if_missing,
                         char** errptr) {
    PyGILState_STATE g = PyGILState_Ensure();
    tpulsm_db_t* out = NULL;
    PyObject* mod = PyImport_ImportModule("toplingdb_tpu.db.db");
    if (!mod) { set_err_from_python(errptr); goto done; }
    {
        PyObject* omod = PyImport_ImportModule("toplingdb_tpu.options");
        if (!omod) { Py_DECREF(mod); set_err_from_python(errptr); goto done; }
        PyObject* opts = PyObject_CallMethod(
            omod, "Options", NULL);
        if (opts) {
            PyObject* flag = create_if_missing ? Py_True : Py_False;
            PyObject_SetAttrString(opts, "create_if_missing", flag);
        }
        PyObject* dbcls = opts ? PyObject_GetAttrString(mod, "DB") : NULL;
        PyObject* db = dbcls ? PyObject_CallMethod(
            dbcls, "open", "sO", path, opts) : NULL;
        if (db) {
            out = (tpulsm_db_t*)malloc(sizeof(*out));
            if (out) {
                out->obj = db;
            } else {
                Py_DECREF(db);
                if (errptr) *errptr = dup_cstr("out of memory");
            }
        } else {
            set_err_from_python(errptr);
        }
        Py_XDECREF(dbcls);
        Py_XDECREF(opts);
        Py_DECREF(omod);
        Py_DECREF(mod);
    }
done:
    PyGILState_Release(g);
    return out;
}

void tpulsm_close(tpulsm_db_t* db) {
    if (!db) return;
    PyGILState_STATE g = PyGILState_Ensure();
    PyObject* r = PyObject_CallMethod(db->obj, "close", NULL);
    if (!r) PyErr_Clear();
    Py_XDECREF(r);
    Py_DECREF(db->obj);
    PyGILState_Release(g);
    free(db);
}

void tpulsm_put(tpulsm_db_t* db, const char* key, size_t keylen,
                const char* val, size_t vallen, char** errptr) {
    if (!db) {
        if (errptr) *errptr = dup_cstr("null db handle");
        return;
    }
    PyGILState_STATE g = PyGILState_Ensure();
    PyObject* r = PyObject_CallMethod(
        db->obj, "put", "y#y#", key, (Py_ssize_t)keylen,
        val, (Py_ssize_t)vallen);
    if (!r) set_err_from_python(errptr);
    Py_XDECREF(r);
    PyGILState_Release(g);
}

char* tpulsm_get(tpulsm_db_t* db, const char* key, size_t keylen,
                 size_t* vallen, char** errptr) {
    if (!db) {
        if (errptr) *errptr = dup_cstr("null db handle");
        if (vallen) *vallen = 0;
        return NULL;
    }
    PyGILState_STATE g = PyGILState_Ensure();
    char* out = NULL;
    if (vallen) *vallen = 0;
    PyObject* r = PyObject_CallMethod(
        db->obj, "get", "y#", key, (Py_ssize_t)keylen);
    if (!r) {
        set_err_from_python(errptr);
    } else if (r != Py_None) {
        char* buf = NULL;
        Py_ssize_t n = 0;
        if (PyBytes_AsStringAndSize(r, &buf, &n) == 0) {
            out = (char*)malloc(n > 0 ? (size_t)n : 1);
            if (out) {
                memcpy(out, buf, (size_t)n);
                if (vallen) *vallen = (size_t)n;
            } else if (errptr) {
                /* NULL + untouched errptr means "absent" — OOM must NOT
                 * masquerade as a missing key. */
                *errptr = dup_cstr("out of memory");
            }
        } else {
            set_err_from_python(errptr);
        }
    }
    Py_XDECREF(r);
    PyGILState_Release(g);
    return out;
}

void tpulsm_delete(tpulsm_db_t* db, const char* key, size_t keylen,
                   char** errptr) {
    if (!db) {
        if (errptr) *errptr = dup_cstr("null db handle");
        return;
    }
    PyGILState_STATE g = PyGILState_Ensure();
    PyObject* r = PyObject_CallMethod(
        db->obj, "delete", "y#", key, (Py_ssize_t)keylen);
    if (!r) set_err_from_python(errptr);
    Py_XDECREF(r);
    PyGILState_Release(g);
}

void tpulsm_flush(tpulsm_db_t* db, char** errptr) {
    if (!db) {
        if (errptr) *errptr = dup_cstr("null db handle");
        return;
    }
    PyGILState_STATE g = PyGILState_Ensure();
    PyObject* r = PyObject_CallMethod(db->obj, "flush", NULL);
    if (!r) set_err_from_python(errptr);
    Py_XDECREF(r);
    PyGILState_Release(g);
}

void tpulsm_compact_range(tpulsm_db_t* db, char** errptr) {
    if (!db) {
        if (errptr) *errptr = dup_cstr("null db handle");
        return;
    }
    PyGILState_STATE g = PyGILState_Ensure();
    PyObject* r = PyObject_CallMethod(db->obj, "compact_range", NULL);
    if (!r) set_err_from_python(errptr);
    Py_XDECREF(r);
    PyGILState_Release(g);
}

void tpulsm_free(void* ptr) { free(ptr); }

/* -- write batches ------------------------------------------------------ */

struct tpulsm_writebatch_t {
    PyObject* obj; /* toplingdb_tpu.db.write_batch.WriteBatch */
};

tpulsm_writebatch_t* tpulsm_writebatch_create(void) {
    PyGILState_STATE g = PyGILState_Ensure();
    tpulsm_writebatch_t* out = NULL;
    PyObject* mod = PyImport_ImportModule("toplingdb_tpu.db.write_batch");
    PyObject* wb = mod ? PyObject_CallMethod(mod, "WriteBatch", NULL) : NULL;
    if (wb) {
        out = (tpulsm_writebatch_t*)malloc(sizeof(*out));
        if (out) out->obj = wb;
        else Py_DECREF(wb);
    } else {
        PyErr_Clear();
    }
    Py_XDECREF(mod);
    PyGILState_Release(g);
    return out;
}

void tpulsm_writebatch_destroy(tpulsm_writebatch_t* wb) {
    if (!wb) return;
    PyGILState_STATE g = PyGILState_Ensure();
    Py_DECREF(wb->obj);
    PyGILState_Release(g);
    free(wb);
}

void tpulsm_writebatch_put(tpulsm_writebatch_t* wb, const char* key,
                           size_t keylen, const char* val, size_t vallen,
                           char** errptr) {
    if (!wb) {
        if (errptr) *errptr = dup_cstr("null writebatch handle");
        return;
    }
    PyGILState_STATE g = PyGILState_Ensure();
    PyObject* r = PyObject_CallMethod(
        wb->obj, "put", "y#y#", key, (Py_ssize_t)keylen,
        val, (Py_ssize_t)vallen);
    if (!r) set_err_from_python(errptr);
    Py_XDECREF(r);
    PyGILState_Release(g);
}

void tpulsm_writebatch_delete(tpulsm_writebatch_t* wb, const char* key,
                              size_t keylen, char** errptr) {
    if (!wb) {
        if (errptr) *errptr = dup_cstr("null writebatch handle");
        return;
    }
    PyGILState_STATE g = PyGILState_Ensure();
    PyObject* r = PyObject_CallMethod(
        wb->obj, "delete", "y#", key, (Py_ssize_t)keylen);
    if (!r) set_err_from_python(errptr);
    Py_XDECREF(r);
    PyGILState_Release(g);
}

void tpulsm_write(tpulsm_db_t* db, tpulsm_writebatch_t* wb, char** errptr) {
    if (!db || !wb) {
        if (errptr) *errptr = dup_cstr("null handle");
        return;
    }
    PyGILState_STATE g = PyGILState_Ensure();
    PyObject* r = PyObject_CallMethod(db->obj, "write", "O", wb->obj);
    if (!r) set_err_from_python(errptr);
    Py_XDECREF(r);
    PyGILState_Release(g);
}

/* -- iterators ----------------------------------------------------------- */

struct tpulsm_iterator_t {
    PyObject* obj;   /* DBIter */
    char* last_err;  /* sticky key/value failure (tpulsm_iter_get_error) */
};

tpulsm_iterator_t* tpulsm_create_iterator(tpulsm_db_t* db, char** errptr) {
    if (!db) {
        if (errptr) *errptr = dup_cstr("null db handle");
        return NULL;
    }
    PyGILState_STATE g = PyGILState_Ensure();
    tpulsm_iterator_t* out = NULL;
    PyObject* it = PyObject_CallMethod(db->obj, "new_iterator", NULL);
    if (it) {
        out = (tpulsm_iterator_t*)malloc(sizeof(*out));
        if (out) {
            out->obj = it;
            out->last_err = NULL;
        } else {
            Py_DECREF(it);
            if (errptr) *errptr = dup_cstr("out of memory");
        }
    } else {
        set_err_from_python(errptr);
    }
    PyGILState_Release(g);
    return out;
}

void tpulsm_iter_destroy(tpulsm_iterator_t* it) {
    if (!it) return;
    PyGILState_STATE g = PyGILState_Ensure();
    Py_DECREF(it->obj);
    PyGILState_Release(g);
    free(it->last_err);
    free(it);
}

static void iter_call0(tpulsm_iterator_t* it, const char* name) {
    if (!it) return;
    PyGILState_STATE g = PyGILState_Ensure();
    PyObject* r = PyObject_CallMethod(it->obj, name, NULL);
    if (!r) PyErr_Clear(); /* position ops surface via valid() */
    Py_XDECREF(r);
    PyGILState_Release(g);
}

void tpulsm_iter_seek_to_first(tpulsm_iterator_t* it) {
    iter_call0(it, "seek_to_first");
}

void tpulsm_iter_seek_to_last(tpulsm_iterator_t* it) {
    iter_call0(it, "seek_to_last");
}

void tpulsm_iter_seek(tpulsm_iterator_t* it, const char* key, size_t keylen) {
    if (!it) return;
    PyGILState_STATE g = PyGILState_Ensure();
    PyObject* r = PyObject_CallMethod(
        it->obj, "seek", "y#", key, (Py_ssize_t)keylen);
    if (!r) PyErr_Clear();
    Py_XDECREF(r);
    PyGILState_Release(g);
}

int tpulsm_iter_valid(tpulsm_iterator_t* it) {
    if (!it) return 0;
    PyGILState_STATE g = PyGILState_Ensure();
    int out = 0;
    PyObject* r = PyObject_CallMethod(it->obj, "valid", NULL);
    if (r) out = PyObject_IsTrue(r) == 1;
    else PyErr_Clear();
    Py_XDECREF(r);
    PyGILState_Release(g);
    return out;
}

void tpulsm_iter_next(tpulsm_iterator_t* it) { iter_call0(it, "next"); }

void tpulsm_iter_prev(tpulsm_iterator_t* it) { iter_call0(it, "prev"); }

static void iter_set_err(tpulsm_iterator_t* it, const char* msg) {
    free(it->last_err);
    it->last_err = dup_cstr(msg);
}

static char* iter_bytes(tpulsm_iterator_t* it, const char* name,
                        size_t* lenp) {
    if (lenp) *lenp = 0;
    if (!it) return NULL;
    PyGILState_STATE g = PyGILState_Ensure();
    char* out = NULL;
    PyObject* r = PyObject_CallMethod(it->obj, name, NULL);
    if (r) {
        char* buf = NULL;
        Py_ssize_t n = 0;
        if (PyBytes_AsStringAndSize(r, &buf, &n) == 0) {
            out = (char*)malloc(n > 0 ? (size_t)n : 1);
            if (out) {
                memcpy(out, buf, (size_t)n);
                if (lenp) *lenp = (size_t)n;
            } else {
                /* NULL must not read as "no data": record the OOM. */
                iter_set_err(it, "out of memory");
            }
        } else {
            char* e = NULL;
            set_err_from_python(&e);
            iter_set_err(it, e ? e : "non-bytes iterator result");
            free(e);
        }
    } else {
        char* e = NULL;
        set_err_from_python(&e);
        iter_set_err(it, e ? e : "iterator access failed");
        free(e);
    }
    Py_XDECREF(r);
    PyGILState_Release(g);
    return out;
}

void tpulsm_iter_get_error(tpulsm_iterator_t* it, char** errptr) {
    if (it && it->last_err && errptr) *errptr = dup_cstr(it->last_err);
}

char* tpulsm_iter_key(tpulsm_iterator_t* it, size_t* klen) {
    return iter_bytes(it, "key", klen);
}

char* tpulsm_iter_value(tpulsm_iterator_t* it, size_t* vlen) {
    return iter_bytes(it, "value", vlen);
}

/* -- introspection ------------------------------------------------------- */

char* tpulsm_property_value(tpulsm_db_t* db, const char* name) {
    if (!db) return NULL;
    PyGILState_STATE g = PyGILState_Ensure();
    char* out = NULL;
    PyObject* r = PyObject_CallMethod(db->obj, "get_property", "s", name);
    if (r && r != Py_None) {
        const char* s = PyUnicode_AsUTF8(r);
        if (s) out = dup_cstr(s);
        else PyErr_Clear();
    } else if (!r) {
        PyErr_Clear();
    }
    Py_XDECREF(r);
    PyGILState_Release(g);
    return out;
}

/* =======================================================================
 * Extended surface: merge/delete_range, snapshots, column families,
 * checkpoint, backup engine, transactions, SST ingest — the
 * rocksdb_c-style breadth (reference include/rocksdb/c.h families).
 * Shared helpers below keep each binding a thin adapter.
 * ======================================================================= */

/* Convert a python bytes/None result to a malloc'd buffer (tpulsm_get's
 * contract); steals nothing, clears nothing. */
static char* bytes_result(PyObject* r, size_t* vallen, char** errptr) {
    char* out = NULL;
    if (vallen) *vallen = 0;
    if (!r) {
        set_err_from_python(errptr);
        return NULL;
    }
    if (r != Py_None) {
        char* buf = NULL;
        Py_ssize_t n = 0;
        if (PyBytes_AsStringAndSize(r, &buf, &n) == 0) {
            out = (char*)malloc(n > 0 ? (size_t)n : 1);
            if (out) {
                memcpy(out, buf, (size_t)n);
                if (vallen) *vallen = (size_t)n;
            } else if (errptr) {
                *errptr = dup_cstr("out of memory");
            }
        } else {
            set_err_from_python(errptr);
        }
    }
    return out;
}

/* Call obj.meth(key[, val]) with the bytes convention; NULL obj guarded by
 * callers. Returns 0 on success. */
static void kv_call(PyObject* obj, const char* meth, const char* a,
                    size_t alen, const char* b, size_t blen, char** errptr) {
    PyGILState_STATE g = PyGILState_Ensure();
    PyObject* r = b
        ? PyObject_CallMethod(obj, meth, "y#y#", a, (Py_ssize_t)alen,
                              b, (Py_ssize_t)blen)
        : PyObject_CallMethod(obj, meth, "y#", a, (Py_ssize_t)alen);
    if (!r) set_err_from_python(errptr);
    Py_XDECREF(r);
    PyGILState_Release(g);
}

void tpulsm_merge(tpulsm_db_t* db, const char* key, size_t keylen,
                  const char* val, size_t vallen, char** errptr) {
    if (!db) { if (errptr) *errptr = dup_cstr("null db handle"); return; }
    kv_call(db->obj, "merge", key, keylen, val, vallen, errptr);
}

void tpulsm_delete_range(tpulsm_db_t* db, const char* begin, size_t blen,
                         const char* end, size_t elen, char** errptr) {
    if (!db) { if (errptr) *errptr = dup_cstr("null db handle"); return; }
    kv_call(db->obj, "delete_range", begin, blen, end, elen, errptr);
}

void tpulsm_writebatch_merge(tpulsm_writebatch_t* wb, const char* key,
                             size_t keylen, const char* val, size_t vallen,
                             char** errptr) {
    if (!wb) { if (errptr) *errptr = dup_cstr("null batch"); return; }
    kv_call(wb->obj, "merge", key, keylen, val, vallen, errptr);
}

void tpulsm_writebatch_delete_range(tpulsm_writebatch_t* wb,
                                    const char* begin, size_t blen,
                                    const char* end, size_t elen,
                                    char** errptr) {
    if (!wb) { if (errptr) *errptr = dup_cstr("null batch"); return; }
    kv_call(wb->obj, "delete_range", begin, blen, end, elen, errptr);
}

void tpulsm_writebatch_clear(tpulsm_writebatch_t* wb) {
    if (!wb) return;
    PyGILState_STATE g = PyGILState_Ensure();
    PyObject* r = PyObject_CallMethod(wb->obj, "clear", NULL);
    if (!r) PyErr_Clear();
    Py_XDECREF(r);
    PyGILState_Release(g);
}

int tpulsm_writebatch_count(tpulsm_writebatch_t* wb) {
    if (!wb) return 0;
    PyGILState_STATE g = PyGILState_Ensure();
    int n = 0;
    PyObject* r = PyObject_CallMethod(wb->obj, "count", NULL);
    if (r) n = (int)PyLong_AsLong(r);
    if (PyErr_Occurred()) PyErr_Clear();
    Py_XDECREF(r);
    PyGILState_Release(g);
    return n;
}

/* -- snapshots ----------------------------------------------------------- */

struct tpulsm_snapshot_t { PyObject* obj; };

tpulsm_snapshot_t* tpulsm_create_snapshot(tpulsm_db_t* db, char** errptr) {
    if (!db) { if (errptr) *errptr = dup_cstr("null db handle"); return NULL; }
    PyGILState_STATE g = PyGILState_Ensure();
    tpulsm_snapshot_t* out = NULL;
    PyObject* r = PyObject_CallMethod(db->obj, "get_snapshot", NULL);
    if (r) {
        out = (tpulsm_snapshot_t*)malloc(sizeof(*out));
        if (out) out->obj = r;
        else { Py_DECREF(r); if (errptr) *errptr = dup_cstr("out of memory"); }
    } else {
        set_err_from_python(errptr);
    }
    PyGILState_Release(g);
    return out;
}

void tpulsm_release_snapshot(tpulsm_snapshot_t* snap) {
    if (!snap) return;
    PyGILState_STATE g = PyGILState_Ensure();
    PyObject* r = PyObject_CallMethod(snap->obj, "release", NULL);
    if (!r) PyErr_Clear();
    Py_XDECREF(r);
    Py_DECREF(snap->obj);
    PyGILState_Release(g);
    free(snap);
}

/* WriteOptions() helper (shared by the *_cf write bindings). */
static PyObject* write_opts_new(void) {
    PyObject* omod = PyImport_ImportModule("toplingdb_tpu.options");
    if (!omod) return NULL;
    PyObject* wo = PyObject_CallMethod(omod, "WriteOptions", NULL);
    Py_DECREF(omod);
    return wo;
}

/* ReadOptions(snapshot=snap) helper. */
static PyObject* read_opts_with(PyObject* snap) {
    PyObject* omod = PyImport_ImportModule("toplingdb_tpu.options");
    if (!omod) return NULL;
    PyObject* cls = PyObject_GetAttrString(omod, "ReadOptions");
    Py_DECREF(omod);
    if (!cls) return NULL;
    PyObject* kw = PyDict_New();
    PyObject* empty = PyTuple_New(0);
    PyObject* ro = NULL;
    if (kw && empty && (!snap || PyDict_SetItemString(kw, "snapshot", snap) == 0))
        ro = PyObject_Call(cls, empty, kw);
    Py_XDECREF(kw);
    Py_XDECREF(empty);
    Py_DECREF(cls);
    return ro;
}

char* tpulsm_get_at_snapshot(tpulsm_db_t* db, tpulsm_snapshot_t* snap,
                             const char* key, size_t keylen, size_t* vallen,
                             char** errptr) {
    if (!db || !snap) {
        if (errptr) *errptr = dup_cstr("null handle");
        if (vallen) *vallen = 0;
        return NULL;
    }
    PyGILState_STATE g = PyGILState_Ensure();
    char* out = NULL;
    PyObject* ro = read_opts_with(snap->obj);
    PyObject* r = ro ? PyObject_CallMethod(
        db->obj, "get", "y#O", key, (Py_ssize_t)keylen, ro) : NULL;
    out = bytes_result(r, vallen, errptr);
    Py_XDECREF(r);
    Py_XDECREF(ro);
    PyGILState_Release(g);
    return out;
}

/* -- column families ----------------------------------------------------- */

struct tpulsm_cf_t { PyObject* obj; };

tpulsm_cf_t* tpulsm_create_column_family(tpulsm_db_t* db, const char* name,
                                         char** errptr) {
    if (!db) { if (errptr) *errptr = dup_cstr("null db handle"); return NULL; }
    PyGILState_STATE g = PyGILState_Ensure();
    tpulsm_cf_t* out = NULL;
    PyObject* r = PyObject_CallMethod(db->obj, "create_column_family", "s",
                                      name);
    if (r) {
        out = (tpulsm_cf_t*)malloc(sizeof(*out));
        if (out) out->obj = r;
        else { Py_DECREF(r); if (errptr) *errptr = dup_cstr("out of memory"); }
    } else {
        set_err_from_python(errptr);
    }
    PyGILState_Release(g);
    return out;
}

tpulsm_cf_t* tpulsm_column_family_handle(tpulsm_db_t* db, const char* name,
                                         char** errptr) {
    if (!db) { if (errptr) *errptr = dup_cstr("null db handle"); return NULL; }
    PyGILState_STATE g = PyGILState_Ensure();
    tpulsm_cf_t* out = NULL;
    PyObject* lst = PyObject_CallMethod(db->obj, "list_column_families", NULL);
    if (lst && PyList_Check(lst)) {
        Py_ssize_t n = PyList_Size(lst);
        for (Py_ssize_t i = 0; i < n; i++) {
            PyObject* h = PyList_GetItem(lst, i); /* borrowed */
            PyObject* nm = h ? PyObject_GetAttrString(h, "name") : NULL;
            const char* s = nm ? PyUnicode_AsUTF8(nm) : NULL;
            if (s && strcmp(s, name) == 0) {
                out = (tpulsm_cf_t*)malloc(sizeof(*out));
                if (out) { Py_INCREF(h); out->obj = h; }
                Py_XDECREF(nm);
                break;
            }
            Py_XDECREF(nm);
        }
        if (!out && errptr)
            *errptr = dup_cstr("column family not found");
    } else {
        set_err_from_python(errptr);
    }
    Py_XDECREF(lst);
    PyGILState_Release(g);
    return out;
}

void tpulsm_drop_column_family(tpulsm_db_t* db, tpulsm_cf_t* cf,
                               char** errptr) {
    if (!db || !cf) { if (errptr) *errptr = dup_cstr("null handle"); return; }
    PyGILState_STATE g = PyGILState_Ensure();
    PyObject* r = PyObject_CallMethod(db->obj, "drop_column_family", "O",
                                      cf->obj);
    if (!r) set_err_from_python(errptr);
    Py_XDECREF(r);
    PyGILState_Release(g);
}

void tpulsm_cf_handle_destroy(tpulsm_cf_t* cf) {
    if (!cf) return;
    PyGILState_STATE g = PyGILState_Ensure();
    Py_DECREF(cf->obj);
    PyGILState_Release(g);
    free(cf);
}

void tpulsm_put_cf(tpulsm_db_t* db, tpulsm_cf_t* cf, const char* key,
                   size_t keylen, const char* val, size_t vallen,
                   char** errptr) {
    if (!db || !cf) { if (errptr) *errptr = dup_cstr("null handle"); return; }
    PyGILState_STATE g = PyGILState_Ensure();
    PyObject* wo = write_opts_new();
    PyObject* r = wo ? PyObject_CallMethod(
        db->obj, "put", "y#y#OO", key, (Py_ssize_t)keylen,
        val, (Py_ssize_t)vallen, wo, cf->obj) : NULL;
    if (!r) set_err_from_python(errptr);
    Py_XDECREF(r);
    Py_XDECREF(wo);
    PyGILState_Release(g);
}

char* tpulsm_get_cf(tpulsm_db_t* db, tpulsm_cf_t* cf, const char* key,
                    size_t keylen, size_t* vallen, char** errptr) {
    if (!db || !cf) {
        if (errptr) *errptr = dup_cstr("null handle");
        if (vallen) *vallen = 0;
        return NULL;
    }
    PyGILState_STATE g = PyGILState_Ensure();
    PyObject* ro = read_opts_with(NULL);
    PyObject* r = ro ? PyObject_CallMethod(
        db->obj, "get", "y#OO", key, (Py_ssize_t)keylen, ro, cf->obj) : NULL;
    char* out = bytes_result(r, vallen, errptr);
    Py_XDECREF(r);
    Py_XDECREF(ro);
    PyGILState_Release(g);
    return out;
}

void tpulsm_delete_cf(tpulsm_db_t* db, tpulsm_cf_t* cf, const char* key,
                      size_t keylen, char** errptr) {
    if (!db || !cf) { if (errptr) *errptr = dup_cstr("null handle"); return; }
    PyGILState_STATE g = PyGILState_Ensure();
    PyObject* wo = write_opts_new();
    PyObject* r = wo ? PyObject_CallMethod(
        db->obj, "delete", "y#OO", key, (Py_ssize_t)keylen, wo, cf->obj)
        : NULL;
    if (!r) set_err_from_python(errptr);
    Py_XDECREF(r);
    Py_XDECREF(wo);
    PyGILState_Release(g);
}

/* -- checkpoint ---------------------------------------------------------- */

void tpulsm_checkpoint_create(tpulsm_db_t* db, const char* dest,
                              char** errptr) {
    if (!db) { if (errptr) *errptr = dup_cstr("null db handle"); return; }
    PyGILState_STATE g = PyGILState_Ensure();
    PyObject* mod = PyImport_ImportModule("toplingdb_tpu.utilities.checkpoint");
    PyObject* r = mod ? PyObject_CallMethod(mod, "create_checkpoint", "Os",
                                            db->obj, dest) : NULL;
    if (!r) set_err_from_python(errptr);
    Py_XDECREF(r);
    Py_XDECREF(mod);
    PyGILState_Release(g);
}

/* -- backup engine ------------------------------------------------------- */

struct tpulsm_backup_engine_t { PyObject* obj; };

tpulsm_backup_engine_t* tpulsm_backup_engine_open(const char* dir,
                                                  char** errptr) {
    PyGILState_STATE g = PyGILState_Ensure();
    tpulsm_backup_engine_t* out = NULL;
    PyObject* mod = PyImport_ImportModule(
        "toplingdb_tpu.utilities.backup_engine");
    PyObject* be = mod ? PyObject_CallMethod(mod, "BackupEngine", "s", dir)
                       : NULL;
    if (be) {
        out = (tpulsm_backup_engine_t*)malloc(sizeof(*out));
        if (out) out->obj = be;
        else { Py_DECREF(be); if (errptr) *errptr = dup_cstr("out of memory"); }
    } else {
        set_err_from_python(errptr);
    }
    Py_XDECREF(mod);
    PyGILState_Release(g);
    return out;
}

void tpulsm_backup_engine_close(tpulsm_backup_engine_t* be) {
    if (!be) return;
    PyGILState_STATE g = PyGILState_Ensure();
    Py_DECREF(be->obj);
    PyGILState_Release(g);
    free(be);
}

int tpulsm_backup_engine_create_backup(tpulsm_backup_engine_t* be,
                                       tpulsm_db_t* db, char** errptr) {
    if (!be || !db) {
        if (errptr) *errptr = dup_cstr("null handle");
        return 0;
    }
    PyGILState_STATE g = PyGILState_Ensure();
    int id = 0;
    PyObject* r = PyObject_CallMethod(be->obj, "create_backup", "O", db->obj);
    if (r) id = (int)PyLong_AsLong(r);
    if (!r || PyErr_Occurred()) set_err_from_python(errptr);
    Py_XDECREF(r);
    PyGILState_Release(g);
    return id;
}

int tpulsm_backup_engine_count(tpulsm_backup_engine_t* be) {
    if (!be) return 0;
    PyGILState_STATE g = PyGILState_Ensure();
    int n = 0;
    PyObject* r = PyObject_CallMethod(be->obj, "get_backup_info", NULL);
    if (r && PyList_Check(r)) n = (int)PyList_Size(r);
    else PyErr_Clear();
    Py_XDECREF(r);
    PyGILState_Release(g);
    return n;
}

void tpulsm_backup_engine_restore(tpulsm_backup_engine_t* be, int backup_id,
                                  const char* target_dir, char** errptr) {
    if (!be) { if (errptr) *errptr = dup_cstr("null handle"); return; }
    PyGILState_STATE g = PyGILState_Ensure();
    if (backup_id <= 0) {
        /* 0 = latest */
        PyObject* info = PyObject_CallMethod(be->obj, "get_backup_info", NULL);
        if (info && PyList_Check(info) && PyList_Size(info) > 0) {
            PyObject* last = PyList_GetItem(info, PyList_Size(info) - 1);
            PyObject* bid = last ? PyDict_GetItemString(last, "backup_id")
                                 : NULL;
            if (bid) backup_id = (int)PyLong_AsLong(bid);
            if (PyErr_Occurred()) PyErr_Clear();
        }
        Py_XDECREF(info);
        if (backup_id <= 0) {
            if (errptr) *errptr = dup_cstr("no backups");
            PyGILState_Release(g);
            return;
        }
    }
    PyObject* r = PyObject_CallMethod(be->obj, "restore_db_from_backup",
                                      "is", backup_id, target_dir);
    if (!r) set_err_from_python(errptr);
    Py_XDECREF(r);
    PyGILState_Release(g);
}

void tpulsm_backup_engine_purge_old(tpulsm_backup_engine_t* be,
                                    int num_to_keep, char** errptr) {
    if (!be) { if (errptr) *errptr = dup_cstr("null handle"); return; }
    PyGILState_STATE g = PyGILState_Ensure();
    PyObject* r = PyObject_CallMethod(be->obj, "purge_old_backups", "i",
                                      num_to_keep);
    if (!r) set_err_from_python(errptr);
    Py_XDECREF(r);
    PyGILState_Release(g);
}

/* -- transactions -------------------------------------------------------- */

struct tpulsm_txndb_t { PyObject* obj; };
struct tpulsm_txn_t { PyObject* obj; };

tpulsm_txndb_t* tpulsm_txndb_open(const char* path, int create_if_missing,
                                  char** errptr) {
    PyGILState_STATE g = PyGILState_Ensure();
    tpulsm_txndb_t* out = NULL;
    PyObject* mod = PyImport_ImportModule(
        "toplingdb_tpu.utilities.transactions");
    PyObject* omod = PyImport_ImportModule("toplingdb_tpu.options");
    PyObject* opts = omod ? PyObject_CallMethod(omod, "Options", NULL) : NULL;
    if (opts)
        PyObject_SetAttrString(opts, "create_if_missing",
                               create_if_missing ? Py_True : Py_False);
    PyObject* cls = mod ? PyObject_GetAttrString(mod, "TransactionDB") : NULL;
    PyObject* tdb = (cls && opts)
        ? PyObject_CallMethod(cls, "open", "sO", path, opts) : NULL;
    if (tdb) {
        out = (tpulsm_txndb_t*)malloc(sizeof(*out));
        if (out) out->obj = tdb;
        else { Py_DECREF(tdb); if (errptr) *errptr = dup_cstr("out of memory"); }
    } else {
        set_err_from_python(errptr);
    }
    Py_XDECREF(cls);
    Py_XDECREF(opts);
    Py_XDECREF(omod);
    Py_XDECREF(mod);
    PyGILState_Release(g);
    return out;
}

void tpulsm_txndb_close(tpulsm_txndb_t* tdb) {
    if (!tdb) return;
    PyGILState_STATE g = PyGILState_Ensure();
    PyObject* r = PyObject_CallMethod(tdb->obj, "close", NULL);
    if (!r) PyErr_Clear();
    Py_XDECREF(r);
    Py_DECREF(tdb->obj);
    PyGILState_Release(g);
    free(tdb);
}

tpulsm_txn_t* tpulsm_txn_begin(tpulsm_txndb_t* tdb, char** errptr) {
    if (!tdb) { if (errptr) *errptr = dup_cstr("null handle"); return NULL; }
    PyGILState_STATE g = PyGILState_Ensure();
    tpulsm_txn_t* out = NULL;
    PyObject* r = PyObject_CallMethod(tdb->obj, "begin_transaction", NULL);
    if (r) {
        out = (tpulsm_txn_t*)malloc(sizeof(*out));
        if (out) out->obj = r;
        else { Py_DECREF(r); if (errptr) *errptr = dup_cstr("out of memory"); }
    } else {
        set_err_from_python(errptr);
    }
    PyGILState_Release(g);
    return out;
}

void tpulsm_txn_put(tpulsm_txn_t* txn, const char* key, size_t keylen,
                    const char* val, size_t vallen, char** errptr) {
    if (!txn) { if (errptr) *errptr = dup_cstr("null handle"); return; }
    kv_call(txn->obj, "put", key, keylen, val, vallen, errptr);
}

char* tpulsm_txn_get(tpulsm_txn_t* txn, const char* key, size_t keylen,
                     size_t* vallen, char** errptr) {
    if (!txn) {
        if (errptr) *errptr = dup_cstr("null handle");
        if (vallen) *vallen = 0;
        return NULL;
    }
    PyGILState_STATE g = PyGILState_Ensure();
    PyObject* r = PyObject_CallMethod(txn->obj, "get", "y#", key,
                                      (Py_ssize_t)keylen);
    char* out = bytes_result(r, vallen, errptr);
    Py_XDECREF(r);
    PyGILState_Release(g);
    return out;
}

void tpulsm_txn_delete(tpulsm_txn_t* txn, const char* key, size_t keylen,
                       char** errptr) {
    if (!txn) { if (errptr) *errptr = dup_cstr("null handle"); return; }
    kv_call(txn->obj, "delete", key, keylen, NULL, 0, errptr);
}

void tpulsm_txn_commit(tpulsm_txn_t* txn, char** errptr) {
    if (!txn) { if (errptr) *errptr = dup_cstr("null handle"); return; }
    PyGILState_STATE g = PyGILState_Ensure();
    PyObject* r = PyObject_CallMethod(txn->obj, "commit", NULL);
    if (!r) set_err_from_python(errptr);
    Py_XDECREF(r);
    PyGILState_Release(g);
}

void tpulsm_txn_rollback(tpulsm_txn_t* txn, char** errptr) {
    if (!txn) { if (errptr) *errptr = dup_cstr("null handle"); return; }
    PyGILState_STATE g = PyGILState_Ensure();
    PyObject* r = PyObject_CallMethod(txn->obj, "rollback", NULL);
    if (!r) set_err_from_python(errptr);
    Py_XDECREF(r);
    PyGILState_Release(g);
}

void tpulsm_txn_destroy(tpulsm_txn_t* txn) {
    if (!txn) return;
    PyGILState_STATE g = PyGILState_Ensure();
    Py_DECREF(txn->obj);
    PyGILState_Release(g);
    free(txn);
}

char* tpulsm_txndb_get(tpulsm_txndb_t* tdb, const char* key, size_t keylen,
                       size_t* vallen, char** errptr) {
    if (!tdb) {
        if (errptr) *errptr = dup_cstr("null handle");
        if (vallen) *vallen = 0;
        return NULL;
    }
    PyGILState_STATE g = PyGILState_Ensure();
    PyObject* r = PyObject_CallMethod(tdb->obj, "get", "y#", key,
                                      (Py_ssize_t)keylen);
    char* out = bytes_result(r, vallen, errptr);
    Py_XDECREF(r);
    PyGILState_Release(g);
    return out;
}

/* -- external SSTs ------------------------------------------------------- */

struct tpulsm_sstwriter_t { PyObject* obj; };

tpulsm_sstwriter_t* tpulsm_sstfilewriter_create(const char* path,
                                                char** errptr) {
    PyGILState_STATE g = PyGILState_Ensure();
    tpulsm_sstwriter_t* out = NULL;
    PyObject* mod = PyImport_ImportModule(
        "toplingdb_tpu.utilities.sst_file_writer");
    PyObject* w = mod ? PyObject_CallMethod(mod, "SstFileWriter", NULL)
                      : NULL;
    if (!w) {
        set_err_from_python(errptr);
    } else {
        PyObject* r = PyObject_CallMethod(w, "open", "s", path);
        if (!r) {
            set_err_from_python(errptr);
            Py_DECREF(w);
            w = NULL;
        }
        Py_XDECREF(r);
    }
    if (w) {
        out = (tpulsm_sstwriter_t*)malloc(sizeof(*out));
        if (out) out->obj = w;
        else { Py_DECREF(w); if (errptr) *errptr = dup_cstr("out of memory"); }
    }
    Py_XDECREF(mod);
    PyGILState_Release(g);
    return out;
}

void tpulsm_sstfilewriter_put(tpulsm_sstwriter_t* w, const char* key,
                              size_t keylen, const char* val, size_t vallen,
                              char** errptr) {
    if (!w) { if (errptr) *errptr = dup_cstr("null handle"); return; }
    kv_call(w->obj, "put", key, keylen, val, vallen, errptr);
}

void tpulsm_sstfilewriter_finish(tpulsm_sstwriter_t* w, char** errptr) {
    if (!w) { if (errptr) *errptr = dup_cstr("null handle"); return; }
    PyGILState_STATE g = PyGILState_Ensure();
    PyObject* r = PyObject_CallMethod(w->obj, "finish", NULL);
    if (!r) set_err_from_python(errptr);
    Py_XDECREF(r);
    PyGILState_Release(g);
}

void tpulsm_sstfilewriter_destroy(tpulsm_sstwriter_t* w) {
    if (!w) return;
    PyGILState_STATE g = PyGILState_Ensure();
    Py_DECREF(w->obj);
    PyGILState_Release(g);
    free(w);
}

void tpulsm_ingest_external_file(tpulsm_db_t* db, const char* path,
                                 char** errptr) {
    if (!db) { if (errptr) *errptr = dup_cstr("null db handle"); return; }
    PyGILState_STATE g = PyGILState_Ensure();
    PyObject* mod = PyImport_ImportModule(
        "toplingdb_tpu.utilities.sst_file_writer");
    PyObject* r = mod ? PyObject_CallMethod(mod, "ingest_external_file",
                                            "Os", db->obj, path) : NULL;
    if (!r) set_err_from_python(errptr);
    Py_XDECREF(r);
    Py_XDECREF(mod);
    PyGILState_Release(g);
}

/* -- SidePluginRepo ------------------------------------------------------ */

struct tpulsm_repo_t { PyObject* obj; };

tpulsm_repo_t* tpulsm_repo_create(char** errptr) {
    PyGILState_STATE g = PyGILState_Ensure();
    tpulsm_repo_t* out = NULL;
    PyObject* mod = PyImport_ImportModule("toplingdb_tpu.utils.config");
    PyObject* r = mod ? PyObject_CallMethod(mod, "SidePluginRepo", NULL)
                      : NULL;
    if (!r) {
        set_err_from_python(errptr);
    } else {
        out = (tpulsm_repo_t*)malloc(sizeof(*out));
        if (out) {
            out->obj = r;
        } else {
            Py_DECREF(r);
            if (errptr) *errptr = dup_cstr("out of memory");
        }
    }
    Py_XDECREF(mod);
    PyGILState_Release(g);
    return out;
}

tpulsm_db_t* tpulsm_repo_open_db(tpulsm_repo_t* repo,
                                 const char* config_json, char** errptr) {
    if (!repo) {
        if (errptr) *errptr = dup_cstr("null repo handle");
        return NULL;
    }
    PyGILState_STATE g = PyGILState_Ensure();
    tpulsm_db_t* out = NULL;
    PyObject* r = PyObject_CallMethod(repo->obj, "open_db", "s",
                                      config_json);
    if (!r) {
        set_err_from_python(errptr);
    } else {
        out = (tpulsm_db_t*)malloc(sizeof(*out));
        if (out) {
            out->obj = r; /* repo also holds a ref; ours via this handle */
        } else {
            Py_DECREF(r);
            if (errptr) *errptr = dup_cstr("out of memory");
        }
    }
    PyGILState_Release(g);
    return out;
}

int tpulsm_repo_start_http(tpulsm_repo_t* repo, int port, char** errptr) {
    if (!repo) {
        if (errptr) *errptr = dup_cstr("null repo handle");
        return -1;
    }
    PyGILState_STATE g = PyGILState_Ensure();
    int bound = -1;
    PyObject* r = PyObject_CallMethod(repo->obj, "start_http", "i", port);
    if (!r) {
        set_err_from_python(errptr);
    } else {
        bound = (int)PyLong_AsLong(r);
        Py_DECREF(r);
    }
    PyGILState_Release(g);
    return bound;
}

void tpulsm_repo_stop_http(tpulsm_repo_t* repo) {
    if (!repo) return;
    PyGILState_STATE g = PyGILState_Ensure();
    PyObject* r = PyObject_CallMethod(repo->obj, "stop_http", NULL);
    if (!r) PyErr_Clear();
    Py_XDECREF(r);
    PyGILState_Release(g);
}

void tpulsm_repo_close_all(tpulsm_repo_t* repo) {
    if (!repo) return;
    PyGILState_STATE g = PyGILState_Ensure();
    PyObject* r = PyObject_CallMethod(repo->obj, "close_all", NULL);
    if (!r) PyErr_Clear();
    Py_XDECREF(r);
    Py_DECREF(repo->obj);
    PyGILState_Release(g);
    free(repo);
}
