/* Flat C binding over the embedded engine (see tpulsm_c.h).
 *
 * Uses the CPython C API directly (no pybind11 in this toolchain). All
 * entry points take the GIL via PyGILState_Ensure, so the library is safe
 * to call from multiple C threads; the engine's own locking provides the
 * DB-level thread safety.
 */
#define PY_SSIZE_T_CLEAN
#include <Python.h>
#include <stdlib.h>
#include <string.h>

#include "tpulsm_c.h"

struct tpulsm_db_t {
    PyObject* obj; /* toplingdb_tpu.db.db.DB instance */
};

static char* dup_cstr(const char* s) {
    size_t n = strlen(s) + 1;
    char* out = (char*)malloc(n);
    if (out) memcpy(out, s, n);
    return out;
}

static void set_err_from_python(char** errptr) {
    PyObject *type, *value, *tb;
    PyErr_Fetch(&type, &value, &tb);
    if (errptr) {
        PyObject* s = value ? PyObject_Str(value) : NULL;
        const char* msg = s ? PyUnicode_AsUTF8(s) : "unknown python error";
        *errptr = dup_cstr(msg ? msg : "unknown python error");
        Py_XDECREF(s);
    }
    Py_XDECREF(type);
    Py_XDECREF(value);
    Py_XDECREF(tb);
}

static PyThreadState* g_main_tstate = NULL;
static int g_owns_interpreter = 0;

int tpulsm_init(void) {
    if (Py_IsInitialized()) return 0; /* host already embeds Python */
    Py_InitializeEx(0);
    g_owns_interpreter = 1;
    /* Release the GIL so worker threads can take it via PyGILState. */
    g_main_tstate = PyEval_SaveThread();
    return 0;
}

void tpulsm_shutdown(void) {
    /* Only tear down an interpreter WE created; finalizing a host's
     * interpreter (or calling Py_FinalizeEx without a thread state) would
     * abort the process. */
    if (!g_owns_interpreter || !Py_IsInitialized()) return;
    PyEval_RestoreThread(g_main_tstate);
    Py_FinalizeEx();
    g_main_tstate = NULL;
    g_owns_interpreter = 0;
}

tpulsm_db_t* tpulsm_open(const char* path, int create_if_missing,
                         char** errptr) {
    PyGILState_STATE g = PyGILState_Ensure();
    tpulsm_db_t* out = NULL;
    PyObject* mod = PyImport_ImportModule("toplingdb_tpu.db.db");
    if (!mod) { set_err_from_python(errptr); goto done; }
    {
        PyObject* omod = PyImport_ImportModule("toplingdb_tpu.options");
        if (!omod) { Py_DECREF(mod); set_err_from_python(errptr); goto done; }
        PyObject* opts = PyObject_CallMethod(
            omod, "Options", NULL);
        if (opts) {
            PyObject* flag = create_if_missing ? Py_True : Py_False;
            PyObject_SetAttrString(opts, "create_if_missing", flag);
        }
        PyObject* dbcls = opts ? PyObject_GetAttrString(mod, "DB") : NULL;
        PyObject* db = dbcls ? PyObject_CallMethod(
            dbcls, "open", "sO", path, opts) : NULL;
        if (db) {
            out = (tpulsm_db_t*)malloc(sizeof(*out));
            if (out) {
                out->obj = db;
            } else {
                Py_DECREF(db);
                if (errptr) *errptr = dup_cstr("out of memory");
            }
        } else {
            set_err_from_python(errptr);
        }
        Py_XDECREF(dbcls);
        Py_XDECREF(opts);
        Py_DECREF(omod);
        Py_DECREF(mod);
    }
done:
    PyGILState_Release(g);
    return out;
}

void tpulsm_close(tpulsm_db_t* db) {
    if (!db) return;
    PyGILState_STATE g = PyGILState_Ensure();
    PyObject* r = PyObject_CallMethod(db->obj, "close", NULL);
    if (!r) PyErr_Clear();
    Py_XDECREF(r);
    Py_DECREF(db->obj);
    PyGILState_Release(g);
    free(db);
}

void tpulsm_put(tpulsm_db_t* db, const char* key, size_t keylen,
                const char* val, size_t vallen, char** errptr) {
    if (!db) {
        if (errptr) *errptr = dup_cstr("null db handle");
        return;
    }
    PyGILState_STATE g = PyGILState_Ensure();
    PyObject* r = PyObject_CallMethod(
        db->obj, "put", "y#y#", key, (Py_ssize_t)keylen,
        val, (Py_ssize_t)vallen);
    if (!r) set_err_from_python(errptr);
    Py_XDECREF(r);
    PyGILState_Release(g);
}

char* tpulsm_get(tpulsm_db_t* db, const char* key, size_t keylen,
                 size_t* vallen, char** errptr) {
    if (!db) {
        if (errptr) *errptr = dup_cstr("null db handle");
        if (vallen) *vallen = 0;
        return NULL;
    }
    PyGILState_STATE g = PyGILState_Ensure();
    char* out = NULL;
    if (vallen) *vallen = 0;
    PyObject* r = PyObject_CallMethod(
        db->obj, "get", "y#", key, (Py_ssize_t)keylen);
    if (!r) {
        set_err_from_python(errptr);
    } else if (r != Py_None) {
        char* buf = NULL;
        Py_ssize_t n = 0;
        if (PyBytes_AsStringAndSize(r, &buf, &n) == 0) {
            out = (char*)malloc(n > 0 ? (size_t)n : 1);
            if (out) {
                memcpy(out, buf, (size_t)n);
                if (vallen) *vallen = (size_t)n;
            } else if (errptr) {
                /* NULL + untouched errptr means "absent" — OOM must NOT
                 * masquerade as a missing key. */
                *errptr = dup_cstr("out of memory");
            }
        } else {
            set_err_from_python(errptr);
        }
    }
    Py_XDECREF(r);
    PyGILState_Release(g);
    return out;
}

void tpulsm_delete(tpulsm_db_t* db, const char* key, size_t keylen,
                   char** errptr) {
    if (!db) {
        if (errptr) *errptr = dup_cstr("null db handle");
        return;
    }
    PyGILState_STATE g = PyGILState_Ensure();
    PyObject* r = PyObject_CallMethod(
        db->obj, "delete", "y#", key, (Py_ssize_t)keylen);
    if (!r) set_err_from_python(errptr);
    Py_XDECREF(r);
    PyGILState_Release(g);
}

void tpulsm_flush(tpulsm_db_t* db, char** errptr) {
    if (!db) {
        if (errptr) *errptr = dup_cstr("null db handle");
        return;
    }
    PyGILState_STATE g = PyGILState_Ensure();
    PyObject* r = PyObject_CallMethod(db->obj, "flush", NULL);
    if (!r) set_err_from_python(errptr);
    Py_XDECREF(r);
    PyGILState_Release(g);
}

void tpulsm_compact_range(tpulsm_db_t* db, char** errptr) {
    if (!db) {
        if (errptr) *errptr = dup_cstr("null db handle");
        return;
    }
    PyGILState_STATE g = PyGILState_Ensure();
    PyObject* r = PyObject_CallMethod(db->obj, "compact_range", NULL);
    if (!r) set_err_from_python(errptr);
    Py_XDECREF(r);
    PyGILState_Release(g);
}

void tpulsm_free(void* ptr) { free(ptr); }
