"""IntegrityScrubber: background corruption detection + quarantine.

The at-rest half of the integrity plane: a rate-limited walker over the
live SST set that re-reads every file FROM DISK, recomputes its whole-file
checksum, and compares it against the value recorded in the MANIFEST at
flush/compaction/ingest time (utils/file_checksum.py). On a mismatch it

  1. quarantines the file (FileMetaData.quarantined: the compaction
     pickers treat it like a perpetually-busy file, so the corruption is
     never merged into new SSTs),
  2. latches the DB's background-error machinery with a kCorruption
     classification (`reason="scrub"` -> HARD_ERROR: foreground writes
     fail until the operator restores/repairs the file — see db/repair.py
     — re-scrubs, and calls resume(); unlike compaction-found corruption
     it is resumable because nothing corrupt was propagated),
  3. fires the on_corruption_detected listener and bumps the
     INTEGRITY_* tickers + scrub.latency.micros histogram.

A clean re-scan of a previously quarantined file (the operator restored
its bytes) lifts the quarantine. Deep mode additionally opens each table
and iterates every block with CRC verification, and probes each
referenced blob record (record-level CRC).

Cadence: Options.integrity_scrub_period_sec > 0 starts the background
thread at DB.open; db.scrub() runs one pass synchronously either way.
"""

from __future__ import annotations

import threading

from toplingdb_tpu.utils import concurrency as ccy
import time

from toplingdb_tpu.db import filename
from toplingdb_tpu.utils import statistics as st
from toplingdb_tpu.utils.file_checksum import (
    FileChecksumGenFactory,
    compute_file_checksum,
)
from toplingdb_tpu.utils.status import Corruption
from toplingdb_tpu.utils import errors as _errors


class _Pacer:
    """Token-bucket byte pacer (the scrubber must not starve foreground
    IO; reference rate-limited file verification)."""

    def __init__(self, bytes_per_sec: int):
        self._rate = max(0, bytes_per_sec)
        self._t0 = time.monotonic()
        self._consumed = 0

    def __call__(self, nbytes: int) -> None:
        if self._rate <= 0:
            return
        self._consumed += nbytes
        ahead = self._consumed / self._rate - (time.monotonic() - self._t0)
        if ahead > 0:
            time.sleep(min(ahead, 0.25))


class IntegrityScrubber:
    def __init__(self, db, bytes_per_sec: int | None = None,
                 period_sec: int | None = None):
        self.db = db
        opts = db.options
        self.bytes_per_sec = (bytes_per_sec if bytes_per_sec is not None
                              else getattr(opts,
                                           "integrity_scrub_bytes_per_sec",
                                           32 << 20))
        self.period_sec = (period_sec if period_sec is not None
                           else getattr(opts,
                                        "integrity_scrub_period_sec", 0))
        self._mu = ccy.Lock("integrity.IntegrityScrubber._mu")
        self._thread: threading.Thread | None = None
        self._stop = threading.Event()
        self._in_progress = False
        self._aio = None  # per-pass AsyncIORing (chunk-read double buffer)
        # Rolling status (the /integrity HTTP view's payload).
        self.passes = 0
        self.last_pass_time: float | None = None
        self.last_pass_micros = 0
        self.bytes_verified_total = 0
        self.corruptions_total = 0
        self.last_report: dict = {}

    # -- lifecycle -----------------------------------------------------

    def start(self) -> None:
        if self.period_sec <= 0 or self._thread is not None:
            return
        self._stop.clear()
        self._thread = ccy.spawn("integrity-scrubber", self._loop,
                                 owner=self.db, stop=self.stop)

    def stop(self) -> None:
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout=10.0)
        self._thread = None

    def _loop(self) -> None:
        while not self._stop.wait(self.period_sec):
            try:
                self.run_pass()
            except Exception as e:
                # a broken pass must not kill the cadence
                _errors.swallow(reason="integrity-pass-retry", exc=e)

    # -- one pass ------------------------------------------------------

    def _snapshot_files(self):
        """(cf_id, FileMetaData) of every live SST, holding the Version
        objects so obsolete-file GC can't delete files mid-scan."""
        db = self.db
        with db._mutex:
            versions = [(cf_id, db.versions.cf_current(cf_id))
                        for cf_id in db.versions.column_families]
        out = []
        seen: set[int] = set()
        for cf_id, version in versions:
            for _lvl, f in version.all_files():
                if f.number not in seen:
                    seen.add(f.number)
                    out.append((cf_id, f))
        return out, versions  # versions returned to keep the pin alive

    def run_pass(self, deep: bool = False) -> dict:
        """Scrub every live SST once; returns the pass report. Safe to
        call concurrently with foreground traffic (reads through the Env,
        paced)."""
        db = self.db
        with self._mu:
            self._in_progress = True
        t0 = time.perf_counter()
        pacer = _Pacer(self.bytes_per_sec)
        report: dict = {
            "deep": deep,
            "files_scanned": 0,
            "files_skipped_no_checksum": 0,
            "bytes_verified": 0,
            "corruptions": [],
            "repaired": [],
            "quarantined": [],
        }
        # Scrub reads submit through the shared Env async-I/O primitive
        # (the write plane's AsyncIORing facility): the next chunk's
        # pread overlaps the current chunk's checksum compute. A private
        # ring, not the WAL's — scrub I/O must not queue behind (or
        # ahead of) group-commit appends.
        from toplingdb_tpu.env.env import AsyncIORing

        self._aio = AsyncIORing(capacity=4, name="tpulsm-scrub-io")
        try:
            files, _pin = self._snapshot_files()
            for cf_id, meta in files:
                if self._stop.is_set():
                    break
                path = filename.table_file_name(db.dbname, meta.number)
                err = self._scrub_file(db, meta, path, pacer, deep, report)
                if err is None:
                    if meta.quarantined:
                        # The operator restored the bytes: lift quarantine.
                        meta.quarantined = False
                        db._quarantined.discard(meta.number)
                        report["repaired"].append(meta.number)
                else:
                    self._on_corruption(db, meta, path, err, report)
        finally:
            self._aio.close()
            self._aio = None
            micros = int((time.perf_counter() - t0) * 1e6)
            with self._mu:
                self._in_progress = False
                self.passes += 1
                self.last_pass_time = time.time()
                self.last_pass_micros = micros
                self.bytes_verified_total += report["bytes_verified"]
                self.corruptions_total += len(report["corruptions"])
                report["pass_micros"] = micros
                self.last_report = report
            if db.stats is not None:
                db.stats.record_tick(st.INTEGRITY_SCRUB_PASSES)
                if report["bytes_verified"]:
                    db.stats.record_tick(st.INTEGRITY_BYTES_VERIFIED,
                                         report["bytes_verified"])
                db.stats.record_in_histogram(st.SCRUB_LATENCY_MICROS,
                                             micros)
            db.event_logger.log(
                "integrity_scrub_pass",
                files=report["files_scanned"],
                bytes=report["bytes_verified"],
                corruptions=len(report["corruptions"]),
                micros=micros,
            )
        return report

    def _scrub_file(self, db, meta, path, pacer, deep, report):
        """Returns None when the file is healthy, else the Corruption."""
        if not meta.file_checksum:
            report["files_skipped_no_checksum"] += 1
            return None
        report["files_scanned"] += 1
        try:
            gen = FileChecksumGenFactory(
                meta.file_checksum_func_name or "crc32c").create()
            actual = compute_file_checksum(db.env, path, gen, pacer=pacer,
                                           aio_ring=self._aio)
        except Corruption as e:
            return e
        except Exception as e:  # unreadable file == corrupt for our purposes
            return Corruption(f"{path}: unreadable during scrub: {e!r}")
        if actual != meta.file_checksum:
            return Corruption(
                f"{path}: file checksum mismatch — MANIFEST records "
                f"{meta.file_checksum.hex()} "
                f"({meta.file_checksum_func_name}), disk has "
                f"{actual.hex()}"
            )
        report["bytes_verified"] += meta.file_size
        if deep:
            err = self._deep_scan(db, meta, path, report)
            if err is not None:
                return err
        return None

    def _deep_scan(self, db, meta, path, report):
        """Block-level re-read: every data/meta block CRC re-verified and
        every referenced blob record probed."""
        import dataclasses as _dc

        from toplingdb_tpu.db import dbformat
        from toplingdb_tpu.table.factory import open_table

        try:
            topts = _dc.replace(db.options.table_options,
                                verify_checksums=True)
            reader = open_table(db.env.new_random_access_file(path),
                                db.icmp, topts)
            try:
                it = reader.new_iterator()
                it.seek_to_first()
                for ik, v in it.entries():
                    if ik[-8] == dbformat.ValueType.BLOB_INDEX:
                        db.blob_source.get(v, verify=True)
            finally:
                reader.close()
        except Corruption as e:
            return e
        except Exception as e:
            return Corruption(f"{path}: deep scrub failed: {e!r}")
        return None

    def _on_corruption(self, db, meta, path, err, report) -> None:
        report["corruptions"].append(
            {"file_number": meta.number, "path": path, "error": str(err)})
        if not meta.quarantined:
            meta.quarantined = True
            db._quarantined.add(meta.number)
            report["quarantined"].append(meta.number)
        if db.stats is not None:
            db.stats.record_tick(st.INTEGRITY_CORRUPTIONS_DETECTED)
        from toplingdb_tpu.utils.listener import CorruptionInfo, notify

        notify(db.options.listeners, "on_corruption_detected", db,
               CorruptionInfo(
                   db_name=db.dbname, file_number=meta.number, path=path,
                   reason=str(err),
                   recorded_checksum=meta.file_checksum.hex(),
                   checksum_func_name=meta.file_checksum_func_name,
               ))
        db.event_logger.log("corruption_detected", file_number=meta.number,
                            path=path, error=str(err))
        latch = Corruption(
            f"scrub detected corruption in {path}: {err}; the file is "
            f"quarantined (excluded from compaction). Restore it from a "
            f"backup/replica or run toplingdb_tpu.db.repair.repair_db, "
            f"re-scrub, then DB.resume()."
        )
        db._set_background_error(latch, reason="scrub")

    # -- status --------------------------------------------------------

    def status(self) -> dict:
        with self._mu:
            return {
                "running": self._thread is not None,
                "in_progress": self._in_progress,
                "period_sec": self.period_sec,
                "bytes_per_sec": self.bytes_per_sec,
                "passes": self.passes,
                "last_pass_time": self.last_pass_time,
                "last_pass_micros": self.last_pass_micros,
                "bytes_verified_total": self.bytes_verified_total,
                "corruptions_total": self.corruptions_total,
                "quarantined_files": sorted(self.db._quarantined),
                "last_report": self.last_report,
            }
