"""DB directory file naming (reference file/filename.cc in /root/reference).

  NNNNNN.log      WAL
  NNNNNN.sst      table file
  MANIFEST-NNNNNN version-edit log
  CURRENT         points at the live MANIFEST
  IDENTITY        db uuid
  LOCK            advisory lock
  OPTIONS-NNNNNN  persisted options
"""

from __future__ import annotations

import enum
import os


class FileType(enum.Enum):
    WAL = "log"
    TABLE = "sst"
    MANIFEST = "manifest"
    CURRENT = "current"
    IDENTITY = "identity"
    LOCK = "lock"
    OPTIONS = "options"
    TEMP = "dbtmp"
    BLOB = "blob"
    UNKNOWN = "unknown"


def log_file_name(dbname: str, number: int) -> str:
    return os.path.join(dbname, f"{number:06d}.log")


def table_file_name(dbname: str, number: int) -> str:
    return os.path.join(dbname, f"{number:06d}.sst")


def manifest_file_name(dbname: str, number: int) -> str:
    return os.path.join(dbname, f"MANIFEST-{number:06d}")


def current_file_name(dbname: str) -> str:
    return os.path.join(dbname, "CURRENT")


def identity_file_name(dbname: str) -> str:
    return os.path.join(dbname, "IDENTITY")


def lock_file_name(dbname: str) -> str:
    return os.path.join(dbname, "LOCK")


def options_file_name(dbname: str, number: int) -> str:
    return os.path.join(dbname, f"OPTIONS-{number:06d}")


def temp_file_name(dbname: str, number: int) -> str:
    return os.path.join(dbname, f"{number:06d}.dbtmp")


def parse_file_name(fname: str) -> tuple[FileType, int]:
    """Classify a basename; returns (type, number) with number=0 when N/A."""
    if fname == "CURRENT":
        return FileType.CURRENT, 0
    if fname == "IDENTITY":
        return FileType.IDENTITY, 0
    if fname == "LOCK":
        return FileType.LOCK, 0
    if fname.startswith("MANIFEST-"):
        tail = fname[len("MANIFEST-"):]
        if tail.isdigit():
            return FileType.MANIFEST, int(tail)
        return FileType.UNKNOWN, 0
    if fname.startswith("OPTIONS-"):
        tail = fname[len("OPTIONS-"):]
        if tail.isdigit():
            return FileType.OPTIONS, int(tail)
        return FileType.UNKNOWN, 0
    stem, _, ext = fname.partition(".")
    if stem.isdigit():
        if ext == "log":
            return FileType.WAL, int(stem)
        if ext == "sst":
            return FileType.TABLE, int(stem)
        if ext == "dbtmp":
            return FileType.TEMP, int(stem)
        if ext == "blob":
            return FileType.BLOB, int(stem)
    return FileType.UNKNOWN, 0


def set_current_file(env, dbname: str, manifest_number: int) -> None:
    """Atomically point CURRENT at MANIFEST-N (write temp + rename)."""
    tmp = temp_file_name(dbname, manifest_number)
    env.write_file(tmp, f"MANIFEST-{manifest_number:06d}\n".encode(), sync=True)
    env.rename_file(tmp, current_file_name(dbname))
