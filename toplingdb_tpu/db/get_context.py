"""GetContext: the per-lookup state machine.

Same role as the reference's GetContext (table/get_context.h:67 in
/root/reference): sources (memtable, immutable memtables, L0 files newest→
oldest, then deeper levels) feed visible entries for the target user key in
newest→oldest order; the context tracks kNotFound → kFound/kDeleted/
kMerge-in-progress transitions, accumulates merge operands, and respects the
max covering range-tombstone seqno seen so far.
"""

from __future__ import annotations

import enum

from toplingdb_tpu.db.dbformat import ValueType
from toplingdb_tpu.utils.status import Corruption, MergeInProgress


class GetState(enum.Enum):
    NOT_FOUND = 0
    FOUND = 1
    DELETED = 2
    MERGE = 3       # operand chain open; keep descending into older sources
    CORRUPT = 4


class GetContext:
    def __init__(self, user_key: bytes, snapshot_seq: int, merge_operator=None,
                 blob_resolver=None, collect_operands: bool = False,
                 excluded_ranges: tuple = ()):
        self.user_key = user_key
        self.snapshot_seq = snapshot_seq
        # Seqno ranges invisible despite being <= snapshot_seq: in-DB data of
        # prepared-but-undecided WritePrepared transactions (the reference's
        # SnapshotChecker role; see db/snapshot.py).
        self.excluded_ranges = excluded_ranges
        self.merge_operator = merge_operator
        self.blob_resolver = blob_resolver  # BLOB_INDEX payload → real value
        self.state = GetState.NOT_FOUND
        self.value: bytes | None = None
        self.result_is_entity = False  # value is a wide-column encoding
        self.operands: list[bytes] = []   # collected newest→oldest
        self.max_covering_tombstone_seq = 0
        self.found_final_value = False
        # collect_operands (reference DB::GetMergeOperands): keep the chain
        # unfolded — same visibility/tombstone state machine, no folding,
        # no merge_operator required.
        self.collect_operands = collect_operands

    # ------------------------------------------------------------------

    def _excluded(self, seq: int) -> bool:
        for lo, hi in self.excluded_ranges:
            if lo <= seq <= hi:
                return True
        return False

    def add_tombstone_seq(self, seq: int) -> None:
        """Register a range tombstone covering the key (from the current or a
        newer source)."""
        if (seq <= self.snapshot_seq and seq > self.max_covering_tombstone_seq
                and not self._excluded(seq)):
            self.max_covering_tombstone_seq = seq

    def save_value(self, seq: int, t: int, value: bytes) -> bool:
        """Feed one visible point entry (seq <= snapshot already filtered by
        caller, newest first). Returns False when the lookup is complete and
        no older sources need to be consulted."""
        assert not self.found_final_value
        if self.excluded_ranges and self._excluded(seq):
            return True  # undecided-transaction data: keep descending
        if seq < self.max_covering_tombstone_seq:
            # Shadowed by a strictly newer range tombstone. Strict: seqnos are
            # unique per write, and seqno-zeroed entries (bottommost
            # compaction) must not be swallowed by the 0 "no tombstone"
            # sentinel.
            t = ValueType.DELETION
        if t == ValueType.BLOB_INDEX:
            if self.blob_resolver is None:
                raise Corruption("blob index found but no blob resolver")
            value = self.blob_resolver(value)
            t = ValueType.VALUE
        if t == ValueType.VALUE:
            if self.state == GetState.MERGE and not self.collect_operands:
                self.state = GetState.FOUND
                self.value = self._fold(value)
            else:
                self.state = GetState.FOUND
                self.value = value
            self.found_final_value = True
            return False
        if t == ValueType.WIDE_COLUMN_ENTITY:
            # A put of a wide-column entity (reference
            # kTypeWideColumnEntity + wide_columns_helper): merge chains
            # fold against the entity's DEFAULT column, and the result
            # stays an entity with the default column replaced.
            if self.state == GetState.MERGE and not self.collect_operands:
                from toplingdb_tpu.db.wide_columns import merge_into_entity

                self.state = GetState.FOUND
                self.value = merge_into_entity(
                    value, lambda base: self._fold(base))
            else:
                self.state = GetState.FOUND
                self.value = value
            self.result_is_entity = True
            self.found_final_value = True
            return False
        if t in (ValueType.DELETION, ValueType.SINGLE_DELETION):
            if self.state == GetState.MERGE:
                if self.collect_operands:
                    pass  # chain ends with no base; keep the operands
                else:
                    self.state = GetState.FOUND
                    self.value = self._fold(None)
            else:
                self.state = GetState.DELETED
            self.found_final_value = True
            return False
        if t == ValueType.MERGE:
            if self.merge_operator is None and not self.collect_operands:
                self.state = GetState.CORRUPT
                self.found_final_value = True
                return False
            self.state = GetState.MERGE
            self.operands.append(value)
            return True
        raise Corruption(f"unexpected value type {t} in lookup")

    def finish(self) -> None:
        """No more sources. Resolve an open merge chain against no base."""
        if self.state == GetState.MERGE and not self.collect_operands:
            self.value = self._fold(None)
            self.state = GetState.FOUND
            self.found_final_value = True

    def merge_operand_list(self) -> list[bytes]:
        """collect_operands result: base value (if any) first, then merge
        operands oldest→newest; [] when missing/deleted."""
        out: list[bytes] = []
        if self.state in (GetState.FOUND, GetState.MERGE) and \
                self.value is not None:
            out.append(self.value)
        out.extend(reversed(self.operands))
        return out

    def _fold(self, base: bytes | None) -> bytes:
        # operands were collected newest→oldest; full_merge wants oldest→newest.
        return self.merge_operator.full_merge(
            self.user_key, base, list(reversed(self.operands))
        )

    # ------------------------------------------------------------------

    def result(self) -> bytes | None:
        """Returns the value, or None if not found / deleted. Raises on
        merge-without-operator."""
        if self.state == GetState.CORRUPT:
            raise MergeInProgress(
                "merge operands found but no merge_operator configured"
            )
        if self.state == GetState.FOUND:
            return self.value
        return None
