"""Internal key format and comparators.

An internal key is `user_key + packed(seqno, type)` where the trailer is 8
bytes: `(seqno << 8) | type`, stored little-endian fixed64 — same layout and
semantics as the reference (db/dbformat.h:43-57,371 in /root/reference).
Ordering: user keys ascending by the user comparator, then seqno DESCENDING,
then type descending — so the newest version of a key sorts first. Because the
trailer is compared as a big integer descending, decreasing (seqno,type) means
increasing encoded trailer is *later*; we compare trailers reversed.

kMaxSequenceNumber is 2^56-1; seqno 0 is reserved to mean "visible to
everyone" (assigned to keys compacted to the bottommost level with no
snapshot in the way).
"""

from __future__ import annotations

import enum
import struct
from dataclasses import dataclass

from toplingdb_tpu.utils.status import Corruption

_U64 = struct.Struct("<Q")

MAX_SEQUENCE_NUMBER = (1 << 56) - 1


class ValueType(enum.IntEnum):
    """Record types in the keyspace (reference db/dbformat.h:43-57)."""

    DELETION = 0x0
    VALUE = 0x1
    MERGE = 0x2
    LOG_DATA = 0x3          # WAL-only annotation, never in the keyspace
    SINGLE_DELETION = 0x7
    RANGE_DELETION = 0xF    # DeleteRange tombstone
    BLOB_INDEX = 0x11       # value is a pointer into a blob file
    WIDE_COLUMN_ENTITY = 0x16  # value is a wide-column entity encoding
    MAX = 0x7F


# Highest type value used when constructing "seek" keys: for a given
# (user_key, seqno), the largest type sorts first in internal order.
VALUE_TYPE_FOR_SEEK = ValueType.MAX


def pack_seq_type(seq: int, t: ValueType | int) -> int:
    assert 0 <= seq <= MAX_SEQUENCE_NUMBER, seq
    return (seq << 8) | int(t)


def unpack_seq_type(packed: int) -> tuple[int, int]:
    return packed >> 8, packed & 0xFF


def make_internal_key(user_key: bytes, seq: int, t: ValueType | int) -> bytes:
    return user_key + _U64.pack(pack_seq_type(seq, t))


def split_internal_key(ikey: bytes) -> tuple[bytes, int, int]:
    """Returns (user_key, seqno, value_type)."""
    if len(ikey) < 8:
        raise Corruption(f"internal key too short: {len(ikey)}")
    seq, t = unpack_seq_type(_U64.unpack_from(ikey, len(ikey) - 8)[0])
    return ikey[:-8], seq, t


def extract_user_key(ikey: bytes) -> bytes:
    if len(ikey) < 8:
        raise Corruption(f"internal key too short: {len(ikey)}")
    return ikey[:-8]


def extract_seqno(ikey: bytes) -> int:
    return _U64.unpack_from(ikey, len(ikey) - 8)[0] >> 8


def extract_value_type(ikey: bytes) -> int:
    # Trailer is little-endian fixed64 of (seqno << 8 | type): the type is the
    # LOW byte, i.e. the first byte of the 8-byte trailer.
    if len(ikey) < 8:
        raise Corruption(f"internal key too short: {len(ikey)}")
    return ikey[-8]


class Comparator:
    """User-key comparator interface (reference include/rocksdb/comparator.h).

    Subclasses override compare/name; find_shortest_separator and
    find_short_successor shorten index-block keys.
    """

    #: bytes of user-defined timestamp suffixed to every user key (reference
    #: Comparator::timestamp_size(); 0 = no timestamps).
    timestamp_size = 0

    def name(self) -> str:
        return "tpulsm.BytewiseComparator"

    def compare(self, a: bytes, b: bytes) -> int:
        return (a > b) - (a < b)

    def equal(self, a: bytes, b: bytes) -> bool:
        return self.compare(a, b) == 0

    def find_shortest_separator(self, start: bytes, limit: bytes) -> bytes:
        """Returns a key k with start <= k < limit, as short as possible."""
        # Find common prefix.
        n = min(len(start), len(limit))
        i = 0
        while i < n and start[i] == limit[i]:
            i += 1
        if i >= n:
            return start  # one is a prefix of the other
        b = start[i]
        if b < 0xFF and b + 1 < limit[i]:
            return start[: i] + bytes([b + 1])
        return start

    def find_short_successor(self, key: bytes) -> bytes:
        """Returns a short key k >= key."""
        for i, b in enumerate(key):
            if b != 0xFF:
                return key[: i] + bytes([b + 1])
        return key


class ReverseBytewiseComparator(Comparator):
    def name(self) -> str:
        return "tpulsm.ReverseBytewiseComparator"

    def compare(self, a: bytes, b: bytes) -> int:
        return (a < b) - (a > b)

    def find_shortest_separator(self, start: bytes, limit: bytes) -> bytes:
        return start

    def find_short_successor(self, key: bytes) -> bytes:
        return key


class U64TsBytewiseComparator(Comparator):
    """Bytewise comparator with a u64 user-defined timestamp per key
    (reference BytewiseComparatorWithU64TsWrapper, util/comparator.cc, the
    TOPLINGDB_WITH_TIMESTAMP feature): keys order ascending and timestamps
    DESCENDING — newer versions of a key sort first, the same recency
    discipline seqnos follow.

    TPU-first twist: instead of a comparator that re-parses every key (the
    reference's approach — hostile to byte-ordered machinery), the ORDER is
    baked into the stored bytes (encode_ts_key): the user key is made
    prefix-free by an order-preserving escape (0x00 → 0x00 0xFF, terminated
    by 0x00 0x00) and suffixed with the BITWISE-INVERTED timestamp. Raw
    bytewise order over the stored bytes is then exactly (key asc, ts
    desc), so the comparator IS plain bytewise, and every byte-ordered
    component — the native arena skiplist, the radix/device sorts, SST
    builders — handles timestamped keys unchanged. Only the encode/decode
    boundary and the read-visibility layer know timestamps exist."""

    timestamp_size = 8

    def name(self) -> str:
        return "tpulsm.BytewiseComparator.u64ts"

    def find_shortest_separator(self, start: bytes, limit: bytes) -> bytes:
        return start  # never synthesize keys across a ts boundary

    def find_short_successor(self, key: bytes) -> bytes:
        return key


def encode_ts(ts: int) -> bytes:
    """u64 timestamp → its 8-byte stored suffix: bitwise-inverted
    big-endian, so ascending byte order == descending timestamp."""
    return (ts ^ MAX_TIMESTAMP).to_bytes(8, "big")


def decode_ts(suffix: bytes) -> int:
    return int.from_bytes(suffix[-8:], "big") ^ MAX_TIMESTAMP


_TS_TERM = b"\x00\x00"


def encode_ts_key(user_key: bytes, ts: int) -> bytes:
    """(key, ts) → stored key: escaped prefix-free key + inverted-ts suffix.
    bytewise(stored_a, stored_b) == (key asc, ts desc)."""
    return user_key.replace(b"\x00", b"\x00\xff") + _TS_TERM + encode_ts(ts)


def split_ts_key(stored: bytes) -> tuple[bytes, int]:
    """Stored key → (user key, ts)."""
    return strip_ts(stored), decode_ts(stored[-8:])


def strip_ts(stored: bytes) -> bytes:
    """Stored key → the user key (escape removed)."""
    esc = stored[:-8]
    if not esc.endswith(_TS_TERM):
        raise ValueError(f"not a timestamped key: {stored!r}")
    return esc[:-2].replace(b"\x00\xff", b"\x00")


MAX_TIMESTAMP = (1 << 64) - 1

BYTEWISE = Comparator()
REVERSE_BYTEWISE = ReverseBytewiseComparator()
U64_TS_BYTEWISE = U64TsBytewiseComparator()


class _OrderedKey:
    """Wrapper making a comparator usable as a sort key function."""

    __slots__ = ("cmp", "k")

    def __init__(self, cmp, k):
        self.cmp = cmp
        self.k = k

    def __lt__(self, other):
        return self.cmp(self.k, other.k) < 0

    def __eq__(self, other):
        return self.cmp(self.k, other.k) == 0


class InternalKeyComparator:
    """Orders internal keys: user key asc, then (seqno, type) desc
    (reference db/dbformat.h InternalKeyComparator)."""

    def __init__(self, user_cmp: Comparator = BYTEWISE):
        self.user_comparator = user_cmp

    def sort_key(self, k: bytes) -> "_OrderedKey":
        """For use as `key=` in sorted()/min()/max() over internal keys."""
        return _OrderedKey(self.compare, k)

    def name(self) -> str:
        return "tpulsm.InternalKeyComparator:" + self.user_comparator.name()

    def compare(self, a: bytes, b: bytes) -> int:
        r = self.user_comparator.compare(a[:-8], b[:-8])
        if r != 0:
            return r
        anum = _U64.unpack_from(a, len(a) - 8)[0]
        bnum = _U64.unpack_from(b, len(b) - 8)[0]
        # Descending by packed (seqno, type).
        return (anum < bnum) - (anum > bnum)

    def find_shortest_separator(self, start: bytes, limit: bytes) -> bytes:
        su, lu = start[:-8], limit[:-8]
        tmp = self.user_comparator.find_shortest_separator(su, lu)
        if len(tmp) < len(su) and self.user_comparator.compare(su, tmp) < 0:
            # User key became shorter physically but larger logically: tag with
            # the earliest possible (seqno, type) so it still sorts before limit.
            out = tmp + _U64.pack(pack_seq_type(MAX_SEQUENCE_NUMBER, VALUE_TYPE_FOR_SEEK))
            assert self.compare(start, out) < 0
            assert self.compare(out, limit) < 0
            return out
        return start

    def find_short_successor(self, key: bytes) -> bytes:
        uk = key[:-8]
        tmp = self.user_comparator.find_short_successor(uk)
        if len(tmp) < len(uk) and self.user_comparator.compare(uk, tmp) < 0:
            out = tmp + _U64.pack(pack_seq_type(MAX_SEQUENCE_NUMBER, VALUE_TYPE_FOR_SEEK))
            assert self.compare(key, out) < 0
            return out
        return key


@dataclass(frozen=True)
class ParsedInternalKey:
    user_key: bytes
    sequence: int
    type: int

    @staticmethod
    def parse(ikey: bytes) -> "ParsedInternalKey":
        uk, seq, t = split_internal_key(ikey)
        return ParsedInternalKey(uk, seq, t)

    def encode(self) -> bytes:
        return make_internal_key(self.user_key, self.sequence, self.type)


class LookupKey:
    """The key forms needed for a point lookup at a snapshot seqno
    (reference db/dbformat.h LookupKey): memtable key == internal key here."""

    def __init__(self, user_key: bytes, seq: int):
        self.user_key = user_key
        self.internal_key = make_internal_key(user_key, seq, VALUE_TYPE_FOR_SEEK)
