"""Version / VersionSet: the LSM metadata spine.

Mirrors the roles of the reference's Version/VersionSet/VersionBuilder
(db/version_set.cc:2606 `Version::Get`, :6033 `LogAndApply`, :6196 `Recover`
in /root/reference): a Version is an immutable snapshot of the file DAG
(per-level sorted file lists); VersionSet owns the current Version, the
MANIFEST log, and the file/sequence number allocators; VersionBuilder applies
VersionEdits to produce new Versions.
"""

from __future__ import annotations

import threading

from toplingdb_tpu.utils import concurrency as ccy
import weakref

from toplingdb_tpu.db import dbformat, filename
from toplingdb_tpu.db.dbformat import InternalKeyComparator, ValueType
from toplingdb_tpu.db.log import LogReader, LogWriter
from toplingdb_tpu.db.version_edit import FileMetaData, VersionEdit
from toplingdb_tpu.utils.status import Corruption, NotFound
from toplingdb_tpu.utils import errors as _errors


class Version:
    """Immutable per-point-in-time file layout: files[level] sorted by
    smallest key (L1+) / newest-first (L0)."""

    def __init__(self, icmp: InternalKeyComparator, num_levels: int):
        self.icmp = icmp
        self.num_levels = num_levels
        self.files: list[list[FileMetaData]] = [[] for _ in range(num_levels)]

    # -- read path ------------------------------------------------------

    def overlapping_files(self, level: int, smallest_user_key: bytes | None,
                          largest_user_key: bytes | None) -> list[FileMetaData]:
        """Files whose user-key range intersects [smallest, largest].
        L1+ file lists are sorted and disjoint, so the scan bisects to the
        first candidate instead of walking the level."""
        ucmp = self.icmp.user_comparator
        fl = self.files[level]
        start = 0
        if level > 0 and smallest_user_key is not None and fl:
            lo, hi = 0, len(fl)
            while lo < hi:
                mid = (lo + hi) // 2
                if ucmp.compare(dbformat.extract_user_key(fl[mid].largest),
                                smallest_user_key) < 0:
                    lo = mid + 1
                else:
                    hi = mid
            start = lo
        out = []
        for f in fl[start:]:
            f_small = dbformat.extract_user_key(f.smallest)
            f_large = dbformat.extract_user_key(f.largest)
            if smallest_user_key is not None and ucmp.compare(f_large, smallest_user_key) < 0:
                continue
            if largest_user_key is not None and ucmp.compare(f_small, largest_user_key) > 0:
                if level > 0:
                    break  # sorted disjoint: nothing further overlaps
                continue
            out.append(f)
        return out

    def native_read_chain(self, table_cache):
        """Native read-chain handle for tpulsm_db_get (built once — a
        Version is immutable): L0 newest-first then deeper levels, each
        table's handle from its (cached) reader. Returns the ctypes
        pointer, or None when the native engine is unavailable. The chain
        keeps strong refs to every reader so table handles outlive it."""
        cached = getattr(self, "_nchain", False)
        if cached is not False:
            return cached[0] if cached else None
        import ctypes
        import weakref

        from toplingdb_tpu import native
        from toplingdb_tpu.db import dbformat as _dbf

        cl = native.lib()
        if cl is None or not hasattr(cl, "tpulsm_version_handle_new"):
            self._nchain = None
            return None
        n_files = sum(len(fl) for fl in self.files)
        if n_files > getattr(table_cache, "_capacity", 512):
            # The chain pins a reader ref + a dup'd fd per file for the
            # version's lifetime; past the table cache's open-file budget
            # that would defeat its eviction contract.
            self._nchain = None
            return None
        readers, handles = [], []
        level_offs = []
        try:
            for level in range(self.num_levels):
                for f in self.files[level]:
                    r = table_cache.get_reader(f.number)
                    h = r.native_get_handle(
                        _dbf.extract_user_key(f.smallest),
                        _dbf.extract_user_key(f.largest),
                    )
                    if h is None:
                        self._nchain = None
                        return None
                    readers.append(r)
                    handles.append(h)
                # level_offs[0] == n_l0; [li], [li+1] bound deeper level li.
                level_offs.append(len(handles))
        except Exception as e:
            _errors.swallow(reason="native-chain-build-fallback", exc=e)
            self._nchain = None
            return None
        n_l0 = level_offs[0]
        offs = (ctypes.c_int32 * len(level_offs))(*level_offs)
        arr = (ctypes.c_void_p * max(1, len(handles)))(*handles)
        vh = cl.tpulsm_version_handle_new(arr, n_l0, offs,
                                          self.num_levels - 1)
        if not vh:
            self._nchain = None
            return None
        self._nchain = (vh, readers)
        weakref.finalize(self, cl.tpulsm_version_handle_free, vh)
        return vh

    def files_for_get(self, user_key: bytes):
        """Yield files that may contain user_key, newest data first:
        L0 newest-to-oldest, then each deeper level's single candidate
        (reference FilePicker, version_set.cc:235)."""
        ucmp = self.icmp.user_comparator
        for f in self.files[0]:  # already newest-first
            if (ucmp.compare(dbformat.extract_user_key(f.smallest), user_key) <= 0
                    and ucmp.compare(user_key, dbformat.extract_user_key(f.largest)) <= 0):
                yield 0, f
        for level in range(1, self.num_levels):
            fl = self.files[level]
            if not fl:
                continue
            lo, hi = 0, len(fl) - 1
            pick = None
            while lo <= hi:
                mid = (lo + hi) // 2
                if ucmp.compare(dbformat.extract_user_key(fl[mid].largest), user_key) < 0:
                    lo = mid + 1
                else:
                    pick = mid
                    hi = mid - 1
            if pick is not None and ucmp.compare(
                dbformat.extract_user_key(fl[pick].smallest), user_key
            ) <= 0:
                yield level, fl[pick]
                # A range tombstone's exclusive end widens a file's largest
                # bound to (end_uk, MAX_SEQ); the NEXT file may legally start
                # at the same user key (reference FilePicker walks files
                # while the user key still overlaps).
                while (pick + 1 < len(fl) and ucmp.compare(
                        dbformat.extract_user_key(fl[pick + 1].smallest),
                        user_key) <= 0):
                    pick += 1
                    yield level, fl[pick]

    def num_files(self) -> int:
        return sum(len(fl) for fl in self.files)

    def total_bytes(self, level: int) -> int:
        return sum(f.file_size for f in self.files[level])

    def all_files(self):
        for level, fl in enumerate(self.files):
            for f in fl:
                yield level, f

    def describe(self) -> str:
        lines = []
        for level, fl in enumerate(self.files):
            if fl:
                lines.append(
                    f"L{level}: " + " ".join(
                        f"{f.number}({f.file_size})" for f in fl
                    )
                )
        return "\n".join(lines)


class VersionBuilder:
    """Applies edits on a base Version to produce the next one
    (reference db/version_builder.cc)."""

    def __init__(self, base: Version):
        self._base = base
        self._added: list[list[FileMetaData]] = [[] for _ in range(base.num_levels)]
        self._deleted: set[tuple[int, int]] = set()

    def apply(self, edit: VersionEdit) -> None:
        for level, number in edit.deleted_files:
            self._deleted.add((level, number))
            # Multi-edit replay (MANIFEST recovery): a file added by an
            # earlier edit and deleted later must not survive in _added.
            self._added[level] = [
                f for f in self._added[level] if f.number != number
            ]
        for level, meta in edit.new_files:
            self._deleted.discard((level, meta.number))
            self._added[level].append(meta)

    def save(self) -> Version:
        v = Version(self._base.icmp, self._base.num_levels)
        icmp = self._base.icmp
        for level in range(self._base.num_levels):
            merged = [
                f for f in self._base.files[level]
                if (level, f.number) not in self._deleted
            ] + self._added[level]
            if level == 0:
                # Newest data first. Seqno order (not file number): a
                # universal compaction's output holds OLD data under a NEW
                # file number and must sort after untouched newer runs.
                merged.sort(key=lambda m: (-m.largest_seqno, -m.number))
            else:
                merged.sort(key=lambda m: _SmallestKey(icmp, m.smallest))
                # Sanity: non-overlapping ranges in L1+.
                for a, b in zip(merged, merged[1:]):
                    if icmp.compare(a.largest, b.smallest) >= 0:
                        raise Corruption(
                            f"overlapping files at L{level}: "
                            f"{a.number} and {b.number}"
                        )
            v.files[level] = merged
        return v


class _SmallestKey:
    __slots__ = ("icmp", "k")

    def __init__(self, icmp, k):
        self.icmp = icmp
        self.k = k

    def __lt__(self, other):
        return self.icmp.compare(self.k, other.k) < 0


class ColumnFamilyState:
    """Per-CF metadata inside the VersionSet (the reference's
    ColumnFamilyData, db/column_family.h)."""

    __slots__ = ("cf_id", "name", "current", "dropped")

    def __init__(self, cf_id: int, name: str, current: Version):
        self.cf_id = cf_id
        self.name = name
        self.current = current
        self.dropped = False


class VersionSet:
    def __init__(self, env, dbname: str, icmp: InternalKeyComparator,
                 num_levels: int = 7):
        self.env = env
        self.dbname = dbname
        self.icmp = icmp
        self.num_levels = num_levels
        # Weak registry of every Version still referenced anywhere (readers
        # hold strong refs while in flight) — the GC analogue of the
        # reference's Version refcounts / SuperVersion (db/column_family.h:210):
        # obsolete-file deletion must respect files visible to ANY live
        # Version, not just `current`.
        self._all_versions: "weakref.WeakSet[Version]" = weakref.WeakSet()
        v0 = Version(icmp, num_levels)
        self._all_versions.add(v0)
        self.column_families: dict[int, ColumnFamilyState] = {
            0: ColumnFamilyState(0, "default", v0)
        }
        self.max_column_family = 0
        self.last_sequence = 0
        self.log_number = 0          # WALs with number < this are obsolete
        self.prev_log_number = 0
        self.manifest_file_number = 0
        self._next_file_number = 2
        self._manifest_writer: LogWriter | None = None
        self._lock = ccy.Lock("version_set.VersionSet._lock")
        # Monotonic count of MANIFEST records in the live manifest — the
        # replication plane's "epoch" minor component: a follower re-reads
        # the MANIFEST when (manifest_file_number, edit_seq) changes
        # (replication/log_shipper.py).
        self.edit_seq = 0

    # The default CF's Version — the single-CF view used everywhere the CF
    # doesn't matter.
    @property
    def current(self) -> Version:
        return self.column_families[0].current

    @current.setter
    def current(self, v: Version) -> None:
        self.column_families[0].current = v

    def cf_current(self, cf_id: int) -> Version:
        return self.column_families[cf_id].current

    # -- number allocation ---------------------------------------------

    def new_file_number(self) -> int:
        with self._lock:
            n = self._next_file_number
            self._next_file_number += 1
            return n

    def mark_file_number_used(self, n: int) -> None:
        with self._lock:
            if self._next_file_number <= n:
                self._next_file_number = n + 1

    @property
    def next_file_number(self) -> int:
        return self._next_file_number

    # -- manifest lifecycle --------------------------------------------

    def create_new(self) -> None:
        """Initialize a brand-new DB: write MANIFEST-1 snapshot + CURRENT."""
        self.manifest_file_number = self.new_file_number()
        edit = VersionEdit(
            comparator=self.icmp.user_comparator.name(),
            log_number=0,
            next_file_number=self._next_file_number,
            last_sequence=0,
            column_family_add="default",
            max_column_family=0,
        )
        path = filename.manifest_file_name(self.dbname, self.manifest_file_number)
        w = self.env.new_writable_file(path)
        self._manifest_writer = LogWriter(w)
        self._manifest_writer.add_record(edit.encode())
        self._manifest_writer.sync()
        self.edit_seq = 1  # record count IN the live manifest file
        filename.set_current_file(self.env, self.dbname, self.manifest_file_number)

    def recover(self, readonly: bool = False) -> None:
        """Replay CURRENT → MANIFEST into the in-memory state
        (reference VersionSet::Recover, version_set.cc:6196). With
        readonly=True the directory is not touched (no manifest roll), and
        log_and_apply is unavailable."""
        cur = self.env.read_file(filename.current_file_name(self.dbname))
        try:
            name = cur.decode().strip()
        except UnicodeDecodeError:
            raise Corruption("CURRENT file holds undecodable bytes") from None
        if not name.startswith("MANIFEST-"):
            raise Corruption(f"CURRENT points at {name!r}")
        try:
            self.manifest_file_number = int(name[len("MANIFEST-"):])
        except ValueError:
            raise Corruption(f"CURRENT points at {name!r}") from None
        path = filename.manifest_file_name(self.dbname, self.manifest_file_number)
        reader = LogReader(self.env.new_sequential_file(path))
        builders: dict[int, VersionBuilder] = {}
        cf_names: dict[int, str] = {}
        dropped: set[int] = set()
        have_comparator = None
        next_cf_hint = 0
        have_log_number = have_next_file = have_last_seq = False
        n_records = 0
        for rec in reader.records():
            n_records += 1
            edit = VersionEdit.decode(rec)
            cf = edit.column_family
            if edit.column_family_add is not None:
                cf_names[cf] = edit.column_family_add
                builders.setdefault(
                    cf, VersionBuilder(Version(self.icmp, self.num_levels))
                )
            if edit.column_family_drop:
                dropped.add(cf)
            if edit.max_column_family is not None:
                next_cf_hint = max(next_cf_hint, edit.max_column_family)
            if edit.comparator is not None:
                have_comparator = edit.comparator
            if edit.log_number is not None:
                self.log_number = edit.log_number
                have_log_number = True
            if edit.prev_log_number is not None:
                self.prev_log_number = edit.prev_log_number
            if edit.next_file_number is not None:
                self._next_file_number = edit.next_file_number
                have_next_file = True
            if edit.last_sequence is not None:
                self.last_sequence = edit.last_sequence
                have_last_seq = True
            if edit.new_files or edit.deleted_files:
                builders.setdefault(
                    cf, VersionBuilder(Version(self.icmp, self.num_levels))
                ).apply(edit)
        if have_comparator is not None and have_comparator != self.icmp.user_comparator.name():
            raise Corruption(
                f"comparator mismatch: DB created with {have_comparator}, "
                f"opened with {self.icmp.user_comparator.name()}"
            )
        # A readable manifest MUST yield the descriptor fields (reference
        # VersionSet::Recover's no-meta-*-entry checks, version_set.cc):
        # a corrupt head otherwise "recovers" an EMPTY DB — the log reader
        # treats undecodable bytes as a torn tail, which is only valid
        # AFTER a good snapshot record. (Found by tools/fuzz_native.py.)
        if not (have_next_file and have_last_seq and have_log_number):
            missing = [name for ok, name in (
                (have_next_file, "next-file"), (have_last_seq, "last-seq"),
                (have_log_number, "log-number")) if not ok]
            raise Corruption(
                f"manifest {path} yields no {'/'.join(missing)} entry "
                f"({n_records} records decoded): corrupt descriptor head"
            )
        builders.setdefault(0, VersionBuilder(Version(self.icmp, self.num_levels)))
        cf_names.setdefault(0, "default")
        self.column_families = {}
        for cf, b in builders.items():
            if cf in dropped:
                continue
            v = b.save()
            self._all_versions.add(v)
            self.column_families[cf] = ColumnFamilyState(
                cf, cf_names.get(cf, f"cf{cf}"), v
            )
        self.max_column_family = max(
            [next_cf_hint] + list(self.column_families)
        )
        self.edit_seq = n_records
        self.mark_file_number_used(self.manifest_file_number)
        if not readonly:
            # Reopen the manifest for appending new edits.
            self._reopen_manifest_for_append(path)

    def _reopen_manifest_for_append(self, path: str) -> None:
        # Env has no append mode; rewrite the manifest as a fresh snapshot in
        # a new file. This also bounds manifest growth on reopen (the
        # reference rolls the manifest similarly on recovery).
        self.manifest_file_number = self.new_file_number()
        newpath = filename.manifest_file_name(self.dbname, self.manifest_file_number)
        w = self.env.new_writable_file(newpath)
        self._manifest_writer = LogWriter(w)
        n = 0
        for snap in self._snapshot_edits():
            self._manifest_writer.add_record(snap.encode())
            n += 1
        self._manifest_writer.sync()
        # Epoch minor = records in the LIVE manifest: a readonly recover of
        # this same file counts the same number, so a directory-sharing
        # follower's local epoch matches the primary's until the next edit.
        self.edit_seq = n
        filename.set_current_file(self.env, self.dbname, self.manifest_file_number)

    def _snapshot_edits(self) -> list[VersionEdit]:
        edits = []
        for cf_id in sorted(self.column_families):
            st = self.column_families[cf_id]
            edit = VersionEdit(
                column_family=cf_id,
                column_family_add=st.name,
                max_column_family=self.max_column_family,
            )
            if cf_id == 0:
                edit.comparator = self.icmp.user_comparator.name()
                edit.log_number = self.log_number
                edit.prev_log_number = self.prev_log_number
                edit.next_file_number = self._next_file_number
                edit.last_sequence = self.last_sequence
            for level, f in st.current.all_files():
                edit.add_file(level, f)
            edits.append(edit)
        return edits

    def manifest_size(self) -> int:
        """Current byte size of the live MANIFEST (synced) — the truncation
        point for consistent file-copy backups (reference GetLiveFiles'
        manifest_file_size)."""
        with self._lock:
            if self._manifest_writer is None:
                # Readonly open (no writer): the on-disk size IS the
                # consistent size (nobody is appending).
                try:
                    return self.env.get_file_size(
                        filename.manifest_file_name(
                            self.dbname, self.manifest_file_number))
                except Exception as e:
                    _errors.swallow(reason="manifest-size-probe", exc=e)
                    return 0
            self._manifest_writer.sync()
            return self._manifest_writer._f.file_size()

    def log_and_apply(self, edit: VersionEdit, sync: bool = True) -> None:
        """Append edit to MANIFEST and install the resulting Version for the
        edit's column family (reference VersionSet::LogAndApply,
        version_set.cc:6033). Failures are tagged _bg_reason="manifest" so
        the DB's ErrorHandler latches them FATAL no matter which caller
        surfaced them (reference BackgroundErrorReason::kManifestWrite)."""
        try:
            self._log_and_apply_locked(edit, sync)
        except BaseException as e:
            try:
                e._bg_reason = "manifest"
            except AttributeError:
                pass  # exceptions with __slots__: classification falls back
            raise

    def _log_and_apply_locked(self, edit: VersionEdit,
                              sync: bool = True) -> None:
        with self._lock:
            cf = edit.column_family
            st = self.column_families.get(cf)
            if st is None:
                # CF dropped while the job was in flight: discard the edit
                # (the reference drops edits for dropped CFs the same way).
                return
            if edit.log_number is not None:
                assert edit.log_number >= self.log_number
                self.log_number = edit.log_number
            edit.next_file_number = self._next_file_number
            edit.last_sequence = self.last_sequence
            builder = VersionBuilder(st.current)
            builder.apply(edit)
            new_version = builder.save()
            assert self._manifest_writer is not None
            from toplingdb_tpu.utils.kill_point import test_kill_random

            test_kill_random("VersionSet::LogAndApply:BeforeManifestWrite")
            self._manifest_writer.add_record(edit.encode())
            if sync:
                self._manifest_writer.sync()
            test_kill_random("VersionSet::LogAndApply:AfterManifestWrite")
            self._all_versions.add(new_version)
            st.current = new_version
            self.edit_seq += 1

    def create_column_family(self, name: str) -> int:
        """Register a new CF in the MANIFEST; returns its id (reference
        VersionSet::CreateColumnFamily)."""
        with self._lock:
            for st in self.column_families.values():
                if st.name == name:
                    raise Corruption(f"column family {name!r} already exists")
            cf_id = self.max_column_family + 1
            self.max_column_family = cf_id
            edit = VersionEdit(
                column_family=cf_id, column_family_add=name,
                max_column_family=cf_id,
            )
            assert self._manifest_writer is not None
            self._manifest_writer.add_record(edit.encode())
            self._manifest_writer.sync()
            self.edit_seq += 1
            v = Version(self.icmp, self.num_levels)
            self._all_versions.add(v)
            self.column_families[cf_id] = ColumnFamilyState(cf_id, name, v)
            return cf_id

    def drop_column_family(self, cf_id: int) -> None:
        with self._lock:
            if cf_id == 0:
                raise Corruption("cannot drop the default column family")
            if cf_id not in self.column_families:
                from toplingdb_tpu.utils.status import InvalidArgument

                raise InvalidArgument(
                    f"column family {cf_id} does not exist (double drop?)"
                )
            st = self.column_families.pop(cf_id)
            st.dropped = True
            edit = VersionEdit(column_family=cf_id, column_family_drop=True)
            assert self._manifest_writer is not None
            self._manifest_writer.add_record(edit.encode())
            self._manifest_writer.sync()
            self.edit_seq += 1

    def close(self) -> None:
        if self._manifest_writer is not None:
            self._manifest_writer.close()
            self._manifest_writer = None

    # -- introspection --------------------------------------------------

    def live_file_sets(self) -> tuple[set[int], set[int]]:
        """(sst_numbers, blob_numbers) referenced by any CF's current version
        OR any version still held by an in-flight reader/iterator — the
        deletion guards for obsolete-file GC, filled in one pass."""
        ssts: set[int] = set()
        blobs: set[int] = set()
        versions = list(self._all_versions) + [
            st.current for st in self.column_families.values()
        ]
        for v in versions:
            for _, f in v.all_files():
                ssts.add(f.number)
                blobs.update(f.blob_refs)
        return ssts, blobs

    def live_files(self) -> set[int]:
        return self.live_file_sets()[0]
