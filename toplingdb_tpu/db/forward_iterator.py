"""ForwardIterator: the tailing iterator.

Analogue of the reference's ForwardIterator (db/forward_iterator.cc,
enabled via ReadOptions.tailing in /root/reference): a forward-only
iterator over a live DB that picks up NEW writes without being recreated.
The reference rebuilds its child iterators whenever the SuperVersion
changes and keeps its position; here the same contract is met by wrapping
DBIter: the fast path is a plain next() on the current view, and when the
view is exhausted (or a seek lands at its end) the iterator rebinds to the
DB's current state and resumes strictly after the last returned key — so a
tail loop `while True: it.next() or retry` observes every write exactly
once, in order.

Forward-only: prev()/seek_to_last() raise NotSupported, as in the
reference (forward_iterator.h notes SeekToLast/Prev are unsupported).
"""

from __future__ import annotations

from toplingdb_tpu.utils.status import NotSupported


class ForwardIterator:
    def __init__(self, db, opts, cf=None):
        # Tailing must read the LIVE tail: a pinned snapshot contradicts it
        # (reference: tailing + snapshot is rejected).
        if opts.snapshot is not None:
            raise NotSupported("tailing iterators cannot use a snapshot")
        self._db = db
        self._opts = opts
        self._cf = cf
        self._inner = db.new_iterator(opts, cf=cf)
        # Where to resume when catching up after end-of-data:
        # None + not positioned → never positioned (next() is an error);
        # None + positioned     → from the first key;
        # (key, False)          → strictly after `key` (it was returned);
        # (key, True)           → at or after `key` (a seek target that
        #                         landed at end-of-data — not yet returned).
        self._resume: tuple[bytes, bool] | None = None
        self._positioned = False

    # -- positioning ----------------------------------------------------

    def seek_to_first(self) -> None:
        self._positioned = True
        self._resume = None
        self._rebind()
        self._inner.seek_to_first()
        self._sync_last()

    def seek(self, user_key: bytes) -> None:
        self._positioned = True
        # If the seek lands at end-of-data, later catch-ups must resume AT
        # the target — never before it.
        self._resume = (user_key, True)
        self._rebind()
        self._inner.seek(user_key)
        self._sync_last()

    def next(self) -> None:
        assert self._positioned, "ForwardIterator.next() before seek"
        if self._inner.valid():
            self._inner.next()
        else:
            # Previously exhausted: catching up IS the advance.
            self._catch_up()
            return
        if not self._inner.valid():
            self._catch_up()
            return
        self._sync_last()

    def seek_to_last(self) -> None:
        raise NotSupported("ForwardIterator is forward-only")

    def prev(self) -> None:
        raise NotSupported("ForwardIterator is forward-only")

    # -- accessors ------------------------------------------------------

    def valid(self) -> bool:
        return self._inner.valid()

    def key(self) -> bytes:
        return self._inner.key()

    def value(self) -> bytes:
        return self._inner.value()

    def entries(self):
        while self.valid():
            yield self.key(), self.value()
            self.next()

    def close(self) -> None:
        self._inner = None

    # -- internals ------------------------------------------------------

    def _sync_last(self) -> None:
        if self._inner.valid():
            self._resume = (self._inner.key(), False)

    def _rebind(self) -> None:
        """Re-create the inner view over the DB's CURRENT sources + latest
        sequence (the reference's SVCleanup/RebuildIterators)."""
        self._inner = self._db.new_iterator(self._opts, cf=self._cf)

    def _catch_up(self) -> None:
        """At end-of-view: rebind and resume from self._resume. Invalid
        afterwards means 'no new data yet' — the caller may call next()
        again later (the tail loop)."""
        self._rebind()
        if self._resume is None:
            self._inner.seek_to_first()
        else:
            key, inclusive = self._resume
            self._inner.seek(key)
            if (not inclusive and self._inner.valid()
                    and self._db.icmp.user_comparator.compare(
                        self._inner.key(), key) == 0):
                self._inner.next()
        self._sync_last()
