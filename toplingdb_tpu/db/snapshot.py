"""Seqno-based MVCC snapshots (reference db/snapshot_impl.h in
/root/reference)."""

from __future__ import annotations

import threading

from toplingdb_tpu.utils import concurrency as ccy


class Snapshot:
    __slots__ = ("sequence", "excluded_ranges", "_list")

    def __init__(self, sequence: int, slist: "SnapshotList",
                 excluded_ranges: tuple = ()):
        self.sequence = sequence
        # Seqno ranges INVISIBLE to this snapshot despite being <= sequence:
        # data written to the DB by prepared-but-undecided transactions at
        # snapshot-creation time (the WritePrepared policy; the reference's
        # SnapshotChecker / old_commit_map role). Any such transaction that
        # later commits gets a commit point after this snapshot, so the
        # exclusion is permanent for this snapshot's lifetime.
        self.excluded_ranges = excluded_ranges
        self._list = slist

    def release(self) -> None:
        self._list.release(self)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.release()


class SnapshotList:
    def __init__(self):
        self._lock = ccy.Lock("snapshot.SnapshotList._lock")
        self._snapshots: list[Snapshot] = []

    def new_snapshot(self, sequence: int,
                     excluded_ranges: tuple = ()) -> Snapshot:
        s = Snapshot(sequence, self, excluded_ranges)
        with self._lock:
            self._snapshots.append(s)
        return s

    def release(self, s: Snapshot) -> None:
        with self._lock:
            try:
                self._snapshots.remove(s)
            except ValueError:
                pass

    def empty(self) -> bool:
        with self._lock:
            return not self._snapshots

    def num_live(self) -> int:
        """Count of live snapshot OBJECTS (distinct seqnos may collapse in
        sequences(); the reference's num-snapshots counts objects)."""
        with self._lock:
            return len(self._snapshots)

    def sequences(self) -> list[int]:
        """Sorted live snapshot seqnos — the visibility stripes compaction
        must preserve (reference CompactionIterator's snapshot list)."""
        with self._lock:
            return sorted({s.sequence for s in self._snapshots})

    def oldest(self) -> int | None:
        seqs = self.sequences()
        return seqs[0] if seqs else None

    def any_excluding(self, lo: int, hi: int) -> bool:
        """Is any live snapshot still excluding a seqno range overlapping
        [lo, hi]? (WritePrepared guard-snapshot lifetime: the compaction
        guard below an undecided range must outlive every snapshot that
        captured its exclusion.)"""
        with self._lock:
            for s in self._snapshots:
                for el, eh in s.excluded_ranges:
                    if el <= hi and lo <= eh:
                        return True
        return False
