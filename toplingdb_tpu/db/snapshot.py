"""Seqno-based MVCC snapshots (reference db/snapshot_impl.h in
/root/reference)."""

from __future__ import annotations

import threading


class Snapshot:
    __slots__ = ("sequence", "_list")

    def __init__(self, sequence: int, slist: "SnapshotList"):
        self.sequence = sequence
        self._list = slist

    def release(self) -> None:
        self._list.release(self)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.release()


class SnapshotList:
    def __init__(self):
        self._lock = threading.Lock()
        self._snapshots: list[Snapshot] = []

    def new_snapshot(self, sequence: int) -> Snapshot:
        s = Snapshot(sequence, self)
        with self._lock:
            self._snapshots.append(s)
        return s

    def release(self, s: Snapshot) -> None:
        with self._lock:
            try:
                self._snapshots.remove(s)
            except ValueError:
                pass

    def empty(self) -> bool:
        with self._lock:
            return not self._snapshots

    def num_live(self) -> int:
        """Count of live snapshot OBJECTS (distinct seqnos may collapse in
        sequences(); the reference's num-snapshots counts objects)."""
        with self._lock:
            return len(self._snapshots)

    def sequences(self) -> list[int]:
        """Sorted live snapshot seqnos — the visibility stripes compaction
        must preserve (reference CompactionIterator's snapshot list)."""
        with self._lock:
            return sorted({s.sequence for s in self._snapshots})

    def oldest(self) -> int | None:
        seqs = self.sequences()
        return seqs[0] if seqs else None
