"""Export / import a column family between DBs.

Analogues of the reference's Checkpoint::ExportColumnFamily
(utilities/checkpoint/checkpoint_impl.cc) and
DB::CreateColumnFamilyWithImport / ImportColumnFamilyJob
(db/import_column_family_job.cc in /root/reference): export hard-links one
CF's SSTs plus a metadata manifest into a directory; import creates a new CF
in another DB and installs those files at their original levels under fresh
file numbers.
"""

from __future__ import annotations

import dataclasses
import json
import os

from toplingdb_tpu.db import filename
from toplingdb_tpu.db.version_edit import FileMetaData, VersionEdit
from toplingdb_tpu.utils.status import Corruption, InvalidArgument, NotSupported
from toplingdb_tpu.utils import errors as _errors

METADATA_FILE = "export_metadata.json"


def _link_or_copy(env, src: str, dst: str) -> None:
    """Hard-link on the real FS; copy through the Env otherwise (MemEnv /
    fault-injection wrappers stay in the loop)."""
    from toplingdb_tpu.env.env import PosixEnv

    if type(env) is PosixEnv:
        try:
            os.link(src, dst)
            return
        except OSError:
            pass
    env.write_file(dst, env.read_file(src), sync=True)


@dataclasses.dataclass
class ExportedFile:
    """One SST in an export (reference LiveFileMetaData subset)."""

    name: str          # file name relative to the export dir
    level: int
    file_size: int
    smallest: bytes    # internal keys
    largest: bytes
    smallest_seqno: int
    largest_seqno: int
    num_entries: int
    num_deletions: int
    num_range_deletions: int
    # Whole-file checksum carried from the source DB's MANIFEST (hex in
    # JSON); import re-verifies the copy against it. Empty = unrecorded
    # (pre-upgrade export) — defaults keep old export dirs loadable.
    file_checksum: str = ""
    file_checksum_func_name: str = ""

    def to_json(self) -> dict:
        d = dataclasses.asdict(self)
        d["smallest"] = self.smallest.hex()
        d["largest"] = self.largest.hex()
        return d

    @staticmethod
    def from_json(d: dict) -> "ExportedFile":
        d = dict(d)
        d["smallest"] = bytes.fromhex(d["smallest"])
        d["largest"] = bytes.fromhex(d["largest"])
        return ExportedFile(**d)


@dataclasses.dataclass
class ExportImportFilesMetaData:
    """What ExportColumnFamily returns and CreateColumnFamilyWithImport
    consumes (reference include/rocksdb/metadata.h)."""

    db_comparator_name: str
    files: list[ExportedFile]

    def save(self, export_dir: str, env) -> None:
        env.write_file(
            os.path.join(export_dir, METADATA_FILE),
            json.dumps({
                "db_comparator_name": self.db_comparator_name,
                "files": [f.to_json() for f in self.files],
            }, indent=1).encode(),
            sync=True,
        )

    @staticmethod
    def load(export_dir: str, env) -> "ExportImportFilesMetaData":
        try:
            raw = env.read_file(os.path.join(export_dir, METADATA_FILE))
        except Exception as e:
            raise InvalidArgument(
                f"no {METADATA_FILE} in {export_dir}: not an exported CF?"
            ) from e
        d = json.loads(raw)
        return ExportImportFilesMetaData(
            db_comparator_name=d["db_comparator_name"],
            files=[ExportedFile.from_json(f) for f in d["files"]],
        )


def export_column_family(db, cf, export_dir: str) -> ExportImportFilesMetaData:
    """Hard-link (or copy) every SST of `cf` into `export_dir` and write the
    metadata manifest. The CF is flushed first so the export is complete."""
    env = db.env
    if env.file_exists(export_dir) and env.get_children(export_dir):
        raise InvalidArgument(f"export dir {export_dir} exists and is not empty")
    if not env.file_exists(export_dir):
        env.create_dir(export_dir)
    db.disable_file_deletions()
    try:
        # Only the file-list snapshot needs the mutex; the deletion pin
        # keeps every listed file alive while the (possibly slow) linking /
        # copying runs unlocked, so concurrent reads/writes aren't stalled.
        with db._mutex:
            db.flush()  # whole-DB flush: the exported CF is certainly complete
            cf_id = cf.id if cf is not None else 0
            st = db.versions.column_families[cf_id]
            snapshot = list(st.current.all_files())
        files: list[ExportedFile] = []
        for lvl, f in snapshot:
            if f.blob_refs:
                raise NotSupported(
                    "cannot export a CF with blob references; disable "
                    "blob separation or compact the blobs away first"
                )
            src = filename.table_file_name(db.dbname, f.number)
            name = os.path.basename(src)
            _link_or_copy(env, src, os.path.join(export_dir, name))
            files.append(ExportedFile(
                name=name, level=lvl, file_size=f.file_size,
                smallest=f.smallest, largest=f.largest,
                smallest_seqno=f.smallest_seqno,
                largest_seqno=f.largest_seqno,
                num_entries=f.num_entries,
                num_deletions=f.num_deletions,
                num_range_deletions=f.num_range_deletions,
                file_checksum=f.file_checksum.hex(),
                file_checksum_func_name=f.file_checksum_func_name,
            ))
        meta = ExportImportFilesMetaData(
            db_comparator_name=db.icmp.user_comparator.name(),
            files=files,
        )
        meta.save(export_dir, env)
        return meta
    finally:
        db.enable_file_deletions()


def import_column_family(db, name: str, source_dir: str,
                         metadata: ExportImportFilesMetaData | None = None,
                         move_files: bool = False):
    """Create CF `name` in `db` populated with the exported files
    (reference DB::CreateColumnFamilyWithImport + ImportColumnFamilyJob).
    Files land at their ORIGINAL levels under fresh file numbers; the DB's
    last_sequence advances past the imported files' seqnos so every imported
    entry is visible. Returns the new ColumnFamilyHandle."""
    env = db.env
    if metadata is None:
        metadata = ExportImportFilesMetaData.load(source_dir, env)
    if metadata.db_comparator_name != db.icmp.user_comparator.name():
        raise InvalidArgument(
            f"comparator mismatch: exported with "
            f"{metadata.db_comparator_name!r}, DB uses "
            f"{db.icmp.user_comparator.name()!r}"
        )
    # Copy + verify every file OUTSIDE the DB mutex (a multi-GB import must
    # not stall concurrent reads/writes); only the CF creation and the
    # version install need the lock. Fresh file numbers are race-free
    # (VersionSet allocates under its own lock) and nothing references the
    # copies until log_and_apply.
    edit_files: list[tuple[int, FileMetaData]] = []
    max_seqno = 0
    copied: list[str] = []
    try:
        for ef in metadata.files:
            src = os.path.join(source_dir, ef.name)
            if not env.file_exists(src):
                raise Corruption(f"exported file missing: {src}")
            num = db.versions.new_file_number()
            dst = filename.table_file_name(db.dbname, num)
            _link_or_copy(env, src, dst)
            copied.append(dst)
            # Verify the table opens and matches the manifest's claims
            # (reference import verifies via GetIngestedFileInfo).
            reader = db.table_cache.get_reader(num)
            if reader.properties.num_entries != ef.num_entries:
                raise Corruption(
                    f"{src}: entry count {reader.properties.num_entries} "
                    f"!= exported metadata {ef.num_entries}"
                )
            meta = FileMetaData(
                number=num, file_size=ef.file_size,
                smallest=ef.smallest, largest=ef.largest,
                smallest_seqno=ef.smallest_seqno,
                largest_seqno=ef.largest_seqno,
                num_entries=ef.num_entries,
                num_deletions=ef.num_deletions,
                num_range_deletions=ef.num_range_deletions,
                file_checksum=bytes.fromhex(ef.file_checksum),
                file_checksum_func_name=ef.file_checksum_func_name,
            )
            if meta.file_checksum:
                # The exported checksum rode from the source DB's
                # MANIFEST: the copy must still match it bit for bit.
                from toplingdb_tpu.utils.file_checksum import (
                    verify_recorded_checksum,
                )

                verify_recorded_checksum(env, dst, meta)
            else:
                # No recorded checksum to inherit: stamp a fresh one so
                # the importing DB's integrity plane covers the file.
                db._stamp_file_checksums([meta])
            edit_files.append((ef.level, meta))
            max_seqno = max(max_seqno, ef.largest_seqno)
    except Exception:
        for p in copied:
            try:
                env.delete_file(p)
            except Exception as e:
                _errors.swallow(reason="import-cleanup-delete", exc=e)
        raise
    with db._mutex:
        handle = db.create_column_family(name)
        try:
            edit = VersionEdit(column_family=handle.id)
            for lvl, meta in edit_files:
                edit.add_file(lvl, meta)
            # Imported seqnos must be visible in THIS DB.
            if max_seqno > db.versions.last_sequence:
                edit.last_sequence = max_seqno
                db.versions.last_sequence = max_seqno
            db.versions.log_and_apply(edit)
        except Exception:
            # Roll the half-created CF back (job-style cleanup).
            for p in copied:
                try:
                    env.delete_file(p)
                except Exception as e:
                    _errors.swallow(reason="import-rollback-delete", exc=e)
            db.drop_column_family(handle)
            raise
    if move_files:
        for ef in metadata.files:
            try:
                env.delete_file(os.path.join(source_dir, ef.name))
            except Exception as e:
                _errors.swallow(reason="import-move-source-delete", exc=e)
    return handle
