"""WAL / MANIFEST record framing: writer and reader.

Same framing as the reference's log format (db/log_format.h:20-43,
db/log_writer.cc, db/log_reader.cc in /root/reference): the file is a
sequence of 32KiB blocks; each record fragment is
    masked_crc32c(4B) | length(2B LE) | type(1B) | payload
with type FULL/FIRST/MIDDLE/LAST so records can span blocks; a block's unusable
tail (<7B) is zero-padded. The CRC covers type+payload. Both the WAL and the
MANIFEST use this framing.
"""

from __future__ import annotations

from toplingdb_tpu.utils import coding, crc32c
from toplingdb_tpu.utils.status import Corruption

BLOCK_SIZE = 32768
HEADER_SIZE = 7

FULL = 1
FIRST = 2
MIDDLE = 3
LAST = 4


class LogWriter:
    def __init__(self, wfile):
        self._f = wfile
        self._block_offset = wfile.file_size() % BLOCK_SIZE

    def add_record(self, data: bytes) -> None:
        left = len(data)
        pos = 0
        begin = True
        while True:
            leftover = BLOCK_SIZE - self._block_offset
            if leftover < HEADER_SIZE:
                if leftover > 0:
                    self._f.append(b"\x00" * leftover)
                self._block_offset = 0
                leftover = BLOCK_SIZE
            avail = leftover - HEADER_SIZE
            frag = min(left, avail)
            end = left == frag
            if begin and end:
                t = FULL
            elif begin:
                t = FIRST
            elif end:
                t = LAST
            else:
                t = MIDDLE
            self._emit(t, data[pos : pos + frag])
            pos += frag
            left -= frag
            begin = False
            if left == 0:
                break

    def _emit(self, t: int, frag: bytes) -> None:
        crc = crc32c.value(bytes([t]) + frag)
        hdr = (
            coding.encode_fixed32(crc32c.mask(crc))
            + coding.encode_fixed16(len(frag))
            + bytes([t])
        )
        self._f.append(hdr)
        self._f.append(frag)
        self._block_offset += HEADER_SIZE + len(frag)

    def flush(self) -> None:
        self._f.flush()

    def sync(self) -> None:
        self._f.sync()

    def close(self) -> None:
        self._f.close()


class LogReader:
    """Sequential record reader. By default tolerates a truncated tail (the
    normal crash case — reference log_reader's eof handling) but raises
    Corruption on checksum mismatches in the middle of the log."""

    def __init__(self, sfile, verify_checksums: bool = True):
        self._f = sfile
        self._verify = verify_checksums
        self._buf = b""
        self._buf_off = 0
        self._eof = False

    def _read_block(self) -> bool:
        data = self._f.read(BLOCK_SIZE)
        self._buf = data
        self._buf_off = 0
        if len(data) < BLOCK_SIZE:
            self._eof = True
        return len(data) > 0

    def _next_fragment(self):
        """Returns (type, payload) or None at end of log."""
        while True:
            if self._buf_off + HEADER_SIZE > len(self._buf):
                if self._eof:
                    return None
                if not self._read_block():
                    return None
                continue
            b = self._buf
            off = self._buf_off
            stored_crc = coding.decode_fixed32(b, off)
            length = coding.decode_fixed16(b, off + 4)
            t = b[off + 6]
            if t == 0 and length == 0:
                # Zero-padded block tail; skip to the next block.
                self._buf_off = len(self._buf)
                continue
            if off + HEADER_SIZE + length > len(b):
                if self._eof:
                    return None  # truncated tail fragment: drop it
                raise Corruption("log fragment overflows block")
            payload = b[off + HEADER_SIZE : off + HEADER_SIZE + length]
            self._buf_off = off + HEADER_SIZE + length
            if self._verify:
                actual = crc32c.value(bytes([t]) + payload)
                if crc32c.unmask(stored_crc) != actual:
                    if self._eof:
                        return None  # torn final write
                    raise Corruption("log record checksum mismatch")
            return t, payload

    def read_record(self) -> bytes | None:
        """Returns the next full record, or None at clean end-of-log."""
        partial = None
        while True:
            frag = self._next_fragment()
            if frag is None:
                # A dangling FIRST/MIDDLE chain at EOF is a torn write: drop.
                return None
            t, payload = frag
            if t == FULL:
                if partial is not None:
                    raise Corruption("FULL record inside fragmented record")
                return bytes(payload)
            if t == FIRST:
                if partial is not None:
                    raise Corruption("FIRST record inside fragmented record")
                partial = bytearray(payload)
            elif t == MIDDLE:
                if partial is None:
                    raise Corruption("MIDDLE record without FIRST")
                partial += payload
            elif t == LAST:
                if partial is None:
                    raise Corruption("LAST record without FIRST")
                partial += payload
                return bytes(partial)
            else:
                raise Corruption(f"unknown log record type {t}")

    def records(self):
        while True:
            r = self.read_record()
            if r is None:
                return
            yield r
