"""WAL / MANIFEST record framing: writer and reader.

Same framing as the reference's log format (db/log_format.h:20-43,
db/log_writer.cc, db/log_reader.cc in /root/reference): the file is a
sequence of 32KiB blocks; each record fragment is
    masked_crc32c(4B) | length(2B LE) | type(1B) | payload
with type FULL/FIRST/MIDDLE/LAST so records can span blocks; a block's unusable
tail (<7B) is zero-padded. The CRC covers type+payload. Both the WAL and the
MANIFEST use this framing.
"""

from __future__ import annotations

from toplingdb_tpu.utils import coding, crc32c
from toplingdb_tpu.utils.status import Corruption

BLOCK_SIZE = 32768
HEADER_SIZE = 7
# Recyclable records carry the owning log number after the type byte
# (reference kRecyclableFullType..kRecyclableLastType, db/log_format.h):
# a reused WAL file's stale tail from its previous life then reads as
# end-of-log instead of replaying into the wrong recovery.
RECYCLABLE_HEADER_SIZE = 11

FULL = 1
FIRST = 2
MIDDLE = 3
LAST = 4
RECYCLABLE_FULL = 5
RECYCLABLE_FIRST = 6
RECYCLABLE_MIDDLE = 7
RECYCLABLE_LAST = 8

_RECYCLE_OF = {FULL: RECYCLABLE_FULL, FIRST: RECYCLABLE_FIRST,
               MIDDLE: RECYCLABLE_MIDDLE, LAST: RECYCLABLE_LAST}


class LogWriter:
    def __init__(self, wfile, log_number: int = 0, recycled: bool = False):
        """`recycled`: emit recyclable record types stamped with
        `log_number` (required for files that may later be reused AND for
        writes into a reused file)."""
        self._f = wfile
        self._block_offset = wfile.file_size() % BLOCK_SIZE
        self._log_number = log_number
        self._recycled = recycled
        self._hdr = RECYCLABLE_HEADER_SIZE if recycled else HEADER_SIZE

    def add_record(self, data: bytes) -> None:
        left = len(data)
        pos = 0
        begin = True
        while True:
            leftover = BLOCK_SIZE - self._block_offset
            if leftover < self._hdr:
                if leftover > 0:
                    self._f.append(b"\x00" * leftover)
                self._block_offset = 0
                leftover = BLOCK_SIZE
            avail = leftover - self._hdr
            frag = min(left, avail)
            end = left == frag
            if begin and end:
                t = FULL
            elif begin:
                t = FIRST
            elif end:
                t = LAST
            else:
                t = MIDDLE
            self._emit(t, data[pos : pos + frag])
            pos += frag
            left -= frag
            begin = False
            if left == 0:
                break

    def framing_state(self) -> tuple[int, int]:
        """(block_offset, log_number_or_-1) for an external framer (the
        native group-commit plane): -1 selects the classic 7-byte record
        headers, >= 0 the recyclable format stamped with that number."""
        return self._block_offset, (self._log_number if self._recycled else -1)

    def append_preframed(self, data, new_block_offset: int) -> None:
        """Append bytes already framed in THIS writer's log format (the
        native plane produced them from framing_state()) and adopt the
        framer's new block offset. The caller guarantees byte-identity
        with add_record of the same logical record."""
        self._f.append(data)
        self._block_offset = new_block_offset

    def _emit(self, t: int, frag: bytes) -> None:
        if self._recycled:
            t = _RECYCLE_OF[t]
            ln = coding.encode_fixed32(self._log_number)
            crc = crc32c.value(bytes([t]) + ln + frag)
            hdr = (
                coding.encode_fixed32(crc32c.mask(crc))
                + coding.encode_fixed16(len(frag))
                + bytes([t]) + ln
            )
        else:
            crc = crc32c.value(bytes([t]) + frag)
            hdr = (
                coding.encode_fixed32(crc32c.mask(crc))
                + coding.encode_fixed16(len(frag))
                + bytes([t])
            )
        self._f.append(hdr)
        self._f.append(frag)
        self._block_offset += self._hdr + len(frag)

    def flush(self) -> None:
        self._f.flush()

    def sync(self) -> None:
        self._f.sync()

    def close(self) -> None:
        self._f.close()


class LogReader:
    """Sequential record reader. By default tolerates a truncated tail (the
    normal crash case — reference log_reader's eof handling) but raises
    Corruption on checksum mismatches in the middle of the log."""

    def __init__(self, sfile, verify_checksums: bool = True,
                 log_number: int | None = None):
        """`log_number`: expected owner of recyclable records; a mismatch
        (the reused file's previous life) reads as end-of-log."""
        self._f = sfile
        self._verify = verify_checksums
        self._log_number = log_number
        self._buf = b""
        self._buf_off = 0
        self._eof = False
        # Once a recyclable record is seen, mid-block garbage is the stale
        # tail of the file's previous life — end-of-log, not corruption.
        self._recycled_seen = False

    def _read_block(self) -> bool:
        data = self._f.read(BLOCK_SIZE)
        self._buf = data
        self._buf_off = 0
        if len(data) < BLOCK_SIZE:
            self._eof = True
        return len(data) > 0

    def _next_fragment(self):
        """Returns (type, payload) or None at end of log."""
        while True:
            if self._buf_off + HEADER_SIZE > len(self._buf):
                if self._eof:
                    return None
                if not self._read_block():
                    return None
                continue
            b = self._buf
            off = self._buf_off
            stored_crc = coding.decode_fixed32(b, off)
            length = coding.decode_fixed16(b, off + 4)
            t = b[off + 6]
            if t == 0 and length == 0:
                # Zero-padded block tail; skip to the next block.
                self._buf_off = len(self._buf)
                continue
            recyclable = RECYCLABLE_FULL <= t <= RECYCLABLE_LAST
            tolerate = self._eof or self._recycled_seen
            if t > RECYCLABLE_LAST:
                if tolerate:
                    return None  # stale previous-life bytes: end of log
                raise Corruption(f"unknown log record type {t}")
            hdr = RECYCLABLE_HEADER_SIZE if recyclable else HEADER_SIZE
            if off + hdr > len(b):
                if tolerate:
                    return None
                raise Corruption("log header overflows block")
            if off + hdr + length > len(b):
                if tolerate:
                    return None  # truncated tail / stale fragment: drop
                raise Corruption("log fragment overflows block")
            payload = b[off + hdr : off + hdr + length]
            self._buf_off = off + hdr + length
            if recyclable:
                rec_ln = coding.decode_fixed32(b, off + 7)
                if (self._log_number is not None
                        and rec_ln != self._log_number):
                    # Previous life of a recycled file: end of THIS log.
                    return None
                if self._verify:
                    actual = crc32c.value(
                        bytes([t]) + b[off + 7: off + 11] + payload)
                    if crc32c.unmask(stored_crc) != actual:
                        if tolerate:
                            return None  # torn write / stale tail
                        raise Corruption("log record checksum mismatch")
                self._recycled_seen = True
                t -= RECYCLABLE_FULL - FULL  # normalize for read_record
                return t, payload
            if self._recycled_seen:
                # A classic-format header after recyclable records can only
                # be previous-life residue: end of this log.
                return None
            if self._verify:
                actual = crc32c.value(bytes([t]) + payload)
                if crc32c.unmask(stored_crc) != actual:
                    if self._eof:
                        return None  # torn final write
                    raise Corruption("log record checksum mismatch")
            return t, payload

    def read_record(self) -> bytes | None:
        """Returns the next full record, or None at clean end-of-log."""
        partial = None
        while True:
            frag = self._next_fragment()
            if frag is None:
                # A dangling FIRST/MIDDLE chain at EOF is a torn write: drop.
                return None
            t, payload = frag
            if t == FULL:
                if partial is not None:
                    raise Corruption("FULL record inside fragmented record")
                return bytes(payload)
            if t == FIRST:
                if partial is not None:
                    raise Corruption("FIRST record inside fragmented record")
                partial = bytearray(payload)
            elif t == MIDDLE:
                if partial is None:
                    raise Corruption("MIDDLE record without FIRST")
                partial += payload
            elif t == LAST:
                if partial is None:
                    raise Corruption("LAST record without FIRST")
                partial += payload
                return bytes(partial)
            else:
                raise Corruption(f"unknown log record type {t}")

    def records(self):
        while True:
            r = self.read_record()
            if r is None:
                return
            yield r


class TailingLogReader:
    """Tail a LIVE log file: poll() returns the complete records appended
    since the previous poll. The crucial property for WAL shipping
    (replication/log_shipper.py) is that a torn/partial trailing record —
    the writer is mid-append, or a crash cut the tail — is RETRIED on the
    next poll instead of being dropped or mis-read, while a bad checksum
    strictly before the durable tail still raises Corruption (real damage
    must not ship to followers).

    The tail-vs-middle rule: an anomalous fragment whose claimed extent
    reaches the file's current end may still be in flight (appends are not
    atomic), so the reader parks at it; an anomaly with durable bytes
    after it can never be completed by the writer and is corruption.
    """

    def __init__(self, env, path: str, verify_checksums: bool = True,
                 log_number: int | None = None):
        self._env = env
        self._path = path
        self._verify = verify_checksums
        self._log_number = log_number
        self._pos = 0           # absolute offset of the first unparsed byte
        self._partial = None    # FIRST..MIDDLE assembly across polls
        self._recycled_seen = False
        self._ended = False     # recycled previous-life boundary reached

    def tell(self) -> int:
        return self._pos

    def _finish(self):
        self._partial = None  # dangling FIRST/MIDDLE chain: torn write

    def poll(self, final: bool = False) -> list[bytes]:
        """New complete records since the last poll. `final=True` declares
        the log closed (a newer WAL exists / the file was archived): any
        parked torn tail is dropped instead of awaited."""
        if self._ended:
            return []
        size = self._env.get_file_size(self._path)
        if size <= self._pos:
            if final:
                self._finish()
            return []
        f = self._env.new_random_access_file(self._path)
        try:
            data = f.read(self._pos, size - self._pos)
        finally:
            f.close()
        base = self._pos
        n = len(data)
        out: list[bytes] = []
        i = 0
        while i < n:
            abs_off = base + i
            rem_block = BLOCK_SIZE - (abs_off % BLOCK_SIZE)
            if rem_block < HEADER_SIZE:
                # Block-tail padding zone; the writer zero-fills it before
                # starting the next record. Mid-fill: wait for the rest.
                if n - i < rem_block:
                    break
                i += rem_block
                continue
            if n - i < HEADER_SIZE:
                break  # torn header: wait
            stored_crc = coding.decode_fixed32(data, i)
            length = coding.decode_fixed16(data, i + 4)
            t = data[i + 6]
            if t == 0 and length == 0:
                # Zero padding to the end of the block.
                if n - i < rem_block:
                    break  # padding still being written
                i += rem_block
                continue
            recyclable = RECYCLABLE_FULL <= t <= RECYCLABLE_LAST
            hdr = RECYCLABLE_HEADER_SIZE if recyclable else HEADER_SIZE
            claimed_end = abs_off + hdr + length
            at_tail = claimed_end >= size
            if t > RECYCLABLE_LAST:
                if at_tail:
                    break  # garbage that may still be overwritten: wait
                raise Corruption(f"unknown log record type {t}")
            if hdr + length > rem_block:
                # Fragments never span blocks; a length pointing past the
                # block can only complete if it is tail garbage in flight.
                if at_tail:
                    break
                raise Corruption("log fragment overflows block")
            if n - i < hdr + length:
                break  # torn fragment: wait
            payload = data[i + hdr : i + hdr + length]
            if recyclable and self._log_number is not None and \
                    coding.decode_fixed32(data, i + 7) != self._log_number:
                # Previous life of a recycled file. Live tailing: the
                # writer may overwrite these bytes next — wait. Final: the
                # log really ends here.
                if final:
                    self._ended = True
                break
            if self._verify:
                blob = bytes([t]) + (
                    bytes(data[i + 7 : i + 11]) if recyclable else b""
                ) + bytes(payload)
                if crc32c.unmask(stored_crc) != crc32c.value(blob):
                    if at_tail:
                        break  # torn append in flight (or final: dropped)
                    raise Corruption("log record checksum mismatch")
            if recyclable:
                self._recycled_seen = True
                t -= RECYCLABLE_FULL - FULL
            elif self._recycled_seen:
                # Classic-format header after recyclable records: residue
                # of the file's previous life — end of this log.
                self._ended = True
                break
            i += hdr + length
            if t == FULL:
                if self._partial is not None:
                    raise Corruption("FULL record inside fragmented record")
                out.append(bytes(payload))
            elif t == FIRST:
                if self._partial is not None:
                    raise Corruption("FIRST record inside fragmented record")
                self._partial = bytearray(payload)
            elif t == MIDDLE:
                if self._partial is None:
                    raise Corruption("MIDDLE record without FIRST")
                self._partial += payload
            else:  # LAST
                if self._partial is None:
                    raise Corruption("LAST record without FIRST")
                self._partial += payload
                out.append(bytes(self._partial))
                self._partial = None
        self._pos = base + i
        if final:
            self._finish()
        return out
