"""LevelIterator: concatenation over one sorted level's files
(reference's LevelIterator inside db/version_set.cc)."""

from __future__ import annotations

from toplingdb_tpu.db.version_edit import FileMetaData


class LevelIterator:
    def __init__(self, table_cache, files: list[FileMetaData], icmp,
                 readahead_size: int = 0, aio_ring=None):
        self._tc = table_cache
        self._files = files
        self._icmp = icmp
        self._file_idx = -1
        self._iter = None
        # ReadOptions.readahead_size: fixed per-file-iterator prefetch
        # window (0 = the buffer's auto-scaling default). `aio_ring`
        # moves each file iterator's readahead windows onto a reader
        # ring thread (async read plane, env/async_reads.py).
        self._ra = readahead_size
        self._aio = aio_ring
        self._pf_hits = 0    # readahead counts of already-closed file iters
        self._pf_misses = 0

    def _open(self, idx: int) -> None:
        self._bank_prefetch()
        self._file_idx = idx
        if 0 <= idx < len(self._files):
            reader = self._tc.get_reader(self._files[idx].number)
            if (self._ra or self._aio is not None) \
                    and hasattr(reader, "new_index_iterator"):
                self._iter = reader.new_iterator(readahead_size=self._ra,
                                                 aio_ring=self._aio)
            else:
                self._iter = reader.new_iterator()
        else:
            self._iter = None

    def _bank_prefetch(self) -> None:
        pc = getattr(self._iter, "prefetch_counts", None)
        if pc is not None:
            h, m = pc()
            self._pf_hits += h
            self._pf_misses += m

    def prefetch_counts(self) -> tuple[int, int]:
        """(hits, misses) of every file iterator's FilePrefetchBuffer so
        far — the compaction input scan exports these as tickers."""
        h, m = self._pf_hits, self._pf_misses
        pc = getattr(self._iter, "prefetch_counts", None)
        if pc is not None:
            ch, cm = pc()
            h += ch
            m += cm
        return h, m

    def valid(self) -> bool:
        return self._iter is not None and self._iter.valid()

    def key(self):
        return self._iter.key()

    def value(self):
        return self._iter.value()

    def seek_to_first(self) -> None:
        self._open(0)
        if self._iter is not None:
            self._iter.seek_to_first()
            self._skip_forward()

    def seek_to_last(self) -> None:
        self._open(len(self._files) - 1)
        if self._iter is not None:
            self._iter.seek_to_last()
            self._skip_backward()

    def seek(self, target) -> None:
        # Binary search for first file whose largest >= target.
        lo, hi = 0, len(self._files) - 1
        pick = len(self._files)
        while lo <= hi:
            mid = (lo + hi) // 2
            if self._icmp.compare(self._files[mid].largest, target) >= 0:
                pick = mid
                hi = mid - 1
            else:
                lo = mid + 1
        self._open(pick)
        if self._iter is not None:
            self._iter.seek(target)
            self._skip_forward()

    def seek_for_prev(self, target) -> None:
        self.seek(target)
        if not self.valid():
            self.seek_to_last()
            return
        if self._icmp.compare(self.key(), target) > 0:
            self.prev()

    def next(self) -> None:
        assert self.valid()
        self._iter.next()
        self._skip_forward()

    def prev(self) -> None:
        assert self.valid()
        self._iter.prev()
        self._skip_backward()

    def _skip_forward(self) -> None:
        while self._iter is not None and not self._iter.valid():
            if self._file_idx + 1 >= len(self._files):
                self._iter = None
                return
            self._open(self._file_idx + 1)
            self._iter.seek_to_first()

    def _skip_backward(self) -> None:
        while self._iter is not None and not self._iter.valid():
            if self._file_idx - 1 < 0:
                self._iter = None
                return
            self._open(self._file_idx - 1)
            self._iter.seek_to_last()
