"""DBIter: the user-facing MVCC iterator.

Same role as the reference's DBIter (db/db_iter.cc in /root/reference): wraps
a MergingIterator over {memtable, immutables, SST levels} and collapses the
internal-key stream into the user view at a snapshot — newest visible version
per user key; tombstones (point + range) hide keys; merge chains are folded.
"""

from __future__ import annotations

from toplingdb_tpu.db import dbformat
from toplingdb_tpu.db.dbformat import ValueType
from toplingdb_tpu.utils.status import Corruption, MergeInProgress


class DBIter:
    def __init__(self, internal_iter, icmp, snapshot_seq: int,
                 range_del_agg=None, merge_operator=None,
                 lower_bound: bytes | None = None,
                 upper_bound: bytes | None = None,
                 pinned=None, blob_resolver=None,
                 prefix_extractor=None, prefix_same_as_start: bool = False,
                 excluded_ranges: tuple = (),
                 read_ts: int | None = None,
                 legacy_wce: bool = False):
        self._blob_resolver = blob_resolver
        # `pinned` keeps the source Version (and anything else) alive for the
        # iterator's lifetime so obsolete-file GC cannot delete SSTs that
        # LevelIterator children will open lazily.
        self._pinned = pinned
        self._iter = internal_iter
        self._icmp = icmp
        self._ucmp = icmp.user_comparator
        self._seq = snapshot_seq
        self._rd = range_del_agg
        self._merge_op = merge_operator
        self._lower = lower_bound
        self._upper = upper_bound
        self._valid = False
        self._key: bytes | None = None
        self._value: bytes | None = None
        self._refresh_fn = None  # set by DB.new_iterator
        # Prefix-mode iteration (reference ReadOptions.prefix_same_as_start):
        # after Seek, the iterator dies at the end of the seek target's
        # prefix group. Armed per-Seek; total-order entry points clear it.
        self._pe = prefix_extractor if prefix_same_as_start else None
        self._prefix: bytes | None = None
        # Undecided WritePrepared transaction data (see db/snapshot.py).
        self._excluded_ranges = excluded_ranges
        # User-defined timestamps (reference ReadOptions.timestamp / the
        # TOPLINGDB_WITH_TIMESTAMP feature): with a ts-carrying comparator,
        # the iterator dedups by the STRIPPED key, hides versions newer than
        # read_ts, and key() returns the stripped key (timestamp() has the
        # version's ts). Requires the bytewise+u64ts comparator, so stripped
        # keys compare as raw bytes.
        self._ts_sz = getattr(self._ucmp, "timestamp_size", 0)
        self._read_ts_b = (
            dbformat.encode_ts(read_ts)
            if (self._ts_sz and read_ts is not None) else None
        )
        self._key_full: bytes | None = None
        self._entry_type: int | None = None  # ValueType of current entry
        self._legacy_wce = legacy_wce  # magic-sniff gate (pre-type DBs)
        # Chunked scan plane (ops/scan_plane.py): when attached, forward
        # ops serve from its chunk cursor; backward ops and mid-stream
        # ineligible shapes degrade to the per-entry path below.
        self._plane = None
        self._pf_banked = (0, 0)
        # Access-pattern tracking for the plane: chunked decode wins for
        # scans but re-decodes blocks per Seek; a seek-dominated pattern
        # (many seeks, few next() steps between them) runs faster on the
        # per-entry path through the warm block cache, so the plane is
        # dropped once that pattern is established.
        self._plane_seeks = 0
        self._plane_steps = 0

    def attach_scan_plane(self, plane) -> None:
        self._plane = plane

    def _plane_sync(self) -> None:
        p = self._plane
        if p.is_valid:
            self._valid = True
            self._key = p.cur_key
            self._key_full = p.cur_key
            self._value = p.cur_value
            self._entry_type = p.cur_type
        else:
            self._valid = False

    def _plane_drop(self) -> None:
        """Deactivate the plane (direction switch / ineligible shape)."""
        self._plane = None
        if self.stats is not None:
            from toplingdb_tpu.utils import statistics as st

            self._tick(st.ITER_CHUNK_FALLBACKS)

    def _plane_position(self, user_key: bytes | None) -> bool:
        """Position the plane (None = start of keyspace/lower bound);
        False = the plane bailed and the caller must run the per-entry
        path for this operation."""
        from toplingdb_tpu.ops.scan_plane import PlaneIneligible

        try:
            if user_key is None:
                self._plane.seek_first()
            else:
                self._plane.seek(user_key)
        except PlaneIneligible:
            self._plane_drop()
            return False
        self._plane_sync()
        return True

    def _resume_per_entry_after(self, cur: bytes) -> None:
        """Position the per-entry path just past `cur` (the plane's last
        emitted key) after a mid-stream degrade."""
        self._seek_impl(cur, arm_prefix=False)
        if self._valid and self._vcmp(self._key, cur) <= 0:
            self._find_next_user_entry(skip_key=cur)

    def _bank_prefetch(self) -> None:
        """Flush the internal iterator's FilePrefetchBuffer deltas into
        the PREFETCH_* tickers (the chunked plane banks its own)."""
        if self.stats is None:
            return
        pc = getattr(self._iter, "prefetch_counts", None)
        if pc is None:
            return
        h, m = pc()
        dh, dm = h - self._pf_banked[0], m - self._pf_banked[1]
        if dh or dm:
            from toplingdb_tpu.utils import statistics as st

            if dh:
                self._tick(st.PREFETCH_HITS, dh)
            if dm:
                self._tick(st.PREFETCH_MISSES, dm)
            self._pf_banked = (h, m)

    def refresh(self) -> None:
        """Rebind to the DB's CURRENT state (reference Iterator::Refresh):
        new memtable/SST sources and the latest sequence. The position is
        invalidated — seek again, as in the reference."""
        if self._refresh_fn is None:
            from toplingdb_tpu.utils.status import NotSupported

            raise NotSupported("iterator was not created by DB.new_iterator")
        fresh = self._refresh_fn()
        # A trace-wrapping proxy may come back; rebind to the REAL DBIter
        # underneath (copying the proxy's __dict__ would silently keep the
        # old sources).
        fresh = getattr(fresh, "_it", fresh)
        fn = self._refresh_fn
        self.__dict__.update(fresh.__dict__)
        self._refresh_fn = fn

    # -- public protocol ------------------------------------------------

    def valid(self) -> bool:
        return self._valid

    def key(self) -> bytes:
        assert self._valid
        return self._key

    def raw_value(self) -> bytes:
        """The stored value WITHOUT wide-column default-column unwrapping
        (internal consumers — get_entity's ts path — need the encoding)."""
        assert self._valid
        return self._value

    def value(self) -> bytes:
        assert self._valid
        v = self._value
        if self._entry_is_entity():
            # Wide-column entity: present the anonymous default column
            # (reference iterator-over-entity semantics); columns() gives
            # the full set.
            from toplingdb_tpu.db.wide_columns import default_column_of

            return default_column_of(v)
        return v

    def _entry_is_entity(self) -> bool:
        """Typed detection (kTypeWideColumnEntity role); the magic sniff
        survives only behind the legacy gate for pre-type databases."""
        if self._entry_type == ValueType.WIDE_COLUMN_ENTITY:
            return True
        return self._legacy_wce and self._value[:1] == b"\x00"

    def columns(self) -> dict[bytes, bytes]:
        """All columns of the current entry (reference
        Iterator::columns(): a plain value presents as the anonymous
        default column)."""
        assert self._valid
        if self._entry_is_entity():
            from toplingdb_tpu.db.wide_columns import decode_entity

            return decode_entity(self._value)
        from toplingdb_tpu.db.wide_columns import DEFAULT_COLUMN

        return {DEFAULT_COLUMN: self._value}

    def timestamp(self) -> int | None:
        """User timestamp of the current entry (ts-comparator DBs only)."""
        assert self._valid
        if not self._ts_sz:
            return None
        return dbformat.decode_ts(self._key_full[-self._ts_sz:])

    def _vkey(self, uk: bytes) -> bytes:
        """The user-VISIBLE key: escape + ts suffix stripped in ts mode."""
        return dbformat.strip_ts(uk) if self._ts_sz else uk

    def _vcmp(self, a: bytes, b: bytes) -> int:
        """Compare two visible keys (already stripped)."""
        if self._ts_sz:
            return (a > b) - (a < b)  # u64ts requires the bytewise base
        return self._ucmp.compare(a, b)

    def _ts_invisible(self, uk: bytes) -> bool:
        # Suffixes store ~ts (dbformat.encode_ts): smaller suffix = newer
        # timestamp, so a version is invisible (ts > read_ts) when its
        # suffix sorts BEFORE the read timestamp's.
        return (self._read_ts_b is not None
                and uk[-self._ts_sz:] < self._read_ts_b)

    def seek_to_first(self) -> None:
        # Total-order entry point: never arms prefix mode, even when a lower
        # bound redirects it through a seek.
        self._prefix = None
        if self._plane is not None and self._plane_position(self._lower):
            self._tick_seek()
            return
        self._bank_prefetch()
        if self._lower is not None:
            self._seek_impl(self._lower, arm_prefix=False)
            self._tick_seek()
            return
        self._iter.seek_to_first()
        self._find_next_user_entry(skip_key=None)
        self._tick_seek()

    def _tick_seek(self) -> None:
        if self.stats is not None:
            from toplingdb_tpu.utils import statistics as st

            self._tick_entry_read(st.NUMBER_DB_SEEK, st.NUMBER_DB_SEEK_FOUND)

    # Optional Statistics sink (set by DB.new_iterator); records the
    # NUMBER_DB_SEEK/NEXT/PREV + ITER_BYTES_READ family.
    stats = None

    def _tick(self, name: str, n: int = 1) -> None:
        if self.stats is not None:
            self.stats.record_tick(name, n)

    def _tick_entry_read(self, op_name: str, found_name: str | None) -> None:
        """One iterator step's tickers: the op count + (when positioned)
        bytes read and the optional found counter."""
        from toplingdb_tpu.utils import statistics as st

        self._tick(op_name)
        if self._valid:
            if found_name is not None:
                self._tick(found_name)
            self._tick(st.ITER_BYTES_READ,
                       len(self._key) + len(self._value))

    def seek(self, user_key: bytes) -> None:
        if self._plane is not None:
            self._plane_seeks += 1
            if self._plane_seeks >= 16 and \
                    self._plane_steps < 64 * self._plane_seeks:
                self._plane_drop()  # seek-dominated: per-entry path wins
            else:
                uk = user_key
                if self._lower is not None \
                        and self._vcmp(uk, self._lower) < 0:
                    uk = self._lower
                if self._plane_position(uk):
                    self._tick_seek()
                    return
        self._bank_prefetch()
        self._seek_impl(user_key, arm_prefix=True)
        self._tick_seek()

    def _seek_impl(self, user_key: bytes, arm_prefix: bool) -> None:
        if self._lower is not None and self._vcmp(user_key, self._lower) < 0:
            user_key = self._lower
        if arm_prefix:
            self._arm_prefix(user_key)
        if self._ts_sz:
            # Land on the newest VISIBLE version: (key, read_ts) sorts after
            # every newer-ts version (ts orders descending), skipping them
            # in the seek itself. No read_ts → newest of all (ts MAX sorts
            # first among the key's versions).
            user_key = dbformat.encode_ts_key(
                user_key,
                dbformat.decode_ts(self._read_ts_b)
                if self._read_ts_b is not None else dbformat.MAX_TIMESTAMP,
            )
        target = dbformat.make_internal_key(
            user_key, self._seq, dbformat.VALUE_TYPE_FOR_SEEK
        )
        self._iter.seek(target)
        self._find_next_user_entry(skip_key=None)

    def seek_to_last(self) -> None:
        if self._plane is not None:
            self._plane_drop()  # backward iteration: per-entry path only
        self._prefix = None
        if self._upper is not None:
            # Upper bound is exclusive: (upper, MAX_SEQ, FOR_SEEK) sorts before
            # every entry of user key `upper`, so seek_for_prev lands strictly
            # below the bound under any comparator.
            upper = self._upper
            if self._ts_sz:
                # ts MAX sorts first: the FIRST version of upper.
                upper = dbformat.encode_ts_key(upper, dbformat.MAX_TIMESTAMP)
            target = dbformat.make_internal_key(
                upper, dbformat.MAX_SEQUENCE_NUMBER,
                dbformat.VALUE_TYPE_FOR_SEEK,
            )
            self._iter.seek_for_prev(target)
            self._find_prev_user_entry()
            return
        self._iter.seek_to_last()
        self._find_prev_user_entry()

    def seek_for_prev(self, user_key: bytes) -> None:
        if self._plane is not None:
            self._plane_drop()  # backward iteration: per-entry path only
        self._arm_prefix(user_key)
        if self._ts_sz:
            # (key, ts=0) is the LAST version of key in ts-descending order.
            user_key = dbformat.encode_ts_key(user_key, 0)
        target = dbformat.make_internal_key(user_key, 0, 0)
        # All entries for user_key sort before target's successor; position at
        # the last entry <= (user_key, seq 0): that's the oldest entry of
        # user_key or an earlier key.
        self._iter.seek_for_prev(target)
        self._find_prev_user_entry()

    def next(self) -> None:
        assert self._valid
        if self._plane is not None:
            from toplingdb_tpu.ops.scan_plane import PlaneIneligible

            self._plane_steps += 1
            cur = self._key
            try:
                self._plane.advance()
            except PlaneIneligible:
                self._plane_drop()
                self._resume_per_entry_after(cur)
            else:
                self._plane_sync()
            if self.stats is not None:
                from toplingdb_tpu.utils import statistics as st

                self._tick_entry_read(st.NUMBER_DB_NEXT, None)
            return
        skip = self._key
        # _iter may sit anywhere within the current user key's versions.
        self._find_next_user_entry(skip_key=skip)
        if self.stats is not None:
            from toplingdb_tpu.utils import statistics as st

            self._tick_entry_read(st.NUMBER_DB_NEXT, None)

    def prev(self) -> None:
        assert self._valid
        if self._plane is not None:
            # Direction switch: degrade to the per-entry path, positioned
            # at the plane's current key (still visible — the snapshot is
            # fixed), then run the normal backward step below.
            cur0 = self._key
            self._plane_drop()
            self._seek_impl(cur0, arm_prefix=False)
        # Move internal iterator to strictly before the current user key.
        cur = self._key  # visible (stripped) key
        if not self._iter.valid():
            # Forward resolution (e.g. a merge chain) exhausted the internal
            # iterator; re-position at the last entry before cur's versions.
            first = (
                dbformat.encode_ts_key(cur, dbformat.MAX_TIMESTAMP)
                if self._ts_sz else cur
            )
            self._iter.seek_for_prev(dbformat.make_internal_key(
                first, dbformat.MAX_SEQUENCE_NUMBER,
                dbformat.VALUE_TYPE_FOR_SEEK
            ))
        else:
            while self._iter.valid() and self._vcmp(
                self._vkey(dbformat.extract_user_key(self._iter.key())), cur
            ) >= 0:
                self._iter.prev()
        self._find_prev_user_entry()
        if self.stats is not None:
            from toplingdb_tpu.utils import statistics as st

            self._tick_entry_read(st.NUMBER_DB_PREV, None)

    def entries(self):
        while self.valid():
            yield self.key(), self.value()
            self.next()

    # -- internals ------------------------------------------------------

    def _arm_prefix(self, seek_key: bytes) -> None:
        self._prefix = (
            self._pe.transform(seek_key)
            if self._pe is not None and self._pe.in_domain(seek_key)
            else None
        )

    def _out_of_prefix(self, uk: bytes) -> bool:
        return self._prefix is not None and (
            not self._pe.in_domain(uk)
            or self._pe.transform(uk) != self._prefix
        )

    def _out_of_upper(self, vk: bytes) -> bool:
        return self._upper is not None and self._vcmp(vk, self._upper) >= 0

    def _out_of_lower(self, vk: bytes) -> bool:
        return self._lower is not None and self._vcmp(vk, self._lower) < 0

    def _excluded(self, seq: int) -> bool:
        for lo, hi in self._excluded_ranges:
            if lo <= seq <= hi:
                return True
        return False

    def _tomb_covers(self, uk: bytes, seq: int) -> bool:
        return (
            self._rd is not None
            and self._rd.max_covering_seq(uk, self._seq) > seq
        )

    def _find_next_user_entry(self, skip_key: bytes | None) -> None:
        """Advance to the newest visible, live entry of the next user key
        (> skip_key if given)."""
        operands: list[bytes] = []
        merge_key: bytes | None = None
        while self._iter.valid():
            ikey = self._iter.key()
            uk, seq, t = dbformat.split_internal_key(ikey)
            vkey = self._vkey(uk)
            if self._out_of_upper(vkey) or self._out_of_prefix(vkey):
                break
            if skip_key is not None and self._vcmp(vkey, skip_key) <= 0:
                self._iter.next()
                continue
            if seq > self._seq or (
                self._excluded_ranges and self._excluded(seq)
            ):
                self._iter.next()
                continue
            if self._ts_sz and self._ts_invisible(uk):
                # Version newer than the read timestamp.
                self._iter.next()
                continue
            if merge_key is not None and self._vcmp(vkey, merge_key) != 0:
                # Merge chain ran to the end of this key with no base.
                self._emit_merge(merge_key, None, operands)
                return
            if self._tomb_covers(uk, seq) or t in (
                ValueType.DELETION, ValueType.SINGLE_DELETION
            ):
                if merge_key is not None:
                    self._emit_merge(merge_key, None, operands)
                    return
                skip_key = vkey  # key is dead; skip all its older versions
                self._iter.next()
                continue
            if t in (ValueType.VALUE, ValueType.BLOB_INDEX,
                     ValueType.WIDE_COLUMN_ENTITY):
                v = self._iter.value()
                if t == ValueType.BLOB_INDEX:
                    v = self._resolve_blob(v)
                    t = ValueType.VALUE
                if merge_key is not None:
                    self._emit_merge(merge_key, v, operands,
                                     base_is_entity=(
                                         t == ValueType.WIDE_COLUMN_ENTITY))
                    return
                self._valid = True
                self._key = vkey
                self._key_full = uk
                self._value = v
                self._entry_type = t
                return
            if t == ValueType.MERGE:
                if self._ts_sz:
                    raise MergeInProgress(
                        "Merge is not supported with user-defined timestamps"
                    )
                if self._merge_op is None:
                    raise MergeInProgress("merge entry but no merge_operator")
                if merge_key is None:
                    merge_key = vkey
                operands.append(self._iter.value())
                self._iter.next()
                continue
            raise Corruption(f"unexpected value type {t} in iterator")
        if merge_key is not None:
            self._emit_merge(merge_key, None, operands)
            return
        self._valid = False
        self._bank_prefetch()

    def _resolve_blob(self, idx: bytes) -> bytes:
        if self._blob_resolver is None:
            raise Corruption("blob index found but no blob resolver")
        return self._blob_resolver(idx)

    def _emit_merge(self, uk: bytes, base: bytes | None,
                    operands: list[bytes],
                    base_is_entity: bool = False) -> None:
        # operands collected newest→oldest. (ts mode never reaches here.)
        self._valid = True
        self._key = uk
        self._key_full = uk
        ops = list(reversed(operands))
        if base_is_entity:
            # Merge folds against the entity's default column; the entry
            # stays an entity (reference wide_columns_helper semantics).
            from toplingdb_tpu.db.wide_columns import merge_into_entity

            self._value = merge_into_entity(
                base, lambda b: self._merge_op.full_merge(uk, b, ops))
            self._entry_type = ValueType.WIDE_COLUMN_ENTITY
        else:
            self._value = self._merge_op.full_merge(uk, base, ops)
            self._entry_type = ValueType.VALUE

    def _find_prev_user_entry(self) -> None:
        """Position at the newest visible, live entry of the user key at or
        before the internal iterator's position, scanning backward."""
        while self._iter.valid():
            uk = dbformat.extract_user_key(self._iter.key())
            vkey = self._vkey(uk)
            if self._out_of_lower(vkey) or self._out_of_prefix(vkey):
                break
            if self._out_of_upper(vkey):
                self._iter.prev()
                continue
            if self._ts_sz:
                if self._resolve_backward_ts(vkey):
                    return
                continue  # key dead/invisible: keep scanning backward
            # Collect all entries of this user key (backward walk hits them
            # oldest-internal-position... i.e. lowest seq first).
            entries: list[tuple[int, int, bytes]] = []
            while self._iter.valid():
                k2 = self._iter.key()
                uk2, seq2, t2 = dbformat.split_internal_key(k2)
                if self._ucmp.compare(uk2, uk) != 0:
                    break
                if seq2 <= self._seq and not (
                    self._excluded_ranges and self._excluded(seq2)
                ):
                    entries.append((seq2, t2, self._iter.value()))
                self._iter.prev()
            # entries is ordered oldest→...→newest? Backward walk yields
            # ascending seq (internal order is seq desc, so walking backward
            # gives seq asc). Resolve from the newest (last element) downward.
            if self._resolve_backward(uk, entries):
                return
            # Key dead/invisible: continue scanning previous keys.
        self._valid = False
        self._bank_prefetch()

    def _resolve_backward_ts(self, vkey: bytes) -> bool:
        """ts-mode backward resolution: walk every (ts, seq) version of the
        stripped key, pick the newest visible one, surface it if live. The
        internal iterator ends strictly before vkey's entries."""
        best = None  # (ts_suffix, seq, type, value) — max by (ts, seq)
        while self._iter.valid():
            uk2, seq2, t2 = dbformat.split_internal_key(self._iter.key())
            if self._vkey(uk2) != vkey:
                break
            if (seq2 <= self._seq
                    and not (self._excluded_ranges and self._excluded(seq2))
                    and not self._ts_invisible(uk2)):
                if t2 == ValueType.MERGE:
                    raise MergeInProgress(
                        "Merge is not supported with user-defined timestamps"
                    )
                # Suffix stores ~ts: the NEWEST version has the SMALLEST
                # suffix; among equal ts the largest seq wins.
                cand = (uk2[-self._ts_sz:], seq2, t2, self._iter.value(), uk2)
                if best is None or (cand[0], -cand[1]) < (best[0], -best[1]):
                    best = cand
            self._iter.prev()
        if best is None:
            return False
        _tsb, seq_, t_, val, full = best
        if self._tomb_covers(full, seq_) or t_ in (
            ValueType.DELETION, ValueType.SINGLE_DELETION
        ):
            return False
        if t_ == ValueType.BLOB_INDEX:
            val = self._resolve_blob(val)
            t_ = ValueType.VALUE
        self._valid = True
        self._key = vkey
        self._key_full = full
        self._value = val
        self._entry_type = t_
        return True

    def _resolve_backward(self, uk: bytes, entries: list[tuple[int, int, bytes]]) -> bool:
        operands: list[bytes] = []
        for seq, t, val in reversed(entries):  # newest first
            if self._tomb_covers(uk, seq) or t in (
                ValueType.DELETION, ValueType.SINGLE_DELETION
            ):
                if operands:
                    self._emit_merge(uk, None, operands)
                    return True
                return False
            if t in (ValueType.VALUE, ValueType.BLOB_INDEX,
                     ValueType.WIDE_COLUMN_ENTITY):
                if t == ValueType.BLOB_INDEX:
                    val = self._resolve_blob(val)
                    t = ValueType.VALUE
                if operands:
                    self._emit_merge(uk, val, operands,
                                     base_is_entity=(
                                         t == ValueType.WIDE_COLUMN_ENTITY))
                else:
                    self._valid = True
                    self._key = uk
                    self._key_full = uk
                    self._value = val
                    self._entry_type = t
                return True
            if t == ValueType.MERGE:
                if self._merge_op is None:
                    raise MergeInProgress("merge entry but no merge_operator")
                operands.append(val)
                continue
        if operands:
            self._emit_merge(uk, None, operands)
            return True
        return False
