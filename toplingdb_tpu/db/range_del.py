"""Range-deletion tombstones: fragmenting and aggregation.

Roles match the reference's FragmentedRangeTombstoneIterator /
RangeDelAggregator (db/range_tombstone_fragmenter.h:135,
db/range_del_aggregator.h:284-407 in /root/reference). A tombstone is
(seq, begin_user_key inclusive, end_user_key exclusive). The aggregator
answers "is this (key, seqno) shadowed by a newer tombstone?" for reads and
compaction, and yields fragments for writing tombstones into output SSTs.
"""

from __future__ import annotations

from dataclasses import dataclass

from toplingdb_tpu.db import dbformat
from toplingdb_tpu.db.dbformat import ValueType


@dataclass(frozen=True)
class RangeTombstone:
    seq: int
    begin: bytes  # user key, inclusive
    end: bytes    # user key, exclusive

    def to_table_entry(self) -> tuple[bytes, bytes]:
        """(internal begin key, end user key) as stored in SST meta blocks."""
        return (
            dbformat.make_internal_key(self.begin, self.seq, ValueType.RANGE_DELETION),
            self.end,
        )

    @staticmethod
    def from_table_entry(begin_ikey: bytes, end_user_key: bytes) -> "RangeTombstone":
        uk, seq, t = dbformat.split_internal_key(begin_ikey)
        assert t == ValueType.RANGE_DELETION, t
        return RangeTombstone(seq, uk, end_user_key)


def fragment_tombstones(tombstones: list[RangeTombstone], ucmp) -> list[RangeTombstone]:
    """Split overlapping tombstones into non-overlapping fragments, keeping
    for each fragment every distinct seqno whose original tombstone covers it
    (reference range_tombstone_fragmenter.cc). Output sorted by (begin, -seq);
    only fragments are emitted (empty input → empty output)."""
    if not tombstones:
        return []
    # Collect all boundary points.
    points = sorted(
        {t.begin for t in tombstones} | {t.end for t in tombstones},
        key=lambda k: _CmpKey(ucmp, k),
    )
    out: list[RangeTombstone] = []
    for a, b in zip(points, points[1:]):
        seqs = sorted(
            {
                t.seq
                for t in tombstones
                if ucmp.compare(t.begin, a) <= 0 and ucmp.compare(b, t.end) <= 0
            },
            reverse=True,
        )
        for s in seqs:
            out.append(RangeTombstone(s, a, b))
    return out


class _CmpKey:
    __slots__ = ("ucmp", "k")

    def __init__(self, ucmp, k):
        self.ucmp = ucmp
        self.k = k

    def __lt__(self, other):
        return self.ucmp.compare(self.k, other.k) < 0


class RangeDelAggregator:
    """Collects tombstones from all sources for one read/compaction."""

    def __init__(self, ucmp):
        self._ucmp = ucmp
        self._tombstones: list[RangeTombstone] = []

    def add(self, t: RangeTombstone) -> None:
        self._tombstones.append(t)

    def add_many(self, ts) -> None:
        for t in ts:
            self.add(t)

    def empty(self) -> bool:
        return not self._tombstones

    def max_covering_seq(self, user_key: bytes, snapshot_seq: int) -> int:
        """Max tombstone seqno <= snapshot covering user_key (0 = none)."""
        best = 0
        for t in self._tombstones:
            if (t.seq <= snapshot_seq and t.seq > best
                    and self._ucmp.compare(t.begin, user_key) <= 0
                    and self._ucmp.compare(user_key, t.end) < 0):
                best = t.seq
        return best

    def should_delete(self, ikey: bytes, snapshot_seq: int = dbformat.MAX_SEQUENCE_NUMBER) -> bool:
        """True if the point entry is shadowed by a strictly newer tombstone."""
        uk, seq, _ = dbformat.split_internal_key(ikey)
        return self.max_covering_seq(uk, snapshot_seq) > seq

    def fragments(self) -> list[RangeTombstone]:
        return fragment_tombstones(self._tombstones, self._ucmp)

    def tombstones(self) -> list[RangeTombstone]:
        return list(self._tombstones)
