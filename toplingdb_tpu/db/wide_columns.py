"""Wide columns: multi-column values (reference db/wide/ in /root/reference,
gated by TOPLINGDB_WITH_WIDE_COLUMNS).

An entity is a set of named columns serialized into one value:
  varint32 num_columns | per column: lp(name) lp(value)
sorted by name; the anonymous default column uses name b"". Entities are
stored under the DEDICATED ValueType.WIDE_COLUMN_ENTITY (the reference's
kTypeWideColumnEntity, db/dbformat.h): plain binary values can never be
reinterpreted as entities. The value payload keeps the magic prefix for
self-description; detection is by TYPE (Options.legacy_wide_column_unwrap
re-enables the pre-type magic sniff for old databases). Entities flow
through compaction as puts, annihilate with SingleDelete, and merge
chains fold against the default column (merge_into_entity).
"""

from __future__ import annotations

from toplingdb_tpu.utils import coding
from toplingdb_tpu.utils.status import Corruption

DEFAULT_COLUMN = b""
_MAGIC = b"\x00WCE1"  # prefix marking a wide-column entity value


def encode_entity(columns: dict[bytes, bytes]) -> bytes:
    out = bytearray(_MAGIC)
    out += coding.encode_varint32(len(columns))
    for name in sorted(columns):
        coding.put_length_prefixed_slice(out, name)
        coding.put_length_prefixed_slice(out, columns[name])
    return bytes(out)


def is_entity(value: bytes) -> bool:
    return value.startswith(_MAGIC)


def decode_entity(value: bytes) -> dict[bytes, bytes]:
    if not is_entity(value):
        # Plain value presents as the anonymous default column.
        return {DEFAULT_COLUMN: value}
    try:
        off = len(_MAGIC)
        n, off = coding.decode_varint32(value, off)
        out: dict[bytes, bytes] = {}
        for _ in range(n):
            name, off = coding.get_length_prefixed_slice(value, off)
            val, off = coding.get_length_prefixed_slice(value, off)
            out[name] = val
        if off != len(value):
            raise Corruption("trailing bytes in wide-column entity")
        return out
    except Corruption:
        # A plain binary value that merely starts with the magic bytes: fall
        # back to the default-column presentation. (A dedicated
        # kTypeWideColumnEntity value type removes the ambiguity entirely;
        # planned for the next round.)
        return {DEFAULT_COLUMN: value}


def put_entity(db, key: bytes, columns: dict[bytes, bytes], *, opts=None,
               cf=None) -> None:
    """Thin alias for DB.put_entity (kept for callers that import the
    module functions)."""
    kw = {"opts": opts} if opts is not None else {}
    db.put_entity(key, columns, cf=cf, **kw)


def get_entity(db, key: bytes, *, opts=None, cf=None) -> dict[bytes, bytes] | None:
    """Thin alias for DB.get_entity."""
    kw = {"opts": opts} if opts is not None else {}
    return db.get_entity(key, cf=cf, **kw)


def merge_into_entity(encoded: bytes, fold_fn) -> bytes:
    """Apply a merge fold to an entity's DEFAULT column (reference
    MergeHelper-over-kTypeWideColumnEntity semantics,
    db/wide/wide_columns_helper): fold_fn receives the current default
    column value (or None when the entity has no default column) and
    returns the merged bytes; the result is the entity re-encoded with
    the default column replaced."""
    cols = dict(decode_entity(encoded))
    cols[DEFAULT_COLUMN] = fold_fn(cols.get(DEFAULT_COLUMN))
    return encode_entity(cols)


def default_column_of(value: bytes) -> bytes:
    """The reference's Get-on-entity semantics (db/wide/wide_columns_helper
    in /root/reference): a plain Get over a wide-column entity returns the
    anonymous default column's value (empty when the entity has none);
    non-entity values pass through untouched."""
    if not is_entity(value):
        return value
    return decode_entity(value).get(DEFAULT_COLUMN, b"")
