"""VersionEdit: one MANIFEST record — a delta on the LSM file metadata.

Tag-encoded like the reference (db/version_edit.h:35-50 in /root/reference):
a sequence of (varint tag, payload) fields. Unknown tags are an error unless
flagged safe-to-ignore (we keep the simple form: unknown → Corruption).
"""

from __future__ import annotations

from dataclasses import dataclass, field

from toplingdb_tpu.utils import coding
from toplingdb_tpu.utils.status import Corruption

# Tags (our own numbering; same roles as the reference's).
TAG_COMPARATOR = 1
TAG_LOG_NUMBER = 2
TAG_NEXT_FILE_NUMBER = 3
TAG_LAST_SEQUENCE = 4
TAG_DELETED_FILE = 5
TAG_NEW_FILE = 6
TAG_PREV_LOG_NUMBER = 7
TAG_MIN_LOG_NUMBER_TO_KEEP = 8
TAG_COLUMN_FAMILY = 9           # selects CF for this edit
TAG_COLUMN_FAMILY_ADD = 10
TAG_COLUMN_FAMILY_DROP = 11
TAG_MAX_COLUMN_FAMILY = 12
TAG_NEW_FILE_EXT = 13           # NEW_FILE + varint flags [+ blob_refs list]
_EXT_FLAG_MARKED = 1            # marked_for_compaction
_EXT_FLAG_BLOBS = 2             # blob_refs list follows
_EXT_FLAG_CHECKSUM = 4          # file checksum (func name + digest) follows


@dataclass
class FileMetaData:
    """Per-SST metadata held in a Version (reference db/version_edit.h
    FileMetaData)."""

    number: int
    file_size: int
    smallest: bytes  # internal key
    largest: bytes   # internal key
    smallest_seqno: int = 0
    largest_seqno: int = 0
    num_entries: int = 0
    num_deletions: int = 0
    num_range_deletions: int = 0
    blob_refs: list[int] = field(default_factory=list)  # referenced blob files
    being_compacted: bool = False  # in-memory only
    # Set by a TablePropertiesCollector's need_compact() — prioritized by the
    # picker; persisted via the extended NEW_FILE tag (reference persists it
    # as a NewFile4 custom field).
    marked_for_compaction: bool = False
    # Whole-file checksum (reference FileMetaData.file_checksum /
    # file_checksum_func_name, recorded per SST in the MANIFEST): digest
    # bytes + the generator name that produced them (utils/file_checksum).
    # Empty = not recorded (pre-upgrade file or checksums disabled).
    file_checksum: bytes = b""
    file_checksum_func_name: str = ""
    # In-memory only: the IntegrityScrubber found this file's on-disk bytes
    # diverging from the recorded checksum — excluded from compaction picks
    # so the corruption is never baked into new SSTs (db/integrity.py).
    quarantined: bool = False

    def _ext_flags(self) -> int:
        return ((_EXT_FLAG_MARKED if self.marked_for_compaction else 0)
                | (_EXT_FLAG_BLOBS if self.blob_refs else 0)
                | (_EXT_FLAG_CHECKSUM if self.file_checksum else 0))

    def encode(self, extended: bool = False) -> bytes:
        out = bytearray()
        out += coding.encode_varint64(self.number)
        out += coding.encode_varint64(self.file_size)
        coding.put_length_prefixed_slice(out, self.smallest)
        coding.put_length_prefixed_slice(out, self.largest)
        out += coding.encode_varint64(self.smallest_seqno)
        out += coding.encode_varint64(self.largest_seqno)
        out += coding.encode_varint64(self.num_entries)
        out += coding.encode_varint64(self.num_deletions)
        out += coding.encode_varint64(self.num_range_deletions)
        if extended:
            # Only under TAG_NEW_FILE_EXT — TAG_NEW_FILE keeps the original
            # layout so MANIFESTs written before the flags existed still parse.
            flags = self._ext_flags()
            out += coding.encode_varint64(flags)
            if flags & _EXT_FLAG_BLOBS:
                out += coding.encode_varint64(len(self.blob_refs))
                for fn in self.blob_refs:
                    out += coding.encode_varint64(fn)
            if flags & _EXT_FLAG_CHECKSUM:
                coding.put_length_prefixed_slice(
                    out, self.file_checksum_func_name.encode())
                coding.put_length_prefixed_slice(out, self.file_checksum)
        return bytes(out)

    @staticmethod
    def decode(buf: bytes, off: int,
               extended: bool = False) -> tuple["FileMetaData", int]:
        number, off = coding.decode_varint64(buf, off)
        size, off = coding.decode_varint64(buf, off)
        smallest, off = coding.get_length_prefixed_slice(buf, off)
        largest, off = coding.get_length_prefixed_slice(buf, off)
        ssq, off = coding.decode_varint64(buf, off)
        lsq, off = coding.decode_varint64(buf, off)
        ne, off = coding.decode_varint64(buf, off)
        nd, off = coding.decode_varint64(buf, off)
        nrd, off = coding.decode_varint64(buf, off)
        refs: list[int] = []
        marked = False
        cksum = b""
        cksum_name = ""
        if extended:
            flags, off = coding.decode_varint64(buf, off)
            marked = bool(flags & _EXT_FLAG_MARKED)
            if flags & _EXT_FLAG_BLOBS:
                nrefs, off = coding.decode_varint64(buf, off)
                for _ in range(nrefs):
                    fn, off = coding.decode_varint64(buf, off)
                    refs.append(fn)
            if flags & _EXT_FLAG_CHECKSUM:
                name_b, off = coding.get_length_prefixed_slice(buf, off)
                cksum_name = name_b.decode()
                cksum, off = coding.get_length_prefixed_slice(buf, off)
        return FileMetaData(number, size, smallest, largest, ssq, lsq,
                            ne, nd, nrd, refs,
                            marked_for_compaction=marked,
                            file_checksum=cksum,
                            file_checksum_func_name=cksum_name), off


@dataclass
class VersionEdit:
    comparator: str | None = None
    log_number: int | None = None
    prev_log_number: int | None = None
    next_file_number: int | None = None
    last_sequence: int | None = None
    min_log_number_to_keep: int | None = None
    column_family: int = 0
    column_family_add: str | None = None
    column_family_drop: bool = False
    max_column_family: int | None = None
    new_files: list[tuple[int, FileMetaData]] = field(default_factory=list)
    deleted_files: list[tuple[int, int]] = field(default_factory=list)  # (level, file#)

    def add_file(self, level: int, meta: FileMetaData) -> None:
        self.new_files.append((level, meta))

    def delete_file(self, level: int, number: int) -> None:
        self.deleted_files.append((level, number))

    def encode(self) -> bytes:
        out = bytearray()

        def tag(t: int):
            out.extend(coding.encode_varint32(t))

        if self.comparator is not None:
            tag(TAG_COMPARATOR)
            coding.put_length_prefixed_slice(out, self.comparator.encode())
        if self.log_number is not None:
            tag(TAG_LOG_NUMBER)
            out += coding.encode_varint64(self.log_number)
        if self.prev_log_number is not None:
            tag(TAG_PREV_LOG_NUMBER)
            out += coding.encode_varint64(self.prev_log_number)
        if self.next_file_number is not None:
            tag(TAG_NEXT_FILE_NUMBER)
            out += coding.encode_varint64(self.next_file_number)
        if self.last_sequence is not None:
            tag(TAG_LAST_SEQUENCE)
            out += coding.encode_varint64(self.last_sequence)
        if self.min_log_number_to_keep is not None:
            tag(TAG_MIN_LOG_NUMBER_TO_KEEP)
            out += coding.encode_varint64(self.min_log_number_to_keep)
        if self.column_family:
            tag(TAG_COLUMN_FAMILY)
            out += coding.encode_varint64(self.column_family)
        if self.column_family_add is not None:
            tag(TAG_COLUMN_FAMILY_ADD)
            coding.put_length_prefixed_slice(out, self.column_family_add.encode())
        if self.column_family_drop:
            tag(TAG_COLUMN_FAMILY_DROP)
        if self.max_column_family is not None:
            tag(TAG_MAX_COLUMN_FAMILY)
            out += coding.encode_varint64(self.max_column_family)
        for level, number in self.deleted_files:
            tag(TAG_DELETED_FILE)
            out += coding.encode_varint64(level)
            out += coding.encode_varint64(number)
        for level, meta in self.new_files:
            ext = meta._ext_flags() != 0
            tag(TAG_NEW_FILE_EXT if ext else TAG_NEW_FILE)
            out += coding.encode_varint64(level)
            out += meta.encode(extended=ext)
        return bytes(out)

    @staticmethod
    def decode(buf: bytes) -> "VersionEdit":
        e = VersionEdit()
        off = 0
        while off < len(buf):
            t, off = coding.decode_varint32(buf, off)
            if t == TAG_COMPARATOR:
                s, off = coding.get_length_prefixed_slice(buf, off)
                e.comparator = s.decode()
            elif t == TAG_LOG_NUMBER:
                e.log_number, off = coding.decode_varint64(buf, off)
            elif t == TAG_PREV_LOG_NUMBER:
                e.prev_log_number, off = coding.decode_varint64(buf, off)
            elif t == TAG_NEXT_FILE_NUMBER:
                e.next_file_number, off = coding.decode_varint64(buf, off)
            elif t == TAG_LAST_SEQUENCE:
                e.last_sequence, off = coding.decode_varint64(buf, off)
            elif t == TAG_MIN_LOG_NUMBER_TO_KEEP:
                e.min_log_number_to_keep, off = coding.decode_varint64(buf, off)
            elif t == TAG_COLUMN_FAMILY:
                cf, off = coding.decode_varint64(buf, off)
                e.column_family = cf
            elif t == TAG_COLUMN_FAMILY_ADD:
                s, off = coding.get_length_prefixed_slice(buf, off)
                e.column_family_add = s.decode()
            elif t == TAG_COLUMN_FAMILY_DROP:
                e.column_family_drop = True
            elif t == TAG_MAX_COLUMN_FAMILY:
                e.max_column_family, off = coding.decode_varint64(buf, off)
            elif t == TAG_DELETED_FILE:
                lvl, off = coding.decode_varint64(buf, off)
                num, off = coding.decode_varint64(buf, off)
                e.deleted_files.append((lvl, num))
            elif t == TAG_NEW_FILE or t == TAG_NEW_FILE_EXT:
                lvl, off = coding.decode_varint64(buf, off)
                meta, off = FileMetaData.decode(
                    buf, off, extended=(t == TAG_NEW_FILE_EXT)
                )
                e.new_files.append((lvl, meta))
            else:
                raise Corruption(f"unknown VersionEdit tag {t}")
        return e
