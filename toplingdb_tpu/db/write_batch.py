"""WriteBatch: the serialized update record and WAL payload.

Same wire shape as the reference (db/write_batch.cc in /root/reference):
    fixed64 sequence | fixed32 count | records*
where each record is a type byte followed by length-prefixed slices:
    VALUE            key value
    DELETION         key
    SINGLE_DELETION  key
    MERGE            key value
    RANGE_DELETION   begin_key end_key
    LOG_DATA         blob                (not counted, not applied)
A batch is the atomic unit of the write path: it is appended to the WAL as one
record and then applied to the memtable entry by entry with consecutive
sequence numbers.

Column families: non-default-CF records use the CF-prefixed record types
(0x80 | base_type) followed by a varint32 column family id — the same scheme
as the reference's kTypeColumnFamily* records.
"""

from __future__ import annotations

from toplingdb_tpu.db.dbformat import ValueType
from toplingdb_tpu.utils import coding
from toplingdb_tpu.utils import protection as _prot
from toplingdb_tpu.utils.status import Corruption

HEADER_SIZE = 12
_CF_FLAG = 0x80


_NP_UNRESOLVED = object()
_np_fn = _NP_UNRESOLVED   # None once resolved-absent
_np_arr_types: dict = {}  # cap -> cached ctypes array type (hot path)


def _native_protect(rep: bytes, pb: int, strip_cf: bool):
    """Whole-batch protection vector in ONE native call (tpulsm_wb_protect;
    bit-identical to utils/protection.py), or None → Python fallback."""
    global _np_fn
    fn = _np_fn
    if fn is _NP_UNRESOLVED:
        from toplingdb_tpu import native

        l = native.lib()
        fn = _np_fn = (getattr(l, "tpulsm_wb_protect", None)
                       if l is not None else None)
    if fn is None:
        return None
    cap = coding.decode_fixed32(rep, 8)
    at = _np_arr_types.get(cap)
    if at is None:
        import ctypes

        if len(_np_arr_types) > 1024:
            _np_arr_types.clear()
        at = _np_arr_types[cap] = ctypes.c_uint64 * cap
    out = at()
    rc = fn(rep, len(rep), pb, 1 if strip_cf else 0, out, cap)
    if rc < 0:
        return None  # unparseable here: the Python walk raises the error
    import numpy as np

    # Zero-copy ndarray VIEW over the ctypes buffer (rc == cap on
    # success, so the view spans it exactly and .base keeps it alive):
    # vector compares and XOR folds run at C speed, and the fused
    # memtable insert (insert_wb_prot) passes .base straight back to
    # ctypes without a data_as() crossing.
    return np.frombuffer(out, dtype=np.uint64)


def _prot_eq(a, b) -> bool:
    """Value equality of two protection vectors (list or uint64 ndarray)."""
    if type(a) is list and type(b) is list:
        return a == b
    import numpy as np

    a = np.asarray(a, dtype=np.uint64)
    b = np.asarray(b, dtype=np.uint64)
    return a.shape == b.shape and bool(np.array_equal(a, b))


class WriteBatch:
    def __init__(self, data: bytes | None = None,
                 protection_bytes_per_key: int = 0):
        # _simple: only default-CF point records so far — eligible for the
        # one-call native wire-image insert (wire-loaded batches decode
        # through the parsed path, so they start non-simple).
        # With protection_bytes_per_key > 0, every counted record gets a
        # per-entry checksum (utils/protection.py) computed at add time and
        # verified at the memtable-insert handoff (reference
        # protection_bytes_per_key / ProtectionInfo, db/kv_checksum.h).
        self._pb = protection_bytes_per_key
        self._prot: list[int] | None = None
        # _prot_n: record count when _prot was materialized. Staleness is
        # _prot_n != _count, so add() pays ZERO protection cost per record;
        # the vector is computed in ONE native pass at the first handoff
        # (ensure_protection at DB.write / insert) — per-record Python
        # hashing would double the write cost.
        self._prot_n = 0
        # Group-plane eligibility hints (db.py _native_group_commit):
        # wide-column entities and merge-heavy batches keep the Python
        # interiors as the oracle (ISSUE 7 fallback matrix).
        self._has_wide = False
        self._n_merge = 0
        if data is not None:
            if len(data) < HEADER_SIZE:
                raise Corruption("write batch header too small")
            self._rep = bytearray(data)
            self._simple = False
            self._count = coding.decode_fixed32(self._rep, 8)
            if protection_bytes_per_key:
                self.attach_protection(protection_bytes_per_key)
        else:
            self._rep = bytearray(HEADER_SIZE)
            self._simple = True
            self._count = 0  # header count patched lazily (see data())
            if protection_bytes_per_key:
                self._prot = []

    # -- mutation -------------------------------------------------------

    def put(self, key: bytes, value: bytes, cf: int = 0) -> None:
        self._add_record(ValueType.VALUE, cf, key, value)

    def delete(self, key: bytes, cf: int = 0) -> None:
        self._add_record(ValueType.DELETION, cf, key)

    def single_delete(self, key: bytes, cf: int = 0) -> None:
        self._add_record(ValueType.SINGLE_DELETION, cf, key)

    def merge(self, key: bytes, value: bytes, cf: int = 0) -> None:
        self._add_record(ValueType.MERGE, cf, key, value)

    def put_entity(self, key: bytes, encoded_entity: bytes,
                   cf: int = 0) -> None:
        """Wide-column entity record (reference kTypeWideColumnEntity,
        db/write_batch.cc WriteBatch::PutEntity) — the DEDICATED value
        type makes plain binary values unambiguous (no magic sniffing)."""
        self._add_record(ValueType.WIDE_COLUMN_ENTITY, cf, key,
                         encoded_entity)

    def delete_range(self, begin: bytes, end: bytes, cf: int = 0) -> None:
        self._add_record(ValueType.RANGE_DELETION, cf, begin, end)

    def put_log_data(self, blob: bytes) -> None:
        self._rep.append(ValueType.LOG_DATA)
        coding.put_length_prefixed_slice(self._rep, blob)

    def _add_record(self, t: ValueType, cf: int, *slices: bytes) -> None:
        rep = self._rep
        if t == ValueType.MERGE:
            self._n_merge += 1
        elif t == ValueType.WIDE_COLUMN_ENTITY:
            self._has_wide = True
        if cf == 0:
            rep.append(t)
            if t == ValueType.RANGE_DELETION:
                self._simple = False
        else:
            self._simple = False
            rep.append(_CF_FLAG | t)
            rep += coding.encode_varint32(cf)
        for s in slices:
            n = len(s)
            if n < 128:  # single-byte varint: the overwhelmingly common case
                rep.append(n)
                rep += s
            else:
                coding.put_length_prefixed_slice(rep, s)
        self._count += 1

    def clear(self) -> None:
        self._rep = bytearray(HEADER_SIZE)
        self._simple = True
        self._count = 0
        self._prot_n = 0
        self._has_wide = False
        self._n_merge = 0
        if self._prot is not None:
            self._prot = []

    def append_from(self, other: "WriteBatch") -> None:
        """Group-commit helper: append other's records to self."""
        self._rep += other._rep[HEADER_SIZE:]
        self._count += other.count()
        self._simple = self._simple and other._simple
        self._has_wide = self._has_wide or other._has_wide
        self._n_merge += other._n_merge
        if self._prot is not None:
            if (other._prot is not None and other._pb == self._pb
                    and self._prot_n == self._count - other.count()
                    and other._prot_n == other.count()):
                if type(self._prot) is list and type(other._prot) is list:
                    self._prot = self._prot + other._prot
                else:
                    import numpy as np

                    self._prot = np.concatenate([
                        np.asarray(self._prot, dtype=np.uint64),
                        np.asarray(other._prot, dtype=np.uint64)])
                self._prot_n = self._count
            else:
                # Mixed-protection merge (only the transient WAL image in
                # group commit): the merged copy drops protection; the
                # member batches keep theirs and are what insert verifies.
                self._prot = None

    # -- protection info (reference protection_bytes_per_key) -----------

    def attach_protection(self, protection_bytes_per_key: int) -> None:
        """Compute per-entry protection for an existing batch (wire-loaded
        batches, batches built before the DB attached them). Protection
        covers the entry from THIS point on."""
        self._pb = protection_bytes_per_key
        prots = _native_protect(self.data(), protection_bytes_per_key,
                                strip_cf=False)
        if prots is None:
            prots = []
            for cf, t, k, v in self.entries_cf():
                prots.append(_prot.truncate(
                    _prot.protect_entry(int(t), k, v, cf),
                    protection_bytes_per_key,
                ))
        self._prot = prots
        self._prot_n = self._count

    def ensure_protection(self, protection_bytes_per_key: int) -> None:
        """Materialize the protection vector if records were added since
        it was last computed (DB.write calls this BEFORE the WAL append
        and group merge, so the insert-time re-verification spans the
        whole commit path)."""
        if (self._prot is not None and self._prot_n == self._count
                and self._pb == protection_bytes_per_key):
            return
        self.attach_protection(protection_bytes_per_key or self._pb)

    def verify_protection(self) -> None:
        """Recompute every record's protection from the wire rep and
        compare with the carried values; raises Corruption on the first
        mismatch. No-op for unprotected batches (a dirty vector is
        materialized first — new records have nothing to verify against)."""
        if self._prot is None:
            return
        if self._prot_n != self._count:
            self.attach_protection(self._pb)
            return
        vec = _native_protect(self.data(), self._pb, strip_cf=False)
        if vec is not None and _prot_eq(vec, self._prot):
            return
        idx = 0
        for cf, t, k, v in self.entries_cf():
            got = _prot.truncate(_prot.protect_entry(int(t), k, v, cf),
                                 self._pb)
            if got != self._prot[idx]:
                raise Corruption(
                    f"write batch protection mismatch at record {idx} "
                    f"(cf={cf}, type={t}): entry bytes changed after add"
                )
            idx += 1
        if idx != len(self._prot):
            raise Corruption(
                f"write batch protection count mismatch: {len(self._prot)} "
                f"protected, {idx} present"
            )

    # -- header ---------------------------------------------------------

    def sequence(self) -> int:
        return coding.decode_fixed64(self._rep, 0)

    def set_sequence(self, seq: int) -> None:
        self._rep[0:8] = coding.encode_fixed64(seq)

    def count(self) -> int:
        return self._count

    def set_count(self, n: int) -> None:
        # _count is the single source of truth; the header bytes are
        # patched only at export (data()).
        self._count = n

    def data(self) -> bytes:
        # The header count is maintained lazily; patch it on export.
        self._rep[8:12] = coding.encode_fixed32(self._count)
        return bytes(self._rep)

    def data_size(self) -> int:
        return len(self._rep)

    def is_empty(self) -> bool:
        return self.count() == 0

    # -- iteration ------------------------------------------------------

    def entries(self):
        """Yields (value_type, key, value_or_none) for the DEFAULT column
        family only (other CFs' records are skipped — use entries_cf() when
        column families matter). RANGE_DELETION yields (type, begin, end);
        LOG_DATA is skipped."""
        for cf, t, k, v in self.entries_cf():
            if cf == 0:
                yield t, k, v

    def entries_cf(self):
        """Yields (cf_id, value_type, key, value_or_none)."""
        rep = self._rep
        off = HEADER_SIZE
        n = 0
        while off < len(rep):
            t = rep[off]
            off += 1
            cf = 0
            if t & _CF_FLAG and t != ValueType.LOG_DATA:
                t &= ~_CF_FLAG
                cf, off = coding.decode_varint32(rep, off)
            if t in (ValueType.VALUE, ValueType.MERGE,
                     ValueType.RANGE_DELETION,
                     ValueType.WIDE_COLUMN_ENTITY):
                k, off = coding.get_length_prefixed_slice(rep, off)
                v, off = coding.get_length_prefixed_slice(rep, off)
                yield cf, t, k, v
                n += 1
            elif t in (ValueType.DELETION, ValueType.SINGLE_DELETION):
                k, off = coding.get_length_prefixed_slice(rep, off)
                yield cf, t, k, None
                n += 1
            elif t == ValueType.LOG_DATA:
                _, off = coding.get_length_prefixed_slice(rep, off)
            else:
                raise Corruption(f"unknown write batch record type {t}")
        if n != self.count():
            raise Corruption(
                f"write batch count mismatch: header {self.count()}, actual {n}"
            )

    def insert_into(self, memtable, sequence: int | None = None) -> int:
        """Apply to one memtable (single-CF) or a {cf_id: memtable} dict;
        returns the number of sequence numbers consumed (== count).
        Records for CFs absent from the dict are skipped (dropped CF).
        Simple batches (default-CF point records only) apply through ONE
        native wire-image call (MemTable.add_encoded — no per-record
        Python); the rest run the parsed path with one GIL-releasing
        native call per same-memtable run.

        Protected batches (protection_bytes_per_key > 0) are re-hashed and
        checked against their carried protection HERE — the
        batch->memtable handoff is the reference's KV-checksum
        verification point — and the CF-stripped form is handed to the
        memtable to carry until flush. The re-hash is ONE native pass
        (tpulsm_wb_protect) when available, so verified simple batches
        still take the wire-image insert; without the native library the
        parsed path verifies record by record."""
        seq = self.sequence() if sequence is None else sequence
        is_map = isinstance(memtable, dict)
        mem0 = memtable.get(0) if is_map else memtable
        prots = self._prot
        verified = False
        if prots is not None and self._prot_n != self._count:
            # Records never materialized (direct insert_into callers):
            # compute now — they are covered from THIS point on.
            self.attach_protection(self._pb)
            prots = self._prot
            verified = True
        if (prots is not None and not verified and self._simple
                and self.count() and mem0 is not None):
            # Fused verify+insert: the memtable's native rep re-hashes
            # every record against `prots` in its validation pass and
            # inserts only if ALL match (raising Corruption otherwise) —
            # one native crossing instead of verify + insert as two.
            enc = getattr(mem0, "add_encoded", None)
            if enc is not None and enc(seq, self.data(), prots=prots,
                                       pb=self._pb) is not None:
                return self.count()
        if prots is not None and not verified and self.count():
            vec = _native_protect(self.data(), self._pb, strip_cf=False)
            if vec is not None:
                if not _prot_eq(vec, prots):
                    bad = next((i for i, (a, b) in enumerate(zip(vec, prots))
                                if a != b), min(len(vec), len(prots)))
                    raise Corruption(
                        f"write batch protection mismatch at record {bad} "
                        f"during memtable insert"
                    )
                verified = True
        if self._simple and self.count() and (prots is None or verified):
            if mem0 is None:
                return self.count()  # default CF dropped: all skipped
            enc = getattr(mem0, "add_encoded", None)
            if enc is not None and enc(seq, self.data(),
                                       prots=prots) is not None:
                return self.count()
        run_mem = None
        run_seq = seq
        run: list = []
        run_prots: list | None = [] if prots is not None else None
        idx = 0
        for cf, t, k, v in self.entries_cf():
            mem = memtable.get(cf) if is_map else memtable
            if mem is not run_mem:
                if run:
                    run_mem.add_batch(run_seq, run, prots=run_prots)
                    run = []
                    run_prots = [] if prots is not None else None
                run_mem = mem
                run_seq = seq
            if mem is not None:
                run.append((t, k, v))
                if prots is not None:
                    if verified and cf == 0:
                        # Native pass proved prots[idx] matches the rep;
                        # cf=0 needs no strip — carry it as-is.
                        run_prots.append(prots[idx])
                    else:
                        full = _prot.protect_entry(
                            int(t), k, v if v is not None else b"", cf)
                        if (not verified and _prot.truncate(full, self._pb)
                                != prots[idx]):
                            raise Corruption(
                                f"write batch protection mismatch at "
                                f"record {idx} (cf={cf}, type={t}) during "
                                f"memtable insert"
                            )
                        run_prots.append(_prot.truncate(
                            _prot.strip_cf(full, cf), self._pb))
            seq += 1
            idx += 1
        if run and run_mem is not None:
            run_mem.add_batch(run_seq, run, prots=run_prots)
        return self.count()
