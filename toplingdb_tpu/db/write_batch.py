"""WriteBatch: the serialized update record and WAL payload.

Same wire shape as the reference (db/write_batch.cc in /root/reference):
    fixed64 sequence | fixed32 count | records*
where each record is a type byte followed by length-prefixed slices:
    VALUE            key value
    DELETION         key
    SINGLE_DELETION  key
    MERGE            key value
    RANGE_DELETION   begin_key end_key
    LOG_DATA         blob                (not counted, not applied)
A batch is the atomic unit of the write path: it is appended to the WAL as one
record and then applied to the memtable entry by entry with consecutive
sequence numbers.

Column families: non-default-CF records use the CF-prefixed record types
(0x80 | base_type) followed by a varint32 column family id — the same scheme
as the reference's kTypeColumnFamily* records.
"""

from __future__ import annotations

from toplingdb_tpu.db.dbformat import ValueType
from toplingdb_tpu.utils import coding
from toplingdb_tpu.utils.status import Corruption

HEADER_SIZE = 12
_CF_FLAG = 0x80


class WriteBatch:
    def __init__(self, data: bytes | None = None):
        # _simple: only default-CF point records so far — eligible for the
        # one-call native wire-image insert (wire-loaded batches decode
        # through the parsed path, so they start non-simple).
        if data is not None:
            if len(data) < HEADER_SIZE:
                raise Corruption("write batch header too small")
            self._rep = bytearray(data)
            self._simple = False
            self._count = coding.decode_fixed32(self._rep, 8)
        else:
            self._rep = bytearray(HEADER_SIZE)
            self._simple = True
            self._count = 0  # header count patched lazily (see data())

    # -- mutation -------------------------------------------------------

    def put(self, key: bytes, value: bytes, cf: int = 0) -> None:
        self._add_record(ValueType.VALUE, cf, key, value)

    def delete(self, key: bytes, cf: int = 0) -> None:
        self._add_record(ValueType.DELETION, cf, key)

    def single_delete(self, key: bytes, cf: int = 0) -> None:
        self._add_record(ValueType.SINGLE_DELETION, cf, key)

    def merge(self, key: bytes, value: bytes, cf: int = 0) -> None:
        self._add_record(ValueType.MERGE, cf, key, value)

    def put_entity(self, key: bytes, encoded_entity: bytes,
                   cf: int = 0) -> None:
        """Wide-column entity record (reference kTypeWideColumnEntity,
        db/write_batch.cc WriteBatch::PutEntity) — the DEDICATED value
        type makes plain binary values unambiguous (no magic sniffing)."""
        self._add_record(ValueType.WIDE_COLUMN_ENTITY, cf, key,
                         encoded_entity)

    def delete_range(self, begin: bytes, end: bytes, cf: int = 0) -> None:
        self._add_record(ValueType.RANGE_DELETION, cf, begin, end)

    def put_log_data(self, blob: bytes) -> None:
        self._rep.append(ValueType.LOG_DATA)
        coding.put_length_prefixed_slice(self._rep, blob)

    def _add_record(self, t: ValueType, cf: int, *slices: bytes) -> None:
        rep = self._rep
        if cf == 0:
            rep.append(t)
            if t == ValueType.RANGE_DELETION:
                self._simple = False
        else:
            self._simple = False
            rep.append(_CF_FLAG | t)
            rep += coding.encode_varint32(cf)
        for s in slices:
            n = len(s)
            if n < 128:  # single-byte varint: the overwhelmingly common case
                rep.append(n)
                rep += s
            else:
                coding.put_length_prefixed_slice(rep, s)
        self._count += 1

    def clear(self) -> None:
        self._rep = bytearray(HEADER_SIZE)
        self._simple = True
        self._count = 0

    def append_from(self, other: "WriteBatch") -> None:
        """Group-commit helper: append other's records to self."""
        self._rep += other._rep[HEADER_SIZE:]
        self._count += other.count()
        self._simple = self._simple and other._simple

    # -- header ---------------------------------------------------------

    def sequence(self) -> int:
        return coding.decode_fixed64(self._rep, 0)

    def set_sequence(self, seq: int) -> None:
        self._rep[0:8] = coding.encode_fixed64(seq)

    def count(self) -> int:
        return self._count

    def set_count(self, n: int) -> None:
        # _count is the single source of truth; the header bytes are
        # patched only at export (data()).
        self._count = n

    def data(self) -> bytes:
        # The header count is maintained lazily; patch it on export.
        self._rep[8:12] = coding.encode_fixed32(self._count)
        return bytes(self._rep)

    def data_size(self) -> int:
        return len(self._rep)

    def is_empty(self) -> bool:
        return self.count() == 0

    # -- iteration ------------------------------------------------------

    def entries(self):
        """Yields (value_type, key, value_or_none) for the DEFAULT column
        family only (other CFs' records are skipped — use entries_cf() when
        column families matter). RANGE_DELETION yields (type, begin, end);
        LOG_DATA is skipped."""
        for cf, t, k, v in self.entries_cf():
            if cf == 0:
                yield t, k, v

    def entries_cf(self):
        """Yields (cf_id, value_type, key, value_or_none)."""
        rep = self._rep
        off = HEADER_SIZE
        n = 0
        while off < len(rep):
            t = rep[off]
            off += 1
            cf = 0
            if t & _CF_FLAG and t != ValueType.LOG_DATA:
                t &= ~_CF_FLAG
                cf, off = coding.decode_varint32(rep, off)
            if t in (ValueType.VALUE, ValueType.MERGE,
                     ValueType.RANGE_DELETION,
                     ValueType.WIDE_COLUMN_ENTITY):
                k, off = coding.get_length_prefixed_slice(rep, off)
                v, off = coding.get_length_prefixed_slice(rep, off)
                yield cf, t, k, v
                n += 1
            elif t in (ValueType.DELETION, ValueType.SINGLE_DELETION):
                k, off = coding.get_length_prefixed_slice(rep, off)
                yield cf, t, k, None
                n += 1
            elif t == ValueType.LOG_DATA:
                _, off = coding.get_length_prefixed_slice(rep, off)
            else:
                raise Corruption(f"unknown write batch record type {t}")
        if n != self.count():
            raise Corruption(
                f"write batch count mismatch: header {self.count()}, actual {n}"
            )

    def insert_into(self, memtable, sequence: int | None = None) -> int:
        """Apply to one memtable (single-CF) or a {cf_id: memtable} dict;
        returns the number of sequence numbers consumed (== count).
        Records for CFs absent from the dict are skipped (dropped CF).
        Simple batches (default-CF point records only) apply through ONE
        native wire-image call (MemTable.add_encoded — no per-record
        Python); the rest run the parsed path with one GIL-releasing
        native call per same-memtable run."""
        seq = self.sequence() if sequence is None else sequence
        is_map = isinstance(memtable, dict)
        mem0 = memtable.get(0) if is_map else memtable
        if self._simple and self.count():
            if mem0 is None:
                return self.count()  # default CF dropped: all skipped
            enc = getattr(mem0, "add_encoded", None)
            if enc is not None and enc(seq, self.data()) is not None:
                return self.count()
        run_mem = None
        run_seq = seq
        run: list = []
        for cf, t, k, v in self.entries_cf():
            mem = memtable.get(cf) if is_map else memtable
            if mem is not run_mem:
                if run:
                    run_mem.add_batch(run_seq, run)
                    run = []
                run_mem = mem
                run_seq = seq
            if mem is not None:
                run.append((t, k, v))
            seq += 1
        if run and run_mem is not None:
            run_mem.add_batch(run_seq, run)
        return self.count()
