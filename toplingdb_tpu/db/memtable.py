"""MemTable: the in-memory sorted run, with pluggable representations.

Role matches the reference MemTable (db/memtable.cc:1263 `Get`, `Add`;
rep factories at include/rocksdb/memtablerep.h:64,309 in /root/reference).
Entries are ordered by (user_key asc, packed(seqno,type) desc) — internal key
order. Range tombstones are kept in a side list (like the reference's separate
range_del memtable) and fragmented at read time.

Reps:
  PyVectorRep  — bisect-maintained sorted list (the default pure-Python rep;
                 analogue of VectorRep + always-sorted).
Future: native C++ skiplist via ctypes, CSPP-style trie.
"""

from __future__ import annotations

import bisect
import threading

from toplingdb_tpu.db import dbformat
from toplingdb_tpu.db.dbformat import ValueType

_MAX_PACKED = (1 << 64) - 1


def _sort_key(user_key: bytes, packed: int) -> tuple[bytes, int]:
    # Ascending tuple order == internal key order (seqno/type descending).
    return (user_key, _MAX_PACKED - packed)


class MemTableRep:
    """Pluggable sorted container of ((user_key, inv_packed) -> value)."""

    def insert(self, skey, value: bytes) -> None:
        raise NotImplementedError

    def iter_from(self, skey):
        raise NotImplementedError

    def iter_all(self):
        raise NotImplementedError

    def __len__(self) -> int:
        raise NotImplementedError


class PyVectorRep(MemTableRep):
    """Entries are stored as single (sort_key, value) tuples in ONE list so
    every insert is a single list mutation — atomic under the GIL — and
    lockless readers can never observe a key paired with the wrong value."""

    def __init__(self):
        self._items: list[tuple[tuple[bytes, int], bytes]] = []

    def insert(self, skey, value: bytes) -> None:
        i = bisect.bisect_left(self._items, skey, key=lambda it: it[0])
        if i < len(self._items) and self._items[i][0] == skey:
            # Same (user_key, seqno, type) re-inserted (WAL replay): last wins.
            self._items[i] = (skey, value)
            return
        self._items.insert(i, (skey, value))

    def iter_from(self, skey):
        i = bisect.bisect_left(self._items, skey, key=lambda it: it[0])
        while i < len(self._items):
            yield self._items[i]
            i += 1

    def iter_all(self):
        yield from self._items

    def __len__(self) -> int:
        return len(self._items)


class MemTable:
    def __init__(self, icmp: dbformat.InternalKeyComparator, rep: MemTableRep | None = None):
        self._icmp = icmp
        self._rep = rep if rep is not None else PyVectorRep()
        self._range_dels: list[tuple[int, bytes, bytes]] = []  # (seq, begin, end)
        self._mem_usage = 0
        self._num_entries = 0
        self._num_deletes = 0
        self._first_seqno: int | None = None
        self._lock = threading.Lock()
        self.mem_id = 0

    # ------------------------------------------------------------------

    def add(self, seq: int, t: int, user_key: bytes, value: bytes) -> None:
        with self._lock:
            if t == ValueType.RANGE_DELETION:
                self._range_dels.append((seq, user_key, value))
            else:
                packed = dbformat.pack_seq_type(seq, t)
                self._rep.insert(_sort_key(user_key, packed), value)
            self._num_entries += 1
            if t in (ValueType.DELETION, ValueType.SINGLE_DELETION):
                self._num_deletes += 1
            self._mem_usage += len(user_key) + len(value) + 24
            if self._first_seqno is None:
                self._first_seqno = seq

    def entries_for_key(self, user_key: bytes, snapshot_seq: int):
        """Yield (seq, type, value) for user_key with seq <= snapshot,
        newest first — the feed for GetContext."""
        start = _sort_key(user_key, dbformat.pack_seq_type(snapshot_seq, 0xFF))
        for (uk, inv), val in self._rep.iter_from(start):
            if uk != user_key:
                break
            seq, t = dbformat.unpack_seq_type(_MAX_PACKED - inv)
            if seq > snapshot_seq:
                continue
            yield seq, t, val

    def covering_tombstone_seq(self, user_key: bytes, snapshot_seq: int) -> int:
        """Max seqno of a range tombstone covering user_key at the snapshot
        (0 = none)."""
        best = 0
        ucmp = self._icmp.user_comparator
        for seq, begin, end in self._range_dels:
            if seq <= snapshot_seq and ucmp.compare(begin, user_key) <= 0 \
                    and ucmp.compare(user_key, end) < 0:
                best = max(best, seq)
        return best

    # ------------------------------------------------------------------

    def iter_entries(self):
        """Yields (internal_key, value) in internal key order (point entries
        only; range tombstones via range_del_entries)."""
        for (uk, inv), val in self._rep.iter_all():
            seq, t = dbformat.unpack_seq_type(_MAX_PACKED - inv)
            yield dbformat.make_internal_key(uk, seq, t), val

    def iter_from(self, ikey: bytes):
        uk, seq, t = dbformat.split_internal_key(ikey)
        start = _sort_key(uk, dbformat.pack_seq_type(seq, t))
        for (k, inv), val in self._rep.iter_from(start):
            s, tt = dbformat.unpack_seq_type(_MAX_PACKED - inv)
            yield dbformat.make_internal_key(k, s, tt), val

    def range_del_entries(self):
        """Yields (seq, begin_user_key, end_user_key)."""
        yield from self._range_dels

    # ------------------------------------------------------------------

    def new_iterator(self) -> "MemTableIterator":
        return MemTableIterator(self)

    def approximate_memory_usage(self) -> int:
        return self._mem_usage

    @property
    def num_entries(self) -> int:
        return self._num_entries

    @property
    def num_deletes(self) -> int:
        return self._num_deletes

    @property
    def first_seqno(self):
        return self._first_seqno

    def empty(self) -> bool:
        return self._num_entries == 0


class MemTableIterator:
    """Standard iterator protocol over a memtable's point entries.

    Tolerates concurrent inserts: positions are re-derived by bisect on the
    stored sort key, so list shifts cannot skip or repeat entries (the Python
    analogue of iterating a lock-free skiplist)."""

    def __init__(self, mem: MemTable):
        self._mem = mem
        self._rep: PyVectorRep = mem._rep  # type: ignore[assignment]
        self._skey = None   # current (user_key, inv_packed) or None
        self._value = None

    def _load(self, i: int) -> None:
        items = self._rep._items
        if 0 <= i < len(items):
            self._skey, self._value = items[i]
        else:
            self._skey = None
            self._value = None

    def valid(self) -> bool:
        return self._skey is not None

    def key(self) -> bytes:
        uk, inv = self._skey
        seq, t = dbformat.unpack_seq_type(_MAX_PACKED - inv)
        return dbformat.make_internal_key(uk, seq, t)

    def value(self) -> bytes:
        return self._value

    def seek_to_first(self) -> None:
        self._load(0)

    def seek_to_last(self) -> None:
        self._load(len(self._rep._items) - 1)

    def seek(self, ikey: bytes) -> None:
        uk, seq, t = dbformat.split_internal_key(ikey)
        skey = _sort_key(uk, dbformat.pack_seq_type(seq, t))
        self._load(bisect.bisect_left(self._rep._items, skey, key=lambda it: it[0]))

    def seek_for_prev(self, ikey: bytes) -> None:
        uk, seq, t = dbformat.split_internal_key(ikey)
        skey = _sort_key(uk, dbformat.pack_seq_type(seq, t))
        self._load(bisect.bisect_right(self._rep._items, skey, key=lambda it: it[0]) - 1)

    def next(self) -> None:
        assert self.valid()
        i = bisect.bisect_right(self._rep._items, self._skey, key=lambda it: it[0])
        self._load(i)

    def prev(self) -> None:
        assert self.valid()
        i = bisect.bisect_left(self._rep._items, self._skey, key=lambda it: it[0])
        self._load(i - 1)
