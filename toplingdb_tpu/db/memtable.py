"""MemTable: the in-memory sorted run, with pluggable representations.

Role matches the reference MemTable (db/memtable.cc:1263 `Get`, `Add`;
rep factories at include/rocksdb/memtablerep.h:64,309 in /root/reference).
Entries are ordered by (user_key asc, packed(seqno,type) desc) — internal key
order. Range tombstones are kept in a side list (like the reference's separate
range_del memtable) and fragmented at read time.

Reps:
  PyVectorRep  — bisect-maintained sorted list (the default pure-Python rep;
                 analogue of VectorRep + always-sorted).
Future: native C++ skiplist via ctypes, CSPP-style trie.
"""

from __future__ import annotations

import bisect
import threading

from toplingdb_tpu.utils import concurrency as ccy

from toplingdb_tpu.db import dbformat
from toplingdb_tpu.db.dbformat import ValueType

_MAX_PACKED = (1 << 64) - 1


def _sort_key(user_key: bytes, packed: int) -> tuple[bytes, int]:
    # Ascending tuple order == internal key order (seqno/type descending).
    return (user_key, _MAX_PACKED - packed)


class MemTableRep:
    """Pluggable sorted container of ((user_key, inv_packed) -> value) —
    the reference's MemTableRep factory seam (memtablerep.h:64,309), where
    the CSPP-style reps plug in."""

    def insert(self, skey, value: bytes) -> None:
        raise NotImplementedError

    def iter_from(self, skey):
        raise NotImplementedError

    def iter_all(self):
        raise NotImplementedError

    def __len__(self) -> int:
        raise NotImplementedError

    # Positional cursor protocol for MemTableIterator: each method returns
    # an opaque position or None; entry_at(pos) -> (skey, value).
    def pos_first(self):
        raise NotImplementedError

    def pos_last(self):
        raise NotImplementedError

    def pos_seek_ge(self, skey):
        raise NotImplementedError

    def pos_seek_lt(self, skey):
        raise NotImplementedError

    def pos_next(self, pos):
        raise NotImplementedError

    def entry_at(self, pos):
        raise NotImplementedError

    def memory_usage(self) -> int:
        return 0


class NativeSkipListRep(MemTableRep):
    """Arena skiplist in C++ (native/tpulsm_native.cc) — the native memtable
    (reference InlineSkipList / the CSPP seam). Requires the native lib.

    The whole ctypes surface is symbol-parameterized (`_sym`): the trie rep
    below shares every method body, differing only in its native prefix
    and the next() call shape."""

    # tpulsm_db_get probes this rep's handle directly; the kind tells the
    # native side which layout to walk (0 = skiplist, 1 = trie); reps
    # without the attribute are not natively probeable.
    _nget_mem_kind = 0
    _sym = "tpulsm_skiplist"
    _entry_sym = "node"  # {sym}_{entry_sym}(pos, ...) decodes a position

    # Both native reps charge handed-out arena bytes (content + node
    # overhead) to flush/WBM budgets — the reference's physical
    # ApproximateMemoryUsage semantics, and rep-fair flush cadence.
    charge_physical_memory = True

    def __init__(self):
        from toplingdb_tpu import native

        self._l = native.pylib()
        if self._l is None or not hasattr(self._l, self._sym + "_new"):
            raise RuntimeError("native library unavailable")
        self._h = getattr(self._l, self._sym + "_new")()

    def __del__(self):
        if getattr(self, "_h", None):
            getattr(self._l, self._sym + "_free")(self._h)
            self._h = None

    def _next(self, pos):
        return self._l.tpulsm_skiplist_next(pos)

    def insert(self, skey, value: bytes) -> None:
        uk, inv = skey
        getattr(self._l, self._sym + "_insert")(
            self._h, uk, len(uk), inv, value, len(value)
        )

    def insert_wb(self, rep: bytes, first_seq: int):
        """Wire-image batch insert: ONE GIL-releasing native call parses
        the WriteBatch bytes and inserts every point record. Returns
        (count, mem_delta, deletes) or None when the native side can't
        take the batch (no symbol, CF-prefixed/range records, corruption
        → caller falls back)."""
        import ctypes

        from toplingdb_tpu import native

        cl = native.lib()  # CDLL: releases the GIL during the call
        fn = getattr(cl, self._sym + "_insert_wb", None) if cl else None
        if fn is None:
            return None
        out = (ctypes.c_int64 * 2)()
        rc = fn(self._h, rep, len(rep), first_seq, out)
        if rc < 0:
            return None
        return int(rc), int(out[0]), int(out[1])

    def insert_wb_prot(self, rep: bytes, first_seq: int, prots, pb: int):
        """Fused verify+insert: ONE native call re-hashes every counted
        record against the batch's carried protection vector `prots`
        (validation pass — on mismatch NOTHING is inserted and Corruption
        is raised naming the record) then inserts. Returns (count,
        mem_delta, deletes) or None when the native side can't take the
        batch (caller falls back to verify-then-insert as two steps)."""
        import ctypes

        import numpy as np

        from toplingdb_tpu import native

        cl = native.lib()
        fn = getattr(cl, self._sym + "_insert_wb_prot", None) if cl else None
        if fn is None:
            return None
        out = (ctypes.c_int64 * 2)()
        base = getattr(prots, "base", None)
        if isinstance(base, ctypes.Array) and len(base) == len(prots):
            ptr = base  # _native_protect's buffer: no data_as() crossing
        else:
            pv = np.ascontiguousarray(prots, dtype=np.uint64)
            ptr = pv.ctypes.data_as(ctypes.POINTER(ctypes.c_uint64))
        rc = fn(self._h, rep, len(rep), first_seq, ptr,
                len(prots), pb, out)
        if rc <= -5:
            from toplingdb_tpu.utils.status import Corruption

            raise Corruption(
                f"write batch protection mismatch at record {-(rc + 5)} "
                f"during memtable insert"
            )
        if rc < 0:
            return None
        return int(rc), int(out[0]), int(out[1])

    def insert_batch(self, keybuf, key_offs, key_lens, invs,
                     valbuf, val_offs, val_lens, n: int) -> None:
        """Bulk insert from flat numpy buffers — ONE ctypes call with the
        GIL released for the whole loop, so concurrent writer threads run
        truly in parallel."""
        from toplingdb_tpu import native

        cl = native.lib()  # CDLL: releases the GIL during the call
        fn = getattr(cl, self._sym + "_insert_batch", None) if cl else None
        if fn is None:
            for i in range(n):
                o, ln = key_offs[i], key_lens[i]
                vo, vl = val_offs[i], val_lens[i]
                self.insert((keybuf[o:o + ln].tobytes(), int(invs[i])),
                            valbuf[vo:vo + vl].tobytes())
            return
        import ctypes

        u64p = ctypes.POINTER(ctypes.c_uint64)
        fn(
            self._h, native.np_u8p(keybuf), native.np_i64p(key_offs),
            native.np_i32p(key_lens),
            invs.ctypes.data_as(u64p), native.np_u8p(valbuf),
            native.np_i64p(val_offs), native.np_i32p(val_lens), n,
        )

    def __len__(self) -> int:
        return getattr(self._l, self._sym + "_count")(self._h)

    def memory_usage(self) -> int:
        return getattr(self._l, self._sym + "_memory")(self._h)

    def export_columnar(self):
        """Whole-rep ordered export in ONE GIL-releasing native call:
        returns (kv: ColumnarKV with INTERNAL keys, seqs u64, vtypes i32)
        or None when the native symbol is missing. Caller must guarantee
        no concurrent inserts (flush runs on an immutable memtable)."""
        import ctypes

        import numpy as np

        from toplingdb_tpu import native
        from toplingdb_tpu.ops.columnar_io import ColumnarKV

        cl = native.lib()
        fn = getattr(cl, self._sym + "_export", None) if cl else None
        if fn is None:
            return None
        u8p = ctypes.POINTER(ctypes.c_uint8)
        u64p = ctypes.POINTER(ctypes.c_uint64)
        sizes = np.zeros(3, dtype=np.int64)
        rows = fn(
            self._h, ctypes.cast(None, u8p), None, None,
            ctypes.cast(None, u64p), None, ctypes.cast(None, u8p), None,
            None, 0, native.np_i64p(sizes),
        )
        if rows < 0 or sizes[0] > 2 ** 31 - 8 or sizes[1] > 2 ** 31 - 8:
            return None  # int32 ColumnarKV offset budget
        key_buf = np.empty(int(sizes[0]), dtype=np.uint8)
        val_buf = np.empty(int(sizes[1]), dtype=np.uint8)
        # The export fills int64 offsets (matching its C signature); the
        # ColumnarKV convention is int32 — converted after the call.
        key_offs = np.empty(rows, dtype=np.int64)
        key_lens = np.empty(rows, dtype=np.int32)
        val_offs = np.empty(rows, dtype=np.int64)
        val_lens = np.empty(rows, dtype=np.int32)
        seqs = np.empty(rows, dtype=np.uint64)
        vtypes = np.empty(rows, dtype=np.int32)
        got = fn(
            self._h, native.np_u8p(key_buf), native.np_i64p(key_offs),
            native.np_i32p(key_lens), seqs.ctypes.data_as(u64p),
            native.np_i32p(vtypes), native.np_u8p(val_buf),
            native.np_i64p(val_offs), native.np_i32p(val_lens), rows,
            native.np_i64p(sizes),
        )
        if got != rows:
            return None  # concurrent mutation — caller uses the slow path
        kv = ColumnarKV(key_buf, key_offs.astype(np.int32),
                        key_lens, val_buf, val_offs.astype(np.int32),
                        val_lens)
        return kv, seqs, vtypes

    def _node_entry(self, node):
        import ctypes

        kptr = ctypes.c_void_p()
        klen = ctypes.c_uint32()
        inv = ctypes.c_uint64()
        vptr = ctypes.c_void_p()
        vlen = ctypes.c_uint32()
        getattr(self._l, f"{self._sym}_{self._entry_sym}")(
            node, ctypes.byref(kptr), ctypes.byref(klen), ctypes.byref(inv),
            ctypes.byref(vptr), ctypes.byref(vlen),
        )
        uk = ctypes.string_at(kptr, klen.value)
        val = ctypes.string_at(vptr, vlen.value)
        return (uk, inv.value), val

    def iter_from(self, skey):
        uk, inv = skey
        node = getattr(self._l, self._sym + "_seek_ge")(
            self._h, uk, len(uk), inv)
        while node:
            yield self._node_entry(node)
            node = self._next(node)

    def iter_all(self):
        node = getattr(self._l, self._sym + "_first")(self._h)
        while node:
            yield self._node_entry(node)
            node = self._next(node)

    def pos_first(self):
        return getattr(self._l, self._sym + "_first")(self._h) or None

    def pos_last(self):
        return getattr(self._l, self._sym + "_last")(self._h) or None

    def pos_seek_ge(self, skey):
        uk, inv = skey
        return getattr(self._l, self._sym + "_seek_ge")(
            self._h, uk, len(uk), inv) or None

    def pos_seek_lt(self, skey):
        uk, inv = skey
        return getattr(self._l, self._sym + "_seek_lt")(
            self._h, uk, len(uk), inv) or None

    def pos_next(self, pos):
        return self._next(pos) or None

    def entry_at(self, pos):
        return self._node_entry(pos)


class NativeTrieRep(NativeSkipListRep):
    """Adaptive-radix-trie memtable in C++ — the CSPP role (reference
    README.md:50: Topling's Crash-Safe Parallel Patricia trie, the 45M
    ops/s write-path headline; factory seam memtablerep.h:309). Original
    design: 257 first-byte-striped ART roots (4/16/48/256-way nodes, path
    compression), per-stripe mutexes so concurrent writers on different
    key regions never contend; versions hang off one leaf per user key
    as release-published atomic lists (lockless readers)."""


    _nget_mem_kind = 1  # TrieRep* layout
    _sym = "tpulsm_trie"
    _entry_sym = "ver"

    def _next(self, pos):
        # The trie successor re-descends from the root: needs the handle.
        return self._l.tpulsm_trie_next(self._h, pos)


class PyVectorRep(MemTableRep):
    """Entries are stored as single (sort_key, value) tuples in ONE list so
    every insert is a single list mutation — atomic under the GIL — and
    lockless readers can never observe a key paired with the wrong value."""

    def __init__(self):
        self._items: list[tuple[tuple[bytes, int], bytes]] = []

    def insert(self, skey, value: bytes) -> None:
        i = bisect.bisect_left(self._items, skey, key=lambda it: it[0])
        if i < len(self._items) and self._items[i][0] == skey:
            # Same (user_key, seqno, type) re-inserted (WAL replay): last wins.
            self._items[i] = (skey, value)
            return
        self._items.insert(i, (skey, value))

    def iter_from(self, skey):
        i = bisect.bisect_left(self._items, skey, key=lambda it: it[0])
        while i < len(self._items):
            yield self._items[i]
            i += 1

    def iter_all(self):
        yield from self._items

    def __len__(self) -> int:
        return len(self._items)

    # Positions are sort keys (re-bisected per step): list shifts from
    # concurrent inserts cannot skip or repeat entries.
    def _at(self, i: int):
        return self._items[i][0] if 0 <= i < len(self._items) else None

    def pos_first(self):
        return self._at(0)

    def pos_last(self):
        return self._at(len(self._items) - 1)

    def pos_seek_ge(self, skey):
        return self._at(bisect.bisect_left(self._items, skey, key=lambda e: e[0]))

    def pos_seek_lt(self, skey):
        return self._at(bisect.bisect_left(self._items, skey, key=lambda e: e[0]) - 1)

    def pos_next(self, pos):
        return self._at(bisect.bisect_right(self._items, pos, key=lambda e: e[0]))

    def entry_at(self, pos):
        # bisect + index are two steps; a concurrent insert between them can
        # shift the list. Entries are never removed, so re-checking the key
        # and re-bisecting converges.
        while True:
            i = bisect.bisect_left(self._items, pos, key=lambda e: e[0])
            entry = self._items[i]
            if entry[0] == pos:
                return entry


class HashPrefixRep(MemTableRep):
    """Prefix-bucketed rep (reference HashSkipListRep / HashLinkListRep,
    memtable/hash_skiplist_rep.cc:22, hash_linklist_rep.cc:160): entries
    bucket by the user key's leading `prefix_len` bytes, so point lookups
    touch one small bucket. Because the bucket key is a LEADING slice of the
    sort key, buckets are contiguous spans of the global order — full
    iteration is sorted-bucket concatenation, not an N-way merge."""

    def __init__(self, prefix_len: int = 8):
        self._plen = prefix_len
        self._buckets: dict[bytes, PyVectorRep] = {}
        # Only WRITERS (serialized by the memtable write lock) replace this
        # list, and they swap in a fully-built one — lockless readers always
        # see a consistent snapshot and never mutate shared state.
        self._sorted: list[bytes] = []
        self._n = 0

    def _pfx(self, skey) -> bytes:
        return skey[0][: self._plen]

    def _prefixes(self) -> list[bytes]:
        return self._sorted

    def insert(self, skey, value: bytes) -> None:
        p = self._pfx(skey)
        b = self._buckets.get(p)
        if b is None:
            b = self._buckets[p] = PyVectorRep()
            self._sorted = sorted(self._buckets)  # atomic swap for readers
        before = len(b)
        b.insert(skey, value)
        self._n += len(b) - before

    def iter_from(self, skey):
        sp = self._prefixes()
        p = self._pfx(skey)
        i = bisect.bisect_left(sp, p)
        if i < len(sp) and sp[i] == p:
            yield from self._buckets[p].iter_from(skey)
            i += 1
        for j in range(i, len(sp)):
            yield from self._buckets[sp[j]].iter_all()

    def iter_all(self):
        for p in self._prefixes():
            yield from self._buckets[p].iter_all()

    def __len__(self) -> int:
        return self._n

    def pos_first(self):
        for p in self._prefixes():
            pos = self._buckets[p].pos_first()
            if pos is not None:
                return pos
        return None

    def pos_last(self):
        for p in reversed(self._prefixes()):
            pos = self._buckets[p].pos_last()
            if pos is not None:
                return pos
        return None

    def pos_seek_ge(self, skey):
        sp = self._prefixes()
        p = self._pfx(skey)
        i = bisect.bisect_left(sp, p)
        while i < len(sp):
            b = self._buckets[sp[i]]
            pos = b.pos_seek_ge(skey) if sp[i] == p else b.pos_first()
            if pos is not None:
                return pos
            i += 1
        return None

    def pos_seek_lt(self, skey):
        sp = self._prefixes()
        p = self._pfx(skey)
        i = bisect.bisect_left(sp, p)
        if i < len(sp) and sp[i] == p:
            pos = self._buckets[p].pos_seek_lt(skey)
            if pos is not None:
                return pos
        i -= 1
        while i >= 0:
            pos = self._buckets[sp[i]].pos_last()
            if pos is not None:
                return pos
            i -= 1
        return None

    def pos_next(self, pos):
        p = self._pfx(pos)
        nxt = self._buckets[p].pos_next(pos)
        if nxt is not None:
            return nxt
        sp = self._prefixes()
        i = bisect.bisect_right(sp, p)
        while i < len(sp):
            q = self._buckets[sp[i]].pos_first()
            if q is not None:
                return q
            i += 1
        return None

    def entry_at(self, pos):
        return self._buckets[self._pfx(pos)].entry_at(pos)

    def memory_usage(self) -> int:
        return sum(b.memory_usage() for b in self._buckets.values())


def create_memtable_rep(name: str) -> MemTableRep:
    """Factory seam (reference memtablerep.h:309):
    'vector' | 'skiplist' | 'hash_skiplist'."""
    if name == "vector":
        return PyVectorRep()
    if name == "skiplist":
        try:
            return NativeSkipListRep()
        except RuntimeError:
            return PyVectorRep()  # no toolchain: degrade gracefully
    if name in ("cspp", "trie", "patricia"):
        # The CSPP-role trie rep (reference README.md:50); degrades to the
        # skiplist chain when the native lib is unavailable.
        try:
            return NativeTrieRep()
        except RuntimeError:
            return create_memtable_rep("skiplist")
    if name in ("hash_skiplist", "hash_linklist", "prefix_hash"):
        return HashPrefixRep()
    from toplingdb_tpu.utils.status import InvalidArgument

    if name.startswith(("hash_skiplist:", "hash_linklist:", "prefix_hash:")):
        # 'hash_skiplist:N' buckets by an N-byte prefix (matches a
        # FixedPrefixTransform(N) CF extractor).
        try:
            plen = int(name.split(":", 1)[1])
        except ValueError as e:
            raise InvalidArgument(f"bad memtable rep prefix len in {name!r}") from e
        if plen <= 0:
            raise InvalidArgument(f"memtable rep prefix len must be positive: {name!r}")
        return HashPrefixRep(prefix_len=plen)
    raise InvalidArgument(f"unknown memtable rep {name!r}")


class MemTable:
    def __init__(self, icmp: dbformat.InternalKeyComparator,
                 rep: MemTableRep | None = None,
                 protection_bytes: int = 0):
        self._icmp = icmp
        self._rep = rep if rep is not None else PyVectorRep()
        self._range_dels: list[tuple[int, bytes, bytes]] = []  # (seq, begin, end)
        self._mem_usage = 0
        self._num_entries = 0
        self._num_deletes = 0
        self._first_seqno: int | None = None
        self._lock = ccy.Lock("memtable.MemTable._lock")
        self.mem_id = 0
        # Per-entry protection carry (reference memtable KV checksums,
        # db/kv_checksum.h): CF-stripped truncated checksums keyed by the
        # rep's sort key, verified when flush re-reads the entry out of
        # the (native) rep — the memtable->flush handoff check.
        self.protection_bytes = protection_bytes
        self._prot: dict | None = {} if protection_bytes else None
        self._rd_prot: dict | None = {} if protection_bytes else None
        # Wire-image inserts defer per-record bookkeeping: (first_seq,
        # rep, prots) tuples drain into _prot lazily at the first flush
        # lookup (_drain_prot_pending) — the write path stays native.
        self._prot_pending: list = []

    # ------------------------------------------------------------------

    def add(self, seq: int, t: int, user_key: bytes, value: bytes,
            prot: int | None = None) -> None:
        with self._lock:
            if t == ValueType.RANGE_DELETION:
                if self._icmp.user_comparator.compare(user_key, value) >= 0:
                    # Empty range [begin >= end): deletes nothing, and a
                    # memtable holding ONLY degenerate tombstones would
                    # otherwise flush a boundless empty table.
                    return
                self._range_dels.append((seq, user_key, value))
                if self._rd_prot is not None:
                    self._rd_prot[(seq, user_key, value)] = \
                        self._entry_prot(t, user_key, value, prot)
            else:
                packed = dbformat.pack_seq_type(seq, t)
                skey = _sort_key(user_key, packed)
                self._rep.insert(skey, value)
                if self._prot is not None:
                    self._prot[skey] = self._entry_prot(
                        t, user_key, value, prot)
            self._num_entries += 1
            if t in (ValueType.DELETION, ValueType.SINGLE_DELETION):
                self._num_deletes += 1
            self._mem_usage += len(user_key) + len(value) + 24
            if self._first_seqno is None:
                self._first_seqno = seq

    def _entry_prot(self, t: int, user_key: bytes, value: bytes,
                    prot: int | None) -> int:
        """The CF-stripped truncated checksum to carry: the one handed
        down by WriteBatch.insert_into (already verified there), or a
        fresh one for direct add() callers."""
        if prot is not None:
            return prot
        from toplingdb_tpu.utils import protection as _p

        return _p.truncate(_p.protect_entry(int(t), user_key, value),
                           self.protection_bytes)

    def add_encoded(self, first_seq: int, rep: bytes,
                    prots=None, pb: int = 0) -> int | None:
        """Apply a whole WriteBatch wire image in one native call (the
        WriteBatchInternal::InsertInto hot loop with zero per-record
        Python). Returns the count applied, or None when the native fast
        path can't take it (caller uses the parsed path). Thread-safe
        against concurrent add/add_batch/add_encoded callers.

        Protected memtables take this path too when the caller hands the
        batch's CF-stripped checksums: the (rep, prots) pair parks in
        _prot_pending and drains into the per-entry map lazily at flush,
        keeping the write path native. With pb > 0 the checksums are NOT
        yet verified — the fused native call (insert_wb_prot) re-hashes
        every record against them in its validation pass and raises
        Corruption (nothing inserted) on the first mismatch; pb == 0
        means the caller already verified them."""
        if self._prot is not None and prots is None:
            return None  # nothing to carry: the parsed path computes them
        if prots is not None and pb:
            wbp = getattr(self._rep, "insert_wb_prot", None)
            if wbp is None:
                return None
            res = wbp(rep, first_seq, prots, pb)  # raises on mismatch
        else:
            wb = getattr(self._rep, "insert_wb", None)
            if wb is None:
                return None
            res = wb(rep, first_seq)
        if res is None:
            return None
        count, delta, deletes = res
        with self._lock:
            if self._prot is not None:
                self._prot_pending.append((first_seq, rep, prots))
            self._num_entries += count
            self._num_deletes += deletes
            self._mem_usage += delta
            if self._first_seqno is None:
                self._first_seqno = first_seq
        return count

    def group_handle(self):
        """(native_rep_handle, kind) for the fused group-commit plane
        (db.py _native_group_commit; kind 0 = skiplist, 1 = trie), or None
        when this rep has no native handle (pure-Python reps)."""
        rep = self._rep
        kind = getattr(rep, "_nget_mem_kind", None)
        h = getattr(rep, "_h", None)
        if kind is None or not h:
            return None
        return h, kind

    def note_group_applied(self, entries_meta, mem_delta: int,
                           deletes: int, total: int) -> None:
        """Bookkeeping for a whole write group the native plane already
        applied straight into the rep (tpulsm_wb_group_commit):
        entries_meta is [(first_seq, rep_bytes, prots_or_None)] per member
        batch — protected members park in _prot_pending exactly like
        add_encoded's wire-image deferral, so flush verification sees the
        same carried checksums either way."""
        with self._lock:
            if self._prot is not None:
                for fs, rep, prots in entries_meta:
                    self._prot_pending.append((fs, rep, prots))
            self._num_entries += total
            self._num_deletes += deletes
            self._mem_usage += mem_delta
            if self._first_seqno is None and entries_meta:
                self._first_seqno = entries_meta[0][0]

    def add_batch(self, first_seq: int, ops, prots=None) -> int:
        """Apply a run of parsed ops [(type, key, value_or_None)] with
        consecutive seqnos starting at first_seq (reference
        WriteBatchInternal::InsertInto driving InsertConcurrently). With the
        native skiplist rep the point inserts happen in ONE GIL-releasing
        native call; thread-safe against concurrent add/add_batch callers.
        `prots`, when given, carries one CF-stripped protection checksum
        per op (WriteBatch.insert_into already verified them).
        Returns the number of sequence numbers consumed (== len(ops))."""
        n = len(ops)
        rep_batch = getattr(self._rep, "insert_batch", None)
        if rep_batch is None or n < 4:
            for i, (t, k, v) in enumerate(ops):
                self.add(first_seq + i, t, k, v if v is not None else b"",
                         prot=prots[i] if prots is not None else None)
            return n
        import numpy as np

        points = []   # (seq, t, k, v) point ops, in order
        mem_delta = 0
        deletes = 0
        with self._lock:
            for i, (t, k, v) in enumerate(ops):
                seq = first_seq + i
                v = v if v is not None else b""
                if t == ValueType.RANGE_DELETION:
                    if self._icmp.user_comparator.compare(k, v) >= 0:
                        continue
                    self._range_dels.append((seq, k, v))
                    if self._rd_prot is not None:
                        self._rd_prot[(seq, k, v)] = self._entry_prot(
                            t, k, v,
                            prots[i] if prots is not None else None)
                else:
                    points.append((seq, t, k, v))
                    if self._prot is not None:
                        self._prot[_sort_key(
                            k, dbformat.pack_seq_type(seq, t))] = \
                            self._entry_prot(
                                t, k, v,
                                prots[i] if prots is not None else None)
                if t in (ValueType.DELETION, ValueType.SINGLE_DELETION):
                    deletes += 1
                mem_delta += len(k) + len(v) + 24
            self._num_entries += n
            self._num_deletes += deletes
            self._mem_usage += mem_delta
            if self._first_seqno is None:
                self._first_seqno = first_seq
        if not points:
            return n
        m = len(points)
        key_lens = np.fromiter((len(p[2]) for p in points), np.int32, m)
        val_lens = np.fromiter((len(p[3]) for p in points), np.int32, m)
        key_offs = np.zeros(m, np.int64)
        val_offs = np.zeros(m, np.int64)
        np.cumsum(key_lens[:-1], out=key_offs[1:])
        np.cumsum(val_lens[:-1], out=val_offs[1:])
        keybuf = np.frombuffer(
            b"".join(p[2] for p in points), np.uint8).copy()
        valbuf = np.frombuffer(
            b"".join(p[3] for p in points), np.uint8).copy()
        invs = np.fromiter(
            (_MAX_PACKED - dbformat.pack_seq_type(p[0], p[1])
             for p in points), np.uint64, m)
        # Outside self._lock: the native rep is internally thread-safe, so
        # concurrent groups' inserts overlap GIL-free.
        rep_batch(keybuf, key_offs, key_lens, invs,
                  valbuf, val_offs, val_lens, m)
        return n

    def export_columnar(self):
        """Columnar flush fast path: ordered (kv, seqs, vtypes) of every
        POINT entry in one native call (range tombstones are stored aside —
        read them via range_del_entries). None when the rep can't bulk
        export; callers fall back to the per-entry iterator."""
        exp = getattr(self._rep, "export_columnar", None)
        return exp() if exp is not None else None

    def _drain_prot_pending(self) -> None:
        """Materialize checksums parked by wire-image inserts into the
        per-entry map (flush-time only: the cold side of the deferral)."""
        with self._lock:
            pending, self._prot_pending = self._prot_pending, []
        if not pending:
            return
        from toplingdb_tpu.db.write_batch import WriteBatch

        for first_seq, rep, prots in pending:
            seq = first_seq
            for i, (t, k, _v) in enumerate(WriteBatch(rep).entries()):
                self._prot[_sort_key(
                    k, dbformat.pack_seq_type(seq + i, t))] = prots[i]

    def protection_map(self) -> dict | None:
        """The fully materialized per-entry checksum map (None when this
        memtable is unprotected) — the flush handoff's reference side."""
        if self._prot is None:
            return None
        self._drain_prot_pending()
        return self._prot

    def protection_aggregate(self) -> tuple[int, int] | None:
        """(count, xor) over every carried point-entry checksum WITHOUT
        parsing the pending wire images — the O(entries) integer fold the
        columnar flush compares against tpulsm_columnar_protect's export
        aggregate. Duplicate replayed entries (WAL recovery) make the
        pending count overshoot the deduplicated rep; callers treat any
        mismatch as "fall back to the per-entry map", never as proof of
        corruption on its own."""
        if self._prot is None:
            return None
        import numpy as np

        with self._lock:
            pending = list(self._prot_pending)
            acc = 0
            cnt = len(self._prot)
            for v in self._prot.values():
                acc ^= int(v)
        for _seq, _rep, prots in pending:
            cnt += len(prots)
            if isinstance(prots, np.ndarray):
                if len(prots):
                    acc ^= int(np.bitwise_xor.reduce(prots))
            else:
                for p in prots:
                    acc ^= int(p)
        return cnt, acc

    def stored_protection(self, user_key: bytes, seq: int, t: int):
        """The carried protection checksum for one point entry, or None
        (unprotected memtable / unknown entry — flush treats 'unknown'
        as corruption when protection is on)."""
        if self._prot is None:
            return None
        if self._prot_pending:
            self._drain_prot_pending()
        return self._prot.get(
            _sort_key(user_key, dbformat.pack_seq_type(seq, t)))

    def stored_rd_protection(self, seq: int, begin: bytes, end: bytes):
        if self._rd_prot is None:
            return None
        return self._rd_prot.get((seq, begin, end))

    def entries_for_key(self, user_key: bytes, snapshot_seq: int):
        """Yield (seq, type, value) for user_key with seq <= snapshot,
        newest first — the feed for GetContext."""
        start = _sort_key(user_key, dbformat.pack_seq_type(snapshot_seq, 0xFF))
        for (uk, inv), val in self._rep.iter_from(start):
            if uk != user_key:
                break
            seq, t = dbformat.unpack_seq_type(_MAX_PACKED - inv)
            if seq > snapshot_seq:
                continue
            yield seq, t, val

    def covering_tombstone_seq(self, user_key: bytes, snapshot_seq: int) -> int:
        """Max seqno of a range tombstone covering user_key at the snapshot
        (0 = none)."""
        best = 0
        ucmp = self._icmp.user_comparator
        for seq, begin, end in self._range_dels:
            if seq <= snapshot_seq and ucmp.compare(begin, user_key) <= 0 \
                    and ucmp.compare(user_key, end) < 0:
                best = max(best, seq)
        return best

    # ------------------------------------------------------------------

    def iter_entries(self):
        """Yields (internal_key, value) in internal key order (point entries
        only; range tombstones via range_del_entries)."""
        for (uk, inv), val in self._rep.iter_all():
            seq, t = dbformat.unpack_seq_type(_MAX_PACKED - inv)
            yield dbformat.make_internal_key(uk, seq, t), val

    def iter_from(self, ikey: bytes):
        uk, seq, t = dbformat.split_internal_key(ikey)
        start = _sort_key(uk, dbformat.pack_seq_type(seq, t))
        for (k, inv), val in self._rep.iter_from(start):
            s, tt = dbformat.unpack_seq_type(_MAX_PACKED - inv)
            yield dbformat.make_internal_key(k, s, tt), val

    def range_del_entries(self):
        """Yields (seq, begin_user_key, end_user_key)."""
        yield from self._range_dels

    # ------------------------------------------------------------------

    def new_iterator(self) -> "MemTableIterator":
        return MemTableIterator(self)

    def approximate_memory_usage(self) -> int:
        # Native reps (skiplist AND trie) charge PHYSICAL handed-out
        # arena bytes — the reference's ApproximateMemoryUsage semantics
        # — so write_buffer_size / WriteBufferManager see real footprint
        # (node towers, version lists). Pure-Python reps keep the
        # logical len+24 estimate.
        if getattr(self._rep, "charge_physical_memory", False):
            rep_mem = self._rep.memory_usage()
            if rep_mem > self._mem_usage:
                return rep_mem
        return self._mem_usage

    @property
    def num_entries(self) -> int:
        return self._num_entries

    @property
    def num_deletes(self) -> int:
        return self._num_deletes

    @property
    def first_seqno(self):
        return self._first_seqno

    def empty(self) -> bool:
        return self._num_entries == 0


class MemTableIterator:
    """Standard iterator protocol over a memtable's point entries, built on
    the rep's positional cursor protocol — works over both the Python vector
    rep and the native C++ skiplist.

    Tolerates concurrent inserts: vector-rep positions are sort keys
    (re-bisected per step, the Python analogue of iterating a lock-free
    skiplist); native skiplist nodes are stable arena pointers."""

    def __init__(self, mem: MemTable):
        self._rep = mem._rep
        self._pos = None
        self._entry = None

    def _set(self, pos) -> None:
        self._pos = pos
        self._entry = self._rep.entry_at(pos) if pos is not None else None

    def valid(self) -> bool:
        return self._entry is not None

    def key(self) -> bytes:
        uk, inv = self._entry[0]
        seq, t = dbformat.unpack_seq_type(_MAX_PACKED - inv)
        return dbformat.make_internal_key(uk, seq, t)

    def value(self) -> bytes:
        return self._entry[1]

    def seek_to_first(self) -> None:
        self._set(self._rep.pos_first())

    def seek_to_last(self) -> None:
        self._set(self._rep.pos_last())

    def seek(self, ikey: bytes) -> None:
        uk, seq, t = dbformat.split_internal_key(ikey)
        self._set(self._rep.pos_seek_ge(
            _sort_key(uk, dbformat.pack_seq_type(seq, t))
        ))

    def seek_for_prev(self, ikey: bytes) -> None:
        uk, seq, t = dbformat.split_internal_key(ikey)
        skey = _sort_key(uk, dbformat.pack_seq_type(seq, t))
        pos = self._rep.pos_seek_ge(skey)
        if pos is not None and self._rep.entry_at(pos)[0] == skey:
            self._set(pos)
        else:
            self._set(self._rep.pos_seek_lt(skey))

    def next(self) -> None:
        assert self.valid()
        self._set(self._rep.pos_next(self._pos))

    def prev(self) -> None:
        assert self.valid()
        self._set(self._rep.pos_seek_lt(self._entry[0]))
