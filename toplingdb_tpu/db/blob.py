"""Integrated blob files: key-value separation for large values.

Reference db/blob/* in /root/reference (BlobFileBuilder/Reader/Source,
BlobIndex): values >= min_blob_size are written to .blob files at flush; the
LSM keeps a BLOB_INDEX entry pointing at (file, offset, size). Each SST's
FileMetaData carries the set of blob files it references (blob_refs), so
obsolete-file GC can delete unreferenced blob files, and compaction-time
blob GC (reference blob_garbage_collection_age_cutoff + BlobCountingIterator)
rewrites survivors out of the oldest referenced blob files via
BlobGarbageCollector.

Blob file format:
  header:  magic "TPULSMBL" (8B)
  record:  varint32 key_len | varint32 val_len | key | value |
           fixed32 masked_crc32c(value)
"""

from __future__ import annotations

import os

from toplingdb_tpu.utils import coding, crc32c
from toplingdb_tpu.utils.status import Corruption
from toplingdb_tpu.utils import errors as _errors

MAGIC = b"TPULSMBL"


def blob_file_name(dbname: str, number: int) -> str:
    return os.path.join(dbname, f"{number:06d}.blob")


def encode_blob_index(file_number: int, offset: int, size: int) -> bytes:
    return (coding.encode_varint64(file_number)
            + coding.encode_varint64(offset)
            + coding.encode_varint64(size))


def decode_blob_index(data: bytes) -> tuple[int, int, int]:
    fn, off = coding.decode_varint64(data, 0)
    offset, off = coding.decode_varint64(data, off)
    size, off = coding.decode_varint64(data, off)
    return fn, offset, size


class BlobFileBuilder:
    """Writes one blob file; returns a BLOB_INDEX payload per value."""

    def __init__(self, env, dbname: str, file_number: int):
        self.file_number = file_number
        self._path = blob_file_name(dbname, file_number)
        self._f = env.new_writable_file(self._path)
        self._f.append(MAGIC)
        self.num_values = 0

    def add(self, key: bytes, value: bytes) -> bytes:
        offset = self._f.file_size()
        rec = bytearray()
        rec += coding.encode_varint32(len(key))
        rec += coding.encode_varint32(len(value))
        rec += key
        rec += value
        rec += coding.encode_fixed32(crc32c.mask(crc32c.value(value)))
        self._f.append(bytes(rec))
        self.num_values += 1
        return encode_blob_index(
            self.file_number, offset, self._f.file_size() - offset
        )

    def finish(self) -> int:
        """Sync + close; returns number of values (0 = caller may delete)."""
        if self.num_values:
            self._f.sync()
        self._f.close()
        return self.num_values


class BlobFileReader:
    def __init__(self, env, dbname: str, file_number: int):
        self._f = env.new_random_access_file(blob_file_name(dbname, file_number))
        if self._f.read(0, len(MAGIC)) != MAGIC:
            raise Corruption(f"bad blob file magic in {file_number}")

    def get(self, offset: int, size: int, verify: bool = True) -> bytes:
        rec = self._f.read(offset, size)
        if len(rec) != size:
            raise Corruption("truncated blob record")
        klen, off = coding.decode_varint32(rec, 0)
        vlen, off = coding.decode_varint32(rec, off)
        off += klen
        value = bytes(rec[off : off + vlen])
        if verify:
            stored = crc32c.unmask(coding.decode_fixed32(rec, off + vlen))
            if crc32c.value(value) != stored:
                raise Corruption("blob value checksum mismatch")
        return value

    def close(self) -> None:
        self._f.close()


class BlobSource:
    """The blob read tier (reference db/blob/blob_source.{h,cc} +
    blob_file_cache.cc): an LRU-capped cache of OPEN blob readers plus an
    optional shared VALUE cache, so hot blob workloads stop re-reading
    files on every Get. Thread-safe. Statistics: BLOB_DB_CACHE_HIT/MISS/
    BYTES, BLOB_DB_BLOB_FILE_BYTES_READ, BLOB_DB_NUM_KEYS_READ."""

    def __init__(self, env, dbname: str, blob_cache=None,
                 open_limit: int = 256, statistics=None):
        from toplingdb_tpu.utils import concurrency as ccy
        from collections import OrderedDict

        self._env = env
        self._dbname = dbname
        self._readers: "OrderedDict[int, BlobFileReader]" = OrderedDict()
        self._open_limit = max(1, int(open_limit))
        self._mu = ccy.Lock("blob.BlobSource._mu")
        self.stats = statistics
        if isinstance(blob_cache, int):
            from toplingdb_tpu.utils.cache import LRUCache

            blob_cache = LRUCache(blob_cache) if blob_cache > 0 else None
        self._cache = blob_cache

    def _reader(self, fn: int) -> BlobFileReader:
        with self._mu:
            r = self._readers.get(fn)
            if r is not None:
                self._readers.move_to_end(fn)
                return r
        r = BlobFileReader(self._env, self._dbname, fn)
        with self._mu:
            existing = self._readers.get(fn)
            if existing is not None:
                r.close()  # lost the open race; ours was never shared
                return existing
            self._readers[fn] = r
            while len(self._readers) > self._open_limit:
                # DROP the evicted reader without closing: another thread
                # may be mid-read on it (the lock is released before the
                # pread). The file object closes when its last reference
                # dies — the LRU only bounds the set WE keep alive.
                self._readers.popitem(last=False)
        return r

    def get(self, blob_index: bytes, verify: bool = True) -> bytes:
        from toplingdb_tpu.utils import statistics as st

        fn, offset, size = decode_blob_index(blob_index)
        s = self.stats
        if s is not None:
            s.record_tick(st.BLOB_DB_NUM_KEYS_READ)
        cache = self._cache
        if cache is not None:
            ck = blob_index if isinstance(blob_index, bytes) \
                else bytes(blob_index)
            v = cache.lookup(ck)
            if v is not None:
                if s is not None:
                    s.record_ticks(((st.BLOB_DB_CACHE_HIT, 1),
                                    (st.BLOB_DB_CACHE_BYTES_READ, len(v)),
                                    (st.BLOB_DB_BYTES_READ, len(v))))
                return v
            if s is not None:
                s.record_tick(st.BLOB_DB_CACHE_MISS)
        value = self._reader(fn).get(offset, size, verify)
        if s is not None:
            s.record_ticks(((st.BLOB_DB_BLOB_FILE_BYTES_READ, size),
                            (st.BLOB_DB_BYTES_READ, len(value))))
        if cache is not None:
            cache.insert(ck, value, len(value))
            if s is not None:
                s.record_tick(st.BLOB_DB_CACHE_BYTES_WRITE, len(value))
        return value

    def evict(self, file_number: int) -> None:
        with self._mu:
            r = self._readers.pop(file_number, None)
        if r is not None:
            r.close()

    def close(self) -> None:
        with self._mu:
            readers = list(self._readers.values())
            self._readers.clear()
        for r in readers:
            r.close()


class BlobGarbageCollector:
    """Compaction-time blob GC (reference
    blob_garbage_collection_age_cutoff semantics,
    db/blob/blob_file_builder.cc + compaction GC wiring): given the blob
    files referenced by the compaction's inputs, the oldest `age_cutoff`
    fraction are GC targets. Surviving entries pointing into a target file
    have their values resolved and rewritten — into a fresh blob file when
    still >= min_blob_size, inline otherwise — so the old file's reference
    count drains and obsolete-file GC reclaims it."""

    def __init__(self, env, dbname: str, input_blob_refs: list[int],
                 age_cutoff: float, min_blob_size: int, blob_resolver,
                 new_file_number):
        import math

        refs = sorted(set(input_blob_refs))
        n_gc = min(len(refs), math.ceil(len(refs) * age_cutoff))
        self.gc_files = set(refs[:n_gc])  # oldest fraction by file number
        self._env = env
        self._dbname = dbname
        self._min_blob_size = min_blob_size
        self._resolver = blob_resolver
        self._new_file_number = new_file_number
        self._builder: BlobFileBuilder | None = None
        self.new_blob_file: int | None = None
        self.rewritten = 0
        self.inlined = 0

    @property
    def active(self) -> bool:
        return bool(self.gc_files)

    def rewrite(self, stream):
        """Map a survivor (internal_key, value) stream, rewriting blob
        indexes that point into GC-target files."""
        from toplingdb_tpu.db import dbformat

        bi = dbformat.ValueType.BLOB_INDEX
        for ikey, value in stream:
            if ikey[-8] == bi:
                fn, _, _ = decode_blob_index(value)
                if fn in self.gc_files:
                    uk, seq, _ = dbformat.split_internal_key(ikey)
                    raw = self._resolver(value)
                    if len(raw) >= self._min_blob_size:
                        if self._builder is None:
                            self.new_blob_file = self._new_file_number()
                            self._builder = BlobFileBuilder(
                                self._env, self._dbname, self.new_blob_file
                            )
                        value = self._builder.add(uk, raw)
                        self.rewritten += 1
                    else:
                        ikey = dbformat.make_internal_key(
                            uk, seq, dbformat.ValueType.VALUE
                        )
                        value = raw
                        self.inlined += 1
            yield ikey, value

    def finish(self) -> None:
        """Close the output blob file (delete if nothing was written)."""
        if self._builder is None:
            return
        if self._builder.finish() == 0:
            try:
                self._env.delete_file(
                    blob_file_name(self._dbname, self.new_blob_file)
                )
            except Exception as e:
                _errors.swallow(reason="blob-empty-file-delete", exc=e)
            self.new_blob_file = None
        self._builder = None

    def abort(self) -> None:
        """Failed compaction: close and delete the half-written output blob
        file (its pointers were never installed in any SST)."""
        if self._builder is None:
            return
        self._builder.finish()
        try:
            self._env.delete_file(
                blob_file_name(self._dbname, self.new_blob_file)
            )
        except Exception as e:
            _errors.swallow(reason="blob-abort-delete", exc=e)
        self.new_blob_file = None
        self._builder = None


def maybe_new_blob_gc(db, compaction, new_file_number):
    """Shared constructor for the compaction-time collector (used by the
    local scheduler AND the device executor so the eligibility policy can't
    diverge): None unless GC is enabled and the inputs reference blob
    files."""
    opts = db.options
    if not opts.enable_blob_garbage_collection:
        return None
    refs = [fn for _, f in compaction.all_inputs() for fn in f.blob_refs]
    if not refs:
        return None
    return BlobGarbageCollector(
        db.env, db.dbname, refs, opts.blob_garbage_collection_age_cutoff,
        opts.min_blob_size, db.blob_source.get, new_file_number,
    )
