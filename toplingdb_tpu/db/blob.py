"""Integrated blob files: key-value separation for large values.

Reference db/blob/* in /root/reference (BlobFileBuilder/Reader/Source,
BlobIndex): values >= min_blob_size are written to .blob files at flush; the
LSM keeps a BLOB_INDEX entry pointing at (file, offset, size). Compaction
passes blob indexes through untouched (blob GC is a later-round item; unknown
file types are never deleted by obsolete-file GC, so blob files are safe).

Blob file format:
  header:  magic "TPULSMBL" (8B)
  record:  varint32 key_len | varint32 val_len | key | value |
           fixed32 masked_crc32c(value)
"""

from __future__ import annotations

import os

from toplingdb_tpu.utils import coding, crc32c
from toplingdb_tpu.utils.status import Corruption

MAGIC = b"TPULSMBL"


def blob_file_name(dbname: str, number: int) -> str:
    return os.path.join(dbname, f"{number:06d}.blob")


def encode_blob_index(file_number: int, offset: int, size: int) -> bytes:
    return (coding.encode_varint64(file_number)
            + coding.encode_varint64(offset)
            + coding.encode_varint64(size))


def decode_blob_index(data: bytes) -> tuple[int, int, int]:
    fn, off = coding.decode_varint64(data, 0)
    offset, off = coding.decode_varint64(data, off)
    size, off = coding.decode_varint64(data, off)
    return fn, offset, size


class BlobFileBuilder:
    """Writes one blob file; returns a BLOB_INDEX payload per value."""

    def __init__(self, env, dbname: str, file_number: int):
        self.file_number = file_number
        self._path = blob_file_name(dbname, file_number)
        self._f = env.new_writable_file(self._path)
        self._f.append(MAGIC)
        self.num_values = 0

    def add(self, key: bytes, value: bytes) -> bytes:
        offset = self._f.file_size()
        rec = bytearray()
        rec += coding.encode_varint32(len(key))
        rec += coding.encode_varint32(len(value))
        rec += key
        rec += value
        rec += coding.encode_fixed32(crc32c.mask(crc32c.value(value)))
        self._f.append(bytes(rec))
        self.num_values += 1
        return encode_blob_index(
            self.file_number, offset, self._f.file_size() - offset
        )

    def finish(self) -> int:
        """Sync + close; returns number of values (0 = caller may delete)."""
        if self.num_values:
            self._f.sync()
        self._f.close()
        return self.num_values


class BlobFileReader:
    def __init__(self, env, dbname: str, file_number: int):
        self._f = env.new_random_access_file(blob_file_name(dbname, file_number))
        if self._f.read(0, len(MAGIC)) != MAGIC:
            raise Corruption(f"bad blob file magic in {file_number}")

    def get(self, offset: int, size: int, verify: bool = True) -> bytes:
        rec = self._f.read(offset, size)
        if len(rec) != size:
            raise Corruption("truncated blob record")
        klen, off = coding.decode_varint32(rec, 0)
        vlen, off = coding.decode_varint32(rec, off)
        off += klen
        value = bytes(rec[off : off + vlen])
        if verify:
            stored = crc32c.unmask(coding.decode_fixed32(rec, off + vlen))
            if crc32c.value(value) != stored:
                raise Corruption("blob value checksum mismatch")
        return value

    def close(self) -> None:
        self._f.close()


class BlobSource:
    """Cache of open blob readers (reference db/blob/blob_source.cc).
    Thread-safe: concurrent Gets race to open the same file otherwise."""

    def __init__(self, env, dbname: str):
        import threading

        self._env = env
        self._dbname = dbname
        self._readers: dict[int, BlobFileReader] = {}
        self._mu = threading.Lock()

    def get(self, blob_index: bytes, verify: bool = True) -> bytes:
        fn, offset, size = decode_blob_index(blob_index)
        with self._mu:
            r = self._readers.get(fn)
        if r is None:
            r = BlobFileReader(self._env, self._dbname, fn)
            with self._mu:
                existing = self._readers.get(fn)
                if existing is not None:
                    r.close()
                    r = existing
                else:
                    self._readers[fn] = r
        return r.get(offset, size, verify)

    def close(self) -> None:
        with self._mu:
            readers = list(self._readers.values())
            self._readers.clear()
        for r in readers:
            r.close()
