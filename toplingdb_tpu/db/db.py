"""DB: the central engine object.

Analogue of the reference's DBImpl (db/db_impl/db_impl.cc in /root/reference):
open/recover, the write path (WAL + memtable), point reads through
memtable → immutables → versioned SST levels, flush, iterators, snapshots,
and obsolete-file GC. Background compaction is driven by the scheduler in
toplingdb_tpu/compaction (installed via `_maybe_schedule_compaction`).
"""

from __future__ import annotations

import threading

from toplingdb_tpu.utils import concurrency as ccy
from toplingdb_tpu.utils import errors as _errors
import time
import uuid
import warnings

from toplingdb_tpu.db import dbformat, filename
from toplingdb_tpu.db.db_iter import DBIter
from toplingdb_tpu.db.dbformat import InternalKeyComparator, ValueType
from toplingdb_tpu.db.flush_job import flush_memtable_to_table
from toplingdb_tpu.db.get_context import GetContext
from toplingdb_tpu.db.level_iterator import LevelIterator
from toplingdb_tpu.db.log import LogReader, LogWriter
from toplingdb_tpu.db.memtable import MemTable
from toplingdb_tpu.db.range_del import RangeDelAggregator, RangeTombstone
from toplingdb_tpu.db.snapshot import SnapshotList
from toplingdb_tpu.db.table_cache import TableCache
from toplingdb_tpu.db.version_edit import VersionEdit
from toplingdb_tpu.db.version_set import VersionSet
from toplingdb_tpu.utils.sync_point import sync_point
from toplingdb_tpu.db.write_batch import WriteBatch
from toplingdb_tpu.env import Env, default_env
from toplingdb_tpu.options import FlushOptions, Options, ReadOptions, WriteOptions
from toplingdb_tpu.utils import statistics as _st
from toplingdb_tpu.utils import telemetry as _tm
from toplingdb_tpu.table.merging_iterator import MergingIterator
from toplingdb_tpu.utils.status import (
    Busy, Corruption, InvalidArgument, IOError_, NotFound,
)

_DEFAULT_READ = ReadOptions()
_DEFAULT_WRITE = WriteOptions()


# Cap on bytes merged into one commit group (reference
# max_write_batch_group_size_bytes, db/db_impl/db_impl_write.cc).
_MAX_WRITE_GROUP_BYTES = 1 << 20

# Cached ctypes array types for the native write plane's per-group
# marshalling (n_batches -> (c_char_p*n, c_int64*n)); building fresh array
# TYPES per group dominates small-group dispatch cost.
_GC_ARR_TYPES: dict = {}


class _Writer:
    """One queued write (reference WriteThread::Writer, db/write_thread.h:32).

    Lifecycle: enqueued → either becomes the group leader (front of queue) or
    blocks on its event until a leader commits it (done=True), promotes it
    to lead the next group (done=False), or drafts it into a parallel
    memtable phase (parallel=True — the reference's
    STATE_PARALLEL_MEMTABLE_WRITER)."""

    __slots__ = ("batch", "opts", "done", "error", "event", "on_sequenced",
                 "parallel", "pg", "pg_mems")

    def __init__(self, batch: WriteBatch, opts: WriteOptions,
                 on_sequenced=None):
        self.batch = batch
        self.opts = opts
        self.done = False
        self.error: BaseException | None = None
        self.event = threading.Event()
        # Optional callable(first_seq, last_seq) fired INSIDE the commit
        # critical section, before the group's last_sequence publishes —
        # the WritePrepared policy registers its undecided seqno range here
        # so no reader can ever observe the data unexcluded.
        self.on_sequenced = on_sequenced
        self.parallel = False          # drafted into parallel memtable phase
        self.pg = None                 # _InsertBarrier of the phase
        self.pg_mems = None            # {cf_id: MemTable} snapshot to insert


class _InsertBarrier:
    """Completion barrier for one group's parallel memtable phase
    (reference WriteThread::LaunchParallelMemTableWriters /
    CompleteParallelMemTableWriter)."""

    __slots__ = ("remaining", "all_done", "error", "lock")

    def __init__(self, n: int):
        self.remaining = n
        self.all_done = threading.Event()
        self.error: BaseException | None = None
        self.lock = ccy.Lock("db._InsertBarrier.lock")

    def member_done(self, err: BaseException | None = None) -> None:
        with self.lock:
            if err is not None and self.error is None:
                self.error = err
            self.remaining -= 1
            if self.remaining == 0:
                self.all_done.set()


class ColumnFamilyHandle:
    """Opaque per-CF handle (reference include/rocksdb/db.h
    ColumnFamilyHandle)."""

    __slots__ = ("id", "name")

    def __init__(self, cf_id: int, name: str):
        self.id = cf_id
        self.name = name

    def __repr__(self):
        return f"ColumnFamilyHandle({self.id}, {self.name!r})"


class _CFData:
    """Mutable per-CF state (the reference's ColumnFamilyData memtable side)."""

    __slots__ = ("handle", "mem", "imm")

    def __init__(self, handle: ColumnFamilyHandle, icmp, rep_name: str = "vector",
                 protection_bytes: int = 0):
        from toplingdb_tpu.db.memtable import create_memtable_rep

        self.handle = handle
        self.mem = MemTable(icmp, create_memtable_rep(rep_name),
                            protection_bytes=protection_bytes)
        self.imm: list[MemTable] = []


class _SeqSnapshot:
    """Sequence-pinning shim for internal reads: quacks like a Snapshot
    (.sequence, no excluded ranges) without registering in the snapshot
    list."""

    __slots__ = ("sequence",)
    excluded_ranges = ()

    def __init__(self, seq: int):
        self.sequence = seq


class _NGetState:
    """Per-thread bound state for the native point-read fast path: the
    native ctx (owns out/value buffers), mapped views, and strong refs to
    the memtables/version whose handles the ctx embeds (identity-compared
    by the caller to detect memtable switches / version installs)."""

    __slots__ = ("mem", "imm", "version", "ctx", "fn", "out",
                 "val_ptr", "val_cap", "_lib", "mg", "mg_arena", "fast",
                 "fast_mg")

    def __del__(self):
        lib = getattr(self, "_lib", None)
        ctx = getattr(self, "ctx", None)
        if lib is not None and ctx:
            try:
                lib.tpulsm_getctx_free(ctx)
            except Exception as e:
                _errors.swallow(reason="getctx-free-at-gc", exc=e)

    def remap(self, lib, vlen: int) -> None:
        # The C side grew its buffer to >= vlen; record vlen as the known
        # capacity so any LARGER future value triggers another remap (the
        # vector may reallocate again, moving the pointer).
        self.val_ptr = lib.tpulsm_getctx_val(self.ctx)
        self.val_cap = vlen

    @classmethod
    def build(cls, lib, mem, imm, version, table_cache):
        import ctypes

        handles = []
        kinds = []
        for m in [mem] + imm:
            h = getattr(m._rep, "_h", None)
            kind = getattr(m._rep, "_nget_mem_kind", None)
            if h is None or kind is None:
                return None  # rep layout the native probe can't walk
            handles.append(h)
            kinds.append(kind)
        vh = version.native_read_chain(table_cache)
        if vh is None and any(version.files):
            return None
        marr = (ctypes.c_void_p * len(handles))(*handles)
        ctx = lib.tpulsm_getctx_new(marr, len(handles), vh, 4096)
        if not ctx:
            return None
        for i, kind in enumerate(kinds):
            if kind:
                lib.tpulsm_getctx_set_mem_kind(ctx, i, kind)
        s = cls.__new__(cls)
        s.mem = mem
        s.imm = list(imm)
        s.version = version
        s.ctx = ctx
        s.fn = lib.tpulsm_getctx_get
        s.out = (ctypes.c_int64 * 8).from_address(
            lib.tpulsm_getctx_out(ctx))
        s.val_ptr = lib.tpulsm_getctx_val(ctx)
        s.val_cap = 4096
        s._lib = lib
        # C-extension fast calls (ctypes marshaling was ~30% of a warm
        # Get); None → the ctypes paths stay in charge.
        from toplingdb_tpu import native as _nat

        s.fast = _nat.fastget()
        s.fast_mg = _nat.fastmultiget()
        return s


class DB:
    """LSM engine instance (multi column family). Use DB.open()."""

    def __init__(self, dbname: str, options: Options, env: Env):
        self.dbname = dbname
        self.options = options
        self.env = env
        self.icmp = InternalKeyComparator(options.comparator)
        self._nget_tl = threading.local()  # native-get per-thread state
        self._op_tracer = None             # DB::StartTrace recorder
        # Integrity plane: per-entry protection + whole-file checksums +
        # scrubber state (utils/protection.py, utils/file_checksum.py,
        # db/integrity.py).
        from toplingdb_tpu.utils.file_checksum import factory_for
        from toplingdb_tpu.utils.protection import check_protection_bytes

        pb = getattr(options, "protection_bytes_per_key", 0)
        check_protection_bytes(pb)
        self._protection = pb
        self._file_checksum_factory = factory_for(options)
        self._quarantined: set[int] = set()
        self._integrity_scrubber = None
        if pb and getattr(options.table_options,
                          "protection_bytes_per_key", 0) != pb:
            # Propagate into the table layer (like prefix_extractor below)
            # so the flush/compaction/scan data planes see the knob without
            # signature plumbing; copy — never mutate the caller's object.
            import dataclasses as _dcs_p

            options.table_options = _dcs_p.replace(
                options.table_options, protection_bytes_per_key=pb,
            )
        if (options.prefix_extractor is not None
                and options.table_options.prefix_extractor is None):
            # CF-level extractor feeds the table layer (prefix blooms, plain
            # format), like reference CFOptions.prefix_extractor does. Copy:
            # the caller's TableOptions object must not be mutated.
            import dataclasses as _dcs

            options.table_options = _dcs.replace(
                options.table_options,
                prefix_extractor=options.prefix_extractor,
            )
        if options.bottommost_format is not None:
            from toplingdb_tpu.table.factory import FORMATS
            from toplingdb_tpu.utils.status import InvalidArgument

            if options.bottommost_format not in FORMATS:
                # Fail at open — a typo must not surface hours later as a
                # repeatedly failing background compaction.
                raise InvalidArgument(
                    f"bottommost_format {options.bottommost_format!r} is "
                    f"not one of {FORMATS}"
                )
        if (getattr(options.table_options, "partition_filters", False)
                and options.table_options.prefix_extractor is not None):
            from toplingdb_tpu.utils.status import InvalidArgument

            # Fail at open, not in the first background flush.
            raise InvalidArgument(
                "partition_filters supports whole-key filtering only "
                "(prefix probes could span filter partitions)"
            )
        if getattr(options.table_options, "format", "block") == "plain":
            # Fail at open, not in a background flush/compaction job.
            from toplingdb_tpu.utils.slice_transform import (
                slice_transform_from_name,
            )
            from toplingdb_tpu.utils.status import InvalidArgument

            pe = options.table_options.prefix_extractor
            if pe is None:
                raise InvalidArgument(
                    "plain table format requires Options.prefix_extractor"
                )
            if (options.compaction_executor_factory is not None
                    and slice_transform_from_name(pe.name()) is None):
                raise InvalidArgument(
                    "plain format with a remote compaction executor needs a "
                    "stock prefix_extractor (fixed/capped/noop) — custom "
                    "extractors can't be reconstructed by workers"
                )
        self.versions = VersionSet(env, dbname, self.icmp, options.num_levels)
        self.table_cache = TableCache(env, dbname, self.icmp,
                                      options.table_options,
                                      block_cache=options.block_cache)
        self.table_cache.stats = options.statistics
        self.default_cf = ColumnFamilyHandle(0, "default")
        self._cfs: dict[int, _CFData] = {
            0: _CFData(self.default_cf, self.icmp, options.memtable_rep,
                       protection_bytes=self._protection)
        }
        from toplingdb_tpu.db.blob import BlobSource

        self.blob_source = BlobSource(
            env, dbname, blob_cache=getattr(options, "blob_cache", None),
            open_limit=getattr(options, "blob_file_open_limit", 256),
            statistics=options.statistics)
        self.snapshots = SnapshotList()
        self._mutex = ccy.RLock("db.DB._mutex")
        self._writers: list[_Writer] = []  # FIFO write queue (leader = [0])
        self._wq_lock = ccy.Lock("db.DB._wq_lock")
        # Staged write modes (pipelined/unordered): seqno ALLOCATION runs
        # ahead of PUBLICATION. _alloc_ranges is a deque of [first, last,
        # done] entries in allocation order (indexed by _alloc_entry for
        # O(1) completion marking); last_sequence advances as an in-order
        # low watermark over the done prefix — no front-of-list pops or
        # set scans on the hot path. _mt_cv (on _mutex) signals completion
        # to memtable-switch / snapshot / close waiters.
        from collections import deque as _deque

        self._mt_cv = ccy.Condition(lock=self._mutex)
        self._mt_inflight = 0
        self._seq_alloc = 0
        self._alloc_ranges: "_deque[list]" = _deque()
        self._alloc_entry: dict[int, list] = {}  # first -> its deque entry
        # Fused native write plane (ISSUE 7 tentpole): TPULSM_WRITE_PLANE=0
        # disables; unset/1 enables when the native symbol + a native
        # memtable rep are available and the comparator carries no
        # timestamp. Resolved lazily (None) to the ctypes fn or False.
        import os as _os

        self._write_plane_knob = (
            _os.environ.get("TPULSM_WRITE_PLANE", "1") != "0")
        self._write_plane = None
        # Async WAL writer ring (Options.enable_async_wal): WAL durability
        # leaves the commit critical section and concurrent leaders' syncs
        # coalesce into shared fsyncs. Shared Env primitive — the
        # IntegrityScrubber and FilePrefetchBuffer submit through the same
        # AsyncIORing facility.
        self._wal_ring = None
        if (options.enable_async_wal and options.wal_enabled
                and not options.read_only):
            from toplingdb_tpu.env.env import AsyncIORing

            stats_ = options.statistics
            self._wal_ring = AsyncIORing(
                capacity=options.async_wal_ring_size,
                coalesce_cb=(
                    (lambda n, s=stats_: s.record_tick(
                        _st.WRITE_GROUP_FSYNCS_COALESCED, n))
                    if stats_ is not None else None),
                fault_hook=getattr(env, "wal_writer_fault", None),
                name="tpulsm-wal-writer")
        self._wal: LogWriter | None = None
        self._wal_number = 0
        self._recycle_wals: list[int] = []  # obsolete WALs kept for reuse
        # Only logs THIS process wrote in recyclable format may enter the
        # pool — a legacy-format WAL's stale records carry no log-number
        # stamp and could silently replay after reuse (reference
        # alive_log_files scoping).
        self._recyclable_written: set[int] = set()
        self._closed = False
        # Wakes sleeping auto-recover threads so close() can join them
        # promptly instead of waiting out their backoff.
        self._recover_stop = threading.Event()
        # Write-stall accounting surfaced by write_stall_state() (the
        # sharding router's backpressure signal): cumulative counters are
        # folded in by _maybe_stall_writes; the live state is derived from
        # L0 vs the triggers at query time.
        self._stall_totals = {"stalls": 0, "stall_micros": 0,
                              "last_stall_micros": 0, "last_state": "none"}
        self._compaction_scheduler = None  # set by compaction module
        self._pending_outputs: set[int] = set()  # files being written by jobs
        self._bg_error: BaseException | None = None
        from toplingdb_tpu.utils.status import Severity as _Sev
        self._bg_error_severity = _Sev.NO_ERROR
        self._bg_error_reason = ""
        self._store_gc_inflight = False  # one reclaim GC sweep at a time
        self._mem_id_counter = 0
        # WritePrepared policy hook (reference SnapshotChecker): a callable
        # returning the seqno ranges of prepared-but-undecided transactions,
        # which every read must treat as invisible. Set by
        # utilities.transactions.TransactionDB under write_prepared /
        # write_unprepared write policies; None = plain visibility.
        self._undecided_provider = None
        self.identity = ""
        self.stats = options.statistics  # may be None
        # Storage-pressure plane: an SstFileManager tracking this DB's
        # live SST+WAL+blob bytes. Caller-shared via
        # Options.sst_file_manager, else built privately when any disk
        # budget/poller knob is set (the common no-knob path carries None
        # and pays nothing).
        from toplingdb_tpu.utils.rate_limiter import SstFileManager
        sfm = options.sst_file_manager
        self._sfm_owned = False
        if sfm is None and (options.max_allowed_space_usage > 0
                            or options.free_space_poll_period_sec > 0):
            headroom = options.flush_headroom_bytes
            if headroom <= 0 and options.max_allowed_space_usage > 0:
                headroom = 2 * options.write_buffer_size
            sfm = SstFileManager(
                env=env, path=dbname,
                max_allowed_space_usage=options.max_allowed_space_usage,
                compaction_buffer_size=options.compaction_buffer_size,
                flush_headroom_bytes=headroom,
                free_space_poll_period_sec=(
                    options.free_space_poll_period_sec),
                amber_free_ratio=options.disk_amber_free_ratio,
                red_free_ratio=options.disk_red_free_ratio,
                pressure_hysteresis=options.disk_pressure_hysteresis,
                statistics=self.stats)
            self._sfm_owned = True
        elif sfm is not None:
            # Shared manager: adopt this DB's env/root/stats only if the
            # owner didn't already bind them.
            if sfm._env is None:
                sfm._env = env
            if sfm._path is None:
                sfm._path = dbname
            if sfm._stats is None:
                sfm._stats = self.stats
        self._sfm = sfm
        if sfm is not None:
            sfm.add_pressure_callback(self._on_disk_pressure_change)
        from toplingdb_tpu.utils.seqno_to_time import SeqnoToTimeMapping
        from toplingdb_tpu.utils.stats_history import (
            StatsDumpScheduler, StatsHistory,
        )

        if (self.stats is not None
                and getattr(options, "histogram_window_sec", None) is not None
                and options.histogram_window_sec != self.stats._window_sec):
            # Re-key the windowed-histogram ring to the DB's knob (only
            # empty histograms are rebuilt; a shared Statistics keeps
            # its populated series).
            self.stats.set_histogram_window(options.histogram_window_sec)
        self.stats_history = StatsHistory(self.stats)
        # SLO engine (utils/slo.py): declarative burn-rate objectives
        # over the stats; /slo/<name> + /metrics serve its verdicts and
        # ShardRouter folds them into per-shard health scores.
        self.slo_engine = None
        if self.stats is not None and getattr(options, "slo_specs", ()):
            from toplingdb_tpu.utils.slo import SLOEngine

            self.slo_engine = SLOEngine(
                self.stats, options.slo_specs, db=self,
                db_name=dbname, listeners=options.listeners,
                default_window_sec=getattr(options, "slo_window_sec", 60.0)
                or 60.0)
            if getattr(options, "slo_eval_period_sec", 0) > 0:
                self.slo_engine.start(options.slo_eval_period_sec)
        self._stats_dumper = (
            StatsDumpScheduler(self.stats_history,
                               options.stats_persist_period_sec)
            if self.stats is not None and options.stats_persist_period_sec > 0
            else None
        )
        # stats_dump_period_sec: periodic snapshot + a compact `stats_dump`
        # event-log line (the reference's stats-dump thread); started after
        # event_logger exists, below.
        self._stats_dump_thread = None
        # Request-scoped span tracer (utils/telemetry.py): None unless a
        # trace_* knob turns it on — the hot paths check `is not None`
        # before paying anything. The get path's 1-in-N decision is a
        # precomputed cycle iterator (`_trace_sched` yields 1 on the Nth
        # op, 2 for slow-watch rounds, 0 otherwise): the unsampled cost
        # is one attribute load + one C-level next + one branch.
        self.tracer = _tm.tracer_from_options(options)
        self._trace_sched = None
        _tr = self.tracer
        if _tr is not None:
            import itertools as _it

            se, slow = _tr.sample_every, _tr.slow_usec
            if se:
                pat = [2 if slow else 0] * (se - 1) + [1]
            else:
                pat = [2]  # slow-watch only
            self._trace_sched = _it.cycle(pat).__next__
        self.seqno_to_time = SeqnoToTimeMapping()
        # The mapping must survive reopens (reference persists it through
        # MANIFEST/SST properties) or every restart would treat ALL data
        # as young for preclude_last_level_data_seconds; a JSON sidecar
        # is our persistence (loaded in DB.open, saved on sample/close).
        self._seqno_time_path = None
        self._seqno_time_dirty = False
        self._last_seqno_time_sample = 0.0
        self._wbm_charged = 0  # bytes charged to options.write_buffer_manager
        self._options_file_number = 0  # latest persisted OPTIONS file
        self._mget_pool = None  # lazy long-lived async multi_get executor
        # Async read plane (env/async_reads.py, TPULSM_ASYNC_READS=1):
        # lazy AsyncReadBatcher fanning batched block fetches across
        # Options.async_read_rings reader rings; closed by DB.close.
        self._read_batcher = None
        self._async_pool = None  # lazy get_async/multi_get_async executor
        # Test seam: set before the first async-routed read to plug a
        # ReadFaultInjector into every reader ring (fault_hook).
        self.read_fault_hook = None
        self._file_deletions_disabled = 0  # DisableFileDeletions pin count
        # Replication plane hook: LogShipper / FollowerDB / ReplicaRouter
        # register a status callable here; the SidePlugin HTTP layer serves
        # it at /replication/<name> (utils/config.py).
        self._repl_status_provider = None
        from toplingdb_tpu.utils.listener import EventLogger

        self._log_file = None
        if not options.read_only:
            try:
                # Through the Env (fault injection / MemEnv see it too); the
                # previous LOG is rolled aside like the reference's
                # auto_roll_logger.
                if env.file_exists(f"{dbname}/LOG"):
                    env.rename_file(f"{dbname}/LOG", f"{dbname}/LOG.old")
                self._log_file = env.new_writable_file(f"{dbname}/LOG")
            except Exception as e:
                _errors.swallow(reason="info-log-roll-best-effort", exc=e)
        self.event_logger = EventLogger(
            (lambda line: self._log_file.append(line.encode() + b"\n"))
            if self._log_file is not None else None
        )
        if (self.stats is not None
                and getattr(options, "stats_dump_period_sec", 0) > 0):
            from toplingdb_tpu.utils.stats_history import StatsDumpScheduler

            self._stats_dump_thread = StatsDumpScheduler(
                self.stats_history, options.stats_dump_period_sec,
                on_snapshot=self._log_stats_dump)

    def _log_stats_dump(self) -> None:
        """One compact stats line per dump period (the reference's periodic
        stats dump into the info LOG), fed from the history ring's latest
        delta sample so the dump and /stats_history always agree."""
        sample = self.stats_history.last_sample()
        if sample is None:
            return
        ts, delta = sample
        top = sorted(delta.items(), key=lambda kv: -abs(kv[1]))[:12]
        self.event_logger.log(
            "stats_dump", sample_ts=ts,
            tickers={k: v for k, v in top},
            last_sequence=self.versions.last_sequence,
        )

    # -- default-CF views (most callers are single-CF) ------------------

    @property
    def mem(self) -> MemTable:
        return self._cfs[0].mem

    @mem.setter
    def mem(self, m: MemTable) -> None:
        self._cfs[0].mem = m

    @property
    def imm(self) -> list:
        return self._cfs[0].imm

    @imm.setter
    def imm(self, v: list) -> None:
        self._cfs[0].imm = v

    def _cf_id(self, cf) -> int:
        if cf is None:
            return 0
        if isinstance(cf, ColumnFamilyHandle):
            return cf.id
        return int(cf)

    def _cf_data(self, cf) -> _CFData:
        cfd = self._cfs.get(self._cf_id(cf))
        if cfd is None:
            raise InvalidArgument(f"unknown column family {cf!r}")
        return cfd

    # -- column family management ---------------------------------------

    def create_column_family(self, name: str) -> ColumnFamilyHandle:
        with self._mutex:
            cf_id = self.versions.create_column_family(name)
            h = ColumnFamilyHandle(cf_id, name)
            self._cfs[cf_id] = _CFData(h, self.icmp, self.options.memtable_rep,
                                       protection_bytes=self._protection)
            return h

    def drop_column_family(self, handle: ColumnFamilyHandle) -> None:
        with self._mutex:
            self.versions.drop_column_family(handle.id)
            self._cfs.pop(handle.id, None)
            self._delete_obsolete_files()

    def create_column_family_with_import(
        self, name: str, source_dir: str, metadata=None,
        move_files: bool = False,
    ) -> ColumnFamilyHandle:
        """Create a CF populated from a Checkpoint export_column_family dir
        (reference DB::CreateColumnFamilyWithImport /
        ImportColumnFamilyJob, db/import_column_family_job.cc)."""
        from toplingdb_tpu.db.import_column_family_job import (
            import_column_family,
        )

        return import_column_family(self, name, source_dir, metadata,
                                    move_files=move_files)

    def list_column_families(self) -> list[ColumnFamilyHandle]:
        with self._mutex:
            return [cfd.handle for cfd in self._cfs.values()]

    def get_column_family(self, name: str) -> ColumnFamilyHandle | None:
        for cfd in self._cfs.values():
            if cfd.handle.name == name:
                return cfd.handle
        return None

    def cf_name(self, cf_id: int) -> str:
        cfd = self._cfs.get(cf_id)
        if cfd is not None:
            return cfd.handle.name
        st = self.versions.column_families.get(cf_id)
        return st.name if st is not None else f"cf{cf_id}"

    # ==================================================================
    # Open / close
    # ==================================================================

    @staticmethod
    def open(dbname: str, options: Options | None = None, env: Env | None = None) -> "DB":
        """Reference DBImpl::Open (db/db_impl/db_impl_open.cc:1906)."""
        options = options or Options()
        env = env or default_env()
        # Disaggregated SST storage (toplingdb_tpu/storage/): when the
        # shared-store knob is on, wrap the env so installed tables
        # publish to the content-addressed store and live as references.
        # The env var wins over Options so the parity harness can flip
        # modes without touching code.
        import os as _os_knob
        spec = _os_knob.environ.get("TPULSM_SHARED_STORE")
        if spec is None:
            spec = options.shared_store
        owns_shared_env = False
        from toplingdb_tpu.storage import store_spec_enabled
        if store_spec_enabled(spec) and not hasattr(env, "publish_sst"):
            from toplingdb_tpu.storage import SharedSstEnv, open_store

            cache_dir = None
            if isinstance(spec, str) and not spec.startswith(
                    ("http://", "https://")):
                cache_dir = _os_knob.path.join(spec, "cache")
            env = SharedSstEnv(env, open_store(spec, env=env),
                               cache_dir=cache_dir,
                               stats=options.statistics)
            owns_shared_env = True
        elif hasattr(env, "publish_sst") and hasattr(env, "retain"):
            # Reopening on a caller-supplied shared env (migration dest,
            # checkpoint restore): co-own it — the LAST close tears down
            # the cache/prefetch threads.
            owns_shared_env = True
        env.create_dir(dbname)
        db = DB(dbname, options, env)
        db._owns_shared_env = owns_shared_env
        if owns_shared_env:
            env.retain()
        current = filename.current_file_name(dbname)
        if env.file_exists(current):
            if options.error_if_exists:
                raise InvalidArgument(f"{dbname} exists (error_if_exists)")
            db._recover()
        else:
            if not options.create_if_missing:
                raise InvalidArgument(f"{dbname} does not exist (create_if_missing=False)")
            db.versions.create_new()
            env.write_file(
                filename.identity_file_name(dbname), uuid.uuid4().hex.encode()
            )
        try:
            db.identity = env.read_file(filename.identity_file_name(dbname)).decode()
        except NotFound:
            db.identity = uuid.uuid4().hex
            env.write_file(filename.identity_file_name(dbname), db.identity.encode())
        db._new_wal()
        import os as _os

        db._seqno_time_path = _os.path.join(dbname, "SEQNO_TIME.json")
        try:
            import json as _json

            raw = env.read_file(db._seqno_time_path)
            db.seqno_to_time.load(_json.loads(raw.decode()))
        except Exception as e:
            # Absent/corrupt sidecar: start fresh (best effort).
            _errors.swallow(reason="seqno-time-sidecar-load", exc=e)
        try:
            from toplingdb_tpu.utils.config import (
                load_latest_options, persist_options,
            )

            if db.icmp.user_comparator.timestamp_size:
                # full_history_ts_low is monotonic ACROSS reopens (the
                # reference persists it in the MANIFEST): take the max of
                # the caller's value and the persisted one — already-trimmed
                # history must never become readable again.
                prev = load_latest_options(dbname, env=env)
                if prev is not None:
                    options.full_history_ts_low = max(
                        options.full_history_ts_low,
                        prev.full_history_ts_low,
                    )
            persist_options(db)  # reference PersistRocksDBOptions on open
        except Exception as e:
            # OPTIONS persistence is best-effort, like the reference.
            _errors.swallow(reason="options-persist-on-open", exc=e,
                            stats=options.statistics)
        db._delete_obsolete_files()
        try:
            # A kill -9'd dcompact worker leaves its job dir (params,
            # partial outputs, stale heartbeat) behind; detect expiry by
            # lease and sweep before background work starts. The job's
            # inputs are still live in the version, so the picker simply
            # re-runs it (compaction/resilience.py).
            from toplingdb_tpu.compaction.resilience import (
                DcompactOptions, sweep_orphan_jobs,
            )

            policy = options.dcompact or DcompactOptions()
            roots = {_os.path.join(dbname, "dcompact")}
            factory = options.compaction_executor_factory
            if factory is not None and getattr(factory, "job_root", None):
                roots.add(factory.job_root)
            for root in roots:
                sweep_orphan_jobs(root, policy.lease_sec,
                                  statistics=options.statistics,
                                  event_logger=db.event_logger)
        except Exception as e:
            # Sweeping is best-effort; never blocks open.
            _errors.swallow(reason="orphan-job-sweep-on-open", exc=e,
                            stats=options.statistics)
        if db._sfm is not None:
            # Seed the manager with the surviving tree (recovered SSTs,
            # blobs, the fresh WAL) so budget math starts from reality,
            # then start the free-space poller.
            for child in env.get_children(dbname):
                ftype, _num = filename.parse_file_name(child)
                if ftype in (filename.FileType.TABLE,
                             filename.FileType.BLOB,
                             filename.FileType.WAL):
                    db._sfm.on_add_file(f"{dbname}/{child}")
            db._sfm.poll()
            db._sfm.start_poller()
        from toplingdb_tpu.compaction.scheduler import CompactionScheduler

        db._compaction_scheduler = CompactionScheduler(db)
        db._maybe_schedule_compaction()
        if (not options.read_only
                and getattr(options, "integrity_scrub_period_sec", 0) > 0):
            from toplingdb_tpu.db.integrity import IntegrityScrubber

            db._integrity_scrubber = IntegrityScrubber(db)
            db._integrity_scrubber.start()
        return db

    def _recover(self) -> None:
        self.versions.recover()
        self._materialize_cfs()
        # Replay WALs >= versions.log_number in file-number order
        # (reference DBImpl::Recover → RecoverLogFiles).
        wal_numbers = []
        for child in self.env.get_children(self.dbname):
            ftype, num = filename.parse_file_name(child)
            if ftype == filename.FileType.WAL and num >= self.versions.log_number:
                wal_numbers.append(num)
            if ftype in (filename.FileType.WAL, filename.FileType.TABLE,
                         filename.FileType.MANIFEST, filename.FileType.BLOB):
                self.versions.mark_file_number_used(num)
        max_seq = self.versions.last_sequence
        mems = {cf_id: cfd.mem for cf_id, cfd in self._cfs.items()}
        for num in sorted(wal_numbers):
            path = filename.log_file_name(self.dbname, num)
            reader = LogReader(self.env.new_sequential_file(path),
                               log_number=num)
            for rec in reader.records():
                # The WAL record's own CRC vouched for `rec`; protection
                # computed here covers the replayed entries from decode
                # through memtable and flush.
                batch = WriteBatch(
                    rec, protection_bytes_per_key=self._protection)
                batch.insert_into(mems)
                end_seq = batch.sequence() + batch.count() - 1
                max_seq = max(max_seq, end_seq)
        self.versions.last_sequence = max_seq
        any_flushed = False
        for cf_id, cfd in self._cfs.items():
            if not cfd.mem.empty():
                self._flush_memtables([cfd.mem], wal_number=None, cf_id=cf_id)
                cfd.mem = self._fresh_memtable()
                any_flushed = True
        if any_flushed:
            # Single atomic log_number advance once every CF is durable.
            self.versions.log_and_apply(
                VersionEdit(log_number=self.versions.next_file_number)
            )

    def _materialize_cfs(self) -> None:
        """Build per-CF memtable state from the recovered VersionSet."""
        for cf_id, st in self.versions.column_families.items():
            if cf_id not in self._cfs:
                h = ColumnFamilyHandle(cf_id, st.name)
                self._cfs[cf_id] = _CFData(h, self.icmp,
                                           self.options.memtable_rep,
                                           protection_bytes=self._protection)

    def _fresh_memtable(self) -> MemTable:
        from toplingdb_tpu.db.memtable import create_memtable_rep

        m = MemTable(self.icmp, create_memtable_rep(self.options.memtable_rep),
                     protection_bytes=self._protection)
        self._mem_id_counter += 1
        m.mem_id = self._mem_id_counter
        return m

    def _new_wal(self) -> None:
        self._wal_number = self.versions.new_file_number()
        path = filename.log_file_name(self.dbname, self._wal_number)
        recycle_on = self.options.recycle_log_file_num > 0
        if recycle_on and self._recycle_wals:
            old_num = self._recycle_wals.pop(0)
            old_path = filename.log_file_name(self.dbname, old_num)
            w = self.env.reuse_writable_file(old_path, path)
            if self._sfm is not None:
                self._sfm.on_delete_file(old_path)  # renamed onto `path`
        else:
            w = self.env.new_writable_file(path)
        if self._wal_ring is not None:
            from toplingdb_tpu.env.env import AsyncWritableFile

            w = AsyncWritableFile(w, self._wal_ring)
        # recycle_log_file_num > 0 => ALWAYS the recyclable record format,
        # so any WAL written from now on is safe to reuse later.
        self._wal = LogWriter(w, log_number=self._wal_number,
                              recycled=recycle_on)
        if recycle_on:
            self._recyclable_written.add(self._wal_number)
        if self._sfm is not None:
            self._sfm.on_add_file(path, 0)  # grows; resized at switch/close

    def close(self) -> None:
        self._recover_stop.set()
        if self._integrity_scrubber is not None:
            self._integrity_scrubber.stop()
        if self._stats_dumper is not None:
            self._stats_dumper.stop()
        if self._stats_dump_thread is not None:
            self._stats_dump_thread.stop()
        if self.slo_engine is not None:
            self.slo_engine.stop()
        if self._mget_pool is not None:
            self._mget_pool.shutdown(wait=True)
            self._mget_pool = None
        if self._async_pool is not None:
            self._async_pool.shutdown(wait=True)
            self._async_pool = None
        if self._read_batcher is not None:
            # Joins every reader-ring thread (zero leaked ring threads
            # after close — the no_thread_leaks guarantee).
            self._read_batcher.close()
            self._read_batcher = None
        if self._compaction_scheduler is not None:
            self._compaction_scheduler.shutdown()
        if self._sfm is not None and self._sfm_owned:
            # Private manager: join its poller + trash deleters. A shared
            # manager (Options.sst_file_manager) outlives this DB and is
            # closed by whoever built it.
            self._sfm.close()
        with self._mutex:
            if self._closed:
                return
            # Drain staged (pipelined/unordered) memtable phases before
            # flushing — their entries are WAL-durable but must land in the
            # memtables for the final flush to carry them.
            while self._mt_inflight > 0:
                self._mt_cv.wait(timeout=10.0)
            if any(not c.mem.empty() or c.imm for c in self._cfs.values()):
                self.flush(FlushOptions())
            if self._wal is not None:
                self._wal.sync()
                self._wal.close()
            wbm = self.options.write_buffer_manager
            if wbm is not None and self._wbm_charged:
                wbm.free(self._wbm_charged)
                self._wbm_charged = 0
            self.seqno_to_time.append(self.versions.last_sequence,
                                      int(time.time()))
            self._save_seqno_time()
            self.versions.close()
            self.table_cache.close()
            self.blob_source.close()
            if self._wal_ring is not None:
                self._wal_ring.close()
            if self._log_file is not None:
                self._log_file.close()
            self._closed = True
        # Shared-store env: DB.open retained it (knob-built or reopened
        # on a caller-supplied one); the last release closes the
        # warm-ring thread + persistent cache.
        if getattr(self, "_owns_shared_env", False):
            self.env.release()
        # Thread-lifecycle check: everything spawned with owner=self must
        # be gone by now. A leak here is a bug in a stop() path above.
        ccy.registry.join_all(owner=self, timeout=5.0)
        leaked = ccy.registry.check_leaks(owner=self)
        if leaked:
            warnings.warn(
                f"DB.close() leaked threads: {leaked}", RuntimeWarning,
                stacklevel=2)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    # ==================================================================
    # Write path
    # ==================================================================

    def _validate_ts_batch(self, batch: WriteBatch) -> None:
        """Every key entering a ts-comparator DB must be encode_ts_key-form;
        a single raw key would poison iteration forever (strip_ts raises on
        it). Write paths that can't carry a timestamp (transactions,
        DeleteRange) are rejected here rather than corrupting the DB."""
        if getattr(batch, "_ts_checked", False):
            return
        for _cf, t, key, _val in batch.entries_cf():
            if t == ValueType.RANGE_DELETION:
                raise InvalidArgument(
                    "DeleteRange is not supported with user-defined "
                    "timestamps"
                )
            if t == ValueType.LOG_DATA:
                continue
            try:
                dbformat.strip_ts(key)
            except ValueError as e:
                raise InvalidArgument(
                    f"key {key!r} lacks a timestamp suffix; this DB's "
                    f"comparator requires ts= on every write (transactions "
                    f"do not support user-defined timestamps)"
                ) from e
        batch._ts_checked = True

    def _ts_key(self, key: bytes, ts: int | None) -> bytes:
        """Suffix the user timestamp when the comparator carries one
        (reference user-defined-timestamp write paths: Put(cf, key, ts, v))."""
        sz = self.icmp.user_comparator.timestamp_size
        if sz == 0:
            if ts is not None:
                raise InvalidArgument(
                    "timestamp given but the comparator has none "
                    "(use Options(comparator=U64_TS_BYTEWISE))"
                )
            return key
        if ts is None:
            raise InvalidArgument(
                "this DB's comparator requires a timestamp on every write"
            )
        return dbformat.encode_ts_key(key, ts)

    def put(self, key: bytes, value: bytes, opts: WriteOptions = _DEFAULT_WRITE,
            cf=None, ts: int | None = None) -> int:
        b = WriteBatch(protection_bytes_per_key=self._protection)
        b.put(self._ts_key(key, ts), value, cf=self._cf_id(cf))
        return self.write(b, opts)

    def delete(self, key: bytes, opts: WriteOptions = _DEFAULT_WRITE,
               cf=None, ts: int | None = None) -> int:
        b = WriteBatch(protection_bytes_per_key=self._protection)
        b.delete(self._ts_key(key, ts), cf=self._cf_id(cf))
        return self.write(b, opts)

    def single_delete(self, key: bytes, opts: WriteOptions = _DEFAULT_WRITE,
                      cf=None, ts: int | None = None) -> int:
        b = WriteBatch(protection_bytes_per_key=self._protection)
        b.single_delete(self._ts_key(key, ts), cf=self._cf_id(cf))
        return self.write(b, opts)

    def merge(self, key: bytes, value: bytes, opts: WriteOptions = _DEFAULT_WRITE,
              cf=None) -> int:
        if self.icmp.user_comparator.timestamp_size:
            raise InvalidArgument(
                "Merge is not supported with user-defined timestamps"
            )
        b = WriteBatch(protection_bytes_per_key=self._protection)
        b.merge(key, value, cf=self._cf_id(cf))
        return self.write(b, opts)

    def delete_range(self, begin: bytes, end: bytes,
                     opts: WriteOptions = _DEFAULT_WRITE, cf=None) -> int:
        if self.icmp.user_comparator.timestamp_size:
            raise InvalidArgument(
                "DeleteRange is not supported with user-defined timestamps"
            )
        b = WriteBatch(protection_bytes_per_key=self._protection)
        b.delete_range(begin, end, cf=self._cf_id(cf))
        return self.write(b, opts)

    def latest_sequence_number(self) -> int:
        """The newest PUBLISHED sequence — a valid staleness token for
        replication/router.py reads (reference GetLatestSequenceNumber)."""
        return self.versions.last_sequence

    def write(self, batch: WriteBatch, opts: WriteOptions = _DEFAULT_WRITE,
              on_sequenced=None) -> int:
        """Group-commit write path (reference DBImpl::WriteImpl +
        WriteThread::JoinBatchGroup, db/db_impl/db_impl_write.cc:169,311):
        concurrent writers queue up; the front writer leads, merging the
        queue into one WAL append + one fsync, then applies every batch to
        the memtables and publishes the group's last sequence at once.

        Returns this batch's LAST sequence number — the staleness token of
        the replication plane: a token-carrying read served by any replica
        whose applied sequence >= token observes this write
        (replication/router.py)."""
        if batch.is_empty():
            return self.versions.last_sequence  # trivially-satisfied token
        self._check_open()  # fail fast before any stall sleep
        if self._protection:
            wp = self._write_plane
            if wp is None:
                wp = self._resolve_write_plane()
            if not wp or batch._pb != self._protection \
                    or batch._prot is None:
                # Materialize (caller-constructed batches / records added
                # since the last compute): one native pass BEFORE the WAL
                # append and group merge — the memtable-insert
                # re-verification then spans the whole commit path.
                batch.ensure_protection(self._protection)
            # else: defer — the plane VERIFIES a current vector or
            # COMPUTES a stale one fused into the WAL frame walk (each
            # record hashed once, not twice); fallback paths attach at
            # the insert handoff exactly like direct insert_into callers.
        tr = self._op_tracer
        if tr is not None:
            tr.record_write(batch.data())
        tracer = self.tracer
        root = None
        if tracer is not None and tracer.sample_every \
                and next(tracer.counter) % tracer.sample_every == 0:
            # Sampled: full span tree for this write (the inline check is
            # the whole unsampled cost — one count + one mod).
            root = tracer.start("db.write", records=batch.count(),
                                bytes=batch.data_size(),
                                sync=bool(opts.sync))
        stats = self.stats
        if stats is None and tracer is None:
            return self._write_impl(batch, opts, on_sequenced)
        # time/_st are module-level imports: no per-call import
        # machinery on the write hot path.
        t0 = time.perf_counter()
        try:
            seq = self._write_impl(batch, opts, on_sequenced)
            if root is not None:
                # Replication propagation: WAL shipping forwards this
                # write's context to followers by sequence range.
                tracer.note_seq(seq, root)
            return seq
        finally:
            micros = (time.perf_counter() - t0) * 1e6
            if stats is not None:
                stats.record_in_histogram(_st.DB_WRITE_MICROS, micros)
            if root is not None:
                root.finish()
            elif tracer is not None and tracer.slow_usec \
                    and micros >= tracer.slow_usec:
                tracer.note_slow("db.write", micros,
                                 records=batch.count())

    @staticmethod
    def _write_token(w: _Writer) -> int:
        """The completed writer's staleness token (its last sequence)."""
        return w.batch.sequence() + w.batch.count() - 1

    def _write_impl(self, batch: WriteBatch, opts: WriteOptions,
                    on_sequenced) -> int:
        if self.icmp.user_comparator.timestamp_size:
            self._validate_ts_batch(batch)
        self._maybe_stall_writes()
        w = _Writer(batch, opts, on_sequenced)
        with self._wq_lock:
            self._writers.append(w)
            is_leader = self._writers[0] is w
        if not is_leader:
            interrupted: BaseException | None = None
            # Time spent queued behind the current leader (a sampled
            # follower's dominant latency component).
            _wsp = _tm.span("write.leader_wait")
            while True:
                try:
                    w.event.wait()
                    break
                except BaseException as e:  # noqa: BLE001
                    # Async interrupt (KeyboardInterrupt) mid-wait: the queue
                    # slot MUST still resolve — abandoning it would deadlock
                    # every later writer behind a never-driven leader.
                    interrupted = e
            _wsp.finish()
            if w.parallel:
                # Drafted into the group's parallel memtable phase: insert
                # our own batch (GIL-free native path), then wait for the
                # leader to publish (reference parallel memtable writers).
                interrupted = self._parallel_member(w) or interrupted
                if interrupted is not None:
                    raise interrupted
                if w.error is not None:
                    raise w.error
                return self._write_token(w)
            if w.done:
                if interrupted is not None:
                    raise interrupted
                if w.error is not None:
                    raise w.error
                return self._write_token(w)
            # Woken with done=False: promoted to lead the next group.
            self._lead_write_group(w)
            if interrupted is not None:
                raise interrupted
            return self._write_token(w)
        self._lead_write_group(w)
        return self._write_token(w)

    def _parallel_member(self, w: _Writer) -> BaseException | None:
        """Follower half of a parallel memtable phase: insert own batch,
        report to the barrier, block until the leader completes the group.
        Returns an async interrupt caught mid-wait (re-raised by write())."""
        w.event.clear()
        err: BaseException | None = None
        try:
            w.batch.insert_into(w.pg_mems)
        except BaseException as e:  # noqa: BLE001
            err = e
        w.pg.member_done(err)
        interrupted: BaseException | None = None
        while True:
            try:
                w.event.wait()
                return interrupted
            except BaseException as e:  # noqa: BLE001
                interrupted = e  # leader WILL complete us; keep the slot

    def _snapshot_group(self, leader: _Writer) -> list[_Writer]:
        # Leader + queued followers with the same WAL disposition, capped in
        # bytes so a giant group can't starve later writers' latency
        # (reference WriteThread::EnterAsBatchGroupLeader).
        with self._wq_lock:
            group = [leader]
            size = leader.batch.data_size()
            for w in self._writers[1:]:
                if w.opts.disable_wal != leader.opts.disable_wal:
                    break
                size += w.batch.data_size()
                if size > _MAX_WRITE_GROUP_BYTES:
                    break
                group.append(w)
        return group

    def _lead_write_group(self, leader: _Writer) -> None:
        group = self._snapshot_group(leader)
        if self.options.unordered_write or self.options.enable_pipelined_write:
            self._lead_write_group_staged(leader, group)
            return
        err: BaseException | None = None
        try:
            self._commit_write_group(group)
        except BaseException as e:  # propagate to the whole group
            err = e
        with self._wq_lock:
            del self._writers[: len(group)]
            nxt = self._writers[0] if self._writers else None
        for w in group:
            w.done = True
            w.error = err
            if w is not leader:
                w.event.set()
        if nxt is not None:
            nxt.event.set()  # done=False → it takes over as leader
        if err is not None:
            raise err

    def _lead_write_group_staged(self, leader: _Writer,
                                 group: list[_Writer]) -> None:
        """Pipelined / unordered write path (reference PipelinedWriteImpl
        db_impl_write.cc:657 and WriteImplWALOnly :267-301): the WAL stage
        runs under _mutex, then the NEXT group's leader is woken — its WAL
        append overlaps this group's memtable inserts. Publication advances
        as an in-order low watermark over completed groups."""
        err: BaseException | None = None
        first = last = 0
        mems: dict | None = None
        wal_wait = None
        plane = None
        wal_on = (self.options.wal_enabled
                  and not group[0].opts.disable_wal)
        try:
            with self._mutex:
                self._check_open()
                if self._bg_error is not None:
                    from toplingdb_tpu.utils.status import Severity as _Sev

                    if self._bg_error_severity >= _Sev.HARD_ERROR:
                        raise IOError_(
                            f"background error pending (call resume()): "
                            f"{self._bg_error!r}"
                        )
                first = max(self._seq_alloc,
                            self.versions.last_sequence) + 1
                seq = first
                for w in group:
                    w.batch.set_sequence(seq)
                    seq += w.batch.count()
                last = seq - 1
                mems = {cf_id: cfd.mem for cf_id, cfd in self._cfs.items()}
                if wal_on:
                    # Native plane frames+appends the merged record here;
                    # its insert half runs OUTSIDE _mutex below, exactly
                    # like the Python interiors it replaces.
                    with _tm.span("write.wal_frame", group=len(group),
                                  staged=True):
                        plane = self._native_group_commit(group, first,
                                                          mems, frame=True)
                        wal_wait = (plane[0] if plane is not None
                                    else self._append_group_wal(group,
                                                                first))
                self._seq_alloc = last
                entry = [first, last, False]
                self._alloc_ranges.append(entry)
                self._alloc_entry[first] = entry
                self._mt_inflight += 1
        except BaseException as e:  # noqa: BLE001
            err = e
        # Hand the queue to the next leader NOW (the overlap window).
        with self._wq_lock:
            del self._writers[: len(group)]
            nxt = self._writers[0] if self._writers else None
        if nxt is not None:
            nxt.event.set()
        if err is not None:
            for w in group:
                w.done = True
                w.error = err
                if w is not leader:
                    w.event.set()
            raise err
        # Memtable phase. The native plane applies the WHOLE group in one
        # GIL-released call; otherwise unordered mode always fans out
        # (each writer inserts its own batch, truly parallel via the
        # GIL-free native inserts) and pipelined-only mode fans out when
        # allowed.
        native_used = False
        _msp = _tm.span("write.memtable_apply", group=len(group),
                        staged=True)
        if plane is not None:
            try:
                plane[1]()
                native_used = True
            except BaseException as e:  # noqa: BLE001
                err = e
                native_used = True  # nothing inserted, but don't re-run
        elif not wal_on:
            try:
                native_used = self._native_group_commit(
                    group, first, mems, frame=False) is not None
            except BaseException as e:  # noqa: BLE001
                err = e
                native_used = True  # nothing inserted, but don't re-run
        if not native_used and err is None:
            fan_out = len(group) > 1 and (
                self.options.unordered_write
                or self.options.allow_concurrent_memtable_write
            )
            if fan_out:
                pg = _InsertBarrier(len(group))
                for w in group[1:]:
                    w.pg = pg
                    w.pg_mems = mems
                    w.parallel = True
                    w.event.set()
                try:
                    leader.batch.insert_into(mems)
                    pg.member_done()
                except BaseException as e:  # noqa: BLE001
                    pg.member_done(e)
                pg.all_done.wait()
                err = pg.error
            else:
                try:
                    for w in group:
                        w.batch.insert_into(mems)
                except BaseException as e:  # noqa: BLE001
                    err = e
        _msp.finish()
        if wal_wait is not None:
            # Async WAL: the durability barrier overlapped the memtable
            # phase; settle it before completion so a failed group never
            # acknowledges.
            sync_point("DBImpl::GroupCommit:BeforeWALBarrier")
            _fsp = _tm.span("write.fsync_barrier", staged=True)
            try:
                wal_wait()
            except BaseException as e:  # noqa: BLE001
                if err is None:
                    err = e
            finally:
                _fsp.finish()
        self._tick_write_group(group, native_used and err is None)
        self._complete_staged_group(group, first, last, err)
        if err is not None:
            raise err

    def _append_group_wal(self, group: list[_Writer], first_seq: int):
        """WAL append for one group through the Python encoder (caller
        holds _mutex). Returns the durability barrier from
        _group_wal_durability: None when durability settled inline, else a
        zero-arg callable the leader invokes AFTER the memtable phase."""
        if not (self.options.wal_enabled and not group[0].opts.disable_wal):
            return None
        if len(group) == 1:
            rec = group[0].batch.data()
        else:
            merged = WriteBatch()
            merged.set_sequence(first_seq)
            for w in group:
                merged.append_from(w.batch)
            rec = merged.data()
        self._wal.add_record(rec)
        return self._group_wal_durability(group, len(rec))

    def _group_wal_durability(self, group: list[_Writer], rec_len: int):
        """Shared durability tail of both WAL encoders (Python merge and
        the native plane): stats ticks plus the sync/flush barrier. With
        the async WAL writer, returns a callable that waits the ring
        barrier — WAL durability leaves the _mutex critical section and
        overlaps the memtable phase; concurrent leaders' sync barriers
        coalesce into shared fsyncs on the writer thread. Without it,
        settles inline (the seed ordering: durability before insert) and
        returns None."""
        from toplingdb_tpu.utils.kill_point import test_kill_random

        stats = self.stats
        if stats is not None:
            stats.record_tick(_st.WAL_BYTES, rec_len)
            stats.record_tick(_st.WRITE_WITH_WAL, len(group))
        if _st.perf_level:
            # PerfContext write-plane feed (reference wal_write_bytes):
            # the leader's thread accounts the whole group's WAL record.
            _st.perf_context().wal_write_bytes += rec_len
        want_sync = any(w.opts.sync for w in group)
        wfile = self._wal._f
        if self._wal_ring is not None and hasattr(wfile, "sync_async"):
            _sp = _tm.current_span()
            if _sp is not None:
                # Ring depth AT ENQUEUE: how backed up the async WAL
                # writer was when this group's barrier was submitted.
                _sp.tag(wal_ring_depth=len(self._wal_ring._q),
                        want_sync=want_sync)
            tok = wfile.sync_async() if want_sync else wfile.append_barrier()

            def wait(tok=tok, want_sync=want_sync, stats=stats):
                t0 = time.perf_counter() if (want_sync
                                             and stats is not None) else 0
                try:
                    tok.wait()
                except BaseException as e:  # noqa: BLE001
                    # The memtable phase already ran: latch a HARD error so
                    # writes stall until resume() (reference ErrorHandler
                    # on a WAL write failure).
                    self._set_background_error(e, reason="wal")
                    raise
                if want_sync and stats is not None:
                    stats.record_tick(_st.WAL_SYNCS)
                    stats.record_in_histogram(
                        _st.WAL_FILE_SYNC_MICROS,
                        (time.perf_counter() - t0) * 1e6)
                test_kill_random("DBImpl::WriteImpl:AfterWAL")

            return wait
        if want_sync:
            t_sync = time.perf_counter() if stats is not None else 0
            self._wal.sync()
            if stats is not None:
                stats.record_tick(_st.WAL_SYNCS)
                stats.record_in_histogram(
                    _st.WAL_FILE_SYNC_MICROS,
                    (time.perf_counter() - t_sync) * 1e6)
        else:
            self._wal.flush()
        test_kill_random("DBImpl::WriteImpl:AfterWAL")
        return None

    # -- fused native write plane (ISSUE 7 tentpole) --------------------

    def _resolve_write_plane(self):
        """tpulsm_wb_group_commit, or False when the plane is unavailable
        for this DB (knob off, no native lib, ts comparator)."""
        wp = self._write_plane
        if wp is not None:
            return wp
        fn = False
        if (self._write_plane_knob
                and self.icmp.user_comparator.timestamp_size == 0):
            from toplingdb_tpu import native

            l = native.lib()
            f = getattr(l, "tpulsm_wb_group_commit", None) \
                if l is not None else None
            if f is not None:
                fn = f
        self._write_plane = fn
        return fn

    def _native_group_commit(self, group: list[_Writer], first_seq: int,
                             mems, frame: bool):
        """The fused native write plane for one group
        (tpulsm_wb_group_commit): the frame call re-sequences the merged
        header, frames the WAL record gather-style (no Python append_from
        copy, no Python crc framing) and re-hashes carried protection in
        the same validation pass; the insert half applies every record to
        the memtable rep with consecutive seqnos in one GIL-released call.

        frame=True (caller holds _mutex, WAL on): frames + appends +
        starts durability, returning (wal_wait_or_None, insert_fn) — the
        caller runs insert_fn() as the memtable phase (outside _mutex in
        the staged modes; the insert call skips re-validation because the
        frame call just proved these exact buffers).
        frame=False (WAL off for this group): validates + inserts in ONE
        call and returns (None, None).
        Returns None on fallback — the Python interiors stay the oracle:
        CF-prefixed records, range deletes, wide-column entities,
        merge-heavy groups, ts comparators, non-native reps, stale
        protection. Raises Corruption — with NOTHING framed or inserted —
        on a protection mismatch."""
        fn = self._resolve_write_plane()
        if not fn:
            return None
        mem0 = mems.get(0)
        gh = mem0.group_handle() if mem0 is not None else None
        if gh is None:
            return None
        pb = self._protection
        reps = []
        prot_vecs = [] if pb else None
        total = 0
        n_stale = 0
        for w in group:
            b = w.batch
            if (not b._simple or b._has_wide
                    or (b._n_merge and b._n_merge * 2 > b._count)):
                return None  # fallback matrix: Python path is the oracle
            if pb:
                if b._prot is None or b._pb != pb:
                    return None
                if b._prot_n != b._count:
                    n_stale += 1
                else:
                    prot_vecs.append(b._prot)
            reps.append(b.data())
            total += b._count
        if total == 0:
            return None
        # Protection: every member current -> VERIFY the carried vectors;
        # every member stale (the DB.write deferral) -> FILL them fused
        # with the frame walk; a mixed group falls back (rare — each
        # member must keep its own verification point).
        fill = n_stale == len(group) if pb and n_stale else False
        if pb and n_stale and not fill:
            return None
        import ctypes

        n = len(reps)
        at = _GC_ARR_TYPES.get(n)
        if at is None:
            if len(_GC_ARR_TYPES) > 512:
                _GC_ARR_TYPES.clear()
            at = _GC_ARR_TYPES[n] = (ctypes.c_char_p * n, ctypes.c_int64 * n)
        rep_arr = at[0](*reps)
        len_arr = at[1](*[len(r) for r in reps])
        prot_ptr = None
        n_prots = 0
        pv = None
        if pb:
            if fill:
                prot_ptr = (ctypes.c_uint64 * total)()
                n_prots = total
            else:
                base = getattr(prot_vecs[0], "base", None) if n == 1 \
                    else None
                if isinstance(base, ctypes.Array) and len(base) == total:
                    # _native_protect's buffer: no data_as crossing.
                    prot_ptr = base
                    n_prots = total
                else:
                    import numpy as np

                    pv = (np.ascontiguousarray(prot_vecs[0],
                                               dtype=np.uint64)
                          if n == 1 else np.concatenate(
                              [np.asarray(p, dtype=np.uint64)
                               for p in prot_vecs]))
                    prot_ptr = pv.ctypes.data_as(
                        ctypes.POINTER(ctypes.c_uint64))
                    n_prots = len(pv)
        # out[0..4]: framed bytes / new block offset / mem byte delta /
        # delete count / merged record length. out[5..7]: native interior
        # timings in ns (validate / WAL frame / memtable insert) — the
        # telemetry plane's window into the GIL-released interior without
        # any per-record Python overhead (older .so builds leave them 0).
        out = (ctypes.c_int64 * 8)()

        def run(mode, block_off=0, log_no=-1, wal_ptr=None, cap=0):
            rc = fn(gh[0], gh[1], rep_arr, len_arr, n, first_seq, prot_ptr,
                    n_prots, pb, mode, block_off,
                    log_no, wal_ptr, cap, out)
            if rc <= -5:
                raise Corruption(
                    f"write batch protection mismatch at record "
                    f"{-(rc + 5)} during group commit"
                )
            return rc

        def adopt_filled():
            # Hand the fused-computed vectors back to the batches (the
            # same zero-copy shape _native_protect produces), so the
            # memtable carry and any later verify see them.
            import numpy as np

            vec = np.frombuffer(prot_ptr, dtype=np.uint64)
            off = 0
            for w in group:
                c = w.batch._count
                w.batch._prot = vec if n == 1 else vec[off:off + c]
                w.batch._prot_n = c
                off += c

        def insert(validated=True):
            rc = run(2 | (4 if validated else 8 if fill else 0))
            if rc < 0:  # only reachable from the unvalidated single call
                return None
            if out[7]:
                _tm.span_event("native.memtable_insert", out[7] // 1000,
                               records=total)
            if fill and not validated:
                adopt_filled()
            seq = first_seq
            meta = []
            for w, rep in zip(group, reps):
                meta.append((seq, rep, w.batch._prot if pb else None))
                seq += w.batch._count
            mem0.note_group_applied(meta, int(out[2]), int(out[3]), rc)
            return rc

        if not frame:
            return (None, None) if insert(validated=False) is not None \
                else None
        if "add_record" in self._wal.__dict__:
            # Instance-hooked writer (tests / sync points interpose on
            # add_record): the hook must see every record — Python path.
            return None
        block_off, log_no = self._wal.framing_state()
        merged_len = 12 + sum(len(r) - 12 for r in reps)
        # Tight framed bound: one 7/11B header per fragment + <=10B of
        # block-tail padding (a fragment spans at most BLOCK-hdr bytes).
        cap = merged_len + 11 * (merged_len // 32757 + 2) + 16
        wal_buf = bytearray(cap)
        wal_ptr = (ctypes.c_ubyte * cap).from_buffer(wal_buf)
        rc = run(1 | (8 if fill else 0), block_off, log_no, wal_ptr, cap)
        del wal_ptr  # release the bytearray's buffer export
        if rc < 0:
            return None  # -2/-4: the Python path decides (and names) it
        if out[5]:
            _tm.span_event("native.wal_validate", out[5] // 1000,
                           records=rc)
        if out[6]:
            _tm.span_event("native.wal_frame", out[6] // 1000,
                           bytes=int(out[0]))
        if fill:
            adopt_filled()
        self._wal.append_preframed(memoryview(wal_buf)[:int(out[0])],
                                   int(out[1]))
        return (self._group_wal_durability(group, int(out[4])), insert)

    def _tick_write_group(self, group: list[_Writer], native: bool) -> None:
        """WRITE_GROUP_* observability for one committed group."""
        stats = self.stats
        if stats is None:
            return
        stats.record_ticks((
            (_st.WRITE_GROUP_LED, 1),
            (_st.WRITE_GROUP_FOLLOWERS, len(group) - 1),
            (_st.WRITE_GROUP_NATIVE_COMMITS if native
             else _st.WRITE_GROUP_FALLBACKS, 1),
        ))
        stats.record_in_histogram(
            _st.WRITE_GROUP_BYTES,
            sum(w.batch.data_size() for w in group))

    def _complete_staged_group(self, group: list[_Writer], first: int,
                               last: int, err: BaseException | None) -> None:
        """Mark one staged group's memtable phase complete, advance the
        publish watermark in allocation order, and run the post-commit work
        (stats, flush trigger) when the watermark moved. The group is marked
        complete even on error — its records are durable in the WAL, and
        stalling the watermark would deadlock every later write."""
        with self._mutex:
            self._mt_inflight -= 1
            if err is None:
                for w in group:
                    if w.on_sequenced is not None:
                        s0 = w.batch.sequence()
                        w.on_sequenced(s0, s0 + w.batch.count() - 1)
            entry = self._alloc_entry.pop(first, None)
            if entry is not None:
                entry[2] = True
            ranges = self._alloc_ranges
            while ranges and ranges[0][2]:
                # In-order publish watermark: O(1) per completed group
                # (deque popleft + dict mark), no front-of-list pops or
                # per-completion set scans.
                self.versions.last_sequence = ranges.popleft()[1]
            if not self._closed:
                self._post_publish_work(group)
            self._mt_cv.notify_all()
        for w in group:
            w.done = True
            w.error = err
            w.parallel = False
            if w is not group[0]:
                w.event.set()

    def _maybe_sample_seqno_time(self, seq: int) -> None:
        """Record (seq, now) when the sampling period elapsed (period 0 =
        manual only); shared by both publish paths. Persistence happens
        off the write hot path (bg flush / explicit flush / close)."""
        period = self.options.seqno_time_sample_period_sec
        if period <= 0:
            return
        now = time.time()
        if now - self._last_seqno_time_sample >= period:
            self._last_seqno_time_sample = now
            self.seqno_to_time.append(seq, int(now))
            self._seqno_time_dirty = True

    def _save_seqno_time(self) -> None:
        """Best-effort sidecar persistence of the seqno<->time mapping
        (the reference rides MANIFEST/SST properties): without it a
        reopen would treat ALL existing data as young for
        preclude_last_level_data_seconds. Called OUTSIDE the write hot
        path — samples mark dirty; flush/close persist."""
        if self._seqno_time_path is None:
            return
        self._seqno_time_dirty = False
        try:
            import json as _json

            self.env.write_file(
                self._seqno_time_path,
                _json.dumps(self.seqno_to_time.to_list()).encode())
        except Exception as e:
            _errors.swallow(reason="seqno-time-sidecar-save", exc=e,
                            stats=self.stats)

    def _post_publish_work(self, group: list[_Writer]) -> None:
        """Stats + seqno/time sampling + flush trigger after a publish
        (caller holds _mutex)."""
        seq_top = self.versions.last_sequence + 1
        self._maybe_sample_seqno_time(seq_top - 1)
        if self.stats is not None:
            from toplingdb_tpu.utils import statistics as st

            self.stats.record_tick(
                st.NUMBER_KEYS_WRITTEN, sum(w.batch.count() for w in group)
            )
            self.stats.record_tick(
                st.BYTES_WRITTEN, sum(w.batch.data_size() for w in group)
            )
        total_mem = sum(
            c.mem.approximate_memory_usage() for c in self._cfs.values()
        )
        wbm = self.options.write_buffer_manager
        self._sync_wbm()
        if total_mem >= self.options.write_buffer_size or (
                wbm is not None and wbm.should_flush()
                and total_mem >= 4096):  # floor: don't thrash tiny DBs
            self._switch_memtable()
            self._flush_immutables()

    def _commit_write_group(self, group: list[_Writer]) -> None:
        with self._mutex:
            self._check_open()
            if self._bg_error is not None:
                from toplingdb_tpu.utils.status import Severity as _Sev

                if self._bg_error_severity >= _Sev.HARD_ERROR:
                    raise IOError_(
                        f"background error pending (call resume()): "
                        f"{self._bg_error!r}"
                    )
            first_seq = max(self._seq_alloc, self.versions.last_sequence) + 1
            seq = first_seq
            for w in group:
                w.batch.set_sequence(seq)
                seq += w.batch.count()
            self._seq_alloc = seq - 1
            mems = {cf_id: cfd.mem for cf_id, cfd in self._cfs.items()}
            # Fused native plane: frame+append the merged WAL record first
            # (mode 1 — durability ordering matches the Python path: a WAL
            # failure inserts NOTHING), then apply the whole group to the
            # memtable rep in one GIL-released call (mode 2).
            wal_on = (self.options.wal_enabled
                      and not group[0].opts.disable_wal)
            wal_wait = None
            with _tm.span("write.wal_frame", group=len(group),
                          wal=wal_on):
                plane = self._native_group_commit(group, first_seq, mems,
                                                  frame=wal_on)
                if plane is None and wal_on:
                    wal_wait = self._append_group_wal(group, first_seq)
            _mt0 = time.perf_counter() if _st.perf_level >= 2 else 0.0
            if plane is not None:
                p_wait, insert_fn = plane
                if p_wait is not None:
                    wal_wait = p_wait
                if insert_fn is not None:
                    with _tm.span("write.memtable_apply",
                                  group=len(group), native=True):
                        insert_fn()
            if plane is not None:
                self._tick_write_group(group, native=True)
            else:
                with _tm.span("write.memtable_apply", group=len(group),
                              native=False):
                    if (self.options.allow_concurrent_memtable_write
                            and len(group) > 1):
                        # Parallel memtable phase (reference
                        # LaunchParallelMemTableWriters): followers insert
                        # their own batches concurrently — the native
                        # skiplist insert is lock-free and GIL-releasing, so
                        # this scales with threads. The leader holds _mutex
                        # throughout, so no memtable switch can race the
                        # phase.
                        pg = _InsertBarrier(len(group))
                        for w in group[1:]:
                            w.pg = pg
                            w.pg_mems = mems
                            w.parallel = True
                            w.event.set()
                        try:
                            group[0].batch.insert_into(mems)
                            pg.member_done()
                        except BaseException as e:  # noqa: BLE001
                            pg.member_done(e)
                        pg.all_done.wait()
                        for w in group[1:]:
                            w.parallel = False
                        if pg.error is not None:
                            raise pg.error
                    else:
                        for w in group:
                            w.batch.insert_into(mems)
                self._tick_write_group(group, native=False)
            if _mt0:
                # PerfContext timed tier (reference write_memtable_time).
                _st.perf_context().write_memtable_time += int(
                    (time.perf_counter() - _mt0) * 1e9)
            if wal_wait is not None:
                # async WAL: durability overlapped the inserts
                sync_point("DBImpl::GroupCommit:BeforeWALBarrier")
                with _tm.span("write.fsync_barrier"):
                    wal_wait()
            # on_sequenced fires only after the WAL append + memtable insert
            # succeeded (a failed group must not leak registrations), but
            # BEFORE the group's sequence publishes: entries stay invisible
            # (seq > last_sequence) until the registration exists.
            for w in group:
                if w.on_sequenced is not None:
                    s0 = w.batch.sequence()
                    w.on_sequenced(s0, s0 + w.batch.count() - 1)
            self.versions.last_sequence = seq - 1
            self._maybe_sample_seqno_time(seq - 1)
            if self.stats is not None:
                from toplingdb_tpu.utils import statistics as st

                self.stats.record_tick(
                    st.NUMBER_KEYS_WRITTEN, sum(w.batch.count() for w in group)
                )
                bw = sum(w.batch.data_size() for w in group)
                self.stats.record_tick(st.BYTES_WRITTEN, bw)
                self.stats.record_in_histogram(st.BYTES_PER_WRITE, bw)
                self.stats.record_tick(st.WRITE_DONE_BY_SELF)
                if len(group) > 1:
                    self.stats.record_tick(st.WRITE_DONE_BY_OTHER,
                                           len(group) - 1)
            total_mem = sum(
                c.mem.approximate_memory_usage() for c in self._cfs.values()
            )
            wbm = self.options.write_buffer_manager
            self._sync_wbm()
            if total_mem >= self.options.write_buffer_size or (
                    wbm is not None and wbm.should_flush()
                    and total_mem >= 4096):  # floor: don't thrash tiny DBs
                self._switch_memtable()
                self._flush_immutables()

    def _sync_wbm(self) -> None:
        """Reconcile this DB's memtable memory with the shared
        WriteBufferManager (reference WriteBufferManager charging) — called
        wherever memtable memory changes (writes AND flushes)."""
        wbm = self.options.write_buffer_manager
        if wbm is None:
            return
        total = sum(
            c.mem.approximate_memory_usage()
            + sum(m.approximate_memory_usage() for m in c.imm)
            for c in self._cfs.values()
        )
        delta = total - self._wbm_charged
        if delta > 0:
            wbm.reserve(delta)
        elif delta < 0:
            wbm.free(-delta)
        self._wbm_charged = total

    def _switch_memtable(self) -> None:
        """Seal every CF's non-empty active memtable and start a new WAL
        (reference DBImpl::SwitchMemtable; all-CF switching = atomic-flush
        behavior so log_number can advance safely)."""
        from toplingdb_tpu.utils.kill_point import test_kill_random

        # Staged groups insert into the active memtables OUTSIDE _mutex
        # (pipelined/unordered modes): sealing a memtable mid-insert could
        # let the flush miss an already-published entry. Drain them first
        # (reference WriteThread::WaitForMemTableWriters).
        while self._mt_inflight > 0:
            self._mt_cv.wait(timeout=10.0)
        test_kill_random("DBImpl::SwitchMemtable:Start")
        # Interleaving seam (tests/test_concurrency_interleavings.py):
        # the switch closes the current WAL, so its ordering against a
        # staged group's async durability barrier is the drain protocol
        # above — this point lets tests pin that order.
        sync_point("DBImpl::SwitchMemtable:Start")
        if self._wal is not None:
            self._wal.sync()
            self._wal.close()
            if self._sfm is not None:
                # Final size of the sealed WAL (tracked as 0 at creation).
                self._sfm.on_add_file(filename.log_file_name(
                    self.dbname, self._wal_number))
        for cfd in self._cfs.values():
            if not cfd.mem.empty():
                cfd.imm.insert(0, cfd.mem)
                cfd.mem = self._fresh_memtable()
        self._new_wal()

    def _flush_immutables(self) -> None:
        flushed = False
        for cf_id, cfd in self._cfs.items():
            if not cfd.imm:
                continue
            mems = list(cfd.imm)
            self._flush_memtables(mems, wal_number=None, cf_id=cf_id)
            cfd.imm = []
            flushed = True
        if flushed:
            # Advance log_number only after EVERY CF's data below the current
            # WAL is durable in SSTs — a crash mid-flush must still replay
            # the old WALs for the unflushed CFs.
            self.versions.log_and_apply(VersionEdit(log_number=self._wal_number))
            self._delete_obsolete_files()
            self._maybe_schedule_compaction()
        self._sync_wbm()

    def _flush_memtables(self, mems: list[MemTable], wal_number: int | None,
                         cf_id: int = 0) -> None:
        from toplingdb_tpu.utils.sync_point import sync_point

        sync_point("FlushJob::Start")
        if self._sfm is not None:
            # Preflight: refuse to START a flush only when even the
            # reserved flush/WAL headroom can't absorb it (flushes may
            # spend the headroom compactions must leave alone, so a
            # red-pressure DB still drains its memtables). A refusal
            # latches SOFT no_space — ingest resumes when space frees.
            est = sum(m.approximate_memory_usage() for m in mems)
            if not self._sfm.check_flush(est):
                if self.stats is not None:
                    self.stats.record_tick(_st.NO_SPACE_PREFLIGHT_BLOCKS, 1)
                from toplingdb_tpu.utils.status import NoSpace

                err = NoSpace(
                    f"flush of ~{est} bytes would breach the disk budget")
                self._set_background_error(err, reason="no_space")
                raise err
        from toplingdb_tpu.utils.thread_status import thread_operation

        with thread_operation("flush", f"cf{cf_id}", self.dbname):
            self._flush_memtables_inner(mems, wal_number, cf_id)

    def _flush_memtables_inner(self, mems: list[MemTable],
                               wal_number: int | None, cf_id: int) -> None:
        # Flushes are rare and high-value: always traced while a tracer
        # exists (sampling applies to the per-op read/write roots only).
        _root = (self.tracer.start("flush", cf_id=cf_id,
                                   memtables=len(mems))
                 if self.tracer is not None else _tm.NOOP_SPAN)
        try:
            self._flush_memtables_traced(mems, wal_number, cf_id)
        finally:
            _root.finish()

    def _flush_memtables_traced(self, mems: list[MemTable],
                                wal_number: int | None, cf_id: int) -> None:
        t0 = time.time()
        if self._seqno_time_dirty:
            # Every flush path (auto-switch, write-path stall, bg worker)
            # funnels here off the write hot path: persist pending
            # seqno-time samples so a crash doesn't lose them and make
            # all existing data look young after reopen.
            self._save_seqno_time()
        fnum = self.versions.new_file_number()
        blob_num = (
            self.versions.new_file_number()
            if self.options.enable_blob_files else None
        )
        # Guard in-flight outputs (incl. the blob sibling) from obsolete-file
        # GC until the version edit lands.
        self._pending_outputs.add(fnum)
        if blob_num is not None:
            self._pending_outputs.add(blob_num)
        try:
            with _tm.span("flush.build_table", file_number=fnum):
                meta = flush_memtable_to_table(
                    self.env, self.dbname, fnum, self.icmp, mems,
                    self.options.table_options_for_level(0),
                    creation_time=int(time.time()),
                    blob_file_number=blob_num,
                    min_blob_size=self.options.min_blob_size,
                    column_family=(cf_id, self.cf_name(cf_id)),
                )
            from toplingdb_tpu.utils.kill_point import test_kill_random

            test_kill_random("FlushJob::AfterTableWrite")
            if meta is not None:
                self._stamp_file_checksums([meta])
            edit = VersionEdit(log_number=wal_number, column_family=cf_id)
            if meta is not None:
                edit.add_file(0, meta)
            self.versions.log_and_apply(edit)
        finally:
            self._pending_outputs.discard(fnum)
            if blob_num is not None:
                self._pending_outputs.discard(blob_num)
        if meta is not None and self._sfm is not None:
            self._sfm.on_add_file(
                filename.table_file_name(self.dbname, meta.number),
                meta.file_size)
            if blob_num is not None:
                from toplingdb_tpu.db.blob import blob_file_name

                bpath = blob_file_name(self.dbname, blob_num)
                if self.env.file_exists(bpath):
                    self._sfm.on_add_file(bpath)
        if meta is not None:
            from toplingdb_tpu.utils import statistics as st
            from toplingdb_tpu.utils.listener import FlushJobInfo, notify

            if self.stats is not None:
                self.stats.record_tick(st.FLUSH_WRITE_BYTES, meta.file_size)
                self.stats.record_in_histogram(
                    st.FLUSH_TIME_MICROS, (time.time() - t0) * 1e6
                )
            self.event_logger.log(
                "flush_finished", file_number=meta.number,
                file_size=meta.file_size, num_entries=meta.num_entries,
            )
            notify(self.options.listeners, "on_flush_completed", self,
                   FlushJobInfo(
                       db_name=self.dbname, file_number=meta.number,
                       file_size=meta.file_size, num_entries=meta.num_entries,
                       smallest_seqno=meta.smallest_seqno,
                       largest_seqno=meta.largest_seqno,
                   ))

    def flush(self, fopts: FlushOptions = FlushOptions()) -> None:
        with self._mutex:
            self._check_open()
            if any(not c.mem.empty() for c in self._cfs.values()):
                self._switch_memtable()
            self._flush_immutables()
        if self._seqno_time_dirty:
            self._save_seqno_time()  # outside _mutex: best-effort IO

    # ==================================================================
    # Read path
    # ==================================================================

    # -- workload tracing (reference DB::StartTrace / EndTrace) ----------

    def start_trace(self, trace_path: str, options=None) -> None:
        """Record every subsequent Get/MultiGet/Write/Iterator-seek to
        `trace_path` until end_trace (reference DB::StartTrace,
        trace_replay/trace_replay.cc). Replay with utils.trace.Replayer."""
        from toplingdb_tpu.utils.trace import OpTracer

        self._check_open()
        if self._op_tracer is not None:
            from toplingdb_tpu.utils.status import InvalidArgument

            raise InvalidArgument("a trace is already being recorded")
        self._op_tracer = OpTracer(self.env, trace_path, options)

    def end_trace(self) -> None:
        tr = self._op_tracer
        self._op_tracer = None
        if tr is not None:
            tr.close()

    def _nget_state(self, cfd, opts):
        """Shared eligibility gate + per-thread call state for the native
        read fast paths. Returns (lib, state) with state None when the
        Python chain must run. State is PER-THREAD (the ctx's out/value
        buffers are written inside a GIL-released call — sharing them
        across threads would race), keyed by object IDENTITY of (active
        mem, imm list, version); the state holds refs so ids can't recycle
        while cached."""
        lib = getattr(self, "_nget_lib", False)
        if lib is False:
            from toplingdb_tpu import native

            lib = native.lib()
            if lib is None or not hasattr(lib, "tpulsm_getctx_get"):
                lib = None
            if getattr(self.options, "block_cache", None) is not None:
                # A user-configured block cache is a contract (capacity
                # budget, secondary tier, tracer, stats) the native
                # engine's internal LRU would silently bypass.
                lib = None
            self._nget_lib = lib
        if (lib is None or opts.just_check_key_exists
                or self._excluded_for(opts)):
            return lib, None
        mem = cfd.mem
        if mem._range_dels:
            # The ACTIVE memtable mutates under a cached state — this
            # check must run per call; immutables are frozen and are
            # vetted once at state-build time below.
            return lib, None
        imm = cfd.imm
        version = self.versions.cf_current(cfd.handle.id)
        tl = self._nget_tl
        try:
            states = tl.states
        except AttributeError:
            states = tl.states = {}
        cc = states.get(cfd.handle.id)
        if cc is not None and cc.mem is mem and cc.version is version \
                and cc.imm == imm:
            return lib, cc
        if any(m._range_dels for m in imm):
            return lib, None
        cc = _NGetState.build(lib, mem, imm, version, self.table_cache)
        if cc is None:
            return lib, None
        states[cfd.handle.id] = cc
        return lib, cc

    def _native_get(self, cfd, key: bytes, snap_seq: int, opts):
        """One-call native point lookup (reference GetImpl's chain in one
        GIL-released call, db_impl.cc:2079 → version_set.cc:2606 →
        block_based_table_reader.cc:2095). Returns (handled, value, src):
        handled=False → run the Python chain (ineligible, or the native
        walk hit something only the Python state machine handles). The
        hot call carries 4 args against a persistent native context; the
        value and counters are read from ctx-owned memory mapped once."""
        # Inlined steady-state check (one cached-state hit per Get is the
        # common case; _nget_state handles every slow/ineligible path).
        mem = cfd.mem
        cc = None
        if (opts is _DEFAULT_READ and not mem._range_dels
                and self._undecided_provider is None):
            states = getattr(self._nget_tl, "states", None)
            if states is not None:
                cc = states.get(cfd.handle.id)
                if cc is not None and (
                        cc.mem is not mem
                        or cc.version is not self.versions.cf_current(
                            cfd.handle.id)
                        or cc.imm != cfd.imm):
                    cc = None
        if cc is None:
            lib, cc = self._nget_state(cfd, opts)
            if cc is None:
                return False, None, None
        fast = cc.fast
        if fast is not None:
            r = fast(cc.ctx, key, snap_seq)
            if r is False:
                return False, None, None
            rc = 0 if r is None else 1
        else:
            rc = cc.fn(cc.ctx, key, len(key), snap_seq)
            if rc == 2 or rc < 0:
                return False, None, None
        out = cc.out
        st = _st
        if st.perf_level:
            pctx = st.perf_context()
            pctx.get_from_memtable_count += out[2]
            pctx.bloom_sst_miss_count += out[3]
            pctx.bloom_sst_hit_count += out[4]
            pctx.block_cache_hit_count += out[5]
            pctx.block_read_count += out[6]
            pctx.block_read_byte += out[7]
        if self.stats is not None and (out[3] or out[5] or out[6]):
            self.stats.record_ticks(
                (t, c) for t, c in ((st.BLOOM_USEFUL, out[3]),
                                    (st.BLOCK_CACHE_HIT, out[5]),
                                    (st.BLOCK_CACHE_MISS, out[6])) if c)
        src = out[1]
        src = "mem" if src == 0 else (src - 1 if src >= 1 else None)
        if rc == 1:
            if fast is not None:
                return True, r, src  # the extension already built bytes
            vlen = out[0]
            if vlen > cc.val_cap:  # ctx grew its buffer: re-map
                cc.remap(cc._lib, vlen)
            import ctypes

            return True, ctypes.string_at(cc.val_ptr, vlen), src
        return True, None, src

    def _probe_memtable(self, mem, key: bytes, snap_seq: int,
                        ctx: GetContext) -> bool:
        """One memtable source; returns False when the lookup is complete."""
        from toplingdb_tpu.utils import statistics as st

        if st.perf_level:
            st.perf_context().get_from_memtable_count += 1
        ctx.add_tombstone_seq(mem.covering_tombstone_seq(key, snap_seq))
        for seq, t, val in mem.entries_for_key(key, snap_seq):
            if not ctx.save_value(seq, t, val):
                return False
        return True

    def _probe_file(self, reader, key: bytes, snap_seq: int, ctx: GetContext,
                    tombs, it=None, preread=None) -> tuple[bool, object]:
        """One SST source; `tombs` is the file's parsed RangeTombstone list;
        `it` is a reusable iterator for this reader (created on demand).
        `preread`: async read plane overlay (block-table PrereadSpans or
        zip value-group preload) — only ever non-None for readers whose
        new_iterator accepts it. Returns (continue?, iterator)."""
        from toplingdb_tpu.utils import statistics as st

        ucmp = self.icmp.user_comparator
        for t in tombs:
            if ucmp.compare(t.begin, key) <= 0 and ucmp.compare(key, t.end) < 0:
                ctx.add_tombstone_seq(t.seq)
        has_filter = (getattr(reader, "_filter_data", None) is not None
                      or getattr(reader, "_filter_top", None) is not None)
        if not reader.key_may_match(key):
            if self.stats is not None:
                self.stats.record_tick(st.BLOOM_USEFUL)
            if st.perf_level:
                st.perf_context().bloom_sst_miss_count += 1
            return True, it
        if has_filter and st.perf_level:
            # Only a CONSULTED filter counts (fail-open paths don't).
            st.perf_context().bloom_sst_hit_count += 1
        if getattr(reader, "has_hash_index", False):
            # O(1) bucket probe (single_fast hash index): lands on the
            # newest version; the loop below skips seqs above the snapshot.
            ordinal = reader.hash_probe(key)
            if ordinal is None:
                return True, it  # definitively absent from this file
            if it is None:
                it = reader.new_iterator()
            it.seek_ordinal(ordinal)
        else:
            if it is None:
                it = (reader.new_iterator(preread=preread)
                      if preread is not None else reader.new_iterator())
            it.seek(dbformat.make_internal_key(
                key, snap_seq, dbformat.VALUE_TYPE_FOR_SEEK
            ))
        while it.valid():
            uk, seq, t = dbformat.split_internal_key(it.key())
            if ucmp.compare(uk, key) != 0:
                break
            if seq <= snap_seq:
                if not ctx.save_value(seq, t, it.value()):
                    return False, it
            it.next()
        return True, it

    def _parsed_tombstones(self, reader):
        return [RangeTombstone.from_table_entry(b, e)
                for b, e in reader.range_del_entries()]

    def get(self, key: bytes, opts: ReadOptions = _DEFAULT_READ,
            cf=None) -> bytes | None:
        """Point lookup (reference DBImpl::GetImpl, db_impl.cc:2079).
        Returns None if not found. A wide-column entity presents as its
        anonymous default column (reference Get-on-entity semantics,
        db/wide/wide_columns_helper) — use get_entity for every column.
        Entity detection is by the DEDICATED kTypeWideColumnEntity-style
        value type, so plain binary values are never reinterpreted;
        Options.legacy_wide_column_unwrap re-enables the old magic-prefix
        sniff for databases written before the dedicated type existed."""
        sched = self._trace_sched
        if sched is not None:
            m = sched()
            if m:
                return self._get_traced(key, opts, cf, m == 1)
        v, is_entity = self._get_impl_entry(key, opts, cf)
        if v is not None:
            if is_entity:
                from toplingdb_tpu.db.wide_columns import default_column_of

                return default_column_of(v)
            if (v[:1] == b"\x00"
                    and getattr(self.options, "legacy_wide_column_unwrap",
                                False)):
                from toplingdb_tpu.db.wide_columns import default_column_of

                return default_column_of(v)
        return v

    def _get_traced(self, key: bytes, opts, cf, sampled: bool):
        """The rare half of get(): sampled root span, or the slow-watch
        backstop when trace_slow_usec is set (every get pays one
        perf_counter pair in that mode)."""
        tracer = self.tracer
        root = tracer.start("db.get") if sampled else None
        t0 = 0.0 if sampled else time.perf_counter()
        try:
            v, is_entity = self._get_impl_entry(key, opts, cf)
        finally:
            if root is not None:
                root.finish()
            else:
                _us = (time.perf_counter() - t0) * 1e6
                if _us >= tracer.slow_usec:
                    tracer.note_slow("db.get", _us)
        if v is not None:
            if is_entity:
                from toplingdb_tpu.db.wide_columns import default_column_of

                return default_column_of(v)
            if (v[:1] == b"\x00"
                    and getattr(self.options, "legacy_wide_column_unwrap",
                                False)):
                from toplingdb_tpu.db.wide_columns import default_column_of

                return default_column_of(v)
        return v

    def _get_impl_entry(self, key: bytes, opts: ReadOptions = _DEFAULT_READ,
                        cf=None, record_trace: bool = True):
        """Returns (value_or_None, is_wide_column_entity)."""
        self._check_open()
        if record_trace:
            tr = self._op_tracer
            if tr is not None:
                tr.record_get(key)
        if self.icmp.user_comparator.timestamp_size:
            return self._get_with_ts(key, opts, cf), False
        self._check_read_ts(opts)
        cfd = self._cf_data(cf)
        snap_seq = (
            opts.snapshot.sequence if opts.snapshot is not None
            else self.versions.last_sequence
        )
        st_on = self.stats is not None
        t0 = time.perf_counter() if st_on else 0.0
        # Native fast chain: memtable skiplists + SST walk in ONE
        # GIL-released C call (reference GetImpl -> Version::Get ->
        # BlockBasedTable::Get). Anything the Python state machine must
        # see (merge operands, single-delete in SSTs, blob indexes, range
        # tombstones, wide-column entities, perf-context accounting)
        # falls through below. TPULSM_ASYNC_READS=1 routes around it:
        # the async read plane lives in the Python walk, whose block
        # fetches batch-submit through the reader rings.
        async_on = self._async_reads_on()
        if not async_on:
            handled, val, src = self._native_get(cfd, key, snap_seq, opts)
            if handled:
                if st_on:
                    self._record_get_stats(t0, val, src)
                return val, False
        ctx = GetContext(
            key, snap_seq, self.options.merge_operator,
            blob_resolver=self.blob_source.get,
            excluded_ranges=self._excluded_for(opts),
        )
        # 1. Active memtable, then immutables (newest first).
        for mem in [cfd.mem] + cfd.imm:
            if not self._probe_memtable(mem, key, snap_seq, ctx):
                val = ctx.result()
                if st_on:
                    self._record_get_stats(t0, val, "mem")
                return val, ctx.result_is_entity
        # 2. SST files, newest data first. Async plane: every candidate
        # file's cache-missing blocks are submitted as ONE batch before
        # the walk, so a multi-level chain overlaps its preads (deeper
        # candidates are speculative — wasted only when an upper level
        # terminates the lookup first).
        version = self.versions.cf_current(cfd.handle.id)
        preread_map = None
        if async_on:
            file_order = [f for _lvl, f in version.files_for_get(key)]
            preread_map = self._plan_async_preread(
                file_order, {f.number: [key] for f in file_order},
                {key}, snap_seq)
        hit_level = self._walk_sst_chain(version, key, snap_seq, ctx,
                                         preread_map=preread_map)
        val = ctx.result()
        if st_on:
            self._record_get_stats(t0, val, hit_level)
        return val, ctx.result_is_entity

    def _record_get_stats(self, t0: float, val, src) -> None:
        """Read-path ticker family (reference MEMTABLE_HIT/GET_HIT_L*,
        statistics.h) — one lock acquisition via Statistics.record_get."""
        self.stats.record_get(
            (time.perf_counter() - t0) * 1e6,
            len(val) if val is not None else None, src)

    def _walk_sst_chain(self, version, key: bytes, snap_seq: int, ctx,
                        tombs_for=None, preread_map=None):
        """Probe the key's SST candidates newest-first until the lookup
        completes (shared by get, async multi_get, get_merge_operands).
        `preread_map`: async read plane overlays keyed by file number —
        the chain's block fetches were batch-submitted up front, so a
        deep walk consumes already-overlapped reads instead of paying
        one serial pread per level. Returns the level that completed
        the lookup, or None."""
        for level, f in version.files_for_get(key):
            reader = self.table_cache.get_reader(f.number)
            tombs = (tombs_for(f) if tombs_for is not None
                     else self._parsed_tombstones(reader))
            more, _ = self._probe_file(
                reader, key, snap_seq, ctx, tombs,
                preread=(preread_map.get(f.number)
                         if preread_map is not None else None))
            if not more:
                return level
        ctx.finish()
        return None

    def _max_l0_files(self) -> int:
        return max(
            (len(self.versions.cf_current(cf_id).files[0])
             for cf_id in self.versions.column_families), default=0,
        )

    def _maybe_stall_writes(self, timeout: float = 10.0) -> None:
        """L0 back-pressure (reference WriteController + the
        level0_slowdown/stop triggers, db_impl_write.cc DelayWrite): past the
        slowdown trigger writes are delayed; past the stop trigger they block
        until compaction drains L0 (the worst CF counts — a pileup in any CF
        throttles). No-op when nothing can drain L0 (auto compaction off /
        no scheduler): stalling a bulk load forever helps no one."""
        import time as _time

        opts = self.options
        if (opts.disable_auto_compactions
                or self._compaction_scheduler is None
                or self._compaction_scheduler._paused):
            return  # nothing can drain L0; stalling would only block
        n_l0 = self._max_l0_files()
        if n_l0 >= opts.level0_stop_writes_trigger:
            t0 = _time.monotonic()
            while (self._max_l0_files() >= opts.level0_stop_writes_trigger
                   and _time.monotonic() - t0 < timeout
                   and not self._closed):
                self._maybe_schedule_compaction()
                _time.sleep(0.01)
            stalled = _time.monotonic() - t0
            self._account_stall("stopped", stalled)
            if stalled >= timeout:
                self.event_logger.log(
                    "write_stall_timeout", l0_files=self._max_l0_files(),
                    stalled_s=round(stalled, 2),
                )
        elif n_l0 >= opts.level0_slowdown_writes_trigger:
            # Proportional delay ramp toward the stop trigger.
            span = max(1, opts.level0_stop_writes_trigger
                       - opts.level0_slowdown_writes_trigger)
            frac = (n_l0 - opts.level0_slowdown_writes_trigger + 1) / span
            delay = min(0.05 * frac, 0.05)
            _time.sleep(delay)
            self._account_stall("delayed", delay)

    def _account_stall(self, state: str, stalled_s: float) -> None:
        """Fold one stall episode into the cumulative totals + the
        STALL_MICROS/WRITE_STALL_COUNT tickers and the write.stall.micros
        histogram (previously only the stop path ticked, and only
        STALL_MICROS — the delay ramp was invisible)."""
        micros = int(stalled_s * 1e6)
        tot = self._stall_totals
        tot["stalls"] += 1
        tot["stall_micros"] += micros
        tot["last_stall_micros"] = micros
        tot["last_state"] = state
        if self.stats is not None:
            from toplingdb_tpu.utils import statistics as st

            self.stats.record_tick(st.STALL_MICROS, micros)
            self.stats.record_tick(st.WRITE_STALL_COUNT)
            self.stats.record_in_histogram(st.WRITE_STALL_MICROS_HIST,
                                           micros)

    def write_stall_state(self) -> dict:
        """Queryable write-stall state (the sharding router's backpressure
        signal, also exposed as /metrics gauges): the LIVE state derived
        from L0 file counts vs the slowdown/stop triggers — "none",
        "delayed", or "stopped" — plus cumulative stall totals. `drainable`
        is False when nothing can reduce L0 (auto compaction off /
        scheduler paused), in which case writes are never stalled either."""
        opts = self.options
        n_l0 = self._max_l0_files()
        drainable = not (opts.disable_auto_compactions
                         or self._compaction_scheduler is None
                         or self._compaction_scheduler._paused)
        if not drainable:
            state = "none"
        elif n_l0 >= opts.level0_stop_writes_trigger:
            state = "stopped"
        elif n_l0 >= opts.level0_slowdown_writes_trigger:
            state = "delayed"
        else:
            state = "none"
        out = dict(self._stall_totals)
        out.update(
            state=state,
            l0_files=n_l0,
            drainable=drainable,
            slowdown_trigger=opts.level0_slowdown_writes_trigger,
            stop_trigger=opts.level0_stop_writes_trigger,
        )
        return out

    def _check_read_ts(self, opts: ReadOptions) -> None:
        """Validate ReadOptions.timestamp against this DB (reference: reads
        need a ts comparator, and reading below full_history_ts_low is
        InvalidArgument — that history may already be collapsed, so the
        answer would depend on compaction timing)."""
        if opts.timestamp is None:
            return
        if self.icmp.user_comparator.timestamp_size == 0:
            raise InvalidArgument(
                "ReadOptions.timestamp requires a timestamp-carrying "
                "comparator (U64_TS_BYTEWISE)"
            )
        if opts.timestamp < self.options.full_history_ts_low:
            raise InvalidArgument(
                f"cannot read at ts={opts.timestamp}: history below "
                f"full_history_ts_low={self.options.full_history_ts_low} "
                f"may be collapsed"
            )

    def _ts_lookup(self, it, key: bytes) -> tuple[bytes, int] | None:
        """Shared ts-DB point lookup over an existing ts-aware iterator:
        seek lands directly on the newest visible version of the key."""
        it.seek(key)
        if it.valid() and it.key() == key:
            # raw: the caller layer does the wide-column unwrap exactly once
            raw = getattr(it, "raw_value", it.value)()
            return raw, it.timestamp()
        return None

    _TS_SLOW = object()  # fast-path bail sentinel

    def _ts_fast_lookup(self, key: bytes, opts: ReadOptions, cf):
        """Layered memtable-first point lookup on a timestamped DB — the
        per-Get full-iterator build was this path's flagged perf debt.
        Each source (memtable, immutables, overlapping files per level) is
        seeked independently for its newest visible version; candidates
        combine by (ts desc, seq desc), matching DBIter's dedup order.
        Returns (value, ts) | None | _TS_SLOW when the workload needs the
        iterator path (merge operator, range tombstones, undecided-seqno
        exclusions)."""
        if self.options.merge_operator is not None:
            return self._TS_SLOW  # operand chains need full resolution
        if self._excluded_for(opts):
            return self._TS_SLOW  # WritePrepared visibility exclusions
        cfd = self._cf_data(cf)
        read_ts = (opts.timestamp if opts.timestamp is not None
                   else dbformat.MAX_TIMESTAMP)
        snap_seq = (
            opts.snapshot.sequence if opts.snapshot is not None
            else self.versions.last_sequence
        )
        enc_hi = dbformat.encode_ts_key(key, read_ts)   # newest visible
        enc_lo = dbformat.encode_ts_key(key, 0)         # oldest possible
        seek_ikey = dbformat.make_internal_key(
            enc_hi, snap_seq, dbformat.VALUE_TYPE_FOR_SEEK)
        best = None  # (ts, seq, vtype, value)

        esc = enc_lo[:-8]  # escaped base key + terminator (ts-independent)

        def probe(it):
            """Source's best visible version into `best`; False = bail."""
            nonlocal best
            it.seek(seek_ikey)
            while it.valid():
                uk, seq, t = dbformat.split_internal_key(it.key())
                if len(uk) != len(esc) + 8 or not uk.startswith(esc):
                    break  # past this base key's versions
                if t in (dbformat.ValueType.MERGE,
                         dbformat.ValueType.SINGLE_DELETION):
                    return False
                if seq <= snap_seq:
                    ts = dbformat.decode_ts(uk[-8:])
                    cand = (ts, seq, t, it.value())
                    if best is None or cand[:2] > best[:2]:
                        best = cand
                    break  # ordered (ts desc, seq desc): first wins here
                it.next()
            return True


        for mem in [cfd.mem] + cfd.imm:
            if mem._range_dels:
                return self._TS_SLOW
            if not probe(mem.new_iterator()):
                return self._TS_SLOW
        version = self.versions.cf_current(cfd.handle.id)
        for level in range(version.num_levels):
            for f in version.overlapping_files(level, enc_hi, enc_lo):
                reader = self.table_cache.get_reader(f.number)
                if reader.range_del_entries():
                    return self._TS_SLOW
                if not probe(reader.new_iterator()):
                    return self._TS_SLOW
        if best is None:
            return None
        if best[2] == dbformat.ValueType.BLOB_INDEX:
            # Resolve through the blob source like GetContext does.
            return self.blob_source.get(best[3]), best[0]
        if best[2] != dbformat.ValueType.VALUE:
            return None
        return best[3], best[0]

    def _ts_point_lookup(self, key: bytes, opts: ReadOptions,
                         cf) -> tuple[bytes, int] | None:
        self._check_read_ts(opts)  # the iterator path checks in new_iterator
        hit = self._ts_fast_lookup(key, opts, cf)
        if hit is not self._TS_SLOW:
            return hit
        return self._ts_lookup(self.new_iterator(opts, cf=cf), key)

    def _get_with_ts(self, key: bytes, opts: ReadOptions, cf) -> bytes | None:
        """Point lookup on a timestamped DB (reference GetImpl with
        ReadOptions.timestamp)."""
        hit = self._ts_point_lookup(key, opts, cf)
        if hit is None:
            return None
        return b"" if opts.just_check_key_exists else hit[0]

    def get_with_ts(self, key: bytes, opts: ReadOptions = _DEFAULT_READ,
                    cf=None) -> tuple[bytes, int] | None:
        """Get returning (value, version timestamp) — the reference's
        Get(..., std::string* timestamp) overload."""
        self._check_open()
        return self._ts_point_lookup(key, opts, cf)

    def _native_multi_get(self, cfd, keys, snap_seq: int, opts, cf=None):
        """Whole-batch native MultiGet: one GIL-released call walks every
        key's chain; only keys the native engine can't decide (merge
        chains, blob indexes, range-tombstoned tables) re-resolve through
        the Python path. Returns (handled, results)."""
        if not keys:
            return False, None
        lib, cc = self._nget_state(cfd, opts)
        if cc is None or not hasattr(lib, "tpulsm_getctx_multiget"):
            return False, None
        if cc.fast_mg is not None and isinstance(keys, list) \
                and all(type(k) is bytes for k in keys):
            # Whole batch + result materialization in the C extension.
            fm = cc.fast_mg(cc.ctx, keys, snap_seq)
            if fm is not None:
                res, ctr = fm
                self._mg_record_stats(ctr)
                return True, self._mg_resolve_fallbacks(
                    res, keys, snap_seq, opts, cf)
        import ctypes

        import numpy as np

        n = len(keys)
        key_lens = np.fromiter((len(k) for k in keys), np.int32, n)
        key_offs = np.zeros(n, np.int64)
        np.cumsum(key_lens[:-1], out=key_offs[1:])
        keybuf = np.frombuffer(b"".join(keys), np.uint8)
        from toplingdb_tpu import native as _nat

        # Per-batch scratch is PERSISTENT on the thread-local get state —
        # a fresh 1MiB arena per 128-key batch dominated the multiget
        # wall at bench scale.
        mg = getattr(cc, "mg", None)
        if mg is None or len(mg[0]) < n:
            cap = max(n, 256)
            mg = cc.mg = (np.zeros(cap, np.int8), np.zeros(cap, np.int64),
                          np.zeros(cap, np.int64))
        status, voffs, vlens = mg
        arena = getattr(cc, "mg_arena", None)
        if arena is None:
            arena = cc.mg_arena = np.empty(1 << 20, np.uint8)
        ctr = (ctypes.c_int64 * 6)()
        used = (ctypes.c_int64 * 1)()
        while True:
            rc = lib.tpulsm_getctx_multiget(
                cc.ctx, _nat.np_u8p(keybuf), _nat.np_i64p(key_offs),
                _nat.np_i32p(key_lens), n, snap_seq,
                status.ctypes.data_as(ctypes.POINTER(ctypes.c_int8)),
                _nat.np_i64p(voffs), _nat.np_i64p(vlens),
                _nat.np_u8p(arena), len(arena), used, ctr,
            )
            if rc == -2:
                arena = cc.mg_arena = np.empty(len(arena) * 4, np.uint8)
                continue
            if rc != 0:
                return False, None
            break
        self._mg_record_stats(ctr)
        mv = memoryview(arena)
        out: list = [None] * n
        for i in range(n):
            s = status[i]
            if s == 1:
                o = voffs[i]
                out[i] = bytes(mv[o: o + vlens[i]])
            elif s == 2:
                out[i] = False  # undecidable natively: resolve below
        return True, self._mg_resolve_fallbacks(out, keys, snap_seq, opts,
                                                cf)

    def _mg_record_stats(self, ctr) -> None:
        """Batch-level perf/ticker accounting from the native MultiGet's
        six counters (shared by the ctypes and C-extension paths)."""
        st = _st
        if st.perf_level:
            pctx = st.perf_context()
            pctx.get_from_memtable_count += ctr[0]
            pctx.bloom_sst_miss_count += ctr[1]
            pctx.bloom_sst_hit_count += ctr[2]
            pctx.block_cache_hit_count += ctr[3]
            pctx.block_read_count += ctr[4]
            pctx.block_read_byte += ctr[5]
        if self.stats is not None:
            for tick, cnt in ((st.BLOOM_USEFUL, ctr[1]),
                              (st.BLOCK_CACHE_HIT, ctr[3]),
                              (st.BLOCK_CACHE_MISS, ctr[4])):
                if cnt:
                    self.stats.record_tick(tick, cnt)

    def _mg_resolve_fallbacks(self, out, keys, snap_seq, opts, cf):
        """Replace False markers (keys the native walk could not decide:
        merge chains, blob indexes, entities, range-tombstoned tables)
        with full per-key Python resolutions, PINNED to the batch's
        snapshot seqno — re-reading at a fresh last_sequence would mix
        sequence points within one MultiGet. No tracer record: the
        OP_MULTIGET record already covers these keys."""
        if not any(v is False for v in out):
            return out
        pinned_opts = opts
        if opts.snapshot is None:
            import dataclasses as _dcs

            pinned_opts = _dcs.replace(opts,
                                       snapshot=_SeqSnapshot(snap_seq))
        for i, v in enumerate(out):
            if v is not False:
                continue
            r, is_entity = self._get_impl_entry(keys[i], pinned_opts, cf,
                                                record_trace=False)
            if r is not None and is_entity:
                from toplingdb_tpu.db.wide_columns import default_column_of

                r = default_column_of(r)
            out[i] = r
        return out

    # -- async read plane (env/async_reads.py; ROADMAP item 4b) --------

    @staticmethod
    def _async_reads_on() -> bool:
        """TPULSM_ASYNC_READS=1 routes multi_get/get block fetches
        through the AsyncReadBatcher; default 0 keeps the synchronous
        path — the byte-parity oracle (write/scan/zip plane pattern)."""
        import os as _os

        return _os.environ.get("TPULSM_ASYNC_READS", "0") == "1"

    def _reader_batcher(self):
        """Lazy per-DB AsyncReadBatcher (first async-routed read)."""
        b = self._read_batcher
        if b is None:
            from toplingdb_tpu.env.async_reads import AsyncReadBatcher

            with self._mutex:
                b = self._read_batcher
                if b is None and not self._closed:
                    opts = self.options
                    b = self._read_batcher = AsyncReadBatcher(
                        rings=max(1, getattr(opts, "async_read_rings", 4)),
                        task_capacity=getattr(
                            opts, "async_read_task_capacity", 256),
                        stats=self.stats,
                        fault_hook=self.read_fault_hook,
                        name="tpulsm-read")
        return b

    def _plan_async_preread(self, file_order, per_file, live, snap_seq):
        """Plan + submit one batch of block fetches for a (multi_)get:
        per candidate file, seek the resident index for each live key's
        data-block handle, drop cache-resident blocks, and fan the rest
        through the reader rings in ONE submit_batch (coalescing merges
        neighbours). Returns {file_number: overlay} where the overlay is
        a PrereadSpans (block tables) or a {vg: token} value-group
        preload (zip tables); files the plane cannot serve (hash-index /
        plain formats) get no entry and probe synchronously —
        READ_ASYNC_FALLBACKS counts them."""
        batcher = self._reader_batcher()
        if batcher is None:
            return None
        mk = dbformat.make_internal_key
        flat: list[tuple] = []       # (rfile, offset, length)
        flat_file: list[int] = []    # aligned file numbers
        zip_plans: dict[int, tuple] = {}
        planned: set[int] = set()
        fallbacks = 0
        for f in file_order:
            if f.number in planned:
                continue
            planned.add(f.number)
            todo = sorted(k for k in per_file[f.number] if k in live)
            if not todo:
                continue
            reader = self.table_cache.get_reader(f.number)
            ikeys = [mk(k, snap_seq, dbformat.VALUE_TYPE_FOR_SEEK)
                     for k in todo if reader.key_may_match(k)]
            if not ikeys:
                continue
            if hasattr(reader, "plan_block_reads") \
                    and not getattr(reader, "has_hash_index", False):
                for off, n in reader.plan_block_reads(ikeys):
                    flat.append((reader._f, off, n))
                    flat_file.append(f.number)
            elif hasattr(reader, "plan_value_groups"):
                vgs = reader.plan_value_groups(ikeys)
                if vgs:
                    zip_plans[f.number] = (reader, vgs)
            else:
                fallbacks += 1
        overlays: dict[int, object] = {}
        if flat:
            from toplingdb_tpu.env.async_reads import PrereadSpans

            toks = batcher.submit_batch(flat)
            spans: dict[int, list] = {}
            for (rf, off, n), fnum, tok in zip(flat, flat_file, toks):
                spans.setdefault(fnum, []).append((off, off + n, tok))
            for fnum, sp in spans.items():
                overlays[fnum] = PrereadSpans(
                    self.table_cache.get_reader(fnum)._f, sp)
        for fnum, (reader, vgs) in zip_plans.items():
            overlays[fnum] = {
                vg: batcher.submit_task(
                    lambda r=reader, v=vg: r._value_group(v))
                for vg in vgs
            }
        if zip_plans and self.stats is not None:
            # A value-group preload is one planned batch too: keep the
            # ticker meaningful for zip-format tables.
            self.stats.record_tick(_st.READ_ASYNC_BATCHES, len(zip_plans))
        if fallbacks and self.stats is not None:
            self.stats.record_tick(_st.READ_ASYNC_FALLBACKS, fallbacks)
        return overlays

    def _submit_async(self, fn):
        """Run `fn` on the lazy async-read executor; returns a
        concurrent.futures.Future."""
        self._check_open()
        pool = self._async_pool
        if pool is None:
            from concurrent.futures import ThreadPoolExecutor

            with self._mutex:
                pool = self._async_pool
                if pool is None:
                    pool = self._async_pool = ThreadPoolExecutor(
                        max_workers=max(
                            2, getattr(self.options, "async_read_rings", 4)),
                        thread_name_prefix="tpulsm-get-async")
        return pool.submit(fn)

    def get_async(self, key: bytes, opts: ReadOptions = _DEFAULT_READ,
                  cf=None):
        """Future-returning point lookup: `.result()` is exactly what
        `get(key, opts, cf)` returns. The batched async surface the
        shard/fleet routers fan requests across shards with."""
        return self._submit_async(lambda: self.get(key, opts, cf))

    def multi_get_async(self, keys: list[bytes],
                        opts: ReadOptions = _DEFAULT_READ, cf=None):
        """Future-returning batched lookup: `.result()` is exactly what
        `multi_get(keys, opts, cf)` returns."""
        keys = list(keys)
        return self._submit_async(lambda: self.multi_get(keys, opts, cf))

    def multi_get(self, keys: list[bytes], opts: ReadOptions = _DEFAULT_READ,
                  cf=None) -> list[bytes | None]:
        """Batched point lookups (reference DBImpl::MultiGet, including the
        Topling fiber variant db_impl.cc:3026-3227 — our batching analogue
        groups all keys per source so each memtable/file is visited once,
        instead of per-key)."""
        self._check_open()
        tr = self._op_tracer
        if tr is not None:
            tr.record_multiget(keys)
        self._check_read_ts(opts)
        tracer = self.tracer
        root = None
        if tracer is not None and tracer.sample_every \
                and next(tracer.counter) % tracer.sample_every == 0:
            root = tracer.start("db.multiget", keys=len(keys))
        t_mg = time.perf_counter() \
            if (self.stats is not None or tracer is not None) else 0.0
        try:
            res = self._multi_get_impl(keys, opts, cf)
        finally:
            if root is not None:
                root.finish()
            elif tracer is not None and tracer.slow_usec:
                _us = (time.perf_counter() - t_mg) * 1e6
                if _us >= tracer.slow_usec:
                    tracer.note_slow("db.multiget", _us, keys=len(keys))
        # Entities were already unwrapped per key by their typed fallback
        # resolution; the magic sniff survives only behind the legacy gate.
        if getattr(self.options, "legacy_wide_column_unwrap", False) \
                and any(v is not None and v[:1] == b"\x00" for v in res):
            from toplingdb_tpu.db.wide_columns import default_column_of

            res = [v if v is None else default_column_of(v) for v in res]
        if self.stats is not None:
            from toplingdb_tpu.utils import statistics as st

            self.stats.record_tick(st.NUMBER_MULTIGET_CALLS)
            self.stats.record_tick(st.NUMBER_MULTIGET_KEYS_READ, len(keys))
            self.stats.record_tick(
                st.NUMBER_MULTIGET_BYTES_READ,
                sum(len(v) for v in res if v is not None))
            self.stats.record_in_histogram(
                st.DB_MULTIGET_MICROS, (time.perf_counter() - t_mg) * 1e6)
        return res

    def _multi_get_impl(self, keys, opts, cf):
        if self.icmp.user_comparator.timestamp_size:
            # ONE iterator for the whole batch (single view/mutex), seeked
            # across the keys in sorted order.
            it = self.new_iterator(opts, cf=cf)
            hits = {}
            for k in sorted(set(keys)):
                hit = self._ts_lookup(it, k)
                hits[k] = None if hit is None else hit[0]
            return [hits[k] for k in keys]
        cfd = self._cf_data(cf)
        snap_seq = (
            opts.snapshot.sequence if opts.snapshot is not None
            else self.versions.last_sequence
        )
        # TPULSM_ASYNC_READS=1: the batch runs the Python per-file walk
        # with its block fetches fanned through the reader rings; the
        # native whole-batch path serializes its preads in-call and is
        # bypassed (knob off = the sync oracle, default).
        async_on = self._async_reads_on()
        if not async_on:
            handled, native_res = self._native_multi_get(cfd, keys, snap_seq,
                                                         opts, cf)
            if handled:
                return native_res
        resolver = self.blob_source.get
        excluded = self._excluded_for(opts)
        ctxs = {
            k: GetContext(k, snap_seq, self.options.merge_operator,
                          blob_resolver=resolver, excluded_ranges=excluded)
            for k in keys
        }
        live = dict(ctxs)
        # 1. Memtables: one pass per source for ALL live keys.
        for mem in [cfd.mem] + cfd.imm:
            for k in list(live):
                if not self._probe_memtable(mem, k, snap_seq, live[k]):
                    del live[k]
        # 2. SSTs: group keys by candidate file so each reader/iterator is
        # reused across the batch (the fiber MultiGet's IO-batching effect).
        version = self.versions.cf_current(cfd.handle.id)
        # Per-file tombstone parses are memoized ONCE per batch and shared
        # by both the fiber path and the sync per-file loop below. The
        # probe runs under the lock: a bare dict.get racing the insert
        # relies on CPython's GIL atomicity; one uncontended acquire on
        # the hit path buys correctness on any runtime, and the parse
        # stays inside the lock so a file is never parsed twice.
        tombs_cache: dict[int, list] = {}
        cache_mu = ccy.Lock("db.DB.cache_mu")

        def tombs_for(f):
            with cache_mu:
                t = tombs_cache.get(f.number)
                if t is None:
                    t = self._parsed_tombstones(
                        self.table_cache.get_reader(f.number))
                    tombs_cache[f.number] = t
            return t

        if live and opts.async_io and len(live) > 1 and not async_on:
            # Fiber-MultiGet analogue: each missing key walks its own file
            # chain on a worker thread (one "fiber" per key; file pread
            # releases the GIL, so misses overlap their IO).
            pool = self._mget_pool
            if pool is None:
                from concurrent.futures import ThreadPoolExecutor

                pool = self._mget_pool = ThreadPoolExecutor(
                    max_workers=max(1, opts.async_queue_depth),
                    thread_name_prefix="mget",
                )
            list(pool.map(
                lambda k: self._walk_sst_chain(
                    version, k, snap_seq, ctxs[k], tombs_for),
                list(live),
            ))
            return [self._ctx_plain_result(ctxs[k]) for k in keys]
        if live:
            per_file: dict[int, list[bytes]] = {}
            for k in live:
                for level, f in version.files_for_get(k):
                    per_file.setdefault(f.number, []).append(k)
            # Visit files in global level order — L0 newest-first, then each
            # deeper level — which preserves EVERY key's newest-first source
            # order (per-key candidates are a subsequence of this walk).
            file_order = [
                f for lvl in range(version.num_levels)
                for f in version.files[lvl] if f.number in per_file
            ]
            # Async read plane: submit EVERY file's cache-missing blocks
            # as one batch before any probe — the fiber-MultiGet overlap
            # (PAPER.md item 4) with the rings doing the preads while
            # this thread decodes whatever completed first.
            overlays = None
            if async_on and file_order:
                overlays = self._plan_async_preread(
                    file_order, per_file, live, snap_seq)
            import contextlib as _ctxlib
            span_cm = (_tm.span("read.async.wait", files=len(file_order))
                       if overlays else _ctxlib.nullcontext())
            with span_cm:
                for f in file_order:
                    todo = [k for k in per_file[f.number] if k in live]
                    if not todo:
                        continue
                    reader = self.table_cache.get_reader(f.number)
                    tombs = tombs_for(f)  # once per file per batch
                    it = None
                    preread = (overlays.get(f.number)
                               if overlays is not None else None)
                    for k in sorted(todo):
                        ctx = live.get(k)
                        if ctx is None:
                            continue
                        more, it = self._probe_file(
                            reader, k, snap_seq, ctx, tombs, it,
                            preread=preread
                        )
                        if not more:
                            del live[k]
        for ctx in live.values():
            ctx.finish()
        return [self._ctx_plain_result(ctxs[k]) for k in keys]

    @staticmethod
    def _ctx_plain_result(ctx):
        """GetContext result for a PLAIN Get: entities present as their
        default column (the typed unwrap; reference Get-on-entity)."""
        v = ctx.result()
        if v is not None and ctx.result_is_entity:
            from toplingdb_tpu.db.wide_columns import default_column_of

            return default_column_of(v)
        return v

    def key_exists(self, key: bytes, opts: ReadOptions = _DEFAULT_READ) -> bool:
        return self.get(key, opts) is not None

    def put_entity(self, key: bytes, columns: dict[bytes, bytes],
                   opts: WriteOptions = _DEFAULT_WRITE, cf=None) -> None:
        """Wide-column write under the DEDICATED entity value type
        (reference DB::PutEntity → kTypeWideColumnEntity)."""
        from toplingdb_tpu.db.wide_columns import encode_entity

        b = WriteBatch()
        b.put_entity(self._ts_key(key, None), encode_entity(columns),
                     cf=self._cf_id(cf))
        self.write(b, opts)

    def get_entity(self, key: bytes, opts: ReadOptions = _DEFAULT_READ,
                   cf=None) -> dict[bytes, bytes] | None:
        """Wide-column read (reference DB::GetEntity); plain values present
        as the anonymous default column."""
        from toplingdb_tpu.db.wide_columns import decode_entity

        v = self._get_raw(key, opts, cf=cf)
        return None if v is None else decode_entity(v)

    def _get_raw(self, key: bytes, opts: ReadOptions = _DEFAULT_READ,
                 cf=None):
        """Point lookup WITHOUT wide-column default-column unwrapping
        (get_entity needs the full encoding)."""
        return self._get_impl_entry(key, opts, cf)[0]

    def get_merge_operands(self, key: bytes,
                           opts: ReadOptions = _DEFAULT_READ,
                           cf=None) -> list[bytes]:
        """The UNMERGED chain for a key (reference DB::GetMergeOperands):
        the base value (if any) first, then merge operands oldest→newest.
        A plain key returns [value]; a missing/deleted key returns [].
        Reuses GetContext's visibility/tombstone state machine in
        collect-only mode."""
        self._check_open()
        cfd = self._cf_data(cf)
        snap_seq = (
            opts.snapshot.sequence if opts.snapshot is not None
            else self.versions.last_sequence
        )
        ctx = GetContext(
            key, snap_seq, None, blob_resolver=self.blob_source.get,
            collect_operands=True, excluded_ranges=self._excluded_for(opts),
        )
        more = True
        for mem in [cfd.mem] + cfd.imm:
            if not self._probe_memtable(mem, key, snap_seq, ctx):
                more = False
                break
        if more:
            version = self.versions.cf_current(cfd.handle.id)
            self._walk_sst_chain(version, key, snap_seq, ctx)
        return ctx.merge_operand_list()

    # ==================================================================
    # Iterators & snapshots
    # ==================================================================

    def new_iterator(self, opts: ReadOptions = _DEFAULT_READ, cf=None) -> DBIter:
        """MVCC iterator over the whole keyspace (reference
        DBImpl::NewIterator → DBIter over a MergingIterator)."""
        self._check_open()
        self._check_read_ts(opts)
        if opts.tailing:
            import dataclasses as _dcs

            from toplingdb_tpu.db.forward_iterator import ForwardIterator

            fwd = ForwardIterator(
                self, _dcs.replace(opts, tailing=False), cf=cf
            )
            tr = self._op_tracer
            if tr is not None:
                from toplingdb_tpu.utils.trace import TracingIterator

                return TracingIterator(fwd, tr)
            return fwd
        cfd = self._cf_data(cf)
        # Async read plane: iterator readahead windows become reader-ring
        # tasks (FilePrefetchBuffer(aio_ring=)); each child pins one ring
        # so its windows stay ordered while children overlap. The batcher
        # is resolved BEFORE taking the DB mutex (its creation takes it).
        batcher = self._reader_batcher() if self._async_reads_on() else None
        with self._mutex:
            snap_seq = (
                opts.snapshot.sequence if opts.snapshot is not None
                else self.versions.last_sequence
            )
            version = self.versions.cf_current(cfd.handle.id)
            children = []
            rd = RangeDelAggregator(self.icmp.user_comparator)
            ra = opts.readahead_size
            for mem in [cfd.mem] + cfd.imm:
                children.append(mem.new_iterator())
                for seq, begin, end in mem.range_del_entries():
                    rd.add(RangeTombstone(seq, begin, end))
            for i, f in enumerate(version.files[0]):
                reader = self.table_cache.get_reader(f.number)
                if (ra or batcher is not None) \
                        and hasattr(reader, "new_index_iterator"):
                    children.append(reader.new_iterator(
                        readahead_size=ra,
                        aio_ring=(batcher.ring_for(i)
                                  if batcher is not None else None)))
                else:
                    children.append(reader.new_iterator())
                for b, e in reader.range_del_entries():
                    rd.add(RangeTombstone.from_table_entry(b, e))
            for level in range(1, version.num_levels):
                if version.files[level]:
                    children.append(
                        LevelIterator(self.table_cache, version.files[level],
                                      self.icmp, readahead_size=ra,
                                      aio_ring=(batcher.ring_for(level)
                                                if batcher is not None
                                                else None))
                    )
                    # Only files that actually hold tombstones are opened here
                    # (num_range_deletions travels in the MANIFEST metadata);
                    # data blocks are still opened lazily by LevelIterator.
                    for f in version.files[level]:
                        if f.num_range_deletions == 0:
                            continue
                        reader = self.table_cache.get_reader(f.number)
                        for b, e in reader.range_del_entries():
                            rd.add(RangeTombstone.from_table_entry(b, e))
            internal = MergingIterator(self.icmp.compare, children)
            it = DBIter(
                internal, self.icmp, snap_seq,
                range_del_agg=None if rd.empty() else rd,
                merge_operator=self.options.merge_operator,
                lower_bound=opts.iterate_lower_bound,
                upper_bound=opts.iterate_upper_bound,
                pinned=version,
                blob_resolver=self.blob_source.get,
                prefix_extractor=self.options.prefix_extractor,
                prefix_same_as_start=(
                    opts.prefix_same_as_start and not opts.total_order_seek
                ),
                excluded_ranges=self._excluded_for(opts),
                read_ts=opts.timestamp,
                legacy_wce=bool(getattr(
                    self.options, "legacy_wide_column_unwrap", False)),
            )
            # Chunked scan plane (ops/scan_plane.py): native block decode
            # + k-way merge for forward scans; None when the iterator
            # shape is ineligible (the per-entry path runs unchanged).
            from toplingdb_tpu.ops.scan_plane import make_scan_plane

            plane = make_scan_plane(
                mems=[cfd.mem] + list(cfd.imm),
                l0_files=list(version.files[0]),
                level_runs=[version.files[lv]
                            for lv in range(1, version.num_levels)
                            if version.files[lv]],
                table_cache=self.table_cache,
                icmp=self.icmp,
                snap_seq=snap_seq,
                rd=None if rd.empty() else rd,
                lower=opts.iterate_lower_bound,
                upper=opts.iterate_upper_bound,
                blob_resolver=self.blob_source.get,
                merge_operator=self.options.merge_operator,
                prefix_mode=(opts.prefix_same_as_start
                             and not opts.total_order_seek
                             and self.options.prefix_extractor is not None),
                excluded=self._excluded_for(opts),
                read_ts=opts.timestamp,
                stats=self.stats,
                readahead_size=ra,
                protection_bytes=self._protection,
                aio_rings=batcher,
            )
            if plane is not None:
                it.attach_scan_plane(plane)
            if opts.snapshot is None:
                # Refresh re-reads at the LATEST sequence; snapshot-pinned
                # iterators can't refresh (reference Iterator::Refresh
                # returns NotSupported for them).
                it._refresh_fn = lambda: self.new_iterator(opts, cf)
            if self.stats is not None:
                from toplingdb_tpu.utils import statistics as st

                it.stats = self.stats
                self.stats.record_tick(st.NO_ITERATOR_CREATED)
            tr = self._op_tracer
            if tr is not None:
                from toplingdb_tpu.utils.trace import TracingIterator

                return TracingIterator(it, tr)
            return it

    def _excluded_for(self, opts) -> tuple:
        """Seqno ranges invisible to this read (undecided WritePrepared
        transactions): a snapshot carries the set captured at its creation;
        snapshot-less reads use the live set."""
        if opts.snapshot is not None:
            return getattr(opts.snapshot, "excluded_ranges", ())
        fn = self._undecided_provider
        return fn() if fn is not None else ()

    def increase_full_history_ts_low(self, ts_low: int) -> None:
        """Raise the UDT history trim point (reference
        DB::IncreaseFullHistoryTsLow): future compactions collapse versions
        below it. Monotonic; requires a ts comparator."""
        if self.icmp.user_comparator.timestamp_size == 0:
            raise InvalidArgument("DB has no user-defined timestamps")
        if ts_low < self.options.full_history_ts_low:
            raise InvalidArgument(
                f"full_history_ts_low can only increase "
                f"({ts_low} < {self.options.full_history_ts_low})"
            )
        old = self.options.full_history_ts_low
        self.options.full_history_ts_low = ts_low
        from toplingdb_tpu.utils.config import persist_options

        try:
            # The bump must be durable BEFORE any compaction trims under it
            # — otherwise a reopen resets the floor and already-collapsed
            # history becomes silently readable. Persist or roll back.
            persist_options(self)
        except Exception:
            self.options.full_history_ts_low = old
            raise

    def get_snapshot(self):
        if self.options.unordered_write:
            # Unordered writes publish out of allocation order: drain the
            # in-flight memtable phases that were allocated before now, so
            # the snapshot sees a prefix-consistent sequence history
            # (reference DBImpl::GetSnapshotImpl -> WaitForPendingWrites).
            with self._mutex:
                target = self._seq_alloc
                while self.versions.last_sequence < target:
                    self._mt_cv.wait(timeout=10.0)
        fn = self._undecided_provider
        return self.snapshots.new_snapshot(
            self.versions.last_sequence,
            excluded_ranges=fn() if fn is not None else (),
        )

    def release_snapshot(self, snap) -> None:
        snap.release()

    # ==================================================================
    # Maintenance
    # ==================================================================

    def compact_range(self, begin: bytes | None = None, end: bytes | None = None) -> None:
        """Manual compaction; wired up by the compaction module."""
        self.flush()
        if self._compaction_scheduler is not None:
            self._compaction_scheduler.compact_range(begin, end)

    def compact_files(self, file_numbers: list[int], output_level: int,
                      cf=None) -> None:
        """Compact a caller-chosen set of files into output_level (reference
        DB::CompactFiles, db.h): files must live at one source level and/or
        at output_level itself."""
        cfd = self._cf_data(cf)
        from toplingdb_tpu.compaction.picker import Compaction

        if not 0 <= output_level < self.options.num_levels:
            raise InvalidArgument(
                f"output_level {output_level} out of range "
                f"[0, {self.options.num_levels})"
            )
        want = set(file_numbers)
        with self._mutex:
            version = self.versions.cf_current(cfd.handle.id)
            by_level: dict[int, list] = {}
            for lvl, f in version.all_files():
                if f.number in want:
                    by_level.setdefault(lvl, []).append(f)
                    want.discard(f.number)
            if want:
                raise InvalidArgument(f"files not live: {sorted(want)}")
            src_levels = [lvl for lvl in by_level if lvl != output_level]
            if len(src_levels) > 1:
                raise InvalidArgument(
                    f"input files span levels {sorted(by_level)}; at most "
                    f"one source level plus output_level {output_level}"
                )
            src = src_levels[0] if src_levels else output_level
            if src > output_level:
                raise InvalidArgument(
                    f"source level {src} is below output level {output_level}"
                )
            inputs = by_level.get(src, [])
            out_inputs = (
                by_level.get(output_level, []) if src != output_level else []
            )
            # Reference CompactFiles sanitization EXPANDS the caller's set
            # rather than rejecting it
            # (compaction_picker.cc:908 SanitizeCompactionInputFilesForAllLevels):
            # at L0 every file OLDER than the newest listed file comes along
            # (newer unlisted runs stay on top, so reads never see stale data
            # below newer data); at sorted levels the listed run is widened
            # across same-user-key boundaries; at the output level all
            # overlapping files are included to keep it non-overlapping.
            listed = {f.number for f in inputs + out_inputs}
            ucmp = self.icmp.user_comparator

            def _widen(lvl_files, lo, hi):
                # Same-user-key boundary widening (reference while-loops at
                # compaction_picker.cc:959-975): a neighbor sharing a
                # boundary user key must come along, else seqno zeroing can
                # reorder that key across the excluded file.
                while lo > 0 and ucmp.compare(
                        dbformat.extract_user_key(lvl_files[lo - 1].largest),
                        dbformat.extract_user_key(
                            lvl_files[lo].smallest)) >= 0:
                    lo -= 1
                while hi + 1 < len(lvl_files) and ucmp.compare(
                        dbformat.extract_user_key(lvl_files[hi + 1].smallest),
                        dbformat.extract_user_key(
                            lvl_files[hi].largest)) <= 0:
                    hi += 1
                return lo, hi

            if inputs and src == 0:
                # L0 is time-ordered, not key-ordered: every file OLDER than
                # the newest listed file comes along (for intra-L0 jobs too —
                # a non-contiguous subset compacted past an unlisted middle
                # file would re-sort newer data below it).
                l0 = version.files[0]  # newest-first
                first = min(i for i, f in enumerate(l0)
                            if f.number in listed)
                inputs = list(l0[first:])
            elif inputs and src >= 1:
                lvl_files = version.files[src]  # sorted by smallest key
                idxs = [i for i, f in enumerate(lvl_files)
                        if f.number in listed]
                lo, hi = _widen(lvl_files, min(idxs), max(idxs))
                inputs = list(lvl_files[lo:hi + 1])
            all_in = inputs + out_inputs
            if all_in:
                su = dbformat.extract_user_key(
                    min((f.smallest for f in all_in), key=self.icmp.sort_key))
                lu = dbformat.extract_user_key(
                    max((f.largest for f in all_in), key=self.icmp.sort_key))
                if src != output_level and output_level > 0:
                    out_files = version.files[output_level]
                    ov = {f.number for f in version.overlapping_files(
                        output_level, su, lu)}
                    oidxs = [i for i, f in enumerate(out_files)
                             if f.number in ov]
                    if oidxs:
                        lo, hi = _widen(out_files, min(oidxs), max(oidxs))
                        out_inputs = list(out_files[lo:hi + 1])
                    else:
                        out_inputs = []
                # Intermediate levels can't be represented by a two-level
                # Compaction: anything overlapping there keeps its newer
                # data ABOVE the moved output, which is unsafe — reject.
                for lvl in range(src + 1, output_level):
                    for f in version.overlapping_files(lvl, su, lu):
                        raise InvalidArgument(
                            f"file #{f.number} at intermediate L{lvl} "
                            f"overlaps the compaction range; compact it "
                            f"first or choose output_level {lvl}"
                        )
            if any(f.being_compacted for f in inputs + out_inputs):
                raise Busy("some input files are already being compacted")
            c = Compaction(
                level=src, output_level=output_level, inputs=inputs,
                output_level_inputs=out_inputs,
                bottommost=self._compaction_scheduler.picker._is_bottommost(
                    version, output_level,
                    min((f.smallest for f in inputs + out_inputs),
                        key=self.icmp.sort_key),
                    max((f.largest for f in inputs + out_inputs),
                        key=self.icmp.sort_key),
                ) if inputs + out_inputs else False,
                reason="compact_files",
                max_output_file_size=self.options.target_file_size(output_level),
                cf_id=cfd.handle.id,
                full_history_ts_low=self.options.full_history_ts_low,
            )
            for _, f in c.all_inputs():
                f.being_compacted = True
        try:
            self._compaction_scheduler._run_compaction(c)
        finally:
            with self._mutex:
                for _, f in c.all_inputs():
                    f.being_compacted = False

    def suggest_compact_range(self, begin: bytes | None = None,
                              end: bytes | None = None, cf=None) -> int:
        """Mark files overlapping [begin, end) for compaction (reference
        DB::SuggestCompactRange): the picker prioritizes marked files on its
        next pass. Returns the number of files marked."""
        cfd = self._cf_data(cf)
        ucmp = self.icmp.user_comparator
        marked = 0
        with self._mutex:
            version = self.versions.cf_current(cfd.handle.id)
            for _lvl, f in version.all_files():
                fs = dbformat.extract_user_key(f.smallest)
                fl = dbformat.extract_user_key(f.largest)
                if begin is not None and ucmp.compare(fl, begin) < 0:
                    continue
                if end is not None and ucmp.compare(fs, end) >= 0:
                    continue
                if not f.marked_for_compaction:
                    f.marked_for_compaction = True
                    marked += 1
        if marked:
            self._maybe_schedule_compaction()
        return marked

    def promote_l0(self, target_level: int = 1, cf=None) -> None:
        """Metadata-only move of ALL L0 files to target_level (reference
        DB::PromoteL0): requires pairwise non-overlapping L0 files and
        empty levels 1..target_level."""
        if not 1 <= target_level < self.options.num_levels:
            raise InvalidArgument(
                f"target_level {target_level} out of range "
                f"[1, {self.options.num_levels})"
            )
        cfd = self._cf_data(cf)
        ucmp = self.icmp.user_comparator
        with self._mutex:
            version = self.versions.cf_current(cfd.handle.id)
            l0 = list(version.files[0])
            if not l0:
                return
            for lvl in range(1, target_level + 1):
                if version.files[lvl]:
                    raise InvalidArgument(
                        f"level {lvl} is not empty; cannot promote L0 over it"
                    )
            ordered = sorted(
                l0, key=lambda f: self.icmp.sort_key(f.smallest)
            )
            for a, b in zip(ordered, ordered[1:]):
                if ucmp.compare(dbformat.extract_user_key(a.largest),
                                dbformat.extract_user_key(b.smallest)) >= 0:
                    raise InvalidArgument(
                        "L0 files overlap; compact instead of promoting"
                    )
            if any(f.being_compacted for f in l0):
                raise Busy("L0 files are being compacted")
            edit = VersionEdit(column_family=cfd.handle.id)
            for f in l0:
                edit.delete_file(0, f.number)
                edit.add_file(target_level, f)
            self.versions.log_and_apply(edit)

    def wait_for_compactions(self) -> None:
        if self._compaction_scheduler is not None:
            self._compaction_scheduler.wait_idle()
        if self._bg_error is not None:
            raise IOError_(f"background error: {self._bg_error!r}")

    def _classify_bg_error(self, e: BaseException, reason: str):
        """Map (error, background reason) → Severity, mirroring the
        reference's ErrorHandler severity tables (db/error_handler.cc:
        kSoft for retryable/no-space flush+compaction IO errors, kFatal for
        MANIFEST failures and corruption, kUnrecoverable for corruption
        found BY compaction — it would be baked into new SSTs). The
        integrity scrubber's kCorruption latch (reason="scrub") is HARD,
        not FATAL: the corrupt file is quarantined before the latch, so
        nothing wrong was served or propagated — after the operator
        restores/repairs the file and a clean re-scrub, resume() is
        legitimate (db/integrity.py)."""
        from toplingdb_tpu.utils.status import Corruption as _Corr
        from toplingdb_tpu.utils.status import Severity

        if isinstance(e, _Corr):
            if reason == "scrub":
                return Severity.HARD_ERROR
            return (Severity.UNRECOVERABLE if reason == "compaction"
                    else Severity.FATAL_ERROR)
        if reason == "manifest":
            return Severity.FATAL_ERROR
        if reason == "no_space":
            # kNoSpace: space comes back (trash drain, store GC, operator
            # freeing the disk) — SOFT, so the auto-recover loop clears
            # the latch once the free-space poller sees headroom again.
            return Severity.SOFT_ERROR
        if getattr(e, "retryable", False) and reason in (
                "flush", "compaction"):
            return Severity.SOFT_ERROR
        return Severity.HARD_ERROR

    def _set_background_error(self, e: BaseException,
                              reason: str = "compaction") -> None:
        """Reference ErrorHandler::SetBGError. Severity decides behavior:
        SOFT (retryable flush/compaction IO) — foreground writes continue,
        background work pauses, auto-recovery retries; HARD — writes raise
        until resume(); FATAL/UNRECOVERABLE (corruption, MANIFEST loss) —
        resume() refuses, the DB must be reopened."""
        from toplingdb_tpu.utils.status import Severity, is_no_space

        if reason != "no_space" and is_no_space(e):
            # Re-reason a raw ENOSPC surfacing through any background
            # path (flush, compaction, WAL sync) so it classifies SOFT
            # and auto-recovers, mirroring the reference's kNoSpace
            # subcode extraction in ErrorHandler::SetBGError.
            reason = "no_space"
        if reason == "no_space":
            try:
                e.retryable = True  # the recover loop's keep-retrying gate
                e._bg_reason = "no_space"
            except Exception as attr_err:  # __slots__-style exceptions
                _errors.swallow(reason="bg-error-annotate", exc=attr_err)
            if self.stats is not None:
                self.stats.record_tick(_st.NO_SPACE_ERRORS, 1)
        sev = self._classify_bg_error(e, reason)
        with self._mutex:
            if self._bg_error is not None:
                # Only ever escalate (reference keeps the max severity).
                if sev <= self._bg_error_severity:
                    return
                self._bg_error = e
                self._bg_error_severity = sev
                self._bg_error_reason = reason
            else:
                self._bg_error = e
                self._bg_error_severity = sev
                self._bg_error_reason = reason
        # Listener + auto-recovery apply to escalations too: monitoring must
        # learn the DB got WORSE, and a retryable error that replaced the
        # one a recovery thread was chasing needs a fresh thread (the old
        # one exits at its `is not target` identity check).
        from toplingdb_tpu.utils.listener import notify

        notify(self.options.listeners, "on_background_error", self, e)
        if sev == Severity.SOFT_ERROR or (
                getattr(e, "retryable", False)
                and sev < Severity.FATAL_ERROR):
            ccy.spawn("db-auto-recover", self._auto_recover_loop,
                      args=(e,), owner=self)

    def _auto_recover_loop(self, target: BaseException,
                           max_attempts: int = 10,
                           base_delay: float = 0.05) -> None:
        """Only ever clears THE error it was started for (or retryable ones
        it re-latched itself) — a concurrently latched non-retryable error,
        or a manual resume(), ends the loop untouched (reference checks the
        recovery error identity the same way)."""
        no_space = getattr(target, "_bg_reason", "") == "no_space" or (
            self._bg_error is target and self._bg_error_reason == "no_space")
        attempt = 0
        backoff = 0  # grows on every pass, attempted or not
        while attempt < max_attempts:
            if self._recover_stop.wait(
                    min(base_delay * (2 ** min(backoff, 8)), 2.0)):
                return  # DB is closing; abandon recovery
            backoff += 1
            with self._mutex:
                if self._closed or self._bg_error is not target:
                    return
            if (no_space and self._sfm is not None
                    and not self._sfm.has_headroom()):
                # Space hasn't come back yet (trash still draining, store
                # GC pending, disk still full). Waiting here doesn't
                # consume an attempt: a no_space latch clears exactly when
                # the poller sees headroom, however long that takes.
                continue
            attempt += 1
            try:
                self.resume(_auto=True)
                self.wait_for_compactions()
                self.event_logger.log("auto_recovery_succeeded",
                                      attempts=attempt)
                return
            except Exception as err:  # still failing
                # ONE thread per latched error: chase only `target`. A new
                # error latched through _set_background_error spawns its
                # own successor thread, so any identity mismatch means
                # this thread's watch is over — re-targeting here would
                # leave two loops calling resume() concurrently.
                with self._mutex:
                    latched = self._bg_error
                if latched is target and getattr(
                        target, "retryable", False):
                    continue  # still our transient error; keep retrying
                if latched is None:
                    # Our retry cleared the old latch but then failed with
                    # a fresh error nothing latched yet: go through the
                    # front door (classification + successor thread) and
                    # bow out.
                    self._set_background_error(
                        err, getattr(err, "_bg_reason", "flush")
                    )
                return
        self.event_logger.log("auto_recovery_gave_up", attempts=max_attempts)

    def resume(self, *, _auto: bool = False) -> None:
        """Clear a background error and restart background work (reference
        DB::Resume / ErrorHandler::RecoverFromBGError). FATAL and
        UNRECOVERABLE errors (corruption, MANIFEST loss) refuse: the DB
        must be reopened to rebuild consistent state. Clearing a live
        latch notifies on_error_recovery_completed on BOTH the manual and
        auto paths (previously only the auto-recover loop notified) and
        ticks BG_ERROR_RESUMES."""
        from toplingdb_tpu.utils.status import Severity as _Sev

        with self._mutex:
            if (self._bg_error is not None
                    and self._bg_error_severity >= _Sev.FATAL_ERROR):
                raise IOError_(
                    f"background error is not resumable "
                    f"({self._bg_error_severity.name}); reopen the DB: "
                    f"{self._bg_error!r}"
                )
            had = self._bg_error
            reason = self._bg_error_reason
            self._bg_error = None
            self._bg_error_severity = _Sev.NO_ERROR
            self._bg_error_reason = ""
        if had is not None:
            if self.stats is not None:
                self.stats.record_tick(_st.BG_ERROR_RESUMES, 1)
            from toplingdb_tpu.utils.listener import (
                ErrorRecoveryInfo, notify,
            )

            notify(self.options.listeners, "on_error_recovery_completed",
                   self, ErrorRecoveryInfo(db_name=self.dbname,
                                           reason=reason, auto=_auto))
        self._maybe_schedule_compaction()

    def _maybe_schedule_compaction(self) -> None:
        if self._compaction_scheduler is not None and not self.options.disable_auto_compactions:
            self._compaction_scheduler.maybe_schedule()

    def disk_pressure(self) -> str:
        """Current storage-pressure level ("ok" / "amber" / "red") from the
        SstFileManager's poller; "ok" when no manager is attached. The
        sharding admission controller and fleet write front door consult
        this to shed writes BEFORE the disk actually fills."""
        return self._sfm.pressure() if self._sfm is not None else "ok"

    def _on_disk_pressure_change(self, level: str, prev: str,
                                 info: dict) -> None:
        """SstFileManager pressure-transition callback (fires outside the
        manager's locks, on the poller thread). Escalations climb the
        reclaim ladder; a recovery to ok restarts paused compactions."""
        from toplingdb_tpu.utils.listener import DiskPressureInfo, notify

        notify(self.options.listeners, "on_disk_pressure", self,
               DiskPressureInfo(
                   db_name=self.dbname, path=self.dbname, level=level,
                   prev_level=prev,
                   free_fraction=info.get("free_fraction", 0.0),
                   tracked_bytes=info.get("tracked_bytes", 0),
                   trash_bytes=info.get("trash_bytes", 0),
                   budget_bytes=info.get("budget_bytes", 0)))
        self.event_logger.log(
            "disk_pressure", level=level, prev=prev,
            free_fraction=round(info.get("free_fraction", 0.0), 4))
        order = {"ok": 0, "amber": 1, "red": 2}
        if order.get(level, 0) > order.get(prev, 0):
            self._run_reclaim_ladder(level)
        elif level == "ok":
            self._maybe_schedule_compaction()

    def _run_reclaim_ladder(self, level: str) -> None:
        """Free bytes in escalating cost order: (1) unpace trash deletion
        — bytes already condemned drain immediately; at red additionally
        (2) drop the clean shared-store cache tier and (3) kick a
        mark-sweep GC of the shared object store (own thread — the sweep
        walks manifests and may contend on the store-gc lease)."""
        if self._sfm is None:
            return
        if self.stats is not None:
            self.stats.record_tick(_st.DISK_RECLAIM_RUNS, 1)
        self._sfm.accelerate_deletes()
        if level != "red":
            return
        tier = getattr(self.env, "tier", None)
        if tier is not None and hasattr(tier, "prune"):
            try:
                tier.prune()
            except Exception as e:
                _errors.swallow(reason="disk-reclaim-cache-prune", exc=e,
                                stats=self.stats)
        store = getattr(self.env, "store", None)
        if store is not None and not self._store_gc_inflight:
            self._store_gc_inflight = True

            def run_gc():
                try:
                    from toplingdb_tpu.storage.gc import mark_sweep

                    # Roots: this DB plus every sibling directory that
                    # looks like a DB (has a CURRENT) — fleet shards
                    # share one store, and a sweep rooted only at *this*
                    # shard would reap its neighbors' live objects. The
                    # grace window additionally shields anything a root
                    # scan can't see yet.
                    import os as _os_gc

                    roots = {self.dbname}
                    parent = _os_gc.path.dirname(self.dbname)
                    try:
                        for child in self.env.get_children(parent or "."):
                            d = f"{parent}/{child}" if parent else child
                            if self.env.file_exists(
                                    filename.current_file_name(d)):
                                roots.add(d)
                    except Exception as probe_err:
                        _errors.swallow(reason="reclaim-gc-root-scan",
                                        exc=probe_err)
                    mark_sweep(store, sorted(roots), env=self.env,
                               grace_sec=60.0, statistics=self.stats)
                except Exception as e:
                    # Busy (another sweeper holds the lease) or a mid-
                    # sweep IO error: reclaim is best-effort by design.
                    _errors.swallow(reason="disk-reclaim-store-gc", exc=e,
                                    stats=self.stats)
                finally:
                    self._store_gc_inflight = False

            ccy.spawn("disk-reclaim-store-gc", run_gc, owner=self)

    def disable_file_deletions(self) -> None:
        """Reference DB::DisableFileDeletions (used by backup/checkpoint
        tools to pin the file set while copying). Counted: each disable
        needs a matching enable."""
        with self._mutex:
            self._file_deletions_disabled += 1

    def enable_file_deletions(self, force: bool = False) -> None:
        with self._mutex:
            n = self._file_deletions_disabled
            self._file_deletions_disabled = 0 if force else max(0, n - 1)
            if n > 0 and self._file_deletions_disabled == 0:
                self._delete_obsolete_files()  # final unpin purges

    def flush_wal(self, sync: bool = False) -> None:
        """Reference DB::FlushWAL/SyncWAL."""
        with self._mutex:
            if self._wal is not None:
                if sync:
                    self._wal.sync()
                else:
                    self._wal.flush()

    def _delete_obsolete_files(self) -> None:
        """GC: remove WALs below the manifest log number, non-live SSTs, and
        stale MANIFESTs (reference DBImpl::DeleteObsoleteFiles)."""
        if self._file_deletions_disabled:
            return  # a backup/checkpoint is pinning the file set
        live, live_blobs = self.versions.live_file_sets()
        for child in self.env.get_children(self.dbname):
            ftype, num = filename.parse_file_name(child)
            keep = True
            if ftype == filename.FileType.WAL:
                keep = (num >= self.versions.log_number
                        or num == self._wal_number
                        or num in self._recycle_wals)
                if not keep and (len(self._recycle_wals)
                                 < self.options.recycle_log_file_num
                                 and num in self._recyclable_written):
                    self._recycle_wals.append(num)
                    keep = True
                if not keep and self.options.wal_ttl_seconds > 0:
                    self._archive_wal(child)
                    continue
            elif ftype == filename.FileType.TABLE:
                keep = num in live or num in self._pending_outputs
            elif ftype == filename.FileType.BLOB:
                keep = num in live_blobs or num in self._pending_outputs
            elif ftype == filename.FileType.MANIFEST:
                keep = num == self.versions.manifest_file_number
            elif ftype == filename.FileType.OPTIONS:
                keep = (num == self._options_file_number
                        or self._options_file_number == 0)
            elif ftype == filename.FileType.TEMP:
                keep = False
            if not keep:
                path = f"{self.dbname}/{child}"
                if ftype == filename.FileType.TABLE:
                    self.table_cache.evict(num)
                elif ftype == filename.FileType.BLOB:
                    self.blob_source.evict(num)
                if (self._sfm is not None
                        and ftype in (filename.FileType.TABLE,
                                      filename.FileType.BLOB)):
                    # Obsolete SSTs/blobs (and store-materialized refs —
                    # the SharedSstEnv rename/delete passthroughs keep the
                    # local tree authoritative) go through the manager:
                    # paced trash deletion + live-byte accounting.
                    self._sfm.schedule_delete(path)
                    continue
                if self._sfm is not None:
                    self._sfm.on_delete_file(path)
                try:
                    self.env.delete_file(path)
                except NotFound:
                    pass

    def _archive_wal(self, child: str) -> None:
        """Move an obsolete WAL to <db>/archive/ and purge entries older
        than wal_ttl_seconds (reference WalManager::ArchiveWALFile /
        PurgeObsoleteWALFiles)."""
        arch = f"{self.dbname}/archive"
        self.env.create_dir(arch)
        try:
            self.env.rename_file(f"{self.dbname}/{child}", f"{arch}/{child}")
        except (OSError, NotFound):
            return
        if self._sfm is not None:
            # Archived WALs leave the tracked tree (TTL purge owns them).
            self._sfm.on_delete_file(f"{self.dbname}/{child}")
        now = time.time()
        try:
            names = self.env.get_children(arch)
        except NotFound:
            return
        for name in names:
            p = f"{arch}/{name}"
            try:
                mtime = self.env.get_file_mtime(p)
                if mtime is not None and \
                        now - mtime > self.options.wal_ttl_seconds:
                    self.env.delete_file(p)
            except (OSError, NotFound):
                continue

    def get_wal_files(self) -> list[tuple[int, str, bool]]:
        """(log_number, path, archived) for every retained WAL — live AND
        archived — oldest first (the reference WalFile metadata shape;
        get_sorted_wal_files keeps its names-only live-file contract for
        the backup tooling)."""
        out = []
        for child in self.env.get_children(self.dbname):
            ftype, num = filename.parse_file_name(child)
            if ftype == filename.FileType.WAL:
                out.append((num, f"{self.dbname}/{child}", False))
        arch = f"{self.dbname}/archive"
        try:
            for child in self.env.get_children(arch):
                ftype, num = filename.parse_file_name(child)
                if ftype == filename.FileType.WAL:
                    out.append((num, f"{arch}/{child}", True))
        except NotFound:
            pass
        return sorted(out)

    def verify_checksum(self) -> None:
        """Full checksum scan of every live SST (reference
        DB::VerifyChecksum): every data block is read FROM DISK and
        CRC-verified — cached readers/blocks are bypassed, as the reference
        scans with fill_cache=false; raises Corruption on the first bad
        block. Opening with verify_checksums=True also CRC-verifies the
        index, metaindex, properties, filter, and range-del meta blocks at
        construction, and every BLOB_INDEX entry's referenced blob record
        is probed with its record CRC — the meta/blob coverage the plain
        data-block walk used to miss. Holding the Version objects pins the
        files against concurrent obsolete-file GC."""
        import dataclasses as _dc

        from toplingdb_tpu.table.factory import open_table
        from toplingdb_tpu.utils import statistics as _st

        with self._mutex:
            versions = [
                self.versions.cf_current(cf_id)
                for cf_id in self.versions.column_families
            ]
        topts = _dc.replace(self.options.table_options, verify_checksums=True)
        bytes_verified = 0
        for version in versions:
            for _, f in version.all_files():
                path = filename.table_file_name(self.dbname, f.number)
                reader = open_table(
                    self.env.new_random_access_file(path), self.icmp, topts
                )
                try:
                    it = reader.new_iterator()
                    it.seek_to_first()
                    for ik, v in it.entries():  # decoding verifies block CRCs
                        if ik[-8] == dbformat.ValueType.BLOB_INDEX:
                            # Sweep the referenced blob record (its value
                            # CRC rides in the blob file, db/blob.py).
                            self.blob_source.get(v, verify=True)
                finally:
                    reader.close()
                bytes_verified += f.file_size
        if self.stats is not None and bytes_verified:
            self.stats.record_tick(_st.INTEGRITY_BYTES_VERIFIED,
                                   bytes_verified)

    def verify_file_checksums(self) -> dict:
        """Recompute every live SST's whole-file checksum and compare with
        the MANIFEST-recorded value (reference DB::VerifyFileChecksums);
        raises Corruption on the first mismatch. Returns
        {'files_verified', 'bytes_verified', 'files_skipped'} — skipped
        files predate checksum recording (or it is disabled)."""
        from toplingdb_tpu.utils import statistics as _st
        from toplingdb_tpu.utils.file_checksum import (
            verify_recorded_checksum,
        )

        with self._mutex:
            versions = [
                self.versions.cf_current(cf_id)
                for cf_id in self.versions.column_families
            ]
        verified = bytes_v = skipped = 0
        seen: set[int] = set()
        for version in versions:
            for _, f in version.all_files():
                if f.number in seen:
                    continue
                seen.add(f.number)
                path = filename.table_file_name(self.dbname, f.number)
                n = verify_recorded_checksum(self.env, path, f)
                if n:
                    verified += 1
                    bytes_v += n
                else:
                    skipped += 1
        if self.stats is not None and bytes_v:
            self.stats.record_tick(_st.INTEGRITY_BYTES_VERIFIED, bytes_v)
        return {"files_verified": verified, "bytes_verified": bytes_v,
                "files_skipped": skipped}

    def scrub(self, deep: bool = False) -> dict:
        """Run one IntegrityScrubber pass synchronously (db/integrity.py)
        and return its report. Detected corruption quarantines the file,
        fires on_corruption_detected, and latches the background-error
        machinery (resume() after repair)."""
        self._check_open()
        if self._integrity_scrubber is None:
            from toplingdb_tpu.db.integrity import IntegrityScrubber

            self._integrity_scrubber = IntegrityScrubber(self)
        return self._integrity_scrubber.run_pass(deep=deep)

    def scrub_status(self) -> dict:
        """The /integrity HTTP view's payload (utils/config.py)."""
        if self._integrity_scrubber is None:
            return {"running": False, "passes": 0,
                    "quarantined_files": sorted(self._quarantined)}
        return self._integrity_scrubber.status()

    def _stamp_file_checksums(self, metas) -> None:
        """Compute + record whole-file checksums on freshly produced SST
        metadata before it reaches the MANIFEST (flush, compaction
        install, ingest, import). No-op when disabled."""
        factory = self._file_checksum_factory
        if factory is None:
            return
        from toplingdb_tpu.utils.file_checksum import stamp_file_checksum

        publish = getattr(self.env, "publish_sst", None)
        for meta in metas:
            path = filename.table_file_name(self.dbname, meta.number)
            stamp_file_checksum(self.env, path, meta, factory)
            # Shared-store mode: every install (flush, compaction,
            # ingest, import) also publishes the table to the
            # content-addressed store. Idempotent — an already-published
            # address (dcompact adoption) is a contains() probe.
            if publish is not None:
                try:
                    publish(path, meta)
                except Exception as e:  # noqa: BLE001 — store outage
                    # The install stays valid on local bytes; a later
                    # checkpoint/dcompact re-publishes (idempotent).
                    from toplingdb_tpu.utils import errors as _errors
                    _errors.swallow(reason="install-publish-sst", exc=e)

    def get_approximate_sizes(self, ranges: list[tuple[bytes, bytes]],
                              cf=None) -> list[int]:
        """Approximate on-disk bytes per [begin, end) user-key range
        (reference DB::GetApproximateSizes via ApproximateOffsetOf)."""
        cfd = self._cf_data(cf)
        ucmp = self.icmp.user_comparator
        version = self.versions.cf_current(cfd.handle.id)
        out = []
        for begin, end in ranges:
            bk = dbformat.make_internal_key(
                begin, dbformat.MAX_SEQUENCE_NUMBER,
                dbformat.VALUE_TYPE_FOR_SEEK)
            ek = dbformat.make_internal_key(
                end, dbformat.MAX_SEQUENCE_NUMBER,
                dbformat.VALUE_TYPE_FOR_SEEK)
            total = 0
            for level in range(version.num_levels):
                for f in version.files[level]:
                    # Metadata-only overlap check before touching a reader.
                    if (ucmp.compare(dbformat.extract_user_key(f.largest),
                                     begin) < 0
                            or ucmp.compare(end, dbformat.extract_user_key(
                                f.smallest)) < 0):
                        continue
                    reader = self.table_cache.get_reader(f.number)
                    lo = reader.approximate_offset_of(bk)
                    hi = reader.approximate_offset_of(ek)
                    if hi > lo:
                        total += hi - lo
            out.append(total)
        return out

    def delete_files_in_range(self, begin: bytes, end: bytes, cf=None) -> int:
        """Drop whole SSTs fully contained in [begin, end) (reference
        DeleteFilesInRange — the bulk-wipe fast path; boundary files keep
        their data, which a DeleteRange + compaction then clears). Returns
        the number of files dropped."""
        cfd = self._cf_data(cf)
        ucmp = self.icmp.user_comparator
        with self._mutex:
            version = self.versions.cf_current(cfd.handle.id)
            doomed: list[tuple[int, int]] = []
            for level in range(1, version.num_levels):  # L0 ranges overlap
                for f in version.files[level]:
                    if f.being_compacted:
                        continue
                    fs = dbformat.extract_user_key(f.smallest)
                    fl = dbformat.extract_user_key(f.largest)
                    if ucmp.compare(begin, fs) <= 0 and ucmp.compare(fl, end) < 0:
                        doomed.append((level, f.number))
            if not doomed:
                return 0
            edit = VersionEdit(column_family=cfd.handle.id)
            for level, num in doomed:
                edit.delete_file(level, num)
            self.versions.log_and_apply(edit)
            self._delete_obsolete_files()
            return len(doomed)

    def get_live_files(self, flush_memtable: bool = True
                       ) -> tuple[list[str], int]:
        """(relative file names, manifest_file_size) — everything a
        consistent copy needs (reference DB::GetLiveFiles): SSTs + blobs +
        CURRENT/MANIFEST/OPTIONS. The live MANIFEST keeps growing, so the
        caller must TRUNCATE its copy at manifest_file_size or the copy
        references files newer than the snapshot. Hold
        disable_file_deletions() while copying."""
        from toplingdb_tpu.db.blob import blob_file_name

        self._check_open()
        if flush_memtable:
            self.flush()
        with self._mutex:
            # CURRENT versions only — files pinned solely by in-flight
            # readers are not part of a consistent copy (reference
            # GetLiveFiles semantics).
            ssts: set[int] = set()
            blobs: set[int] = set()
            for cf_id in self.versions.column_families:
                for _, f in self.versions.cf_current(cf_id).all_files():
                    ssts.add(f.number)
                    blobs.update(f.blob_refs)
            # filename helpers with dbname="" yield bare basenames.
            out = [filename.table_file_name("", n) for n in sorted(ssts)]
            out += [blob_file_name("", n) for n in sorted(blobs)]
            out.append(filename.current_file_name(""))
            out.append(filename.manifest_file_name(
                "", self.versions.manifest_file_number))
            if self._options_file_number:
                out.append(filename.options_file_name(
                    "", self._options_file_number))
            return out, self.versions.manifest_size()

    def get_sorted_wal_files(self) -> list[str]:
        """Live WAL file names, oldest first (reference
        DB::GetSortedWalFiles). While file deletions are disabled, EVERY
        on-disk WAL is returned — a concurrent flush may have advanced
        log_number, but the pinned older WALs can still carry data absent
        from a get_live_files snapshot taken earlier."""
        self._check_open()
        with self._mutex:
            pinned = self._file_deletions_disabled > 0
            nums = sorted(
                num for child in self.env.get_children(self.dbname)
                for t, num in [filename.parse_file_name(child)]
                if t == filename.FileType.WAL
                and (pinned or num >= self.versions.log_number
                     or num == self._wal_number)
            )
            return [filename.log_file_name("", n) for n in nums]

    def pause_background_work(self) -> None:
        if self._compaction_scheduler is not None:
            self._compaction_scheduler.pause()

    def continue_background_work(self) -> None:
        if self._compaction_scheduler is not None:
            self._compaction_scheduler.resume_background()

    _MUTABLE_OPTIONS = frozenset({
        "write_buffer_size", "level0_file_num_compaction_trigger",
        "level0_slowdown_writes_trigger", "level0_stop_writes_trigger",
        "disable_auto_compactions", "max_bytes_for_level_base",
        "max_bytes_for_level_multiplier", "target_file_size_base",
        "target_file_size_multiplier", "max_compaction_bytes",
        "max_subcompactions", "max_background_jobs",
        "enable_blob_garbage_collection",
        "blob_garbage_collection_age_cutoff", "min_blob_size",
        "seqno_time_sample_period_sec", "fifo_ttl_seconds",
        "periodic_compaction_seconds",
    })

    def set_options(self, changes: dict) -> None:
        """Online option changes for the mutable subset (reference
        DB::SetOptions; the SidePlugin online-config mechanism). Unknown or
        immutable names — and values of the wrong type — raise
        InvalidArgument; the new values persist to a fresh OPTIONS file
        (persistence failures propagate). Serialized under the DB mutex so
        concurrent callers (the threaded HTTP server) can't interleave the
        OPTIONS-file roll."""
        base = Options()
        for k, v in changes.items():
            if k not in self._MUTABLE_OPTIONS:
                raise InvalidArgument(f"option {k!r} is not dynamically "
                                      f"changeable")
            want = type(getattr(base, k))
            if want is bool:
                ok = isinstance(v, bool)
            elif want is int:
                ok = isinstance(v, int) and not isinstance(v, bool)
            elif want is float:
                ok = isinstance(v, (int, float)) and not isinstance(v, bool)
            else:
                ok = isinstance(v, want)
            if not ok:
                raise InvalidArgument(
                    f"option {k!r} expects {want.__name__}, "
                    f"got {type(v).__name__}"
                )
        from toplingdb_tpu.utils.config import persist_options

        with self._mutex:
            for k, v in changes.items():
                setattr(self.options, k, v)
            old = self._options_file_number
            persist_options(self)
            if old:
                try:
                    self.env.delete_file(
                        filename.options_file_name(self.dbname, old))
                except NotFound:
                    pass
        self._maybe_schedule_compaction()

    _STATS_CF = "__tpulsm_stats__"

    def get_stats_history(self, start_time: int = 0, end_time: int = 2 ** 62,
                          include_persisted: bool = False):
        """Time-series ticker deltas (reference DBImpl::GetStatsHistory,
        db/db_impl/db_impl.cc:1102). Samples are taken every
        stats_persist_period_sec, or manually via persist_stats(). With
        include_persisted, samples stored in the hidden stats CF by
        persist_stats(to_db=True) are merged in (the reference's
        persist_stats_to_disk / ___rocksdb_stats_history___ CF)."""
        out = self.stats_history.get(start_time, end_time)
        if include_persisted:
            import json as _json

            in_memory = {ts for ts, _ in out}
            cf = self.get_column_family(self._STATS_CF)
            if cf is not None:
                it = self.new_iterator(cf=cf)
                it.seek(b"%020d" % start_time)
                while it.valid():
                    try:
                        ts = int(it.key().split(b".")[0].decode())
                        delta = {
                            k: int(v) for k, v in
                            _json.loads(it.value().decode()).items()
                        }
                    except (ValueError, UnicodeDecodeError):
                        it.next()
                        continue  # foreign/corrupt entry: skip, don't crash
                    if ts >= end_time:
                        break
                    if ts not in in_memory:  # avoid double-counting samples
                        out.append((ts, delta))
                    it.next()
                out.sort(key=lambda s: s[0])
        return out

    def persist_stats(self, to_db: bool = False) -> None:
        self.stats_history.snapshot()
        if not to_db:
            return
        sample = self.stats_history.last_sample()
        if sample is None:
            return
        import json as _json

        with self._mutex:
            cf = self.get_column_family(self._STATS_CF)
            if cf is None:
                cf = self.create_column_family(self._STATS_CF)
            self._stats_persist_seq = getattr(
                self, "_stats_persist_seq", 0) + 1
            seq = self._stats_persist_seq
        ts, delta = sample
        # Counter suffix: two persists in the same second must not collide.
        self.put(b"%020d.%06d" % (ts, seq), _json.dumps(delta).encode(),
                 cf=cf)

    def get_property(self, name: str) -> str | None:
        v = self.versions.current
        if name == "tpulsm.stats" or name == "tpulsm.levelstats":
            lines = [f"last_seq={self.versions.last_sequence} "
                     f"mem_entries={self.mem.num_entries} imm={len(self.imm)}"]
            for level in range(v.num_levels):
                n = len(v.files[level])
                if n:
                    lines.append(f"L{level}: {n} files {v.total_bytes(level)} bytes")
            return "\n".join(lines)
        if name == "tpulsm.num-files":
            return str(v.num_files())
        if name == "tpulsm.background-errors":
            return str(int(self._bg_error is not None))
        if name == "tpulsm.bg-error-severity":
            return self._bg_error_severity.name
        if name == "tpulsm.estimate-num-keys":
            # Reference rocksdb.estimate-num-keys: live table entries minus
            # deletions plus memtable entries (overcounts overwrites).
            n = sum(
                max(0, m.num_entries - 2 * m.num_deletes)
                for c in self._cfs.values() for m in [c.mem] + c.imm
            )
            for cf_id in self.versions.column_families:
                for _, f in self.versions.cf_current(cf_id).all_files():
                    n += max(0, f.num_entries - 2 * f.num_deletions)
            return str(n)
        if name == "tpulsm.cur-size-all-mem-tables":
            return str(sum(
                c.mem.approximate_memory_usage()
                + sum(m.approximate_memory_usage() for m in c.imm)
                for c in self._cfs.values()
            ))
        if name == "tpulsm.num-snapshots":
            return str(self.snapshots.num_live())
        if name == "tpulsm.estimate-live-data-size":
            return str(sum(
                f.file_size
                for cf_id in self.versions.column_families
                for _, f in self.versions.cf_current(cf_id).all_files()
            ))
        if name == "tpulsm.background-errors":
            return "1" if self._bg_error is not None else "0"
        if name == "tpulsm.num-running-compactions":
            s = self._compaction_scheduler
            return str(s._running if s is not None else 0)
        if name == "tpulsm.threads":
            import json as _json

            from toplingdb_tpu.utils.thread_status import get_thread_list

            return _json.dumps(get_thread_list())
        if name.startswith("tpulsm.num-files-at-level"):
            try:
                lvl = int(name[len("tpulsm.num-files-at-level"):])
            except ValueError:
                return None
            return str(len(v.files[lvl])) if 0 <= lvl < v.num_levels else None
        return None

    def _check_open(self) -> None:
        if self._closed:
            from toplingdb_tpu.utils.status import ShutdownInProgress

            raise ShutdownInProgress("DB is closed")
