"""FlushJob: memtable → L0 SST (reference db/flush_job.cc:213,833
`WriteLevel0Table` in /root/reference)."""

from __future__ import annotations

from toplingdb_tpu.db import filename
from toplingdb_tpu.db.memtable import MemTable
from toplingdb_tpu.db.range_del import RangeTombstone, fragment_tombstones
from toplingdb_tpu.db.version_edit import FileMetaData, VersionEdit
from toplingdb_tpu.table.factory import new_table_builder
from toplingdb_tpu.table.merging_iterator import MergingIterator


def _flush_columnar(env, dbname, file_number, icmp, mem, table_options,
                    tombstones, creation_time, column_family):
    """Single-memtable columnar flush: ONE native export of the whole rep +
    the native block-building SST writer — no per-entry Python. Returns the
    FileMetaData, or None when ineligible (caller uses the iterator path).
    This is the write-path half of the memtable performance story: without
    it, flushing a full memtable walks ~10^5 Python iterations while the
    write group waits (reference FlushJob::WriteLevel0Table's tight C++
    scan, db/flush_job.cc:833)."""
    from toplingdb_tpu.db import dbformat as _dbf

    if (getattr(table_options, "format", "block") != "block"
            or getattr(table_options, "index_type", "binary") != "binary"
            or getattr(table_options, "properties_collector_factories", None)
            or getattr(table_options, "prefix_extractor", None) is not None
            or getattr(table_options, "partition_filters", False)
            or icmp.user_comparator.name() != _dbf.BYTEWISE.name()):
        return None
    exported = mem.export_columnar()
    if exported is None:
        return None
    kv, seqs, vtypes = exported
    if kv.n == 0:
        # Tombstone-only table: the columnar writer's n==0 seqno accounting
        # differs from TableBuilder's — the iterator path stays bit-true.
        return None
    import numpy as np

    from toplingdb_tpu.ops.columnar_io import write_tables_columnar
    from toplingdb_tpu.utils.status import NotSupported

    frags = list(fragment_tombstones(tombstones, icmp.user_comparator))

    numbers = iter([file_number])

    def alloc():
        return next(numbers)  # one output only (max size unbounded)

    try:
        files = write_tables_columnar(
            env, dbname, alloc, icmp, table_options, kv,
            np.arange(kv.n, dtype=np.int32),
            np.full(kv.n, -1, dtype=np.int64), vtypes, seqs, frags,
            creation_time, column_family=column_family,
        )
    except NotSupported:
        return None  # oversized keys etc. — iterator path handles them
    if not files:
        return None
    fnum, path, props, smallest, largest, _sel = files[0]
    return FileMetaData(
        number=fnum,
        file_size=env.get_file_size(path),
        smallest=smallest,
        largest=largest,
        smallest_seqno=props.smallest_seqno,
        largest_seqno=props.largest_seqno,
        num_entries=props.num_entries,
        num_deletions=props.num_deletions,
        num_range_deletions=props.num_range_deletions,
    )


def flush_memtable_to_table(env, dbname: str, file_number: int, icmp,
                            memtables: list[MemTable], table_options,
                            creation_time: int = 0,
                            blob_file_number: int | None = None,
                            min_blob_size: int = 0,
                            column_family: tuple[int, str] = (0, "default"),
                            ) -> FileMetaData | None:
    """Write one or more memtables (newest first) to a single L0 SST via a
    k-way merge of their already-sorted iterators. Returns None if there was
    nothing to write. With blob_file_number set, values >= min_blob_size go
    to a sibling blob file and the SST stores BLOB_INDEX pointers
    (reference BlobFileBuilder integration in flush)."""
    tombstones: list[RangeTombstone] = []
    total = 0
    for mem in memtables:
        total += len(mem._rep)
        for seq, begin, end in mem.range_del_entries():
            tombstones.append(RangeTombstone(seq, begin, end))
    if total == 0 and not tombstones:
        return None

    if len(memtables) == 1 and blob_file_number is None:
        meta = _flush_columnar(env, dbname, file_number, icmp, memtables[0],
                               table_options, tombstones, creation_time,
                               column_family)
        if meta is not None:
            return meta

    blob_builder = None
    if blob_file_number is not None:
        # min_blob_size == 0 means "separate every value" (the reference's
        # semantics), not "disabled" — the enable flag gates separation.
        from toplingdb_tpu.db.blob import BlobFileBuilder

        blob_builder = BlobFileBuilder(env, dbname, blob_file_number)

    path = filename.table_file_name(dbname, file_number)
    w = env.new_writable_file(path)
    try:
        builder = new_table_builder(
            w, icmp, table_options, creation_time=creation_time,
            column_family_id=column_family[0],
            column_family_name=column_family[1],
        )
        merger = MergingIterator(
            icmp.compare, [m.new_iterator() for m in memtables]
        )
        merger.seek_to_first()
        last_ikey = None
        from toplingdb_tpu.db import dbformat as _dbf

        for ikey, val in merger.entries():
            # Exact duplicate internal keys across memtables (WAL replay):
            # the newer source (lower child index) surfaced first; skip dups.
            if last_ikey is not None and icmp.compare(last_ikey, ikey) == 0:
                continue
            last_ikey = ikey
            if (blob_builder is not None
                    and ikey[-8] == _dbf.ValueType.VALUE
                    and len(val) >= min_blob_size):
                uk, seq, _ = _dbf.split_internal_key(ikey)
                idx = blob_builder.add(uk, val)
                builder.add(
                    _dbf.make_internal_key(uk, seq, _dbf.ValueType.BLOB_INDEX),
                    idx,
                )
                continue
            builder.add(ikey, val)
        for frag in fragment_tombstones(tombstones, icmp.user_comparator):
            begin_ikey, end_uk = frag.to_table_entry()
            builder.add_tombstone(begin_ikey, end_uk)
        if builder.num_entries == 0:
            # Defense-in-depth: with the memtable rejecting degenerate
            # tombstones this is unreachable from current callers, but a
            # boundless empty table must NEVER reach the MANIFEST.
            w.close()
            env.delete_file(path)
            return None
        props = builder.finish()
        w.sync()
    finally:
        w.close()
        if blob_builder is not None:
            from toplingdb_tpu.db.blob import blob_file_name

            if blob_builder.finish() == 0:
                try:
                    env.delete_file(blob_file_name(dbname, blob_file_number))
                except Exception:
                    pass

    return FileMetaData(
        number=file_number,
        file_size=env.get_file_size(path),
        smallest=builder.smallest_key,
        largest=builder.largest_key,
        smallest_seqno=props.smallest_seqno,
        largest_seqno=props.largest_seqno,
        num_entries=props.num_entries,
        num_deletions=props.num_deletions,
        num_range_deletions=props.num_range_deletions,
        blob_refs=(
            [blob_file_number]
            if blob_builder is not None and blob_builder.num_values else []
        ),
        marked_for_compaction=builder.need_compaction,
    )
