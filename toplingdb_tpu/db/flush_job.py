"""FlushJob: memtable → L0 SST (reference db/flush_job.cc:213,833
`WriteLevel0Table` in /root/reference)."""

from __future__ import annotations

from toplingdb_tpu.db import filename
from toplingdb_tpu.db.memtable import MemTable
from toplingdb_tpu.db.range_del import RangeTombstone, fragment_tombstones
from toplingdb_tpu.db.version_edit import FileMetaData, VersionEdit
from toplingdb_tpu.table.factory import new_table_builder
from toplingdb_tpu.table.merging_iterator import MergingIterator
from toplingdb_tpu.utils.status import Corruption
from toplingdb_tpu.utils import errors as _errors


def _flush_protection(memtables, table_options):
    """(pb, mems) when per-entry protection is active for this flush —
    every memtable must carry checksums, or verification is off."""
    pb = getattr(table_options, "protection_bytes_per_key", 0)
    if pb and all(m._prot is not None for m in memtables):
        return pb, memtables
    return 0, ()


def _columnar_protect_xor(kv, vtypes, pb: int) -> int | None:
    """XOR fold of every exported entry's checksum in ONE native call
    (tpulsm_columnar_protect), or None -> caller walks per entry."""
    import ctypes

    import numpy as np

    from toplingdb_tpu import native

    l = native.lib()
    fn = getattr(l, "tpulsm_columnar_protect", None) if l is not None else None
    if fn is None:
        return None
    ko = np.ascontiguousarray(kv.key_offs, dtype=np.int32)
    kl = np.ascontiguousarray(kv.key_lens, dtype=np.int32)
    vo = np.ascontiguousarray(kv.val_offs, dtype=np.int32)
    vl = np.ascontiguousarray(kv.val_lens, dtype=np.int32)
    vt = np.ascontiguousarray(vtypes, dtype=np.int32)
    out = ctypes.c_uint64()
    rc = fn(native.np_u8p(kv.key_buf), native.np_i32p(ko),
            native.np_i32p(kl), native.np_u8p(kv.val_buf),
            native.np_i32p(vo), native.np_i32p(vl), native.np_i32p(vt),
            kv.n, pb, ctypes.byref(out))
    if rc != kv.n:
        return None
    return out.value


def _verify_flush_entry(mems, pb, uk: bytes, seq: int, t: int,
                        value: bytes) -> None:
    """The memtable->flush handoff check (reference memtable KV-checksum
    verification): the entry coming back OUT of the (native) rep must
    match the checksum recorded when it went IN."""
    from toplingdb_tpu.utils import protection as _p

    for m in mems:
        stored = m.stored_protection(uk, seq, t)
        if stored is not None:
            if stored != _p.truncate(_p.protect_entry(int(t), uk, value),
                                     pb):
                raise Corruption(
                    f"flush protection mismatch: key {uk!r} seq={seq} "
                    f"type={t} changed inside the memtable rep"
                )
            return
    raise Corruption(
        f"flush protection: no checksum recorded for key {uk!r} seq={seq} "
        f"type={t} (entry fabricated or index corrupted)"
    )


def _verify_flush_tombstones(memtables, pb) -> None:
    from toplingdb_tpu.utils import protection as _p
    from toplingdb_tpu.db.dbformat import ValueType as _VT

    for m in memtables:
        for seq, begin, end in m.range_del_entries():
            stored = m.stored_rd_protection(seq, begin, end)
            if stored is None or stored != _p.truncate(
                    _p.protect_entry(int(_VT.RANGE_DELETION), begin, end),
                    pb):
                raise Corruption(
                    f"flush protection mismatch on range tombstone "
                    f"[{begin!r}, {end!r}) seq={seq}"
                )


def _flush_columnar(env, dbname, file_number, icmp, mem, table_options,
                    tombstones, creation_time, column_family):
    """Single-memtable columnar flush: ONE native export of the whole rep +
    the native block-building SST writer — no per-entry Python. Returns the
    FileMetaData, or None when ineligible (caller uses the iterator path).
    This is the write-path half of the memtable performance story: without
    it, flushing a full memtable walks ~10^5 Python iterations while the
    write group waits (reference FlushJob::WriteLevel0Table's tight C++
    scan, db/flush_job.cc:833)."""
    from toplingdb_tpu.db import dbformat as _dbf

    if (getattr(table_options, "format", "block") != "block"
            or getattr(table_options, "index_type", "binary") != "binary"
            or getattr(table_options, "properties_collector_factories", None)
            or getattr(table_options, "prefix_extractor", None) is not None
            or getattr(table_options, "partition_filters", False)
            or icmp.user_comparator.name() != _dbf.BYTEWISE.name()):
        return None
    exported = mem.export_columnar()
    if exported is None:
        return None
    kv, seqs, vtypes = exported
    if kv.n == 0:
        # Tombstone-only table: the columnar writer's n==0 seqno accounting
        # differs from TableBuilder's — the iterator path stays bit-true.
        return None
    pb, pmems = _flush_protection([mem], table_options)
    if pb:
        # Verify the whole native export against the carried checksums
        # BEFORE any byte reaches the SST writer. Fast path: ONE native
        # pass folds the export into an XOR aggregate (checksums are
        # XOR-composable) and compares it with the memtable's carried
        # fold — no per-entry Python. Only on mismatch (or without the
        # native symbol) does the per-entry walk run, to name the
        # culprit record — or to absolve a benign aggregate drift
        # (duplicate WAL-replay entries dedup in the rep but not in the
        # pending fold).
        agg = _columnar_protect_xor(kv, vtypes, pb)
        ref = mem.protection_aggregate()
        if agg is None or ref is None or ref != (kv.n, agg):
            if kv.n != len(mem.protection_map()):
                raise Corruption(
                    f"flush protection: exported {kv.n} entries, "
                    f"{len(mem.protection_map())} protected"
                )
            for i in range(kv.n):
                ik = kv.ikey(i)
                _verify_flush_entry(pmems, pb, ik[:-8], int(seqs[i]),
                                    int(vtypes[i]), kv.value(i))
    import numpy as np

    from toplingdb_tpu.ops.columnar_io import write_tables_columnar
    from toplingdb_tpu.utils.status import NotSupported

    frags = list(fragment_tombstones(tombstones, icmp.user_comparator))

    numbers = iter([file_number])

    def alloc():
        return next(numbers)  # one output only (max size unbounded)

    try:
        files = write_tables_columnar(
            env, dbname, alloc, icmp, table_options, kv,
            np.arange(kv.n, dtype=np.int32),
            np.full(kv.n, -1, dtype=np.int64), vtypes, seqs, frags,
            creation_time, column_family=column_family,
        )
    except NotSupported:
        return None  # oversized keys etc. — iterator path handles them
    if not files:
        return None
    fnum, path, props, smallest, largest, _sel = files[0]
    return FileMetaData(
        number=fnum,
        file_size=env.get_file_size(path),
        smallest=smallest,
        largest=largest,
        smallest_seqno=props.smallest_seqno,
        largest_seqno=props.largest_seqno,
        num_entries=props.num_entries,
        num_deletions=props.num_deletions,
        num_range_deletions=props.num_range_deletions,
    )


def flush_memtable_to_table(env, dbname: str, file_number: int, icmp,
                            memtables: list[MemTable], table_options,
                            creation_time: int = 0,
                            blob_file_number: int | None = None,
                            min_blob_size: int = 0,
                            column_family: tuple[int, str] = (0, "default"),
                            ) -> FileMetaData | None:
    """Write one or more memtables (newest first) to a single L0 SST via a
    k-way merge of their already-sorted iterators. Returns None if there was
    nothing to write. With blob_file_number set, values >= min_blob_size go
    to a sibling blob file and the SST stores BLOB_INDEX pointers
    (reference BlobFileBuilder integration in flush)."""
    tombstones: list[RangeTombstone] = []
    total = 0
    for mem in memtables:
        total += len(mem._rep)
        for seq, begin, end in mem.range_del_entries():
            tombstones.append(RangeTombstone(seq, begin, end))
    if total == 0 and not tombstones:
        return None
    pb, pmems = _flush_protection(memtables, table_options)
    if pb:
        _verify_flush_tombstones(memtables, pb)

    if len(memtables) == 1 and blob_file_number is None:
        meta = _flush_columnar(env, dbname, file_number, icmp, memtables[0],
                               table_options, tombstones, creation_time,
                               column_family)
        if meta is not None:
            return meta

    blob_builder = None
    if blob_file_number is not None:
        # min_blob_size == 0 means "separate every value" (the reference's
        # semantics), not "disabled" — the enable flag gates separation.
        from toplingdb_tpu.db.blob import BlobFileBuilder

        blob_builder = BlobFileBuilder(env, dbname, blob_file_number)

    path = filename.table_file_name(dbname, file_number)
    w = env.new_writable_file(path)
    try:
        builder = new_table_builder(
            w, icmp, table_options, creation_time=creation_time,
            column_family_id=column_family[0],
            column_family_name=column_family[1],
        )
        merger = MergingIterator(
            icmp.compare, [m.new_iterator() for m in memtables]
        )
        merger.seek_to_first()
        last_ikey = None
        from toplingdb_tpu.db import dbformat as _dbf

        for ikey, val in merger.entries():
            # Exact duplicate internal keys across memtables (WAL replay):
            # the newer source (lower child index) surfaced first; skip dups.
            if last_ikey is not None and icmp.compare(last_ikey, ikey) == 0:
                continue
            last_ikey = ikey
            if pb:
                uk_, seq_, t_ = _dbf.split_internal_key(ikey)
                _verify_flush_entry(pmems, pb, uk_, seq_, t_, val)
            if (blob_builder is not None
                    and ikey[-8] == _dbf.ValueType.VALUE
                    and len(val) >= min_blob_size):
                uk, seq, _ = _dbf.split_internal_key(ikey)
                idx = blob_builder.add(uk, val)
                builder.add(
                    _dbf.make_internal_key(uk, seq, _dbf.ValueType.BLOB_INDEX),
                    idx,
                )
                continue
            builder.add(ikey, val)
        for frag in fragment_tombstones(tombstones, icmp.user_comparator):
            begin_ikey, end_uk = frag.to_table_entry()
            builder.add_tombstone(begin_ikey, end_uk)
        if builder.num_entries == 0:
            # Defense-in-depth: with the memtable rejecting degenerate
            # tombstones this is unreachable from current callers, but a
            # boundless empty table must NEVER reach the MANIFEST.
            w.close()
            env.delete_file(path)
            return None
        props = builder.finish()
        w.sync()
    finally:
        w.close()
        if blob_builder is not None:
            from toplingdb_tpu.db.blob import blob_file_name

            if blob_builder.finish() == 0:
                try:
                    env.delete_file(blob_file_name(dbname, blob_file_number))
                except Exception as e:
                    _errors.swallow(reason="blob-empty-file-delete", exc=e)

    return FileMetaData(
        number=file_number,
        file_size=env.get_file_size(path),
        smallest=builder.smallest_key,
        largest=builder.largest_key,
        smallest_seqno=props.smallest_seqno,
        largest_seqno=props.largest_seqno,
        num_entries=props.num_entries,
        num_deletions=props.num_deletions,
        num_range_deletions=props.num_range_deletions,
        blob_refs=(
            [blob_file_number]
            if blob_builder is not None and blob_builder.num_values else []
        ),
        marked_for_compaction=builder.need_compaction,
    )
