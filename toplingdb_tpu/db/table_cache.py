"""TableCache: cache of open TableReaders keyed by file number
(reference db/table_cache.cc:92 in /root/reference)."""

from __future__ import annotations

import threading

from toplingdb_tpu.utils import concurrency as ccy
import time
from collections import OrderedDict

from toplingdb_tpu.db import filename
from toplingdb_tpu.db.dbformat import InternalKeyComparator
from toplingdb_tpu.table.builder import TableOptions
from toplingdb_tpu.table.factory import open_table


class TableCache:
    def __init__(self, env, dbname: str, icmp: InternalKeyComparator,
                 table_options: TableOptions | None = None, capacity: int = 512,
                 block_cache=None):
        import uuid

        self._env = env
        self._dbname = dbname
        self._icmp = icmp
        self._topts = table_options or TableOptions()
        self._capacity = capacity
        self._block_cache = block_cache
        # Per-DB-open uniquifier: a shared block cache (reference cache-key
        # session id) must never serve one DB's blocks to another DB whose
        # file numbers collide.
        self._cache_session = uuid.uuid4().bytes[:8]
        self._readers: OrderedDict[int, TableReader] = OrderedDict()
        self._lock = ccy.Lock("table_cache.TableCache._lock")
        self.stats = None  # optional Statistics sink (set by the DB)

    def get_reader(self, file_number: int) -> TableReader:
        with self._lock:
            r = self._readers.get(file_number)
            if r is not None:
                self._readers.move_to_end(file_number)
                return r
        path = filename.table_file_name(self._dbname, file_number)
        t0 = time.perf_counter() if self.stats is not None else None
        try:
            r = open_table(
                self._env.new_random_access_file(path), self._icmp,
                self._topts, block_cache=self._block_cache,
                cache_key_prefix=self._cache_session
                + file_number.to_bytes(8, "little"),
            )
        except Exception:
            if self.stats is not None:
                from toplingdb_tpu.utils import statistics as st

                self.stats.record_tick(st.NO_FILE_ERRORS)
            raise
        if t0 is not None:
            from toplingdb_tpu.utils import statistics as st

            self.stats.record_tick(st.NO_FILE_OPENS)
            self.stats.record_in_histogram(
                st.TABLE_OPEN_IO_MICROS, (time.perf_counter() - t0) * 1e6)
        with self._lock:
            existing = self._readers.get(file_number)
            if existing is not None:
                r.close()
                return existing
            self._readers[file_number] = r
            while len(self._readers) > self._capacity:
                # Drop the reference only: live iterators may still hold the
                # reader; its file handle is reclaimed when the last reference
                # dies (the Python analogue of the reference's cache pinning).
                self._readers.popitem(last=False)
            return r

    def evict(self, file_number: int) -> None:
        with self._lock:
            self._readers.pop(file_number, None)

    def close(self) -> None:
        with self._lock:
            for r in self._readers.values():
                r.close()
            self._readers.clear()
