"""DB repair: rebuild a usable MANIFEST from the SSTs on disk
(reference db/repair.cc in /root/reference).

Strategy (same as the reference's RepairDB): archive the old MANIFEST/CURRENT,
scan every .sst for bounds/seqnos (checksum-verified), replay any WALs into a
fresh L0 table, then write a new MANIFEST placing every surviving table in L0
— overlap-safe because L0 allows overlapping ranges; the next compaction
re-sorts the tree.

Limitation (round 1): multi-CF DBs are flattened into the default column
family (the MANIFEST that mapped tables to CFs is the thing that was lost);
CF reconstruction from table properties is a later refinement.
"""

from __future__ import annotations

import os

from toplingdb_tpu.db import dbformat, filename
from toplingdb_tpu.db.dbformat import InternalKeyComparator
from toplingdb_tpu.db.log import LogReader, LogWriter
from toplingdb_tpu.db.memtable import MemTable
from toplingdb_tpu.db.flush_job import flush_memtable_to_table
from toplingdb_tpu.db.version_edit import FileMetaData, VersionEdit
from toplingdb_tpu.db.write_batch import WriteBatch
from toplingdb_tpu.options import Options
from toplingdb_tpu.table.factory import open_table


def repair_db(dbname: str, options: Options | None = None, env=None) -> dict:
    """Returns a report dict: tables kept/dropped, wal records recovered."""
    options = options or Options()
    from toplingdb_tpu.env import default_env

    env = env or default_env()
    icmp = InternalKeyComparator(options.comparator)
    report = {"tables_kept": 0, "tables_dropped": 0, "wal_records": 0,
              "archived": []}

    children = env.get_children(dbname)
    # 1. Archive old metadata (lost+found style).
    archive = os.path.join(dbname, "lost")
    env.create_dir(archive)
    for child in children:
        ftype, num = filename.parse_file_name(child)
        if ftype in (filename.FileType.MANIFEST, filename.FileType.CURRENT):
            env.rename_file(f"{dbname}/{child}", f"{archive}/{child}")
            report["archived"].append(child)

    # 2. Scan tables: verified ones survive with recomputed metadata.
    metas: list[FileMetaData] = []
    max_file_number = 1
    max_seq = 0
    for child in children:
        ftype, num = filename.parse_file_name(child)
        if ftype != filename.FileType.TABLE:
            continue
        max_file_number = max(max_file_number, num)
        path = filename.table_file_name(dbname, num)
        try:
            r = open_table(env.new_random_access_file(path), icmp,
                            options.table_options)
            it = r.new_iterator()
            it.seek_to_first()
            smallest = None
            largest = None
            n = 0
            for k, _ in it.entries():  # checksum-verified full scan
                if smallest is None:
                    smallest = k
                largest = k
                n += 1
            for b, e in r.range_del_entries():
                if smallest is None or icmp.compare(b, smallest) < 0:
                    smallest = b
                end_ikey = dbformat.make_internal_key(
                    e, dbformat.MAX_SEQUENCE_NUMBER,
                    dbformat.VALUE_TYPE_FOR_SEEK,
                )
                if largest is None or icmp.compare(end_ikey, largest) > 0:
                    largest = end_ikey
            if smallest is None:
                raise ValueError("empty table")
            props = r.properties
            metas.append(FileMetaData(
                number=num, file_size=env.get_file_size(path),
                smallest=smallest, largest=largest,
                smallest_seqno=props.smallest_seqno,
                largest_seqno=props.largest_seqno,
                num_entries=n,
                num_range_deletions=props.num_range_deletions,
            ))
            max_seq = max(max_seq, props.largest_seqno)
            report["tables_kept"] += 1
        except Exception:
            env.rename_file(path, f"{archive}/{child}")
            report["tables_dropped"] += 1

    # 3. Replay WALs into a fresh L0 table. Only CORRUPTION stops a WAL
    # (its tail is unrecoverable); anything else is a real error the caller
    # must see — swallowing it would silently drop acknowledged writes.
    from toplingdb_tpu.utils.status import Corruption, NotFound

    report["wal_errors"] = 0
    mem = MemTable(icmp)
    for child in children:
        ftype, num = filename.parse_file_name(child)
        if ftype != filename.FileType.WAL:
            continue
        max_file_number = max(max_file_number, num)
        try:
            reader = LogReader(env.new_sequential_file(
                filename.log_file_name(dbname, num)))
            for rec in reader.records():
                batch = WriteBatch(rec)
                batch.insert_into(mem)
                report["wal_records"] += batch.count()
                max_seq = max(max_seq, batch.sequence() + batch.count() - 1)
        except (Corruption, NotFound):
            report["wal_errors"] += 1
    if not mem.empty():
        fnum = max_file_number + 1
        max_file_number = fnum
        meta = flush_memtable_to_table(
            env, dbname, fnum, icmp, [mem], options.table_options
        )
        if meta is not None:
            metas.append(meta)
            report["tables_kept"] += 1

    # 4. Fresh MANIFEST: everything goes to L0 (overlap-legal).
    manifest_number = max_file_number + 1
    edit = VersionEdit(
        comparator=icmp.user_comparator.name(),
        log_number=max_file_number + 2,
        next_file_number=max_file_number + 3,
        last_sequence=max_seq,
        column_family_add="default",
        max_column_family=0,
    )
    for m in metas:
        edit.add_file(0, m)
    w = LogWriter(env.new_writable_file(
        filename.manifest_file_name(dbname, manifest_number)))
    w.add_record(edit.encode())
    w.sync()
    w.close()
    filename.set_current_file(env, dbname, manifest_number)
    return report
